"""CoA / Disconnect server (RFC 5176): dynamic authorization from RADIUS.

Parity: pkg/radius/coa.go (CoAServer :119, request-authenticator verify
:486-502) + coa_handler.go (CoAProcessor :16-460: session lookup by
Acct-Session-Id / Framed-IP / Calling-Station-Id, policy update wired to
the QoS tables, disconnect wired to session teardown).
"""

from __future__ import annotations

import socket
import threading

from bng_tpu.control.radius import packet as rp
from bng_tpu.control.radius.packet import RadiusPacket


class CoAProcessor:
    """Applies CoA/Disconnect actions to live sessions.

    session_index: callables that resolve a session handle;
    qos_update(ip, policy_name) is the EBPFQoSUpdaterFunc role
    (coa_handler.go:175-460) — here it writes the device QoS tables.
    """

    def __init__(
        self,
        find_by_session_id=None,  # (sid) -> session | None
        find_by_ip=None,  # (ip_u32) -> session | None
        find_by_mac=None,  # (mac_str) -> session | None
        qos_update=None,  # (framed_ip_u32, policy_name) -> bool
        disconnect=None,  # (session) -> bool
        policy_manager=None,
    ):
        self.find_by_session_id = find_by_session_id
        self.find_by_ip = find_by_ip
        self.find_by_mac = find_by_mac
        self.qos_update = qos_update
        self.disconnect = disconnect
        self.policy_manager = policy_manager
        self.stats = {"coa_ack": 0, "coa_nak": 0, "disc_ack": 0, "disc_nak": 0}

    def _locate(self, req: RadiusPacket):
        sid = req.get_str(rp.ACCT_SESSION_ID)
        if sid and self.find_by_session_id:
            s = self.find_by_session_id(sid)
            if s is not None:
                return s
        ip = req.get_int(rp.FRAMED_IP_ADDRESS)
        if ip and self.find_by_ip:
            s = self.find_by_ip(ip)
            if s is not None:
                return s
        mac = req.get_str(rp.CALLING_STATION_ID)
        if mac and self.find_by_mac:
            return self.find_by_mac(mac)
        return None

    def process(self, req: RadiusPacket) -> RadiusPacket:
        session = self._locate(req)
        if req.code == rp.DISCONNECT_REQUEST:
            if session is not None and self.disconnect and self.disconnect(session):
                self.stats["disc_ack"] += 1
                return RadiusPacket(rp.DISCONNECT_ACK, req.id)
            self.stats["disc_nak"] += 1
            return RadiusPacket(rp.DISCONNECT_NAK, req.id)

        # CoA: policy change via Filter-Id
        if session is None:
            self.stats["coa_nak"] += 1
            return RadiusPacket(rp.COA_NAK, req.id)
        policy_name = req.get_str(rp.FILTER_ID) or ""
        ok = True
        if policy_name and self.qos_update:
            framed_ip = req.get_int(rp.FRAMED_IP_ADDRESS) or getattr(session, "ip", 0)
            if self.policy_manager and self.policy_manager.get(policy_name) is None:
                ok = False
            else:
                ok = self.qos_update(framed_ip, policy_name)
        if ok:
            self.stats["coa_ack"] += 1
            return RadiusPacket(rp.COA_ACK, req.id)
        self.stats["coa_nak"] += 1
        return RadiusPacket(rp.COA_NAK, req.id)


class CoAServer:
    """UDP listener for CoA/Disconnect (coa.go:119-240). handle_raw is
    also callable directly for tests (no socket needed)."""

    def __init__(self, secret: bytes, processor: CoAProcessor,
                 bind: tuple[str, int] = ("0.0.0.0", 3799)):
        self.secret = secret
        self.processor = processor
        self.bind = bind
        self._sock: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._running = False
        self.stats = {"bad_auth": 0, "bad_packet": 0, "handled": 0}

    def handle_raw(self, data: bytes) -> bytes | None:
        try:
            req = RadiusPacket.decode(data)
        except ValueError:
            self.stats["bad_packet"] += 1
            return None
        if req.code not in (rp.COA_REQUEST, rp.DISCONNECT_REQUEST):
            self.stats["bad_packet"] += 1
            return None
        if not req.verify_request(self.secret, data):
            self.stats["bad_auth"] += 1
            return None  # silently drop on bad authenticator (coa.go:495)
        resp = self.processor.process(req)
        self.stats["handled"] += 1
        return resp.encode(self.secret, request_auth=req.authenticator)

    # -- socket runtime --
    def start(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(self.bind)
        self.addr = self._sock.getsockname()  # bind=port 0 -> real port
        self._sock.settimeout(0.5)
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while self._running:
            try:
                data, addr = self._sock.recvfrom(4096)
            except socket.timeout:
                continue
            except OSError:
                break
            resp = self.handle_raw(data)
            if resp is not None:
                self._sock.sendto(resp, addr)

    def stop(self) -> None:
        self._running = False
        if self._thread:
            self._thread.join(timeout=2)
        if self._sock:
            self._sock.close()
