"""RADIUS wire codec (RFC 2865/2866/5176).

Parity: the role layeh.com/radius plays for pkg/radius (client.go), built
from scratch: header, TLV attributes, request/response authenticators,
User-Password crypt, Message-Authenticator (HMAC-MD5, client.go:405).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct

# Codes
ACCESS_REQUEST = 1
ACCESS_ACCEPT = 2
ACCESS_REJECT = 3
ACCOUNTING_REQUEST = 4
ACCOUNTING_RESPONSE = 5
ACCESS_CHALLENGE = 11
DISCONNECT_REQUEST = 40
DISCONNECT_ACK = 41
DISCONNECT_NAK = 42
COA_REQUEST = 43
COA_ACK = 44
COA_NAK = 45

# Attribute types (subset the BNG uses)
USER_NAME = 1
USER_PASSWORD = 2
CHAP_PASSWORD = 3
NAS_IP_ADDRESS = 4
NAS_PORT = 5
SERVICE_TYPE = 6
FRAMED_IP_ADDRESS = 8
FILTER_ID = 11
REPLY_MESSAGE = 18
STATE = 24
CLASS = 25
VENDOR_SPECIFIC = 26
SESSION_TIMEOUT = 27
IDLE_TIMEOUT = 28
CALLED_STATION_ID = 30
CALLING_STATION_ID = 31
NAS_IDENTIFIER = 32
ACCT_STATUS_TYPE = 40
ACCT_DELAY_TIME = 41
ACCT_INPUT_OCTETS = 42
ACCT_OUTPUT_OCTETS = 43
ACCT_SESSION_ID = 44
ACCT_SESSION_TIME = 46
ACCT_INPUT_PACKETS = 47
ACCT_OUTPUT_PACKETS = 48
ACCT_TERMINATE_CAUSE = 49
CHAP_CHALLENGE = 60
NAS_PORT_TYPE = 61
EVENT_TIMESTAMP = 55
MESSAGE_AUTHENTICATOR = 80

# Acct-Status-Type values
ACCT_START, ACCT_STOP, ACCT_INTERIM = 1, 2, 3
# Terminate causes (RFC 2866 §5.10)
TERM_USER_REQUEST, TERM_LOST_CARRIER, TERM_IDLE_TIMEOUT, TERM_SESSION_TIMEOUT, TERM_ADMIN_RESET = 1, 2, 4, 5, 6


class RadiusPacket:
    def __init__(self, code: int, pid: int = 0, authenticator: bytes = b"\x00" * 16):
        self.code = code
        self.id = pid
        self.authenticator = authenticator
        self.attributes: list[tuple[int, bytes]] = []

    # -- attribute helpers --
    def add(self, attr_type: int, value: bytes | str | int) -> None:
        if isinstance(value, str):
            value = value.encode()
        elif isinstance(value, int):
            value = struct.pack("!I", value)
        if len(value) > 253:
            raise ValueError("attribute too long")
        self.attributes.append((attr_type, value))

    def get(self, attr_type: int) -> bytes | None:
        for t, v in self.attributes:
            if t == attr_type:
                return v
        return None

    def get_all(self, attr_type: int) -> list[bytes]:
        return [v for t, v in self.attributes if t == attr_type]

    def get_int(self, attr_type: int) -> int | None:
        v = self.get(attr_type)
        return struct.unpack("!I", v)[0] if v and len(v) == 4 else None

    def get_str(self, attr_type: int) -> str | None:
        v = self.get(attr_type)
        return v.decode(errors="replace") if v is not None else None

    # -- wire --
    def _attrs_bytes(self) -> bytes:
        out = b""
        for t, v in self.attributes:
            out += bytes([t, len(v) + 2]) + v
        return out

    def encode(self, secret: bytes = b"", request_auth: bytes | None = None,
               sign_message_authenticator: bool = False) -> bytes:
        """Encode; computes the correct (request/response/accounting)
        authenticator when `secret` is given."""
        if sign_message_authenticator:
            # placeholder first; HMAC over the packet with zeroed MA
            self.attributes = [(t, v) for t, v in self.attributes if t != MESSAGE_AUTHENTICATOR]
            self.attributes.append((MESSAGE_AUTHENTICATOR, b"\x00" * 16))
        attrs = self._attrs_bytes()
        length = 20 + len(attrs)

        if self.code == ACCESS_REQUEST:
            auth = self.authenticator  # random request authenticator
        elif self.code in (ACCOUNTING_REQUEST, DISCONNECT_REQUEST, COA_REQUEST):
            # Request Authenticator = MD5(Code+ID+Len+16 zeros+Attrs+Secret)
            hdr = struct.pack("!BBH", self.code, self.id, length)
            auth = hashlib.md5(hdr + b"\x00" * 16 + attrs + secret).digest()
            self.authenticator = auth
        else:
            # response: MD5(Code+ID+Len+RequestAuth+Attrs+Secret)
            assert request_auth is not None, "response needs the request authenticator"
            hdr = struct.pack("!BBH", self.code, self.id, length)
            auth = hashlib.md5(hdr + request_auth + attrs + secret).digest()
            self.authenticator = auth

        if sign_message_authenticator:
            hdr = struct.pack("!BBH", self.code, self.id, length)
            base = self.authenticator if self.code == ACCESS_REQUEST else auth
            mac = hmac.new(secret, hdr + base + attrs, hashlib.md5).digest()
            self.attributes[-1] = (MESSAGE_AUTHENTICATOR, mac)
            attrs = self._attrs_bytes()

        return struct.pack("!BBH", self.code, self.id, length) + self.authenticator + attrs

    @classmethod
    def decode(cls, data: bytes) -> "RadiusPacket":
        if len(data) < 20:
            raise ValueError("RADIUS packet too short")
        code, pid, length = struct.unpack_from("!BBH", data, 0)
        if length > len(data) or length < 20:
            raise ValueError("bad RADIUS length")
        p = cls(code, pid, data[4:20])
        i = 20
        while i + 2 <= length:
            t, ln = data[i], data[i + 1]
            if ln < 2 or i + ln > length:
                raise ValueError("bad attribute length")
            p.attributes.append((t, data[i + 2 : i + ln]))
            i += ln
        return p

    # -- crypto --
    def verify_response(self, secret: bytes, request_auth: bytes, raw: bytes) -> bool:
        """Validate a response authenticator against the original request."""
        hdr = raw[:4]
        attrs = raw[20 : struct.unpack("!H", raw[2:4])[0]]
        expect = hashlib.md5(hdr + request_auth + attrs + secret).digest()
        return hmac.compare_digest(expect, self.authenticator)

    def verify_request(self, secret: bytes, raw: bytes) -> bool:
        """Validate a CoA/Disconnect/Accounting request authenticator
        (parity: coa.go:486-502)."""
        hdr = raw[:4]
        attrs = raw[20 : struct.unpack("!H", raw[2:4])[0]]
        expect = hashlib.md5(hdr + b"\x00" * 16 + attrs + secret).digest()
        return hmac.compare_digest(expect, self.authenticator)


def encrypt_password(password: bytes, secret: bytes, request_auth: bytes) -> bytes:
    """RFC 2865 §5.2 User-Password obfuscation."""
    if len(password) % 16:
        password += b"\x00" * (16 - len(password) % 16)
    out = b""
    prev = request_auth
    for i in range(0, len(password), 16):
        key = hashlib.md5(secret + prev).digest()
        block = bytes(a ^ b for a, b in zip(password[i : i + 16], key))
        out += block
        prev = block
    return out


def decrypt_password(blob: bytes, secret: bytes, request_auth: bytes) -> bytes:
    out = b""
    prev = request_auth
    for i in range(0, len(blob), 16):
        key = hashlib.md5(secret + prev).digest()
        out += bytes(a ^ b for a, b in zip(blob[i : i + 16], key))
        prev = blob[i : i + 16]
    return out.rstrip(b"\x00")


def new_request_authenticator() -> bytes:
    return os.urandom(16)
