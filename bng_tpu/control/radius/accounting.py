"""Accounting manager: session records, interim updates, crash recovery.

Parity: pkg/radius/accounting.go — AccountingManager (:19), interim loop
(:410-497), pending-record disk persistence + recoverOrphanedSessions
(:729-877). Loops are explicit tick() methods (the engine/operator calls
them); persistence is JSON lines in a spool file.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import asdict, dataclass, field

from bng_tpu.control.radius import packet as rp


@dataclass
class AcctSession:
    session_id: str
    username: str
    framed_ip: int
    mac: str
    start_time: float
    last_interim: float = 0.0
    input_octets: int = 0
    output_octets: int = 0
    input_packets: int = 0
    output_packets: int = 0


@dataclass
class PendingRecord:
    session_id: str
    status: int
    payload: dict
    attempts: int = 0
    queued_at: float = 0.0


class AccountingManager:
    def __init__(
        self,
        client,  # RadiusClient
        interim_interval_s: int = 300,
        spool_path: str | None = None,
        max_retries: int = 10,
        clock=time.time,
    ):
        self.client = client
        self.interim_interval_s = interim_interval_s
        self.spool_path = spool_path
        self.max_retries = max_retries
        self.clock = clock
        self.sessions: dict[str, AcctSession] = {}
        self.pending: list[PendingRecord] = []
        if spool_path and os.path.exists(spool_path):
            self._recover()

    # -- session lifecycle --
    def start(self, session_id: str, username: str, framed_ip: int, mac: str = "") -> bool:
        s = AcctSession(session_id, username, framed_ip, mac, self.clock())
        self.sessions[session_id] = s
        ok = self.client.send_accounting(session_id, rp.ACCT_START,
                                         username=username, framed_ip=framed_ip)
        if not ok:
            self._queue(session_id, rp.ACCT_START, {"username": username, "framed_ip": framed_ip})
        self._persist()
        return ok

    def update_counters(self, session_id: str, input_octets: int, output_octets: int,
                        input_packets: int = 0, output_packets: int = 0) -> None:
        s = self.sessions.get(session_id)
        if s:
            s.input_octets = input_octets
            s.output_octets = output_octets
            s.input_packets = input_packets
            s.output_packets = output_packets

    def stop(self, session_id: str, terminate_cause: int = rp.TERM_USER_REQUEST) -> bool:
        s = self.sessions.pop(session_id, None)
        if s is None:
            return False
        now = self.clock()
        ok = self.client.send_accounting(
            session_id, rp.ACCT_STOP, username=s.username, framed_ip=s.framed_ip,
            input_octets=s.input_octets, output_octets=s.output_octets,
            input_packets=s.input_packets, output_packets=s.output_packets,
            session_time=int(now - s.start_time), terminate_cause=terminate_cause,
        )
        if not ok:
            self._queue(session_id, rp.ACCT_STOP, {
                "username": s.username, "framed_ip": s.framed_ip,
                "input_octets": s.input_octets, "output_octets": s.output_octets,
                "session_time": int(now - s.start_time),
                "terminate_cause": terminate_cause,
            })
        self._persist()
        return ok

    # -- ticks (the reference's goroutine loops, accounting.go:410-497) --
    def interim_tick(self, now: float | None = None) -> int:
        """Send interim updates for sessions past the interval."""
        now = now if now is not None else self.clock()
        sent = 0
        for s in self.sessions.values():
            due = max(s.last_interim, s.start_time) + self.interim_interval_s
            if now < due:
                continue
            ok = self.client.send_accounting(
                s.session_id, rp.ACCT_INTERIM, username=s.username,
                framed_ip=s.framed_ip, input_octets=s.input_octets,
                output_octets=s.output_octets,
                session_time=int(now - s.start_time),
            )
            if ok:
                s.last_interim = now
                sent += 1
        return sent

    def retry_tick(self) -> int:
        """Retry queued records; drop after max_retries (accounting.go:500+)."""
        kept, sent = [], 0
        for rec in self.pending:
            ok = self.client.send_accounting(rec.session_id, rec.status, **{
                k: v for k, v in rec.payload.items()
                if k in ("username", "framed_ip", "input_octets", "output_octets",
                         "session_time", "terminate_cause")
            })
            if ok:
                sent += 1
                continue
            rec.attempts += 1
            if rec.attempts < self.max_retries:
                kept.append(rec)
        self.pending = kept
        self._persist()
        return sent

    # -- persistence / orphan recovery (accounting.go:729-877) --
    def _queue(self, session_id: str, status: int, payload: dict) -> None:
        self.pending.append(PendingRecord(session_id, status, payload,
                                          queued_at=self.clock()))

    def _persist(self) -> None:
        if not self.spool_path:
            return
        tmp = self.spool_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({
                "sessions": {k: asdict(v) for k, v in self.sessions.items()},
                "pending": [asdict(p) for p in self.pending],
            }, f)
        os.replace(tmp, self.spool_path)

    def _recover(self) -> None:
        """Reload sessions + pending from disk. Live sessions found on disk
        at startup are orphans: a crash interrupted them — close them out
        with Acct-Stop(Lost-Carrier) like recoverOrphanedSessions."""
        try:
            with open(self.spool_path) as f:
                d = json.load(f)
        except (json.JSONDecodeError, OSError):
            return
        self.pending = [PendingRecord(**p) for p in d.get("pending", [])]
        for sid, sd in d.get("sessions", {}).items():
            s = AcctSession(**sd)
            self._queue(sid, rp.ACCT_STOP, {
                "username": s.username, "framed_ip": s.framed_ip,
                "input_octets": s.input_octets, "output_octets": s.output_octets,
                "session_time": int(self.clock() - s.start_time),
                "terminate_cause": rp.TERM_LOST_CARRIER,
            })
