from bng_tpu.control.allocator.bitmap import IPAllocator  # noqa: F401
from bng_tpu.control.allocator.epoch_bitmap import EpochBitmapAllocator  # noqa: F401
from bng_tpu.control.allocator.store import (  # noqa: F401
    AllocationRecord,
    AllocationStore,
    MemoryAllocationStore,
)
from bng_tpu.control.allocator.distributed import DistributedAllocator  # noqa: F401
from bng_tpu.control.allocator.modes import (  # noqa: F401
    Allocator,
    HybridAllocator,
    LocalAllocator,
)
