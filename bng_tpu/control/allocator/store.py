"""Allocation stores: the persistence/coordination boundary.

Parity: pkg/allocator/store.go — AllocationStore interface (:86),
MemoryAllocationStore (:114), PoolAllocator (:381). The memory store is
the in-process fake the reference uses in tests (SURVEY.md §4.6); real
deployments back this with the Nexus store (bng_tpu.control.nexus).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Protocol


@dataclass
class AllocationRecord:
    ip: str
    subscriber_id: str
    allocated_at: float
    expires_at: float = 0.0
    node_id: str = ""
    meta: dict = field(default_factory=dict)


class AllocationStore(Protocol):
    def get(self, ip: str) -> AllocationRecord | None: ...

    def put(self, rec: AllocationRecord) -> bool: ...

    def delete(self, ip: str) -> bool: ...

    def list_all(self) -> list[AllocationRecord]: ...

    def find_by_subscriber(self, subscriber_id: str) -> AllocationRecord | None: ...


class MemoryAllocationStore:
    """In-memory AllocationStore (parity: store.go:114-310)."""

    def __init__(self):
        self._by_ip: dict[str, AllocationRecord] = {}
        self._by_sub: dict[str, str] = {}

    def get(self, ip: str) -> AllocationRecord | None:
        return self._by_ip.get(ip)

    def put(self, rec: AllocationRecord) -> bool:
        old = self._by_ip.get(rec.ip)
        if old is not None and old.subscriber_id != rec.subscriber_id:
            return False  # conflict: occupied by someone else
        self._by_ip[rec.ip] = rec
        self._by_sub[rec.subscriber_id] = rec.ip
        return True

    def put_if_absent(self, rec: AllocationRecord) -> bool:
        if rec.ip in self._by_ip:
            return self._by_ip[rec.ip].subscriber_id == rec.subscriber_id
        return self.put(rec)

    def delete(self, ip: str) -> bool:
        rec = self._by_ip.pop(ip, None)
        if rec is None:
            return False
        if self._by_sub.get(rec.subscriber_id) == ip:
            del self._by_sub[rec.subscriber_id]
        return True

    def list_all(self) -> list[AllocationRecord]:
        return list(self._by_ip.values())

    def find_by_subscriber(self, subscriber_id: str) -> AllocationRecord | None:
        ip = self._by_sub.get(subscriber_id)
        return self._by_ip.get(ip) if ip else None

    def expire(self, now: float | None = None) -> int:
        now = now if now is not None else time.time()
        dead = [ip for ip, r in self._by_ip.items() if r.expires_at and r.expires_at < now]
        for ip in dead:
            self.delete(ip)
        return len(dead)
