"""Allocation mode façades: local / hybrid (Nexus-primary, local-fallback).

Parity: pkg/allocator/modes.go — Allocator interface (:46), LocalAllocator
(:92), HybridAllocator with partition detection + reconcile (:344-510).
The hybrid mode is the partition-tolerance seam: while the central
allocator (Nexus) is unreachable, allocation falls back to a local range
and every fallback allocation is recorded for post-heal reconciliation
(bng_tpu.control.resilience consumes that record).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Protocol

from bng_tpu.control.allocator.bitmap import IPAllocator
from bng_tpu.utils.structlog import ErrorLog


class Allocator(Protocol):
    def allocate(self, subscriber_id: str) -> str | None: ...

    def release(self, subscriber_id: str) -> bool: ...


class LocalAllocator:
    """Purely local bitmap allocation (parity: modes.go:92-180)."""

    def __init__(self, cidr: str):
        self.bitmap = IPAllocator(cidr)
        self._by_sub: dict[str, str] = {}

    def allocate(self, subscriber_id: str) -> str | None:
        if subscriber_id in self._by_sub:
            return self._by_sub[subscriber_id]
        try:
            ip = str(self.bitmap.allocate(subscriber_id))
        except Exception:
            return None
        self._by_sub[subscriber_id] = ip
        return ip

    def release(self, subscriber_id: str) -> bool:
        ip = self._by_sub.pop(subscriber_id, None)
        if ip is None:
            return False
        return self.bitmap.release(ip)


@dataclass
class FallbackAllocation:
    subscriber_id: str
    ip: str
    at: float


class HybridAllocator:
    """Primary (remote/Nexus) with local fallback under partition.

    Parity: modes.go:344-510 — IsPartitionActive, fallback records,
    reconcile loop. `primary` is any Allocator (DistributedAllocator,
    HTTPAllocator...); failures flip partition state after
    `failure_threshold` consecutive errors.
    """

    def __init__(self, primary, fallback_cidr: str, failure_threshold: int = 3,
                 clock=time.time):
        self.primary = primary
        self.local = LocalAllocator(fallback_cidr)
        self.failure_threshold = failure_threshold
        self.clock = clock
        self._failures = 0
        self.partition_active = False
        self.fallback_allocations: list[FallbackAllocation] = []
        self.release_errors = 0
        self._release_err_log = ErrorLog(
            "allocator", "primary release failed (local release still "
            "applies)")

    def is_partition_active(self) -> bool:
        return self.partition_active

    def _primary_alloc(self, subscriber_id: str) -> str | None:
        try:
            ip = self.primary.allocate(subscriber_id)
            self._failures = 0
            if self.partition_active:
                pass  # healing is driven by reconcile(), not a lone success
            return ip
        except Exception:
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self.partition_active = True
            return None

    def allocate(self, subscriber_id: str) -> str | None:
        if not self.partition_active:
            ip = self._primary_alloc(subscriber_id)
            if ip is not None:
                return ip
            if not self.partition_active:
                return None  # primary healthy but exhausted: no fallback
        ip = self.local.allocate(subscriber_id)
        if ip is not None:
            self.fallback_allocations.append(
                FallbackAllocation(subscriber_id, ip, self.clock())
            )
        return ip

    def release(self, subscriber_id: str) -> bool:
        ok = False
        try:
            ok = self.primary.release(subscriber_id)
        except Exception as e:
            # a leaked primary allocation is exactly what reconcile()
            # heals — but it must be visible, not silent (BNG020)
            self.release_errors += 1
            self._release_err_log.report(e, subscriber_id=subscriber_id)
        return self.local.release(subscriber_id) or ok

    def reconcile(self) -> tuple[int, list[tuple[FallbackAllocation, str]]]:
        """Post-heal: migrate fallback allocations to the primary.

        Returns (migrated_count, renumbered): every successfully migrated
        subscriber whose primary-assigned address differs from its fallback
        address appears in `renumbered` as (fallback, new_ip) — the caller
        (DHCP server via short leases, resilience.Manager) pushes the new
        address at next renewal (modes.go:344-510 / manager.go:342-528
        parity: the partition loser is force-renumbered).
        """
        migrated, renumbered = 0, []
        remaining = []
        for fb in self.fallback_allocations:
            try:
                ip = self.primary.allocate(fb.subscriber_id)
            except Exception:
                remaining.append(fb)
                continue
            if ip is None:
                remaining.append(fb)
                continue
            migrated += 1
            self.local.release(fb.subscriber_id)
            if ip != fb.ip:
                renumbered.append((fb, ip))
        self.fallback_allocations = remaining
        if not remaining:
            self.partition_active = False
            self._failures = 0
        return migrated, renumbered
