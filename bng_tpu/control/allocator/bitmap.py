"""Bitmap IP allocator (IPv4 + IPv6 prefixes).

Parity: pkg/allocator/bitmap.go (IPAllocator, :46-427; JSON snapshot
:428-497). numpy bool bitmap instead of Go's []uint64; IPv6 handled with
python big-int offset math like the reference's big.Int.
"""

from __future__ import annotations

import ipaddress
import json

import numpy as np


class BitmapExhaustedError(Exception):
    pass


class IPAllocator:
    """Allocates offsets within one CIDR prefix via a bitmap."""

    def __init__(self, cidr: str, reserve_network: bool = True,
                 reserve_broadcast: bool = True, max_size: int = 1 << 22):
        self.net = ipaddress.ip_network(cidr, strict=False)
        total = self.net.num_addresses
        self.size = min(total, max_size)
        self.bitmap = np.zeros(self.size, dtype=bool)
        self.owners: dict[int, str] = {}
        self._next = 0
        self.allocated_count = 0
        if self.net.version == 4 and reserve_network and total > 2:
            self._reserve(0)
        if self.net.version == 4 and reserve_broadcast and total > 2 and total <= self.size:
            self._reserve(total - 1)

    def _reserve(self, off: int) -> None:
        if not self.bitmap[off]:
            self.bitmap[off] = True
            self.allocated_count += 1
            self.owners[off] = "__reserved__"

    def ip_at(self, offset: int):
        return self.net.network_address + offset

    def offset_of(self, ip) -> int:
        addr = ipaddress.ip_address(ip) if isinstance(ip, (str, int)) else ip
        off = int(addr) - int(self.net.network_address)
        if off < 0 or off >= self.size:
            raise ValueError(f"{addr} not in {self.net}")
        return off

    def allocate(self, owner: str = ""):
        """Next-free scan from a moving cursor (parity: bitmap.go:100-180)."""
        if self.allocated_count >= self.size:
            raise BitmapExhaustedError(str(self.net))
        free = np.nonzero(~self.bitmap[self._next :])[0]
        if len(free) == 0:
            free = np.nonzero(~self.bitmap[: self._next])[0]
            if len(free) == 0:
                raise BitmapExhaustedError(str(self.net))
            off = int(free[0])
        else:
            off = self._next + int(free[0])
        self.bitmap[off] = True
        self.owners[off] = owner
        self.allocated_count += 1
        self._next = (off + 1) % self.size
        return self.ip_at(off)

    def allocate_specific(self, ip, owner: str = "") -> bool:
        off = self.offset_of(ip)
        if self.bitmap[off]:
            return self.owners.get(off) == owner and owner != ""
        self.bitmap[off] = True
        self.owners[off] = owner
        self.allocated_count += 1
        return True

    def allocate_at(self, offset: int, owner: str = "") -> bool:
        if offset < 0 or offset >= self.size or self.bitmap[offset]:
            return False
        self.bitmap[offset] = True
        self.owners[offset] = owner
        self.allocated_count += 1
        return True

    def is_free(self, offset: int) -> bool:
        return 0 <= offset < self.size and not self.bitmap[offset]

    def release(self, ip) -> bool:
        off = self.offset_of(ip)
        if not self.bitmap[off] or self.owners.get(off) == "__reserved__":
            return False
        self.bitmap[off] = False
        self.owners.pop(off, None)
        self.allocated_count -= 1
        return True

    def owner_of(self, ip) -> str | None:
        return self.owners.get(self.offset_of(ip))

    def utilization(self) -> float:
        return self.allocated_count / self.size if self.size else 1.0

    # -- snapshot (parity: bitmap.go:428-497 JSON round-trip) --
    def to_json(self) -> str:
        return json.dumps({
            "cidr": str(self.net),
            "next": self._next,
            "allocated": {str(off): owner for off, owner in self.owners.items()},
        })

    @classmethod
    def from_json(cls, data: str) -> "IPAllocator":
        d = json.loads(data)
        a = cls(d["cidr"], reserve_network=False, reserve_broadcast=False)
        for off_s, owner in d["allocated"].items():
            off = int(off_s)
            a.bitmap[off] = True
            a.owners[off] = owner
            a.allocated_count += 1
        a._next = d.get("next", 0)
        return a
