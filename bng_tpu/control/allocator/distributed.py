"""Distributed allocator: deterministic hashring allocation over a store.

Parity: pkg/allocator/distributed.go (:14-540). Combines the hashring
candidate sequence (pkg/nexus/client.go:487-577 — hash(subscriber+attempt)
with bounded probing) with a shared AllocationStore: two nodes allocating
for the same subscriber race toward the same candidate address, and the
store's put-if-absent is the tiebreaker. Lease epochs drive expiry.
"""

from __future__ import annotations

import time

from bng_tpu.control.allocator.bitmap import IPAllocator
from bng_tpu.control.allocator.store import AllocationRecord, AllocationStore
from bng_tpu.parallel.hashring import hashring_allocate
from bng_tpu.utils.net import fnv1a32


class DistributedAllocator:
    def __init__(
        self,
        cidr: str,
        store,  # AllocationStore
        node_id: str = "node0",
        lease_seconds: int = 3600,
        max_attempts: int = 1024,
        clock=time.time,
    ):
        self.bitmap = IPAllocator(cidr)
        self.store = store
        self.node_id = node_id
        self.lease_seconds = lease_seconds
        self.max_attempts = max_attempts
        self.clock = clock

    def allocate(self, subscriber_id: str) -> str | None:
        """Deterministic candidate sequence + store claim."""
        existing = self.store.find_by_subscriber(subscriber_id)
        now = self.clock()
        if existing is not None and (not existing.expires_at or existing.expires_at > now):
            self.bitmap.allocate_specific(existing.ip, subscriber_id)
            return existing.ip

        def is_free(idx: int) -> bool:
            ip = str(self.bitmap.ip_at(idx))
            rec = self.store.get(ip)
            if rec is not None and rec.expires_at and rec.expires_at < now:
                # lazy expiry: free both the shared record and our bitmap view
                self.store.delete(ip)
                try:
                    self.bitmap.release(ip)
                except ValueError:
                    pass
                rec = None
            return rec is None and self.bitmap.is_free(idx)

        idx = hashring_allocate(subscriber_id, self.bitmap.size, is_free, self.max_attempts)
        if idx is None:
            return None
        ip = str(self.bitmap.ip_at(idx))
        rec = AllocationRecord(
            ip=ip, subscriber_id=subscriber_id, allocated_at=now,
            expires_at=now + self.lease_seconds, node_id=self.node_id,
        )
        claim = getattr(self.store, "put_if_absent", self.store.put)
        if not claim(rec):
            # lost the race — retry once with the next candidates
            idx = hashring_allocate(subscriber_id + "#retry", self.bitmap.size,
                                    is_free, self.max_attempts)
            if idx is None:
                return None
            ip = str(self.bitmap.ip_at(idx))
            rec.ip = ip
            if not claim(rec):
                return None
        self.bitmap.allocate_at(self.bitmap.offset_of(ip), subscriber_id)
        return ip

    def renew(self, subscriber_id: str) -> bool:
        rec = self.store.find_by_subscriber(subscriber_id)
        if rec is None:
            return False
        rec.expires_at = self.clock() + self.lease_seconds
        return self.store.put(rec)

    def release(self, subscriber_id: str) -> bool:
        rec = self.store.find_by_subscriber(subscriber_id)
        if rec is None:
            return False
        self.store.delete(rec.ip)
        try:
            self.bitmap.release(rec.ip)
        except ValueError:
            pass
        return True

    def sync_from_store(self) -> int:
        """Rebuild the local bitmap from the shared store (remote-change
        watcher role, distributed.go:480-520). Returns live record count."""
        now = self.clock()
        self.bitmap = IPAllocator(str(self.bitmap.net))
        n = 0
        for rec in self.store.list_all():
            if rec.expires_at and rec.expires_at < now:
                continue
            try:
                self.bitmap.allocate_specific(rec.ip, rec.subscriber_id)
                n += 1
            except ValueError:
                continue
        return n
