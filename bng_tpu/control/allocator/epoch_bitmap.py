"""Epoch-bitmap allocator: 2-bit generation tags, O(1) lease expiry.

Parity: pkg/allocator/epoch_bitmap.go (Issue #66, :10-358; snapshot
:372-428). Every entry carries a 2-bit generation tag; a whole epoch of
leases expires with a single counter bump (AdvanceEpoch, :225) and stale
entries are reclaimed lazily on allocation — no per-lease timers.

Tag encoding (2 bits): 0 = free; {1, 2, 3} = allocated in generation g.
Generations cycle 1 -> 2 -> 3 -> 1. With current generation c, an entry is
live iff tag == c or tag == prev(c); anything else is expired (lazy free).
Memory: one uint8 per address here (the reference packs 4/byte — 16KB per
/16; packing is a numpy view detail, not semantics).

TPU note (SURVEY.md §2.3): these tags are designed to colocate with HBM
table entries — the device lease check `now > lease_expiry`
(dhcp_fastpath.c:690) can become `tag is live`, making "expire a million
leases" a scalar broadcast instead of a table rewrite.
"""

from __future__ import annotations

import ipaddress
import json

import numpy as np


def _next_gen(g: int) -> int:
    return g % 3 + 1


def _prev_gen(g: int) -> int:
    return (g - 2) % 3 + 1


class EpochBitmapAllocator:
    def __init__(self, cidr: str, max_size: int = 1 << 22):
        self.net = ipaddress.ip_network(cidr, strict=False)
        self.size = min(self.net.num_addresses, max_size)
        self.tags = np.zeros(self.size, dtype=np.uint8)
        self.owners: dict[int, str] = {}
        self.current_gen = 1
        self.epoch = 0
        self._next = 0

    # -- generation liveness --
    def _live_mask(self) -> np.ndarray:
        return (self.tags == self.current_gen) | (self.tags == _prev_gen(self.current_gen))

    def is_live(self, offset: int) -> bool:
        t = int(self.tags[offset])
        return t != 0 and (t == self.current_gen or t == _prev_gen(self.current_gen))

    def advance_epoch(self) -> int:
        """O(1): everything allocated 2 epochs ago silently expires.

        Parity: AdvanceEpoch (epoch_bitmap.go:225). Returns the new epoch.
        """
        self.current_gen = _next_gen(self.current_gen)
        self.epoch += 1
        return self.epoch

    def allocate(self, owner: str = ""):
        """Allocate a free-or-expired slot; refreshes tag to current gen."""
        live = self._live_mask()
        order = np.concatenate([np.arange(self._next, self.size), np.arange(self._next)])
        free_positions = order[~live[order]]
        if len(free_positions) == 0:
            raise RuntimeError(f"epoch allocator {self.net} exhausted")
        off = int(free_positions[0])
        # lazy reclaim of an expired entry
        if self.tags[off] != 0:
            self.owners.pop(off, None)
        self.tags[off] = self.current_gen
        self.owners[off] = owner
        self._next = (off + 1) % self.size
        return self.net.network_address + off

    def touch(self, ip) -> bool:
        """Renew a lease into the current generation (keeps it live for
        two more epochs)."""
        off = self._offset(ip)
        if not self.is_live(off):
            return False
        self.tags[off] = self.current_gen
        return True

    def release(self, ip) -> bool:
        off = self._offset(ip)
        if self.tags[off] == 0:
            return False
        self.tags[off] = 0
        self.owners.pop(off, None)
        return True

    def owner_of(self, ip) -> str | None:
        off = self._offset(ip)
        return self.owners.get(off) if self.is_live(off) else None

    def _offset(self, ip) -> int:
        addr = ipaddress.ip_address(ip) if isinstance(ip, (str, int)) else ip
        off = int(addr) - int(self.net.network_address)
        if off < 0 or off >= self.size:
            raise ValueError(f"{addr} not in {self.net}")
        return off

    def live_count(self) -> int:
        return int(self._live_mask().sum())

    def utilization(self) -> float:
        return self.live_count() / self.size if self.size else 1.0

    # -- snapshot (parity: epoch_bitmap.go:372-428) --
    def to_json(self) -> str:
        live = self._live_mask()
        return json.dumps({
            "cidr": str(self.net),
            "epoch": self.epoch,
            "current_gen": self.current_gen,
            "entries": {
                str(off): {"tag": int(self.tags[off]), "owner": self.owners.get(off, "")}
                for off in np.nonzero(self.tags)[0]
                if live[off]
            },
        })

    @classmethod
    def from_json(cls, data: str) -> "EpochBitmapAllocator":
        d = json.loads(data)
        a = cls(d["cidr"])
        a.epoch = d["epoch"]
        a.current_gen = d["current_gen"]
        for off_s, e in d["entries"].items():
            off = int(off_s)
            a.tags[off] = e["tag"]
            if e["owner"]:
                a.owners[off] = e["owner"]
        return a
