"""DNS wire transport: message codec, UDP upstream forwarder, UDP server.

The missing half of control/dns.py (VERDICT r3 missing #4): the reference
actually forwards queries over the network and serves subscribers
(pkg/dns/resolver.go:116-210 — forward at :173-186); here the resolver
was a library with an injectable forwarder and no socket anywhere. This
module supplies:

- a compact DNS message codec (header/question/A/AAAA/CNAME answers,
  compression-pointer-safe parsing with a bounded jump count — the same
  bounded-walk discipline the fast-path parsers use);
- ``UDPForwarder``: ``Callable[[Query], Response]`` over UDP with
  per-upstream timeout and multi-upstream failover, drop-in for
  ``Resolver(forwarder=...)`` (parity: resolver.go:173-186, upstream
  rotation on failure);
- ``DNSServer``: a UDP listener serving ``Resolver`` to subscribers —
  the walled-garden answer path end-to-end (query in, portal IP out).

Everything is real-socket but loopback-testable: the tests run a fake
upstream on 127.0.0.1 and resolve through the full stack.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

from bng_tpu.control.dns import (
    CLASS_IN,
    RCODE_NAME_ERROR,
    RCODE_SERVER_FAILURE,
    RCODE_SUCCESS,
    Query,
    Record,
    Resolver,
    Response,
    TYPE_A,
    TYPE_AAAA,
    TYPE_CNAME,
    TYPE_MX,
    TYPE_NS,
    TYPE_PTR,
    TYPE_SRV,
)

MAX_NAME_JUMPS = 16  # bounded compression-pointer walk (loop safety)
MAX_UDP = 4096


class WireError(ValueError):
    pass


# ---------------------------------------------------------------------------
# names
# ---------------------------------------------------------------------------

def _encode_name(name: str) -> bytes:
    out = bytearray()
    for label in name.rstrip(".").split("."):
        if not label:
            continue
        raw = label.encode("idna") if not label.isascii() else label.encode()
        if len(raw) > 63:
            raise WireError(f"label too long: {label!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    return bytes(out)


def _decode_name(data: bytes, off: int) -> tuple[str, int]:
    """Returns (name, next_offset). Follows compression pointers with a
    bounded jump count; next_offset is past the FIRST pointer (or the
    terminating zero when uncompressed)."""
    labels = []
    jumps = 0
    next_off = None  # set at the first pointer
    while True:
        if off >= len(data):
            raise WireError("name runs past buffer")
        length = data[off]
        if length & 0xC0 == 0xC0:  # pointer
            if off + 2 > len(data):
                raise WireError("truncated pointer")
            if next_off is None:
                next_off = off + 2
            off = ((length & 0x3F) << 8) | data[off + 1]
            jumps += 1
            if jumps > MAX_NAME_JUMPS:
                raise WireError("compression loop")
            continue
        if length > 63:
            raise WireError("bad label length")
        off += 1
        if length == 0:
            break
        if off + length > len(data):
            raise WireError("label runs past buffer")
        labels.append(data[off : off + length].decode("ascii", "replace"))
        off += length
    return ".".join(labels), (next_off if next_off is not None else off)


# ---------------------------------------------------------------------------
# messages
# ---------------------------------------------------------------------------

def encode_query(query: Query, txid: int, recursion_desired: bool = True) -> bytes:
    flags = 0x0100 if recursion_desired else 0
    hdr = struct.pack("!HHHHHH", txid, flags, 1, 0, 0, 0)
    return hdr + _encode_name(query.name) + struct.pack(
        "!HH", query.qtype, query.qclass)


def decode_query(data: bytes) -> tuple[int, Query]:
    if len(data) < 12:
        raise WireError("short header")
    txid, flags, qd, _an, _ns, _ar = struct.unpack("!HHHHHH", data[:12])
    if flags & 0x8000:
        raise WireError("not a query")
    if qd < 1:
        raise WireError("no question")
    name, off = _decode_name(data, 12)
    if off + 4 > len(data):
        raise WireError("truncated question")
    qtype, qclass = struct.unpack("!HH", data[off : off + 4])
    return txid, Query(name=name, qtype=qtype, qclass=qclass)


def _encodable(rec: Record) -> bool:
    if rec.rtype == TYPE_A:
        return bool(rec.ipv4)
    if rec.rtype == TYPE_AAAA:
        return bool(rec.ipv6)
    if rec.rtype in (TYPE_CNAME, TYPE_NS, TYPE_PTR):
        return bool(rec.target)
    return bool(rec.rdata)


def _encode_record(rec: Record) -> bytes:
    if rec.rtype == TYPE_A:
        rdata = socket.inet_aton(rec.ipv4)
    elif rec.rtype == TYPE_AAAA:
        rdata = socket.inet_pton(socket.AF_INET6, rec.ipv6)
    elif rec.rtype in (TYPE_CNAME, TYPE_NS, TYPE_PTR):
        rdata = _encode_name(rec.target)
    elif rec.rdata:
        # decompressed verbatim rdata captured by decode_response (TXT,
        # MX, SRV, ...) — re-emitted as-is
        rdata = rec.rdata
    else:
        raise WireError(f"unsupported rtype {rec.rtype}")
    return (_encode_name(rec.name)
            + struct.pack("!HHIH", rec.rtype, rec.rclass, rec.ttl, len(rdata))
            + rdata)


def encode_response(resp: Response, txid: int) -> bytes:
    # QR=1, RD+RA set (we are a recursive forwarder), rcode in low bits
    flags = 0x8180 | (resp.rcode & 0xF)
    answers = [r for r in resp.answers if _encodable(r)]
    hdr = struct.pack("!HHHHHH", txid, flags, 1, len(answers), 0, 0)
    body = _encode_name(resp.query.name) + struct.pack(
        "!HH", resp.query.qtype, resp.query.qclass)
    for rec in answers:
        body += _encode_record(rec)
    return hdr + body


def decode_response(data: bytes) -> tuple[int, Query, Response]:
    if len(data) < 12:
        raise WireError("short header")
    txid, flags, qd, an, _ns, _ar = struct.unpack("!HHHHHH", data[:12])
    if not flags & 0x8000:
        raise WireError("not a response")
    rcode = flags & 0xF
    off = 12
    name, qtype, qclass = "", TYPE_A, CLASS_IN
    for _ in range(qd):
        name, off = _decode_name(data, off)
        if off + 4 > len(data):
            raise WireError("truncated question")
        qtype, qclass = struct.unpack("!HH", data[off : off + 4])
        off += 4
    query = Query(name=name, qtype=qtype, qclass=qclass)
    answers = []
    for _ in range(an):
        rname, off = _decode_name(data, off)
        if off + 10 > len(data):
            raise WireError("truncated answer")
        rtype, rclass, ttl, rdlen = struct.unpack("!HHIH", data[off : off + 10])
        off += 10
        if off + rdlen > len(data):
            raise WireError("rdata runs past buffer")
        rdata = data[off : off + rdlen]
        rec = Record(name=rname, rtype=rtype, rclass=rclass, ttl=ttl)
        if rtype == TYPE_A and rdlen == 4:
            rec.ipv4 = socket.inet_ntoa(rdata)
        elif rtype == TYPE_AAAA and rdlen == 16:
            rec.ipv6 = socket.inet_ntop(socket.AF_INET6, rdata)
        elif rtype in (TYPE_CNAME, TYPE_NS, TYPE_PTR):
            rec.target, _ = _decode_name(data, off)
        elif rtype == TYPE_MX and rdlen >= 3:
            # preference + exchange name: decompress so the copy can be
            # re-emitted outside the original message
            name, _ = _decode_name(data, off + 2)
            rec.rdata = rdata[:2] + _encode_name(name)
        elif rtype == TYPE_SRV and rdlen >= 7:
            name, _ = _decode_name(data, off + 6)
            rec.rdata = rdata[:6] + _encode_name(name)
        else:
            # name-free rdata (TXT, A6, CAA, ...) is position-independent
            # and passes through verbatim. (Name-bearing types beyond the
            # handled set — e.g. SOA in an answer section — would need
            # their own decompression; they are not served to subscribers
            # by this forwarder.)
            rec.rdata = rdata
        answers.append(rec)
        off += rdlen
    return txid, query, Response(query=query, answers=answers, rcode=rcode)


# ---------------------------------------------------------------------------
# upstream forwarder
# ---------------------------------------------------------------------------

class UDPForwarder:
    """Default upstream forwarder: UDP query with timeout + failover.

    Parity: resolver.go:173-186 — try each configured upstream in order,
    per-upstream timeout, first good answer wins; every upstream failing
    raises (the resolver maps that to SERVFAIL). Transaction IDs are
    random per query and verified on the reply, and replies are received
    on a connected socket so only the queried upstream can answer."""

    def __init__(self, upstreams: list[str], timeout: float = 2.0):
        if not upstreams:
            raise ValueError("need at least one upstream")
        self.upstreams = [self._parse(u) for u in upstreams]
        self.timeout = timeout
        self.stats = {"sent": 0, "failovers": 0, "timeouts": 0}

    @staticmethod
    def _parse(u: str) -> tuple[str, int]:
        host, _, port = u.partition(":")
        return host, int(port or 53)

    def __call__(self, query: Query) -> Response:
        last_err: Exception | None = None
        for i, addr in enumerate(self.upstreams):
            if i:
                self.stats["failovers"] += 1
            txid = int.from_bytes(os.urandom(2), "big")
            pkt = encode_query(query, txid)
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.settimeout(self.timeout)
                s.connect(addr)  # replies restricted to this upstream
                s.send(pkt)
                self.stats["sent"] += 1
                # per-upstream DEADLINE (advisor r4): re-arming the full
                # timeout per stale reply would let a mismatch flood hold
                # this upstream far past its budget
                deadline = time.monotonic() + self.timeout
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(f"upstream {addr} deadline")
                    s.settimeout(remaining)
                    data = s.recv(MAX_UDP)
                    try:
                        rtxid, rq, resp = decode_response(data)
                    except WireError:
                        # an UNDECODABLE datagram is the same off-path
                        # noise as a wrong txid: keep waiting for the
                        # real answer, don't abandon a live upstream
                        continue
                    if rtxid != txid:
                        continue  # stale/spoofed id: keep waiting
                    # the echoed question must match what we asked
                    # (RFC 5452 §4.2 entropy checks: id AND question;
                    # a qdcount=0 reply decodes to name="" and fails here)
                    if (rq.name.rstrip(".").lower()
                            != query.name.rstrip(".").lower()
                            or rq.qtype != query.qtype):
                        continue
                    resp.query = query
                    return resp
            except (TimeoutError, socket.timeout) as e:
                self.stats["timeouts"] += 1
                last_err = e
            except (OSError, WireError) as e:
                last_err = e
            finally:
                s.close()
        raise RuntimeError(f"all upstreams failed: {last_err!r}")


# ---------------------------------------------------------------------------
# UDP server
# ---------------------------------------------------------------------------

class DNSServer:
    """UDP listener serving a Resolver to subscribers.

    Receive loop on one thread; resolution runs on a bounded worker pool
    so a slow upstream head-of-line blocks ONE query, not every
    subscriber's DNS (cache hits and garden answers stay fast while a
    cache miss waits on the wire). Saturation drops queries (counted) —
    DNS clients retry, and a bounded drop beats an unbounded queue.
    The client's source IP becomes Query.source so walled-garden and
    rate-limit policy apply per subscriber. Close via stop(). Malformed
    packets are dropped (counted), resolver errors answer SERVFAIL — the
    listener must never die to a bad packet."""

    def __init__(self, resolver: Resolver, host: str = "0.0.0.0",
                 port: int = 53, workers: int = 8,
                 max_inflight: int = 256):
        import concurrent.futures

        self.resolver = resolver
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.addr = self.sock.getsockname()
        self.stats = {"served": 0, "bad_packets": 0, "server_errors": 0,
                      "overloaded": 0}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="bng-dns-worker")
        self._inflight = threading.BoundedSemaphore(max_inflight)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="bng-dns-udp")
        self._thread.start()

    def _serve(self) -> None:
        self.sock.settimeout(0.25)
        while not self._stop.is_set():
            try:
                data, client = self.sock.recvfrom(MAX_UDP)
            except (TimeoutError, socket.timeout):
                continue
            except OSError:
                break
            try:
                txid, query = decode_query(data)
            except WireError:
                self.stats["bad_packets"] += 1
                continue
            query.source = client[0]
            if not self._inflight.acquire(blocking=False):
                self.stats["overloaded"] += 1
                continue
            try:
                self._pool.submit(self._answer, txid, query, client)
            except RuntimeError:  # pool shut down mid-stop
                self._inflight.release()
                break

    def _answer(self, txid: int, query: Query, client) -> None:
        try:
            try:
                resp = self.resolver.resolve(query)
            except Exception:  # resolver bug must not kill the worker
                self.stats["server_errors"] += 1
                resp = Response(query=query, rcode=RCODE_SERVER_FAILURE)
            try:
                self.sock.sendto(encode_response(resp, txid), client)
                self.stats["served"] += 1
            except (OSError, WireError):
                self.stats["server_errors"] += 1
        finally:
            self._inflight.release()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._pool.shutdown(wait=False)
        self.sock.close()


__all__ = [
    "DNSServer",
    "UDPForwarder",
    "WireError",
    "decode_query",
    "decode_response",
    "encode_query",
    "encode_response",
    "RCODE_NAME_ERROR",
    "RCODE_SUCCESS",
]
