"""RFC 6908 NAT compliance logging with LEA query support.

Parity: pkg/nat/logging.go — Logger with buffered entries + flush
(logging.go:63-214, :349-414), formats json/syslog/csv/nel
(:416-523), bulk port-block logging (RFC 6908 reduced-volume mode,
:51-61, :364-414), size-based rotation with gzip + max-age cleanup
(:525-683), QueryByPublicEndpoint — "who had this public IP:port at this
time?" — backed by a real in-memory interval index here (the reference
stubs it behind an index database, :685-694).

Consumes the device ring-buffer events via NATManager's log_sink
(control/nat.py NATLogEntry; bpf/nat44.c:531-562 analog).
"""

from __future__ import annotations

import gzip
import json
import os
import threading
import time
from dataclasses import dataclass, field

from bng_tpu.control.nat import (LOG_PORT_BLOCK_ASSIGN, LOG_PORT_BLOCK_RELEASE,
                                 LOG_SESSION_CREATE, LOG_SESSION_DELETE,
                                 NATLogEntry)
from bng_tpu.utils.net import u32_to_ip

_EVENT_NAMES = {
    LOG_SESSION_CREATE: "session_create",
    LOG_SESSION_DELETE: "session_delete",
    LOG_PORT_BLOCK_ASSIGN: "port_block_assign",
    LOG_PORT_BLOCK_RELEASE: "port_block_release",
    5: "port_exhaustion", 6: "hairpin", 7: "alg_trigger",
}

_PROTO_NAMES = {1: "icmp", 6: "tcp", 17: "udp"}


def _ts(t: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))


@dataclass
class PortBlockRecord:
    """RFC 6908 bulk record (logging.go:51-61): one line covers the whole
    block instead of per-session churn."""

    timestamp: float
    event: str  # assign | release
    subscriber_id: int
    private_ip: str
    public_ip: str
    port_start: int
    port_end: int


@dataclass
class NATLoggerConfig:
    """logging.go:95-113."""

    enabled: bool = True
    file_path: str = ""  # empty -> in-memory only
    fmt: str = "json"  # json | syslog | csv | nel
    buffer_size: int = 1000
    bulk_logging: bool = False
    max_file_size: int = 0  # bytes; 0 = no rotation
    max_age: float = 0.0  # seconds; 0 = keep forever
    compress: bool = True
    enable_index: bool = True
    index_capacity: int = 1_000_000


class NATComplianceLogger:
    """logging.go:63-724."""

    def __init__(self, config: NATLoggerConfig | None = None, clock=time.time):
        self.config = config or NATLoggerConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._buffer: list[dict] = []
        self._block_buffer: list[PortBlockRecord] = []
        self._fh = None
        self._size = 0
        # Compliance index: (public_ip, port) -> list of
        # (start_ts, end_ts|None, record) in insertion (time) order.
        self._index: dict[tuple[str, int], list] = {}
        self._indexed = 0
        self.stats = {"entries": 0, "block_entries": 0, "flushes": 0,
                      "rotations": 0, "dropped": 0}
        if self.config.file_path:
            os.makedirs(os.path.dirname(self.config.file_path) or ".",
                        exist_ok=True)
            self._fh = open(self.config.file_path, "ab")
            self._size = self._fh.tell()

    # -- ingestion ------------------------------------------------------

    def log_device_event(self, e: NATLogEntry) -> None:
        """The NATManager log_sink target (logging.go LogFromBPF :293-333)."""
        if not self.config.enabled:
            return
        event = _EVENT_NAMES.get(e.event_type, f"event_{e.event_type}")
        if self.config.bulk_logging and e.event_type in (
                LOG_PORT_BLOCK_ASSIGN, LOG_PORT_BLOCK_RELEASE):
            self._add_block(PortBlockRecord(
                timestamp=float(e.timestamp),
                event="assign" if e.event_type == LOG_PORT_BLOCK_ASSIGN
                else "release",
                subscriber_id=e.subscriber_id,
                private_ip=u32_to_ip(e.private_ip),
                public_ip=u32_to_ip(e.public_ip),
                port_start=e.private_port, port_end=e.public_port))
            return
        if self.config.bulk_logging and e.event_type in (
                LOG_SESSION_CREATE, LOG_SESSION_DELETE):
            return  # RFC 6908: block records subsume per-session lines
        self._add({
            "ts": _ts(float(e.timestamp)), "t": float(e.timestamp),
            "event": event, "subscriber": e.subscriber_id,
            "private_ip": u32_to_ip(e.private_ip), "private_port": e.private_port,
            "public_ip": u32_to_ip(e.public_ip), "public_port": e.public_port,
            "dest_ip": u32_to_ip(e.dest_ip), "dest_port": e.dest_port,
            "protocol": _PROTO_NAMES.get(e.protocol, str(e.protocol)),
        })

    def log_session(self, private_ip: str, private_port: int, public_ip: str,
                    public_port: int, dest_ip: str = "", dest_port: int = 0,
                    protocol: int = 6, subscriber_id: int = 0,
                    end: bool = False) -> None:
        """logging.go:239-291."""
        now = self._clock()
        self._add({
            "ts": _ts(now), "t": now,
            "event": "session_delete" if end else "session_create",
            "subscriber": subscriber_id,
            "private_ip": private_ip, "private_port": private_port,
            "public_ip": public_ip, "public_port": public_port,
            "dest_ip": dest_ip, "dest_port": dest_port,
            "protocol": _PROTO_NAMES.get(protocol, str(protocol)),
        })

    def log_allocation(self, subscriber_id: int, private_ip: str,
                       public_ip: str, port_start: int, port_end: int,
                       release: bool = False) -> None:
        """logging.go:178-237: the RFC 6908 bulk path."""
        self._add_block(PortBlockRecord(
            timestamp=self._clock(),
            event="release" if release else "assign",
            subscriber_id=subscriber_id, private_ip=private_ip,
            public_ip=public_ip, port_start=port_start, port_end=port_end))

    def _add(self, rec: dict) -> None:
        with self._lock:
            self._buffer.append(rec)
            self.stats["entries"] += 1
            if self.config.enable_index:
                self._index_session(rec)
            full = len(self._buffer) >= self.config.buffer_size
        if full:
            self.flush()

    def _add_block(self, rec: PortBlockRecord) -> None:
        with self._lock:
            self._block_buffer.append(rec)
            self.stats["block_entries"] += 1
            if self.config.enable_index:
                self._index_block(rec)
            full = len(self._block_buffer) >= self.config.buffer_size
        if full:
            self.flush()

    # -- compliance index ----------------------------------------------

    def _index_session(self, rec: dict) -> None:
        key = (rec["public_ip"], rec["public_port"])
        if rec["event"] == "session_create":
            self._index.setdefault(key, []).append(
                [rec["t"], None, rec])
            self._indexed += 1
        elif rec["event"] == "session_delete":
            for iv in reversed(self._index.get(key, [])):
                if iv[1] is None:
                    iv[1] = rec["t"]
                    break
        if self._indexed > self.config.index_capacity:
            self._evict_index()

    def _index_block(self, rec: PortBlockRecord) -> None:
        # One interval entry per block, keyed port 0 + range kept in the
        # record; query expands the range check.
        key = (rec.public_ip, -1)
        if rec.event == "assign":
            self._index.setdefault(key, []).append(
                [rec.timestamp, None, rec])
            self._indexed += 1
        else:
            for iv in reversed(self._index.get(key, [])):
                r = iv[2]
                if iv[1] is None and r.port_start == rec.port_start \
                        and r.private_ip == rec.private_ip:
                    iv[1] = rec.timestamp
                    break
        if self._indexed > self.config.index_capacity:
            self._evict_index()

    def _evict_index(self) -> None:
        # Drop oldest closed intervals first.
        for key in list(self._index):
            ivs = self._index[key]
            keep = [iv for iv in ivs if iv[1] is None]
            dropped = len(ivs) - len(keep)
            if dropped:
                closed = sorted((iv for iv in ivs if iv[1] is not None),
                                key=lambda iv: iv[1])
                keep = closed[dropped // 2:] + keep
                self._index[key] = keep
                self._indexed -= dropped // 2
            if self._indexed <= self.config.index_capacity:
                break

    def query_by_public_endpoint(self, public_ip: str, public_port: int,
                                 timestamp: float) -> dict | None:
        """The LEA question (logging.go:685-694): who held public
        ip:port at time T? Checks session intervals then port blocks."""
        with self._lock:
            for start, end, rec in self._index.get((public_ip, public_port), []):
                if start <= timestamp and (end is None or timestamp < end):
                    return dict(rec)
            for start, end, rec in self._index.get((public_ip, -1), []):
                if (start <= timestamp and (end is None or timestamp < end)
                        and rec.port_start <= public_port <= rec.port_end):
                    return {"event": "port_block", "subscriber": rec.subscriber_id,
                            "private_ip": rec.private_ip,
                            "public_ip": rec.public_ip,
                            "port_start": rec.port_start,
                            "port_end": rec.port_end, "t": start}
        return None

    # -- formatting (logging.go:416-523) --------------------------------

    def _format(self, rec: dict) -> bytes:
        fmt = self.config.fmt
        if fmt == "json":
            return (json.dumps({k: v for k, v in rec.items() if k != "t"},
                               separators=(",", ":")) + "\n").encode()
        if fmt == "syslog":
            return (f"{rec['ts']} NAT {rec['event']}: "
                    f"subscriber={rec['subscriber']} "
                    f"private={rec['private_ip']}:{rec['private_port']} "
                    f"public={rec['public_ip']}:{rec['public_port']} "
                    f"dest={rec['dest_ip']}:{rec['dest_port']} "
                    f"proto={rec['protocol']}\n").encode()
        if fmt == "csv":
            cols = (rec["ts"], rec["event"], rec["subscriber"],
                    rec["private_ip"], rec["private_port"], rec["public_ip"],
                    rec["public_port"], rec["dest_ip"], rec["dest_port"],
                    rec["protocol"])
            return (",".join(str(c) for c in cols) + "\n").encode()
        if fmt == "nel":
            nel = {"type": "NAT", "age": 0,
                   "body": {k: rec[k] for k in
                            ("event", "subscriber", "private_ip",
                             "private_port", "public_ip", "public_port",
                             "dest_ip", "dest_port", "protocol")}}
            return (json.dumps(nel, separators=(",", ":")) + "\n").encode()
        raise ValueError(f"unknown format {fmt}")

    def _format_block(self, rec: PortBlockRecord) -> bytes:
        return (json.dumps({
            "ts": _ts(rec.timestamp), "event": f"port_block_{rec.event}",
            "subscriber": rec.subscriber_id, "private_ip": rec.private_ip,
            "public_ip": rec.public_ip, "port_start": rec.port_start,
            "port_end": rec.port_end}, separators=(",", ":")) + "\n").encode()

    # -- flush + rotation (logging.go:376-414, :525-683) -----------------

    def flush(self) -> int:
        with self._lock:
            buf, self._buffer = self._buffer, []
            blocks, self._block_buffer = self._block_buffer, []
            if not buf and not blocks:
                return 0
            data = b"".join(self._format(r) for r in buf) + \
                b"".join(self._format_block(r) for r in blocks)
            self.stats["flushes"] += 1
            if self._fh is None:
                return len(buf) + len(blocks)
            self._fh.write(data)
            self._fh.flush()
            self._size += len(data)
            if self.config.max_file_size and \
                    self._size >= self.config.max_file_size:
                self._rotate_locked()
        return len(buf) + len(blocks)

    def _rotate_locked(self) -> None:
        self._fh.close()
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(self._clock()))
        rotated = f"{self.config.file_path}.{stamp}.{self.stats['rotations']}"
        os.rename(self.config.file_path, rotated)
        if self.config.compress:
            with open(rotated, "rb") as src, \
                    gzip.open(rotated + ".gz", "wb") as dst:
                dst.write(src.read())
            os.remove(rotated)
        self._fh = open(self.config.file_path, "ab")
        self._size = 0
        self.stats["rotations"] += 1

    def clean_old_logs(self) -> int:
        """Age-based retention sweep (logging.go:646-683)."""
        if not self.config.max_age or not self.config.file_path:
            return 0
        base = os.path.basename(self.config.file_path)
        d = os.path.dirname(self.config.file_path) or "."
        cutoff = self._clock() - self.config.max_age
        removed = 0
        for name in os.listdir(d):
            if not name.startswith(base + "."):
                continue
            path = os.path.join(d, name)
            if os.path.getmtime(path) < cutoff:
                os.remove(path)
                removed += 1
        return removed

    def close(self) -> None:
        self.flush()
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def get_stats(self) -> dict:
        with self._lock:
            return dict(self.stats, buffer_used=len(self._buffer),
                        block_buffer_used=len(self._block_buffer),
                        indexed=self._indexed,
                        format=self.config.fmt,
                        bulk_logging=self.config.bulk_logging)
