"""ZTP TLS hardening: cert pinning, expiry checks, chain validation.

Parity: /root/reference/pkg/ztp/tls.go:20-527 — the reference's bootstrap
client authenticates Nexus with CA validation, SHA-256 certificate
pinning (TOFU for bootstrap, where no CA is provisioned yet), minimum TLS
version, chain checks, and expiry warnings. This is the TPU build's
equivalent for control/ztp.py's BootstrapClient.

Implementation notes (Python stdlib only — no `cryptography` package in
the image):
- ``build_ssl_context`` maps the config onto ``ssl.SSLContext`` (CA file/
  PEM, min version, hostname handling).
- Pinning and expiry run POST-handshake on the peer's DER cert
  (``verify_peer``): Python's ssl module has no per-cert verify hook, so
  the transport calls ``verify_peer`` after connecting and aborts on
  mismatch — the same enforcement point as tls.go's
  VerifyPeerCertificate callback (tls.go:208-229).
- Certificate fields (serial, validity, subject/issuer CN, SAN, isCA)
  come from a minimal DER/ASN.1 walker (``parse_certificate``): X.509's
  TBSCertificate layout is fixed, and the walker is bounds-checked and
  fuzz-tested like every other parser in this codebase.
"""

from __future__ import annotations

import hashlib
import ssl
from dataclasses import dataclass, field
from datetime import datetime, timezone
from urllib.parse import urlparse


# ---------------------------------------------------------------------------
# config (tls.go:20-71)
# ---------------------------------------------------------------------------

@dataclass
class TLSConfig:
    enabled: bool = True
    ca_cert_file: str = ""
    ca_cert_pem: str = ""
    pinned_certs: list[str] = field(default_factory=list)  # hex SHA256 of DER
    server_name: str = ""
    min_version: str = "1.2"  # "1.2" | "1.3"
    insecure_skip_verify: bool = False
    cert_expiry_warning_days: int = 30
    require_valid_chain: bool = True
    # our client identity, presented when the server demands mTLS
    # (sync.go:151-185's mutual-TLS mode on the HA wire)
    client_cert_file: str = ""
    client_key_file: str = ""


@dataclass
class ServerTLSConfig:
    """Listener-side TLS (the sync.go:151-185 server role): cert/key to
    present; set client_ca_* to REQUIRE verified client certificates
    (mutual TLS). Used by control.cluster_http.ClusterServer."""

    cert_file: str = ""
    key_file: str = ""
    client_ca_file: str = ""
    client_ca_pem: str = ""
    min_version: str = "1.2"


def build_server_ssl_context(cfg: ServerTLSConfig) -> ssl.SSLContext:
    if not cfg.cert_file or not cfg.key_file:
        raise ValueError("server TLS needs cert_file and key_file")
    if cfg.min_version not in ("1.2", "1.3"):
        raise ValueError(f"min_version {cfg.min_version!r}: expected 1.2/1.3")
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = (ssl.TLSVersion.TLSv1_3 if cfg.min_version == "1.3"
                           else ssl.TLSVersion.TLSv1_2)
    ctx.load_cert_chain(cfg.cert_file, cfg.key_file)
    if cfg.client_ca_file or cfg.client_ca_pem:
        if cfg.client_ca_pem:
            ctx.load_verify_locations(cadata=cfg.client_ca_pem)
        else:
            ctx.load_verify_locations(cafile=cfg.client_ca_file)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


class CertificateValidationError(ConnectionError):
    # A ConnectionError subclass ON PURPOSE: a pin/validity refusal is a
    # failed connection to every failover path (HA standby backoff, peer
    # pool ranking, CRDT round skip) — the node stays up and retries,
    # while callers that care about the WHY can still catch this type.

    def __init__(self, reason: str, subject: str = "", underlying=None):
        self.reason = reason
        self.subject = subject
        self.underlying = underlying
        msg = (f"certificate validation failed for {subject}: {reason}"
               if subject else f"certificate validation failed: {reason}")
        super().__init__(msg)


@dataclass
class CertificateInfo:
    subject: str = ""
    issuer: str = ""
    serial_number: str = ""
    not_before: datetime | None = None
    not_after: datetime | None = None
    fingerprint: str = ""
    is_ca: bool = False
    dns_names: list[str] = field(default_factory=list)
    ip_addresses: list[str] = field(default_factory=list)


@dataclass
class TLSValidationResult:
    valid: bool = False
    server_name: str = ""
    certificate_chain: list[CertificateInfo] = field(default_factory=list)
    pinning_verified: bool = False
    warnings: list[str] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)


def validate_tls_config(cfg: TLSConfig) -> None:
    """Config sanity (tls.go:277-315): reject contradictory settings
    before they silently weaken the connection."""
    if not cfg.enabled:
        return
    if cfg.min_version not in ("1.2", "1.3"):
        raise ValueError(f"min_version {cfg.min_version!r}: expected 1.2/1.3")
    if cfg.insecure_skip_verify and cfg.pinned_certs:
        raise ValueError(
            "insecure_skip_verify with pinned_certs: pinning implies you "
            "want verification — pick one")
    if not cfg.require_valid_chain and not cfg.pinned_certs:
        raise ValueError(
            "require_valid_chain=false needs pinned_certs: a self-signed "
            "cert with no pin authenticates nobody")
    for fp in cfg.pinned_certs:
        n = normalize_fingerprint(fp)
        if len(n) != 64 or any(c not in "0123456789abcdef" for c in n):
            raise ValueError(f"pinned cert {fp!r} is not a hex SHA-256")


# ---------------------------------------------------------------------------
# fingerprints (tls.go:466-506)
# ---------------------------------------------------------------------------

def normalize_fingerprint(fp: str) -> str:
    return fp.replace(":", "").replace(" ", "").lower()


def cert_fingerprint(der: bytes) -> str:
    """Hex SHA-256 of the DER-encoded certificate (tls.go:487-501)."""
    return hashlib.sha256(der).hexdigest()


def pem_to_der(pem: str | bytes) -> list[bytes]:
    """All certificates in a PEM bundle, DER-decoded."""
    import base64

    text = pem.decode() if isinstance(pem, bytes) else pem
    ders = []
    lines: list[str] | None = None
    for line in text.splitlines():
        line = line.strip()
        if line == "-----BEGIN CERTIFICATE-----":
            lines = []
        elif line == "-----END CERTIFICATE-----":
            if lines is not None:
                ders.append(base64.b64decode("".join(lines)))
            lines = None
        elif lines is not None:
            lines.append(line)
    return ders


# ---------------------------------------------------------------------------
# minimal DER/X.509 parser (bounds-checked; fuzz-tested)
# ---------------------------------------------------------------------------

class _Der:
    def __init__(self, data: bytes, off: int = 0, end: int | None = None):
        self.d = data
        self.off = off
        self.end = len(data) if end is None else end

    def _tlv(self) -> tuple[int, int, int]:
        """Returns (tag, content_start, content_end); advances nothing."""
        d, i = self.d, self.off
        if i + 2 > self.end:
            raise ValueError("DER: truncated TLV")
        tag = d[i]
        ln = d[i + 1]
        i += 2
        if ln & 0x80:
            n = ln & 0x7F
            if n == 0 or n > 4 or i + n > self.end:
                raise ValueError("DER: bad long-form length")
            ln = int.from_bytes(d[i : i + n], "big")
            i += n
        if i + ln > self.end:
            raise ValueError("DER: content past end")
        return tag, i, i + ln

    def next(self) -> tuple[int, "_Der"]:
        tag, start, end = self._tlv()
        inner = _Der(self.d, start, end)
        self.off = end
        return tag, inner

    def skip(self) -> None:
        _, _, end = self._tlv()
        self.off = end

    def bytes(self) -> bytes:
        return self.d[self.off : self.end]

    def has_more(self) -> bool:
        return self.off < self.end


_OID_CN = bytes.fromhex("550403")  # 2.5.4.3
_OID_BASIC_CONSTRAINTS = bytes.fromhex("551d13")  # 2.5.29.19
_OID_SAN = bytes.fromhex("551d11")  # 2.5.29.17


def _parse_time(tag: int, content: bytes) -> datetime:
    s = content.decode("ascii", "replace")
    if tag == 0x17:  # UTCTime YYMMDDHHMMSSZ
        year = int(s[:2])
        year += 2000 if year < 50 else 1900
        s = f"{year}{s[2:]}"
    return datetime.strptime(s.rstrip("Z"), "%Y%m%d%H%M%S").replace(
        tzinfo=timezone.utc)


def _parse_name(name: _Der) -> str:
    """RDNSequence -> 'CN=x' (CN only; enough for logs/pins)."""
    cn = ""
    while name.has_more():
        tag, rdn_set = name.next()  # SET
        if tag != 0x31:
            continue
        while rdn_set.has_more():
            tag, atv = rdn_set.next()  # SEQ { OID, value }
            if tag != 0x30:
                continue
            tag, oid = atv.next()
            if tag == 0x06 and oid.bytes() == _OID_CN and atv.has_more():
                _, val = atv.next()
                cn = val.bytes().decode("utf-8", "replace")
    return f"CN={cn}" if cn else ""


def parse_certificate(der: bytes) -> CertificateInfo:
    """Extract the fields tls.go's CertificateInfo carries (tls.go:94-105)."""
    info = CertificateInfo(fingerprint=cert_fingerprint(der))
    tag, cert = _Der(der).next()  # Certificate SEQ
    if tag != 0x30:
        raise ValueError("X.509: not a SEQUENCE")
    tag, tbs = cert.next()  # TBSCertificate SEQ
    if tag != 0x30:
        raise ValueError("X.509: bad TBSCertificate")
    # [0] version (optional)
    t, start, end = tbs._tlv()
    if t == 0xA0:
        tbs.off = end
    # serialNumber INTEGER
    tag, serial = tbs.next()
    if tag == 0x02:
        info.serial_number = serial.bytes().hex()
    tbs.skip()  # signature AlgorithmIdentifier
    tag, issuer = tbs.next()
    info.issuer = _parse_name(issuer)
    tag, validity = tbs.next()  # SEQ { notBefore, notAfter }
    t1, nb = validity.next()
    info.not_before = _parse_time(t1, nb.bytes())
    t2, na = validity.next()
    info.not_after = _parse_time(t2, na.bytes())
    tag, subject = tbs.next()
    info.subject = _parse_name(subject)
    tbs.skip()  # SubjectPublicKeyInfo
    # optional [1]/[2] unique ids, then [3] extensions
    while tbs.has_more():
        t, ext_wrap = tbs.next()
        if t != 0xA3:
            continue
        _, exts = ext_wrap.next()  # SEQ OF Extension
        while exts.has_more():
            _, ext = exts.next()  # SEQ { oid, [critical], OCTET STRING }
            t, oid = ext.next()
            if t != 0x06:
                continue
            t, nxt = ext.next()
            if t == 0x01 and ext.has_more():  # critical BOOLEAN: skip
                t, nxt = ext.next()
            if t != 0x04:
                continue
            body = _Der(nxt.bytes())
            if oid.bytes() == _OID_BASIC_CONSTRAINTS:
                t, bc = body.next()  # SEQ { [cA BOOLEAN], ... }
                if t == 0x30 and bc.has_more():
                    t, ca = bc.next()
                    info.is_ca = (t == 0x01 and ca.bytes() != b"\x00"
                                  and len(ca.bytes()) > 0)
            elif oid.bytes() == _OID_SAN:
                t, names = body.next()  # SEQ OF GeneralName
                if t == 0x30:
                    while names.has_more():
                        t, gn = names.next()
                        if t == 0x82:  # dNSName [2] IA5String
                            info.dns_names.append(
                                gn.bytes().decode("ascii", "replace"))
                        elif t == 0x87:  # iPAddress [7]
                            b = gn.bytes()
                            if len(b) == 4:
                                info.ip_addresses.append(
                                    ".".join(str(x) for x in b))
    return info


# ---------------------------------------------------------------------------
# validation (tls.go:208-275, 317-464, 508-522)
# ---------------------------------------------------------------------------

def is_certificate_expiring_soon(der: bytes, within_days: float,
                                 now: datetime | None = None
                                 ) -> tuple[bool, float]:
    """(expiring, remaining_days) — tls.go:508-522."""
    info = parse_certificate(der)
    now = now or datetime.now(timezone.utc)
    remaining = (info.not_after - now).total_seconds() / 86400.0
    return remaining <= within_days, remaining


def verify_peer(der_chain: list[bytes], cfg: TLSConfig,
                now: datetime | None = None) -> TLSValidationResult:
    """Post-handshake verification: pinning + validity window + expiry
    warnings over the presented chain (the VerifyPeerCertificate role,
    tls.go:208-275). Raises CertificateValidationError on failure,
    returns the result (with warnings) on success."""
    res = TLSValidationResult(server_name=cfg.server_name)
    if not der_chain:
        raise CertificateValidationError("no peer certificates presented")
    now = now or datetime.now(timezone.utc)
    for der in der_chain:
        try:
            res.certificate_chain.append(parse_certificate(der))
        except ValueError as e:
            raise CertificateValidationError(
                f"unparseable certificate: {e}") from e

    leaf = res.certificate_chain[0]
    if cfg.pinned_certs:
        pins = {normalize_fingerprint(p) for p in cfg.pinned_certs}
        chain_fps = {c.fingerprint for c in res.certificate_chain}
        if not (pins & chain_fps):
            raise CertificateValidationError(
                "no presented certificate matches a pinned fingerprint",
                subject=leaf.subject)
        res.pinning_verified = True

    for info in res.certificate_chain:
        if info.not_before and now < info.not_before:
            raise CertificateValidationError(
                "certificate not yet valid", subject=info.subject)
        if info.not_after and now > info.not_after:
            raise CertificateValidationError(
                "certificate expired", subject=info.subject)
        remaining = ((info.not_after - now).total_seconds() / 86400.0
                     if info.not_after else float("inf"))
        if remaining <= cfg.cert_expiry_warning_days:
            res.warnings.append(
                f"{info.subject or info.fingerprint[:16]} expires in "
                f"{remaining:.1f} days")
    res.valid = True
    return res


def build_ssl_context(cfg: TLSConfig) -> ssl.SSLContext:
    """ssl.SSLContext from the config (the BuildTLSConfig role,
    tls.go:125-206). Pinning/expiry still require verify_peer post-
    handshake — ssl has no per-cert hook."""
    validate_tls_config(cfg)
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = (ssl.TLSVersion.TLSv1_3 if cfg.min_version == "1.3"
                           else ssl.TLSVersion.TLSv1_2)
    if cfg.client_cert_file and cfg.client_key_file:
        # our identity for servers demanding mutual TLS (loaded before the
        # early returns: mTLS composes with every verification mode)
        ctx.load_cert_chain(cfg.client_cert_file, cfg.client_key_file)
    if cfg.insecure_skip_verify:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    if not cfg.require_valid_chain:
        # self-signed + pinning (tls.go:59-61): the chain check is off but
        # verify_peer's pin match is mandatory (validate_tls_config
        # enforces pins exist)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx
    if cfg.ca_cert_pem:
        ctx.load_verify_locations(cadata=cfg.ca_cert_pem)
    elif cfg.ca_cert_file:
        ctx.load_verify_locations(cafile=cfg.ca_cert_file)
    else:
        ctx.load_default_certs()
    if cfg.server_name:
        # hostname checked against server_name by the caller's connect
        ctx.check_hostname = True
    return ctx


def verify_wrapped_socket(tls_sock, cfg: TLSConfig) -> TLSValidationResult:
    """Post-handshake pin/validity verification of an ssl-wrapped socket
    — the one shared implementation for every TLS dial path
    (https_get_json, the cluster proxies, the ETSI delivery sink).

    Chain note: Python < 3.13 exposes only the leaf certificate
    (no SSLSocket.get_unverified_chain), so pins must cover the LEAF
    there; on 3.13+ a pinned intermediate/CA anywhere in the presented
    chain also matches (the tls.go:208-229 rawCerts behavior)."""
    chain: list[bytes] = []
    if hasattr(tls_sock, "get_unverified_chain"):  # Python 3.13+
        for c in tls_sock.get_unverified_chain() or []:
            if hasattr(c, "public_bytes"):
                # ssl.Certificate.public_bytes takes an _ssl encoding
                # enum: DER == 2 (PEM == 1 — a str, which would break
                # the DER parser and the fingerprint hash)
                chain.append(c.public_bytes(2))
            else:
                chain.append(c)
    if not chain:
        der = tls_sock.getpeercert(binary_form=True)
        chain = [der] if der else []
    return verify_peer(chain, cfg)


def extract_server_name_from_url(url: str) -> str:
    """tls.go:524-527."""
    host = urlparse(url).hostname
    return host or ""


def https_get_json(url: str, cfg: TLSConfig, timeout: float = 10.0,
                   method: str = "GET", body: bytes | None = None,
                   headers: dict | None = None):
    """Pinning-enforcing HTTPS helper for the bootstrap client.

    Dials the URL's host but performs SNI + hostname verification against
    cfg.server_name when set (the tls.go ServerName role: Nexus reached
    by IP while the cert names a host), runs verify_peer on the presented
    chain BEFORE any request bytes are sent, then performs the request.
    Returns (status, parsed-json-or-None, warnings).

    Chain note: Python < 3.13 exposes only the leaf certificate
    (no SSLSocket.get_unverified_chain), so pins must cover the LEAF
    there; on 3.13+ a pinned intermediate/CA anywhere in the presented
    chain also matches (the tls.go:208-229 rawCerts behavior)."""
    import http.client
    import json
    import socket as _socket

    sn = cfg.server_name or extract_server_name_from_url(url)
    u = urlparse(url)
    ctx = build_ssl_context(cfg)
    raw = _socket.create_connection((u.hostname, u.port or 443),
                                    timeout=timeout)
    tls = None
    try:
        tls = ctx.wrap_socket(raw, server_hostname=sn)
        res = verify_wrapped_socket(tls, cfg)
        path = u.path or "/"
        if u.query:
            path += "?" + u.query
        req = [f"{method} {path} HTTP/1.1", f"Host: {sn}",
               "Connection: close"]
        if body is not None:
            req.append(f"Content-Length: {len(body)}")
        for k, v in (headers or {}).items():
            req.append(f"{k}: {v}")
        tls.sendall(("\r\n".join(req) + "\r\n\r\n").encode()
                    + (body or b""))
        resp = http.client.HTTPResponse(tls, method=method)
        resp.begin()
        data = resp.read()
        try:
            parsed = json.loads(data) if data else None
        except ValueError:
            parsed = None
        return resp.status, parsed, res.warnings
    finally:
        if tls is not None:
            tls.close()
        else:
            raw.close()
