"""Slow-path protocol demux: one entry point for the ring's PASS lanes.

The reference runs one goroutine + socket per protocol server (cmd/bng
main.go:1063-1180: DHCPv4 on UDP:67, DHCPv6 on UDP6:547, SLAAC on raw
ICMPv6, PPPoE on AF_PACKET). In the TPU build every packet the device
PASSes lands on ONE slow queue (the ring), so the composition root needs
one callable that dispatches each Ethernet frame to the server that owns
it and returns the reply frame(s) for TX injection.

Framing: DHCPv4 and SLAAC servers speak Ethernet frames natively; the
DHCPv6 server speaks raw DHCPv6 messages (like the reference's, which
gets UDP payloads from its socket — server.go:420), so this module owns
the Eth/IPv6/UDP encap/decap around it.
"""

from __future__ import annotations

from bng_tpu.control import packets

ETH_P_IPV6 = 0x86DD
DHCP6_SERVER_PORT = 547
DHCP6_CLIENT_PORT = 546
ALL_DHCP_AGENTS = bytes.fromhex("ff020000000000000000000000010002")


class SlowPathDemux:
    """Dispatch PASSed frames to DHCPv4 / DHCPv6 / SLAAC / PPPoE.

    Every handler is optional (nil-safe, the reference's optional-manager
    discipline); unmatched frames return None (frame recycles). The
    callable signature matches Engine/ShardedCluster ``slow_path``.
    """

    def __init__(self, dhcp=None, dhcpv6=None, slaac=None, pppoe=None,
                 clock=None):
        import time

        self.dhcp = dhcp
        self.dhcpv6 = dhcpv6
        self.slaac = slaac
        self.pppoe = pppoe
        self.clock = clock or time.time
        self.stats = {"dhcp4": 0, "dhcp6": 0, "slaac": 0, "pppoe": 0,
                      "unmatched": 0}
        # PPPoE negotiation can emit several frames per input (e.g.
        # CHAP-Success + IPCP Conf-Req); the ring's slow contract is one
        # inline reply, the rest queue here for drain_pending()
        self._pending: list[bytes] = []

    def __call__(self, frame: bytes) -> bytes | None:
        if len(frame) < 14:
            self.stats["unmatched"] += 1
            return None
        ethertype = int.from_bytes(frame[12:14], "big")
        if ethertype in (0x8863, 0x8864) and self.pppoe is not None:
            self.stats["pppoe"] += 1
            replies = self.pppoe.handle_frame(frame, self.clock())
            # one reply rides back inline; extras queue for drain_pending()
            self._pending.extend(replies[1:])
            return replies[0] if replies else None
        if ethertype == ETH_P_IPV6:
            reply = self._try_dhcpv6(frame)
            if reply is not None:
                return reply
            if self.slaac is not None:
                reply = self.slaac.handle_frame(frame)
                if reply is not None:
                    self.stats["slaac"] += 1
                    return reply
            self.stats["unmatched"] += 1
            return None
        if self.dhcp is not None:
            reply = self.dhcp.handle_frame(frame)
            if reply is not None:
                self.stats["dhcp4"] += 1
                return reply
        self.stats["unmatched"] += 1
        return None

    def drain_pending(self) -> list[bytes]:
        """Frames beyond the one-reply-per-input ring contract (PPPoE
        multi-frame negotiation); the composition root TX-injects these
        every beat (drive_once) — the socket-write role of the
        reference's per-protocol goroutines."""
        out, self._pending = self._pending, []
        return out

    def requeue(self, frames: list[bytes], front: bool = False) -> None:
        """Public re-queue onto the pending queue (drain_pending's
        counterpart): CoA teardown frames enter here for the next beat's
        TX injection, and the composition root puts back the un-injected
        remainder when the TX ring fills (`front=True` preserves wire
        order). Callers never touch the private list."""
        if front:
            self._pending[:0] = frames
        else:
            self._pending.extend(frames)

    def _try_dhcpv6(self, frame: bytes) -> bytes | None:
        """Eth/IPv6/UDP:547 -> DHCPv6Server.handle_message -> framed reply."""
        if self.dhcpv6 is None or len(frame) < 14 + 40 + 8:
            return None
        # Eth(14) + IPv6: next-header lives at offset 14+6=20 (frame[18:20]
        # is the payload-length field). No ext headers on control traffic.
        if frame[20] != 17:
            return None
        udp = 14 + 40
        dport = int.from_bytes(frame[udp + 2 : udp + 4], "big")
        if dport != DHCP6_SERVER_PORT:
            return None
        udp_len = int.from_bytes(frame[udp + 4 : udp + 6], "big")
        payload = frame[udp + 8 : udp + udp_len]
        if not payload:
            return None
        reply = self.dhcpv6.handle_message(payload)
        if reply is None:
            return None
        self.stats["dhcp6"] += 1
        client_mac = frame[6:12]
        client_ip = frame[22:38]  # IPv6 source
        server_mac = getattr(self.dhcpv6.config, "server_mac",
                             b"\x02\xbb\x00\x00\x00\x01")
        # RFC 8415 §7.2: clients listen on 546, RELAY AGENTS on 547 — a
        # Relay-Reply framed to 546 would never reach the relay's socket
        from bng_tpu.control.dhcpv6.protocol import RELAY_REPL

        dport = (DHCP6_SERVER_PORT if reply and reply[0] == RELAY_REPL
                 else DHCP6_CLIENT_PORT)
        return packets.udp6_packet(server_mac, client_mac,
                                   self._server_ip6(server_mac), client_ip,
                                   DHCP6_SERVER_PORT, dport,
                                   reply)

    def _server_ip6(self, server_mac: bytes) -> bytes:
        """Reply source: configured server address if set, else the
        EUI-64 link-local derived from server_mac (reference replies
        from its real bound address — server.go:18)."""
        configured = getattr(self.dhcpv6.config, "server_ip6", b"")
        if configured:
            return configured
        from bng_tpu.control.slaac import link_local

        return link_local(server_mac)
