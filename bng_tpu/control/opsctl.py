"""Runtime operations control: the `bng ctl` wire + the autoscaler.

A running `bng run` process owns a dataplane loop that must never be
raced by an operator thread — every zero-downtime transition (fleet
resize, rolling worker restart, blue/green engine swap) has to execute
at a batch boundary under the app's control lock. This module is the
plumbing that gets an operator's request onto that boundary:

- `OpsController` — a bounded queue of requested transitions. HTTP
  handler threads (and anything else) `submit()` and block on a result;
  the run loop calls `run_pending()` once per beat, executing each op
  through the BNGApp's locked transition methods. The op runs where the
  dataplane can see it atomically; the requester gets the transition
  report back.

- `OpsServer` — a tiny loopback HTTP listener (`bng run --ctl-listen`):
  POST /ops/fleet/resize {"n": N}, POST /ops/fleet/rolling-restart,
  POST /ops/engine/swap, GET /ops/status. The `bng ctl` subcommand is
  its client. OPT-IN and unauthenticated: the surface moves
  subscriber-serving state, so `bng run` starts no listener unless
  --ctl-listen is given — even loopback exposure (any local process
  could resize or swap a production dataplane) is a deployment
  decision, not a default.

- `FleetAutoscaler` — the watermark hook for live elasticity: scale up
  when the admission controller sheds (the fleet is underwater NOW) or
  mean worker busy-fraction crosses the high watermark; scale down only
  after the busy-fraction sits under the low watermark for `hold`
  consecutive looks (hysteresis — a quiet second must not thrash the
  fleet). Driven from App.tick; acts through the same resize verb the
  operator uses, so autoscaling and `bng ctl` can never disagree on
  semantics.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from dataclasses import dataclass

from bng_tpu.analysis.sanitize import ctx_enter, owned_by
from bng_tpu.utils.structlog import get_logger

# ops the controller will route to a BNGApp (name -> app method)
OPS = {
    "fleet/resize": "fleet_resize",
    "fleet/rolling-restart": "fleet_rolling_restart",
    "engine/swap": "engine_swap",
}


@owned_by(None, guard="_stats_lock", attrs=("executed", "rejected"))
class OpsController:
    """Bounded transition queue, drained at the batch boundary.

    Counter ownership (BNG_SANITIZE): executed/rejected are bumped from
    both the ctl threads and the loop drain — always under _stats_lock;
    the @owned_by stamp raises if a future edit drops the lock."""

    def __init__(self, app, max_queue: int = 8):
        self.app = app
        self._q: queue.Queue = queue.Queue(maxsize=max_queue)
        self.executed = 0
        self.rejected = 0
        # counters are bumped from BOTH the ctl (HTTP handler) threads
        # and the loop's drain — a bare `+= 1` is a read-modify-write
        # that loses updates across contexts (BNG060)
        self._stats_lock = threading.Lock()
        self._log = get_logger("ops")

    def submit(self, op: str, args: dict | None = None,
               timeout_s: float = 60.0) -> dict:
        """Enqueue one op and block until the run loop executes it.
        Returns the transition report, or an error report when the op is
        unknown, the queue is full, or nothing drained the queue in time
        (no run loop driving — e.g. `bng run --once`)."""
        method = OPS.get(op)
        if method is None:
            with self._stats_lock:
                self.rejected += 1
            return {"op": op, "outcome": "rejected",
                    "error": f"unknown op {op!r} (have {sorted(OPS)})"}
        done = threading.Event()
        box: dict = {}
        try:
            self._q.put_nowait((method, args or {}, done, box))
        except queue.Full:
            with self._stats_lock:
                self.rejected += 1
            return {"op": op, "outcome": "rejected",
                    "error": "ops queue full: a transition is already "
                             "pending"}
        if not done.wait(timeout_s):
            # cancel, don't abandon: a queued-but-timed-out op must not
            # fire later (the operator will retry — executing both would
            # double a rolling restart, or land a stale resize target
            # after a newer one). The claim is ATOMIC (GIL-atomic
            # dict.setdefault), so exactly one side wins: a
            # check-then-act flag here would let the loop pass the check
            # just before the deadline and execute an op we reported as
            # 'timeout'. Losing the claim means the loop is executing it
            # NOW — wait out the run and return the real report instead
            # of a lie the operator would retry on.
            if box.setdefault("owner", "client") == "client":
                return {"op": op, "outcome": "timeout",
                        "error": f"no run loop drained the op within "
                                 f"{timeout_s:.0f}s — is `bng run` "
                                 f"driving?"}
            # the loop owns the claim: the transition is executing now
            # and completes in bounded time — a fixed grace, not the
            # client deadline that already expired
            if not done.wait(60.0):
                return {"op": op, "outcome": "unknown",
                        "error": "op claimed by the run loop but no "
                                 "report within grace — check "
                                 "bng_ops_transitions_total before "
                                 "retrying"}
        return box.get("report", {"op": op, "outcome": "failed"})

    def run_pending(self) -> int:
        """Execute every queued op (run-loop thread, between batches).
        An op that raises reports 'failed' to its requester and never
        takes the loop down."""
        n = 0
        while True:
            try:
                method, args, done, box = self._q.get_nowait()
            except queue.Empty:
                return n
            if box.setdefault("owner", "loop") != "loop":
                # the requester timed out and won the claim: cancelled
                with self._stats_lock:
                    self.rejected += 1
                done.set()
                continue
            try:
                box["report"] = getattr(self.app, method)(**args)
            except Exception as e:  # noqa: BLE001 — the report IS the error
                self._log.error("ops transition failed", op=method,
                                error=f"{type(e).__name__}: {e}")
                box["report"] = {"op": method, "outcome": "failed",
                                 "error": f"{type(e).__name__}: {e}"[:300]}
            finally:
                with self._stats_lock:
                    self.executed += 1
                done.set()
                n += 1

    def stats_snapshot(self) -> dict:
        return {"executed": self.executed, "rejected": self.rejected,
                "pending": self._q.qsize()}


class OpsServer:
    """Loopback HTTP listener for OpsController (`bng run --ctl-listen`)."""

    def __init__(self, controller: OpsController, host: str = "127.0.0.1",
                 port: int = 0):
        import http.server

        ctl = controller

        class Handler(http.server.BaseHTTPRequestHandler):
            def _reply(self, code: int, doc: dict) -> None:
                body = json.dumps(doc, indent=2, sort_keys=True).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                ctx_enter("ctl")
                if self.path != "/ops/status":
                    self._reply(404, {"error": "unknown path"})
                    return
                self._reply(200, ctl.app.ops_status())

            def do_POST(self):  # noqa: N802
                ctx_enter("ctl")
                if not self.path.startswith("/ops/"):
                    self._reply(404, {"error": "unknown path"})
                    return
                op = self.path[len("/ops/"):]
                n = int(self.headers.get("Content-Length") or 0)
                args: dict = {}
                if n:
                    try:
                        args = json.loads(self.rfile.read(n) or b"{}")
                    except ValueError:
                        self._reply(400, {"error": "bad JSON body"})
                        return
                report = ctl.submit(op, args)
                ok = report.get("outcome") in ("ok", "noop")
                self._reply(200 if ok else 409, report)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        self.addr = self._httpd.server_address

    def start(self) -> "OpsServer":
        threading.Thread(target=self._httpd.serve_forever,
                         daemon=True).start()
        return self

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


def ctl_request(addr: str, op: str, args: dict | None = None,
                timeout_s: float = 90.0) -> tuple[int, dict]:
    """`bng ctl` client: (http_status, report) from a live process's ops
    listener. GETs /ops/status for op='status', POSTs everything else."""
    import urllib.error
    import urllib.request

    url = f"http://{addr}/ops/{op}"
    if op == "status":
        req = urllib.request.Request(url)
    else:
        req = urllib.request.Request(
            url, data=json.dumps(args or {}).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except ValueError:
            return e.code, {"error": f"HTTP {e.code}"}


@dataclass
class AutoscaleConfig:
    min_workers: int = 1
    max_workers: int = 8
    busy_hi: float = 0.75  # mean busy-fraction that triggers scale-up
    busy_lo: float = 0.20  # ... under which scale-down hysteresis counts
    hold: int = 3  # consecutive calm looks before scaling down
    cooldown_s: float = 30.0  # min seconds between transitions


class FleetAutoscaler:
    """Watermark-driven target-size recommender over a live fleet."""

    def __init__(self, fleet, cfg: AutoscaleConfig | None = None,
                 clock=time.time):
        self.fleet = fleet
        self.cfg = cfg or AutoscaleConfig()
        self.clock = clock
        self._last_shed = fleet.admission.shed_total()
        self._last_busy = fleet.busy_seconds_total()
        self._last_look: float | None = None
        self._last_change = 0.0
        self._calm = 0
        self.decisions = 0

    def target(self, now: float | None = None) -> int | None:
        """The recommended worker count, or None for no change. Call on
        a steady cadence (App.tick); busy fraction is measured between
        consecutive calls."""
        now = now if now is not None else self.clock()
        cfg = self.cfg
        shed = self.fleet.admission.shed_total()
        busy = self.fleet.busy_seconds_total()
        if self._last_look is None:
            self._last_look, self._last_shed = now, shed
            self._last_busy = busy
            return None
        if busy < self._last_busy:
            # a resize/rolling restart reset the per-worker stats the
            # busy counter sums over — this look's delta is meaningless.
            # Re-baseline and decide nothing: a negative delta must not
            # credit a "calm" hysteresis look while the fleet may in
            # fact be saturated.
            self._last_look, self._last_shed = now, shed
            self._last_busy = busy
            return None
        dt = now - self._last_look
        shed_delta = shed - self._last_shed
        busy_frac = ((busy - self._last_busy)
                     / (dt * max(1, self.fleet.n))) if dt > 0 else 0.0
        self._last_look, self._last_shed = now, shed
        self._last_busy = busy
        if now - self._last_change < cfg.cooldown_s:
            return None
        n = self.fleet.n
        if (shed_delta > 0 or busy_frac >= cfg.busy_hi) \
                and n < cfg.max_workers:
            self._calm = 0
            self._last_change = now
            self.decisions += 1
            return min(cfg.max_workers, n + 1)
        if busy_frac <= cfg.busy_lo and shed_delta == 0:
            self._calm += 1
            if self._calm >= cfg.hold and n > cfg.min_workers:
                self._calm = 0
                self._last_change = now
                self.decisions += 1
                return max(cfg.min_workers, n - 1)
        else:
            self._calm = 0
        return None
