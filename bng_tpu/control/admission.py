"""Admission control for the slow-path worker fleet.

The reference never needs this: its slow path is concurrent Go behind a
kernel UDP socket whose receive buffer IS the admission policy (overflow
= silent tail drop, pkg/dhcp/server.go:302 reads as fast as it can). The
TPU re-host funnels every PASS lane through Python workers, so overload
has to be shaped deliberately — and DHCP gives us a protocol-aware way
to shed that a socket buffer cannot:

- **DISCOVER is free to shed.** Clients retransmit DISCOVERs by design
  (RFC 2131 §4.1 backoff); dropping one costs a retry, never state.
- **REQUEST must not be shed once we OFFERed.** The server has already
  promised an address; shedding the REQUEST strands the client mid-DORA
  until its offer times out, and a later retry can race the offer
  expiry into a NAK storm. The controller tracks OFFERed/ACKed client
  MACs (fed back from worker results) and always admits their
  REQUEST/RELEASE/DECLINE traffic.
- **Never half-allocate.** Shedding happens BEFORE a frame reaches a
  worker — an admitted frame always runs the full handler, so an
  address is either fully leased or untouched. (Worker-side exhaustion
  stays silent per the server's normal pool-exhausted path.)

Deadline shedding: a frame that waited longer than `deadline_ms` in the
scheduler/ring queues is answered too late to matter (the client already
retransmitted); stale DISCOVERs are dropped instead of burning worker
time on replies nobody is listening for. REQUESTs are exempt — late is
still better than stranded.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from bng_tpu.chaos import faults
from bng_tpu.chaos.faults import fault_point
from bng_tpu.control import dhcp_codec

# shed reasons (the bng_slowpath_shed_total label values)
SHED_INBOX_FULL = "inbox_full"
SHED_DEADLINE = "deadline"
SHED_REQUEST_OVERFLOW = "request_overflow"


def _bootp_off(frame: bytes) -> int | None:
    """Offset of the BOOTP payload in an Eth/IPv4/UDP frame (0-2 VLAN
    tags), or None when the frame isn't shaped like one. Mirrors the
    ring classifier's walk (runtime/ring.py classify_dhcp) but accepts
    either UDP port pair so it peeks replies too."""
    if len(frame) < 14:
        return None
    off = 12
    et = (frame[off] << 8) | frame[off + 1]
    for _ in range(2):
        if et not in (0x8100, 0x88A8):
            break
        off += 4
        if len(frame) < off + 2:
            return None
        et = (frame[off] << 8) | frame[off + 1]
    off += 2
    if et != 0x0800 or len(frame) < off + 20 or (frame[off] >> 4) != 4:
        return None
    ihl = (frame[off] & 0x0F) * 4
    if ihl < 20 or frame[off + 9] != 17:
        return None
    if ((frame[off + 6] << 8) | frame[off + 7]) & 0x3FFF:
        return None  # fragment: no parseable L4
    l4 = off + ihl
    bootp = l4 + 8
    if len(frame) < bootp + 240:
        return None
    return bootp


def peek_dhcp(frame: bytes) -> tuple[int, int] | None:
    """Cheap (msg_type, mac_u64) peek without a full codec decode — the
    admission decision must cost nanoseconds, not a parse. Returns None
    for anything that isn't a plausible DHCPv4 frame (those are admitted
    as-is; the worker's per-frame isolation owns malformed input)."""
    bootp = _bootp_off(frame)
    if bootp is None:
        return None
    if int.from_bytes(frame[bootp + 236 : bootp + 240], "big") != dhcp_codec.DHCP_MAGIC:
        return None
    mac = int.from_bytes(frame[bootp + 28 : bootp + 34], "big")
    # option scan for 53 (bounded: options are TLV until END)
    i = bootp + 240
    end = len(frame)
    for _ in range(64):
        if i >= end:
            break
        code = frame[i]
        if code == dhcp_codec.OPT_END:
            break
        if code == dhcp_codec.OPT_PAD:
            i += 1
            continue
        if i + 1 >= end:
            break
        ln = frame[i + 1]
        if code == dhcp_codec.OPT_MSG_TYPE and ln >= 1 and i + 2 < end:
            return frame[i + 2], mac
        i += 2 + ln
    return 0, mac


def peek_reply(frame: bytes) -> tuple[int, int] | None:
    """(msg_type, client mac_u64) of a server-built reply frame. Replies
    from DHCPServer always carry OPT_MSG_TYPE as the first option, so
    this is a fixed-offset read."""
    bootp = _bootp_off(frame)
    if bootp is None or frame[bootp] != 2:  # BOOTREPLY only
        return None
    o = bootp + 240
    if len(frame) < o + 3 or frame[o] != dhcp_codec.OPT_MSG_TYPE:
        return None
    return frame[o + 2], int.from_bytes(frame[bootp + 28 : bootp + 34], "big")


@dataclass
class AdmissionConfig:
    # per-worker inbox bound: DISCOVER/INFORM admitted while the worker's
    # backlog is below this
    inbox_capacity: int = 512
    # hard bound for lease-mutating messages from UNKNOWN clients (a
    # known client's REQUEST/RELEASE/DECLINE is never shed)
    request_hard_capacity: int = 2048
    # queue-age deadline: a DISCOVER older than this at admission time is
    # answered too late to matter (client already retransmitted)
    deadline_ms: float = 50.0
    # how long an un-ACKed OFFER protects its client's REQUESTs
    offer_ttl_s: float = 60.0
    offer_cap: int = 1 << 16  # bounded OFFER tracking (FIFO eviction)
    # bounded leased-MAC tracking: sized for the subscriber scale
    # target; release/expiry feedback trims it in normal operation,
    # the cap is the backstop against MAC-randomizing churn
    lease_cap: int = 1 << 20


@dataclass
class AdmissionStats:
    admitted: int = 0
    unparsed: int = 0  # admitted without a DHCP peek (worker isolates)
    shed: dict = field(default_factory=lambda: {
        SHED_INBOX_FULL: 0, SHED_DEADLINE: 0, SHED_REQUEST_OVERFLOW: 0})


class AdmissionController:
    """Per-worker bounded-inbox + deadline shedding, DHCP-correct.

    The fleet calls `admit()` for every frame BEFORE it reaches a worker
    inbox and feeds OFFER/ACK observations back from worker results
    (`note_offer`/`note_ack`) so the never-shed-after-OFFER invariant
    holds across batches and across workers.
    """

    # lease-mutating message types: shedding one can strand client state
    _PROTECTED = (dhcp_codec.REQUEST, dhcp_codec.RELEASE, dhcp_codec.DECLINE)

    def __init__(self, cfg: AdmissionConfig | None = None,
                 clock: Callable[[], float] | None = None):
        import time

        self.cfg = cfg or AdmissionConfig()
        self.clock = clock or time.time
        self.stats = AdmissionStats()
        # mac_u64 -> offer timestamp (insertion-ordered: FIFO eviction)
        self._offered: dict[int, float] = {}
        # mac_u64 -> True, insertion-ordered for FIFO eviction at cap
        self._leased: dict[int, bool] = {}
        # lazily rebuilt sorted-array mirrors of the known-client sets —
        # the vectorized membership lookup (ISSUE 14). Rebuilt only when
        # a BATCH query finds them stale: the unpressured fast-admit
        # path never queries membership, so steady-state note_* churn
        # costs one dirty flag, not a re-sort.
        self._known_dirty = True
        self._leased_arr = self._offered_arr = self._offered_ts = None

    # -- observations from worker results --------------------------------

    def note_offer(self, mac_u64: int, now: float | None = None) -> None:
        now = now if now is not None else self.clock()
        self._offered.pop(mac_u64, None)  # re-offer refreshes FIFO order
        self._offered[mac_u64] = now
        while len(self._offered) > self.cfg.offer_cap:
            self._offered.pop(next(iter(self._offered)))
        self._known_dirty = True

    def note_ack(self, mac_u64: int) -> None:
        self._offered.pop(mac_u64, None)
        self._leased.pop(mac_u64, None)  # refresh FIFO order
        self._leased[mac_u64] = True
        while len(self._leased) > self.cfg.lease_cap:
            self._leased.pop(next(iter(self._leased)))
        self._known_dirty = True

    def note_release(self, mac_u64: int) -> None:
        self._offered.pop(mac_u64, None)
        self._leased.pop(mac_u64, None)
        self._known_dirty = True

    def is_known(self, mac_u64: int, now: float | None = None) -> bool:
        """Client with a live OFFER or lease — its lease-mutating
        traffic is never shed."""
        if mac_u64 in self._leased:
            return True
        ts = self._offered.get(mac_u64)
        if ts is None:
            return False
        now = now if now is not None else self.clock()
        if now - ts > self.cfg.offer_ttl_s:
            del self._offered[mac_u64]
            self._known_dirty = True
            return False
        return True

    # -- the decision -----------------------------------------------------

    def admit(self, frame: bytes, inbox_depth: int, now: float,
              enq_t: float | None = None) -> tuple[bool, str | None]:
        """(admitted, shed_reason). `inbox_depth` is the target worker's
        current backlog; `enq_t` (when the caller tracked it — the
        scheduler's lanes do) enables deadline shedding."""
        fp = fault_point("admission.admit")
        if fp is not None and fp.kind == "force_shed":
            # chaos: shed a frame the policy would admit. Service-only
            # degradation by construction — a shed frame never reached a
            # worker, so no allocation can be half-done.
            return self._shed("chaos")
        # fast path: no inbox pressure, no deadline breach — admit
        # without peeking. The peek exists to decide WHAT to shed; when
        # nothing sheds it is pure per-frame overhead on the parent,
        # which is the fleet's serial section.
        if inbox_depth < self.cfg.inbox_capacity and (
                enq_t is None
                or (now - enq_t) * 1000.0 <= self.cfg.deadline_ms):
            self.stats.admitted += 1
            return True, None
        peek = peek_dhcp(frame)
        if peek is None:
            # non-DHCP / unparsable: admit — the worker's per-frame
            # isolation owns poison input, and v6/SLAAC/PPPoE frames ride
            # the same PASS lanes
            self.stats.unparsed += 1
            self.stats.admitted += 1
            return True, None
        msg_type, mac = peek
        if msg_type in self._PROTECTED:
            if self.is_known(mac, now):
                self.stats.admitted += 1
                return True, None  # never shed after OFFER/lease
            if inbox_depth >= self.cfg.request_hard_capacity:
                return self._shed(SHED_REQUEST_OVERFLOW)
            self.stats.admitted += 1
            return True, None
        # DISCOVER / INFORM / unknown: the shed-first class
        if inbox_depth >= self.cfg.inbox_capacity:
            return self._shed(SHED_INBOX_FULL)
        if (enq_t is not None
                and (now - enq_t) * 1000.0 > self.cfg.deadline_ms):
            return self._shed(SHED_DEADLINE)
        self.stats.admitted += 1
        return True, None

    def _shed(self, reason: str) -> tuple[bool, str]:
        self.stats.shed[reason] = self.stats.shed.get(reason, 0) + 1
        return False, reason

    # -- the batched decision (ISSUE 14) ----------------------------------
    #
    # Design thesis (PERF_NOTES §16): the vector path decides exactly the
    # cases with NO sequential cross-frame coupling — unpressured inbox
    # depth (proven by a worst-case per-worker bound) with at most
    # deadline shedding, which is depth-independent. Anything else (a
    # worker whose inbox could cross a capacity mid-batch, or an armed
    # chaos plan whose per-call hit accounting a batched path would
    # shift) runs the per-frame scalar oracle on the SAME inputs, so the
    # two paths can never disagree on a single verdict or counter.

    def _known_arrays(self):
        """Sorted-array mirrors of (_leased, _offered) for vectorized
        membership. Rebuilt lazily on a stale batch query."""
        if self._known_dirty:
            self._leased_arr = np.sort(np.fromiter(
                self._leased.keys(), dtype=np.uint64,
                count=len(self._leased)))
            ok = np.fromiter(self._offered.keys(), dtype=np.uint64,
                             count=len(self._offered))
            ts = np.fromiter(self._offered.values(), dtype=np.float64,
                             count=len(self._offered))
            order = np.argsort(ok)
            self._offered_arr, self._offered_ts = ok[order], ts[order]
            self._known_dirty = False
        return self._leased_arr, self._offered_arr, self._offered_ts

    @staticmethod
    def _member(sorted_arr: np.ndarray, keys: np.ndarray) -> np.ndarray:
        if len(sorted_arr) == 0:
            return np.zeros(len(keys), dtype=bool)
        pos = np.minimum(np.searchsorted(sorted_arr, keys),
                         len(sorted_arr) - 1)
        return sorted_arr[pos] == keys

    def is_known_batch(self, macs: np.ndarray, now: float) -> np.ndarray:
        """Vectorized is_known over mac_u64 keys — sorted-array
        membership plus the scalar's exact TTL semantics: every QUERIED
        expired offer is evicted (and only those), so the controller
        state after a batch matches the per-frame walk."""
        leased, offered, ots = self._known_arrays()
        known = self._member(leased, macs)
        # leased macs short-circuit in the scalar walk (is_known returns
        # before the _offered lookup), so the TTL check — and crucially
        # its EVICTION — must never run for them: a leased client's
        # stale re-offer entry stays put, exactly like scalar, or the
        # two paths' offer_cap FIFO evictions silently diverge
        off_m = self._member(offered, macs) & ~known
        if off_m.any():
            pos = np.searchsorted(offered, macs[off_m])
            fresh = (now - ots[pos]) <= self.cfg.offer_ttl_s
            live = off_m.copy()
            live[off_m] = fresh
            known |= live
            if not fresh.all():
                for mac in np.unique(macs[off_m][~fresh]).tolist():
                    self._offered.pop(int(mac), None)
                self._known_dirty = True
        return known

    def admit_batch(self, frames: list, workers: np.ndarray,
                    buf: np.ndarray | None, lens: np.ndarray,
                    now: float, enq_t: np.ndarray | None = None,
                    depth0=None) -> np.ndarray:
        """Batched admit over a frame batch: [n] admitted mask,
        bit-identical (verdicts AND counters) to calling admit() per
        frame in order with the fleet's running-depth bookkeeping.
        `workers` are the frames' target shards, `depth0` the current
        per-worker backlogs (mapping or None). `buf` (packed rows,
        runtime/hostpath.pack_into) may be None — the peek that needs
        it only runs for deadline-breached lanes, and those rows are
        packed lazily: the unpressured fast path never pays a byte of
        staging."""
        n = len(frames)
        out = np.ones(n, dtype=bool)
        if n == 0:
            return out
        d0 = np.zeros(int(workers.max()) + 1, dtype=np.int64)
        if depth0:
            for w, d in depth0.items():
                if w <= int(workers.max()):
                    d0[w] = d
        counts = np.bincount(workers, minlength=len(d0))
        cap = min(self.cfg.inbox_capacity, self.cfg.request_hard_capacity)
        if faults.any_armed() or ((d0 + counts) > cap).any():
            return self._admit_scalar_fallback(frames, workers, now, enq_t)
        breached = (np.zeros(n, dtype=bool) if enq_t is None else
                    (now - enq_t) * 1000.0 > self.cfg.deadline_ms)
        nb = int(breached.sum())
        if nb == 0:
            # the unpressured fast-admit path: no peek, no membership,
            # no per-frame Python — exactly the scalar fast path taken
            # n times
            self.stats.admitted += n
            return out
        # deadline-pressured, depth-unpressured: the peek decides WHAT
        # to shed, vectorized over only the breached lanes
        from bng_tpu.runtime import hostpath

        bl = np.nonzero(breached)[0]
        if buf is None:
            bbuf, _bl2 = hostpath.pack_rows([frames[i] for i in bl.tolist()])
            blens = lens[bl]
        else:
            bbuf, blens = buf[bl], lens[bl]
        msg, mac, parsed = hostpath.peek_dhcp_batch(bbuf, blens)
        protected = parsed & np.isin(msg, self._PROTECTED)
        # scalar parity: is_known is queried (and its TTL eviction
        # fires) only for protected-type frames
        known = np.zeros(len(bl), dtype=bool)
        if protected.any():
            known[protected] = self.is_known_batch(mac[protected], now)
        # protected (known or not — depth is proven under the hard cap)
        # and unparsed frames admit; the rest shed on the deadline
        shed = parsed & ~protected
        out[bl[shed]] = False
        n_shed = int(shed.sum())
        self.stats.unparsed += int((~parsed).sum())
        self.stats.admitted += n - n_shed
        if n_shed:
            self.stats.shed[SHED_DEADLINE] = (
                self.stats.shed.get(SHED_DEADLINE, 0) + n_shed)
        return out

    def _admit_scalar_fallback(self, frames, workers, now,
                               enq_t) -> np.ndarray:
        """The pressured path: per-frame admit() with the fleet's exact
        running-depth bookkeeping (sequential coupling: every admitted
        frame changes its worker's depth for every later frame)."""
        n = len(frames)
        out = np.zeros(n, dtype=bool)
        depth: dict[int, int] = {}
        wl = workers.tolist()
        el = enq_t.tolist() if enq_t is not None else [None] * n
        for i, frame in enumerate(frames):
            w = wl[i]
            ok, _reason = self.admit(frame, depth.get(w, 0), now, el[i])
            if ok:
                out[i] = True
                depth[w] = depth.get(w, 0) + 1
        return out

    def shed_total(self) -> int:
        """Cumulative shed count across every reason — the watermark the
        fleet autoscaler scales up on (shedding means the worker set is
        underwater NOW; queue depth alone lags a burst)."""
        return sum(self.stats.shed.values())

    def stats_snapshot(self) -> dict:
        return {
            "admitted": self.stats.admitted,
            "unparsed": self.stats.unparsed,
            "shed": dict(self.stats.shed),
            "offers_tracked": len(self._offered),
            "leases_tracked": len(self._leased),
        }
