"""Host-side CGNAT manager — the pkg/nat role plus the kernel's new-flow path.

In the reference, new-flow port allocation happens *in* the eBPF datapath
with benign races (bpf/nat44.c:408-528) while pkg/nat/manager.go carves
port blocks and populates maps. In the TPU build the device punts new
flows (verdict PASS), and this manager — the single writer — performs:

- RFC 6431 port-block allocation per subscriber
  (parity: AllocateNAT, pkg/nat/manager.go:398-495)
- RFC 4787 Endpoint-Independent Mapping (parity: get_eim_mapping,
  bpf/nat44.c:469-528), including port parity preservation for RTP
  (NAT_FLAG_PORT_PARITY, bpf/nat44.c:419,438)
- session + reverse row insertion into the device tables
- idle-session expiry with per-protocol/state timeouts
  (parity: timeouts, bpf/nat44.c:49-53)
- compliance event log records (parity: nat_log_rb ring buffer events,
  bpf/nat44.c:531-562 / pkg/nat/logging.go)
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np
import jax.numpy as jnp

from bng_tpu.chaos.faults import fault_point
from bng_tpu.ops.nat44 import (
    BV_FLAGS,
    BV_IN_USE,
    BV_NEXT_PORT,
    BV_PORT_END,
    BV_PORT_START,
    BV_PUBLIC_IP,
    BV_SUB_ID,
    FLAG_EIM,
    FLAG_PORT_PARITY,
    NATGeom,
    NATTables,
    REVERSE_WORDS,
    SESSION_WORDS,
    SUBNAT_WORDS,
    SV_BYTES_IN,
    SV_BYTES_OUT,
    SV_CREATED,
    SV_DEST_IP,
    SV_DEST_PORT,
    SV_LAST_SEEN,
    SV_NAT_IP,
    SV_NAT_PORT,
    SV_ORIG_IP,
    SV_ORIG_PORT,
    SV_PKTS_IN,
    SV_PKTS_OUT,
    SV_PROTO,
    SV_STATE,
    NAT_STATE_NEW,
    NAT_STATE_CLOSING,
)
from bng_tpu.ops.parse import PROTO_ICMP, PROTO_TCP, PROTO_UDP
from bng_tpu.ops.table import HostTable, TableGeom, TableUpdate, apply_update
from bng_tpu.utils.structlog import ErrorLog

# timeouts in seconds (parity: bpf/nat44.c:49-53)
UDP_TIMEOUT_S = 120
TCP_TRANSIENT_TIMEOUT_S = 240
TCP_EST_TIMEOUT_S = 7200
ICMP_TIMEOUT_S = 60

# log events (parity: enum nat_log_event, bpf/nat44.c:74-82)
(LOG_SESSION_CREATE, LOG_SESSION_DELETE, LOG_PORT_BLOCK_ASSIGN,
 LOG_PORT_BLOCK_RELEASE, LOG_PORT_EXHAUSTION, LOG_HAIRPIN, LOG_ALG_TRIGGER) = range(1, 8)


@dataclasses.dataclass
class NATLogEntry:
    """Parity: struct nat_log_entry (bpf/nat44.c:193-205)."""

    timestamp: int
    event_type: int
    subscriber_id: int
    private_ip: int
    public_ip: int
    private_port: int
    public_port: int
    dest_ip: int
    dest_port: int
    protocol: int
    flags: int = 0


def apply_nat_updates(tables: NATTables, upd: tuple) -> NATTables:
    sessions, reverse, sub_nat, hairpin, alg, config = upd
    return NATTables(
        sessions=apply_update(tables.sessions, sessions),
        reverse=apply_update(tables.reverse, reverse),
        sub_nat=apply_update(tables.sub_nat, sub_nat),
        hairpin_ips=hairpin,
        alg_ports=alg,
        config=config,
    )


class NATExhaustedError(Exception):
    """Carrier for the rate-limited exhaustion log lines (the allocator
    itself returns None/0 — degraded service, not an exception path)."""


class NATManager:
    def __init__(
        self,
        public_ips: list[int],
        ports_per_subscriber: int = 1024,
        port_range: tuple[int, int] = (1024, 65535),
        flags: int = FLAG_EIM,
        sessions_nbuckets: int = 1 << 14,
        sub_nat_nbuckets: int = 1 << 10,
        stash: int = 64,
        update_slots: int = 512,
        log_sink: Callable[[NATLogEntry], None] | None = None,
    ):
        self.sessions = HostTable(sessions_nbuckets, key_words=4, val_words=SESSION_WORDS, stash=stash, name="nat_sessions")
        self.reverse = HostTable(sessions_nbuckets, key_words=4,
                                 val_words=REVERSE_WORDS, stash=stash,
                                 name="nat_reverse",
                                 # pre-ISSUE-11 checkpoints carried bare
                                 # 4-word key rows; live 8 is a pure pad
                                 compat_val_pad_from=(4,))
        self.sub_nat = HostTable(sub_nat_nbuckets, key_words=1, val_words=SUBNAT_WORDS, stash=stash, name="subscriber_nat")
        self.hairpin = np.zeros((256,), dtype=np.uint32)
        self.alg = np.zeros((64,), dtype=np.uint32)
        self.flags = flags
        self.port_range = port_range
        self.ports_per_subscriber = ports_per_subscriber
        self.public_ips = list(public_ips)
        self.update_slots = update_slots
        self.log_sink = log_sink
        self.geom = NATGeom(
            sessions=TableGeom(sessions_nbuckets, stash),
            reverse=TableGeom(sessions_nbuckets, stash),
            sub_nat=TableGeom(sub_nat_nbuckets, stash),
        )
        # block carving state: per public IP, next block start + free list of
        # released block starts (blocks are uniform size, so reuse is exact)
        self._next_block: dict[int, int] = {ip: port_range[0] for ip in self.public_ips}
        self._free_blocks: dict[int, list[int]] = {ip: [] for ip in self.public_ips}
        self._ip_round_robin = 0
        # EIM host authority: (int_ip, int_port, proto) -> [ext_ip, ext_port, refcount]
        self.eim: dict[tuple[int, int, int], list[int]] = {}
        # allocated external ports: (pub_ip, ext_port, proto) -> eim key
        self._ext_ports: dict[tuple[int, int, int], tuple] = {}
        # per-subscriber block bookkeeping: priv_ip -> dict
        self.blocks: dict[int, dict] = {}
        self._sub_id_seq = 1
        # degraded-verdict counters (Yuan-class hygiene): a refused
        # block carve or port allocation drops the flow by design, but
        # the decision is counted + rate-limit logged, never silent
        self.exhausted = {"block": 0, "port": 0}
        self._exhaust_log = ErrorLog(
            "cgnat", "CGNAT allocator exhausted — flow/subscriber refused")

    # -- logging --
    def _log(self, event: int, sub_id: int, priv_ip: int, pub_ip: int,
             priv_port: int, pub_port: int, dest_ip: int, dest_port: int,
             proto: int, now: int, flags: int = 0) -> None:
        if self.log_sink:
            self.log_sink(NATLogEntry(now, event, sub_id, priv_ip, pub_ip,
                                      priv_port, pub_port, dest_ip, dest_port, proto, flags))

    # -- port block allocation (parity: pkg/nat/manager.go:398-495) --
    def allocate_nat(self, private_ip: int, now: int = 0) -> dict | None:
        """Carve a port block for a subscriber and install subscriber_nat."""
        if private_ip in self.blocks:
            return self.blocks[private_ip]
        n = self.ports_per_subscriber
        for _ in range(len(self.public_ips)):
            pub_ip = self.public_ips[self._ip_round_robin % len(self.public_ips)]
            if self._free_blocks[pub_ip]:
                start = self._free_blocks[pub_ip].pop()
            else:
                start = self._next_block[pub_ip]
                if start + n - 1 > self.port_range[1]:
                    self._ip_round_robin += 1
                    continue
                self._next_block[pub_ip] = start + n
            sub_id = self._sub_id_seq
            self._sub_id_seq += 1
            block = {
                "public_ip": pub_ip,
                "port_start": start,
                "port_end": start + n - 1,
                "next_port": start,
                "subscriber_id": sub_id,
                "private_ip": private_ip,
            }
            self.blocks[private_ip] = block
            row = np.zeros((SUBNAT_WORDS,), dtype=np.uint32)
            row[BV_PUBLIC_IP] = pub_ip
            row[BV_PORT_START] = start
            row[BV_PORT_END] = start + n - 1
            row[BV_NEXT_PORT] = start
            row[BV_SUB_ID] = sub_id
            self.sub_nat.insert([private_ip], row)
            self._log(LOG_PORT_BLOCK_ASSIGN, sub_id, private_ip, pub_ip,
                      0, start, 0, start + n - 1, 0, now)
            return block
        # every public IP's port space is fully carved: the subscriber
        # gets no NAT (degraded verdict) — counted, never silent
        self.exhausted["block"] += 1
        self._exhaust_log.report(
            NATExhaustedError(f"no free port block for {private_ip:#x} "
                              f"across {len(self.public_ips)} public IPs"),
            resource="block")
        return None  # pool exhausted

    def restore_block(self, private_ip: int, public_ip: int,
                      port_start: int, port_end: int, now: int = 0) -> bool:
        """Re-install a subscriber's EXACT port block — the HA-failover
        restore path (failover.go:400-500 consumes the replicated
        SessionState's nat fields): the promoted node must answer for
        the same public mappings the failed active advertised, or every
        established flow's return traffic blackholes. Returns False if
        the block is unknown geometry or already claimed."""
        if private_ip in self.blocks:
            return True  # idempotent
        if public_ip not in self._next_block:
            return False  # not one of OUR public IPs
        if port_end - port_start + 1 != self.ports_per_subscriber:
            return False
        # carve the range out of the allocator's bookkeeping so later
        # fresh allocations can never hand the same ports out again
        if port_start in self._free_blocks[public_ip]:
            self._free_blocks[public_ip].remove(port_start)
        elif port_start >= self._next_block[public_ip]:
            # advance the cursor past the restored block, returning any
            # skipped-over blocks to the free list
            cur = self._next_block[public_ip]
            while cur < port_start:
                self._free_blocks[public_ip].append(cur)
                cur += self.ports_per_subscriber
            self._next_block[public_ip] = port_start + self.ports_per_subscriber
        else:
            return False  # inside an already-allocated region
        sub_id = self._sub_id_seq
        self._sub_id_seq += 1
        block = {
            "public_ip": public_ip,
            "port_start": port_start,
            "port_end": port_end,
            "next_port": port_start,
            "subscriber_id": sub_id,
            "private_ip": private_ip,
        }
        self.blocks[private_ip] = block
        row = np.zeros((SUBNAT_WORDS,), dtype=np.uint32)
        row[BV_PUBLIC_IP] = public_ip
        row[BV_PORT_START] = port_start
        row[BV_PORT_END] = port_end
        row[BV_NEXT_PORT] = port_start
        row[BV_SUB_ID] = sub_id
        self.sub_nat.insert([private_ip], row)
        self._log(LOG_PORT_BLOCK_ASSIGN, sub_id, private_ip, public_ip,
                  0, port_start, 0, port_end, 0, now)
        return True

    def bulk_allocate_nat(self, private_ips, now: int = 0) -> int:
        """Carve port blocks for many subscribers at once (1M-scale build).

        Same carving policy as allocate_nat (round-robin public IPs,
        sequential blocks, free-list reuse) but assembles all subscriber_nat
        rows and installs them with one vectorized bulk_insert instead of a
        per-key Python cuckoo walk. Skips per-block compliance logging —
        this is the bench/restore path, not live allocation. Returns the
        number of blocks created.
        """
        fresh = [int(ip) for ip in private_ips if int(ip) not in self.blocks]
        if not fresh:
            return 0
        n = self.ports_per_subscriber
        keys = np.zeros((len(fresh), 1), dtype=np.uint32)
        rows = np.zeros((len(fresh), SUBNAT_WORDS), dtype=np.uint32)
        made = 0
        for i, priv in enumerate(fresh):
            block = None
            for _ in range(len(self.public_ips)):
                pub_ip = self.public_ips[self._ip_round_robin % len(self.public_ips)]
                if self._free_blocks[pub_ip]:
                    start = self._free_blocks[pub_ip].pop()
                else:
                    start = self._next_block[pub_ip]
                    if start + n - 1 > self.port_range[1]:
                        self._ip_round_robin += 1
                        continue
                    self._next_block[pub_ip] = start + n
                block = {
                    "public_ip": pub_ip, "port_start": start,
                    "port_end": start + n - 1, "next_port": start,
                    "subscriber_id": self._sub_id_seq, "private_ip": priv,
                }
                self._sub_id_seq += 1
                break
            if block is None:
                break  # pool exhausted; remaining rows stay zero and are trimmed
            self.blocks[priv] = block
            keys[made, 0] = priv
            rows[made, BV_PUBLIC_IP] = block["public_ip"]
            rows[made, BV_PORT_START] = block["port_start"]
            rows[made, BV_PORT_END] = block["port_end"]
            rows[made, BV_NEXT_PORT] = block["next_port"]
            rows[made, BV_IN_USE] = 0
            rows[made, BV_SUB_ID] = block["subscriber_id"]
            made += 1
        if made:
            self.sub_nat.bulk_insert(keys[:made], rows[:made])
        return made

    def bulk_flows(self, src_ips, dst_ips, src_ports, dst_ports, protos,
                   pkt_len: int, now: int):
        """Vectorized session+reverse build for bench-scale flow setup.

        Requires blocks already allocated for every src_ip (allocate_nat /
        bulk_allocate_nat) and 5-tuples unique within the batch and fresh.
        Under FLAG_EIM (RFC 4787 endpoint-independent mapping), flows
        sharing an internal endpoint (src_ip, src_port, proto) share ONE
        external mapping — existing EIM mappings are reused and refcounted,
        new endpoints get sequential ports from the subscriber's block.
        Without FLAG_EIM, each flow gets its own port (plain NAPT).
        Parity probing (RFC 4787 port parity) is the live slow path's job
        (handle_new_flow).

        Returns (nat_ips, nat_ports, ok) arrays; ok=False lanes had no
        block or an exhausted block.
        """
        src_ips = np.atleast_1d(np.asarray(src_ips, dtype=np.uint32))
        nf = len(src_ips)
        dst_ips = np.broadcast_to(np.asarray(dst_ips, dtype=np.uint32), (nf,))
        src_ports = np.broadcast_to(np.asarray(src_ports, dtype=np.uint32), (nf,))
        dst_ports = np.broadcast_to(np.asarray(dst_ports, dtype=np.uint32), (nf,))
        protos = np.broadcast_to(np.asarray(protos, dtype=np.uint32), (nf,))
        dstp = np.where(protos == PROTO_ICMP, 0, dst_ports).astype(np.uint32)

        def _assign_sequential(ips_arr):
            """Per-subscriber sequential port assignment for `ips_arr` units.

            Returns (nat_ip, nat_port, ok) per unit and advances next_port.
            """
            nu = len(ips_arr)
            uq, inv = np.unique(ips_arr, return_inverse=True)
            blks = [self.blocks.get(int(ip)) for ip in uq]
            has = np.array([b is not None for b in blks], dtype=bool)
            pub = np.array([b["public_ip"] if b else 0 for b in blks], dtype=np.uint32)
            pend = np.array([b["port_end"] if b else 0 for b in blks], dtype=np.int64)
            pnext = np.array([b["next_port"] if b else 0 for b in blks], dtype=np.int64)
            counts = np.bincount(inv, minlength=len(uq))
            order = np.argsort(inv, kind="stable")
            group_starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
            ranks = np.empty((nu,), dtype=np.int64)
            ranks[order] = np.arange(nu) - np.repeat(group_starts, counts)
            port = pnext[inv] + ranks
            u_ok = has[inv] & (port <= pend[inv])
            u_ip = np.where(u_ok, pub[inv], 0).astype(np.uint32)
            u_port = np.where(u_ok, port, 0).astype(np.uint32)
            for i, b in enumerate(blks):  # advance counters per subscriber
                if b is not None and counts[i]:
                    b["next_port"] = int(min(pnext[i] + counts[i], pend[i] + 1))
            return u_ip, u_port, u_ok

        if self.flags & FLAG_EIM:
            # one external mapping per unique internal endpoint
            ep = np.stack([src_ips, src_ports, protos], axis=1)
            uq_ep, ep_inv = np.unique(ep, axis=0, return_inverse=True)
            n_ep = len(uq_ep)
            ep_ip = np.zeros((n_ep,), dtype=np.uint32)
            ep_port = np.zeros((n_ep,), dtype=np.uint32)
            reused = np.zeros((n_ep,), dtype=bool)
            for j in range(n_ep):
                m = self.eim.get((int(uq_ep[j, 0]), int(uq_ep[j, 1]), int(uq_ep[j, 2])))
                if m is not None:
                    reused[j] = True
                    ep_ip[j], ep_port[j] = m[0], m[1]
            ep_ok = reused.copy()
            new_j = np.nonzero(~reused)[0]
            if len(new_j):
                n_ip, n_port, n_ok = _assign_sequential(uq_ep[new_j, 0])
                ep_ip[new_j], ep_port[new_j], ep_ok[new_j] = n_ip, n_port, n_ok
            nat_ip = ep_ip[ep_inv]
            nat_port = ep_port[ep_inv]
            ok = ep_ok[ep_inv]
            # refcount bookkeeping per endpoint
            ep_counts = np.bincount(ep_inv, minlength=n_ep)
            for j in range(n_ep):
                if not ep_ok[j]:
                    continue
                k = (int(uq_ep[j, 0]), int(uq_ep[j, 1]), int(uq_ep[j, 2]))
                if reused[j]:
                    self.eim[k][2] += int(ep_counts[j])
                else:
                    self.eim[k] = [int(ep_ip[j]), int(ep_port[j]), int(ep_counts[j])]
                    self._ext_ports[(int(ep_ip[j]), int(ep_port[j]), k[2])] = k
        else:
            nat_ip, nat_port, ok = _assign_sequential(src_ips)

        sel = np.nonzero(ok)[0]
        if len(sel):
            skey = np.stack(
                [src_ips, dst_ips,
                 ((src_ports & 0xFFFF) << np.uint32(16)) | (dstp & 0xFFFF),
                 protos], axis=1).astype(np.uint32)
            rows = np.zeros((nf, SESSION_WORDS), dtype=np.uint32)
            rows[:, SV_NAT_IP] = nat_ip
            rows[:, SV_NAT_PORT] = nat_port
            rows[:, SV_ORIG_IP] = src_ips
            rows[:, SV_ORIG_PORT] = src_ports
            rows[:, SV_DEST_IP] = dst_ips
            rows[:, SV_DEST_PORT] = dstp
            rows[:, SV_CREATED] = now
            rows[:, SV_LAST_SEEN] = now
            rows[:, SV_STATE] = NAT_STATE_NEW
            rows[:, SV_PROTO] = protos
            rows[:, SV_PKTS_OUT] = 1
            rows[:, SV_BYTES_OUT] = pkt_len
            self.sessions.bulk_insert(skey[sel], rows[sel])
            r_src = np.where(protos == PROTO_ICMP, 0, dstp).astype(np.uint32)
            rkey = np.stack(
                [dst_ips, nat_ip,
                 ((r_src & 0xFFFF) << np.uint32(16)) | (nat_port & 0xFFFF),
                 protos], axis=1).astype(np.uint32)
            rrows = np.zeros((len(skey), REVERSE_WORDS), dtype=np.uint32)
            rrows[:, :4] = skey
            self.reverse.bulk_insert(rkey[sel], rrows[sel])
        return nat_ip, nat_port, ok

    def release_nat(self, private_ip: int, now: int = 0) -> bool:
        block = self.blocks.pop(private_ip, None)
        if block is None:
            return False
        self.sub_nat.delete([private_ip])
        # drop this subscriber's EIM mappings + sessions
        for key in [k for k in self.eim if k[0] == private_ip]:
            ext_ip, ext_port, _ = self.eim.pop(key)
            self._ext_ports.pop((ext_ip, ext_port, key[2]), None)
        # purge live session + reverse rows before the block can be
        # recycled: a stale reverse row on a reused port would DNAT the
        # new subscriber's inbound traffic to the old private IP
        for s in np.nonzero(self.sessions.used)[0]:
            key = self.sessions.keys[s]
            if int(key[0]) != private_ip:
                continue
            v = self.sessions.vals[s]
            dst_ip, ports, proto_k = int(key[1]), int(key[2]), int(key[3])
            r_src_port = 0 if proto_k == PROTO_ICMP else ports & 0xFFFF
            nat_ip, nat_port = int(v[SV_NAT_IP]), int(v[SV_NAT_PORT])
            self.sessions.delete(key.copy())
            self.reverse.delete(self._key(dst_ip, nat_ip, r_src_port, nat_port, proto_k))
        # return the port block for reuse (RFC 6431 block recycling)
        self._free_blocks.setdefault(block["public_ip"], []).append(
            block["port_start"])
        self._log(LOG_PORT_BLOCK_RELEASE, block["subscriber_id"], private_ip,
                  block["public_ip"], 0, block["port_start"], 0, block["port_end"], 0, now)
        return True

    # -- EIM + port allocation (parity: bpf/nat44.c:408-528, host-exact) --
    def _allocate_port(self, block: dict, orig_port: int, proto: int) -> int:
        parity = self.flags & FLAG_PORT_PARITY
        start, end = block["port_start"], block["port_end"]
        span = end - start + 1
        port = block["next_port"]
        for _ in range(span):
            if port > end:
                port = start
            cand = port
            port += 1
            if parity and ((cand & 1) != (orig_port & 1)):
                continue
            if (block["public_ip"], cand, proto) in self._ext_ports:
                continue
            block["next_port"] = port
            return cand
        return 0  # exhaustion

    def _get_eim(self, int_ip: int, int_port: int, proto: int, block: dict, now: int) -> tuple[int, int] | None:
        key = (int_ip, int_port, proto)
        m = self.eim.get(key)
        if m is not None:
            m[2] += 1
            return m[0], m[1]
        ext_port = self._allocate_port(block, int_port, proto)
        if ext_port == 0:
            return None
        self.eim[key] = [block["public_ip"], ext_port, 1]
        self._ext_ports[(block["public_ip"], ext_port, proto)] = key
        return block["public_ip"], ext_port

    # -- new-flow punt handling (the device's egress-miss path) --
    @staticmethod
    def _key(src_ip, dst_ip, src_port, dst_port, proto):
        return [src_ip, dst_ip, ((src_port & 0xFFFF) << 16) | (dst_port & 0xFFFF), proto]

    def handle_new_flow(self, src_ip: int, dst_ip: int, src_port: int,
                        dst_port: int, proto: int, pkt_len: int, now: int,
                        is_hairpin: bool = False) -> tuple[int, int] | None:
        """Create session + reverse rows for a punted first packet.

        Returns (nat_ip, nat_port) or None (no allocation / exhaustion).
        ICMP key convention matches the device: egress (echo_id, 0).
        """
        block = self.blocks.get(src_ip)
        if block is None:
            return None
        if proto == PROTO_ICMP:
            dst_port = 0
        skey = self._key(src_ip, dst_ip, src_port, dst_port, proto)
        existing = self.sessions.lookup(skey)
        if existing is not None:
            return int(existing[SV_NAT_IP]), int(existing[SV_NAT_PORT])

        if self.flags & FLAG_EIM:
            got = self._get_eim(src_ip, src_port, proto, block, now)
        else:
            p = self._allocate_port(block, src_port, proto)
            got = (block["public_ip"], p) if p else None
        if got is None:
            self._log(LOG_PORT_EXHAUSTION, block["subscriber_id"], src_ip,
                      block["public_ip"], src_port, 0, dst_ip, dst_port, proto, now)
            self.exhausted["port"] += 1
            self._exhaust_log.report(
                NATExhaustedError(f"port block {block['port_start']}-"
                                  f"{block['port_end']} full for subscriber "
                                  f"{block['subscriber_id']}"),
                resource="port")
            return None
        nat_ip, nat_port = got

        row = np.zeros((SESSION_WORDS,), dtype=np.uint32)
        row[SV_NAT_IP] = nat_ip
        row[SV_NAT_PORT] = nat_port
        row[SV_ORIG_IP] = src_ip
        row[SV_ORIG_PORT] = src_port
        row[SV_DEST_IP] = dst_ip
        row[SV_DEST_PORT] = dst_port
        row[SV_CREATED] = now
        row[SV_LAST_SEEN] = now
        row[SV_STATE] = NAT_STATE_NEW
        row[SV_PROTO] = proto
        row[SV_PKTS_OUT] = 1
        row[SV_BYTES_OUT] = pkt_len
        self.sessions.insert(skey, row)
        # reverse: remote -> nat endpoint. ICMP matches (0, echo_id)
        # (parity: nat44.c:846-851 — ingress src_port=0, dst_port=id)
        r_src_port = 0 if proto == PROTO_ICMP else dst_port
        rkey = self._key(dst_ip, nat_ip, r_src_port, nat_port, proto)
        rrow = np.zeros((REVERSE_WORDS,), dtype=np.uint32)
        rrow[:4] = skey
        self.reverse.insert(rkey, rrow)
        self._log(LOG_SESSION_CREATE, block["subscriber_id"], src_ip, nat_ip,
                  src_port, nat_port, dst_ip, dst_port, proto, now,
                  flags=1 if is_hairpin else 0)
        return nat_ip, nat_port

    # -- expiry (host sweep over device-authoritative last_seen) --
    def expire_sessions(self, now: int, device_vals: np.ndarray | None = None) -> int:
        """Remove idle sessions. device_vals: fetched session value array
        (device-authoritative counters/last_seen); defaults to host mirror.

        The candidate scan is vectorized: per-slot timeouts come from one
        numpy pass over the occupied rows' proto/state words, and the
        Python loop below runs only over the already-expired indices — at
        the 1M-session target a full sweep with a per-slot Python body
        was the cost of the sweep, not the deletions."""
        fp = fault_point("nat.expire")
        if fp is not None and fp.kind == "skew":
            # chaos: the expiry clock jumps (NTP step / host suspend);
            # the sweep must stay consistent in BOTH directions
            now = int(now + fp.arg)
        vals = device_vals if device_vals is not None else self.sessions.vals
        used = self.sessions.used
        expired = 0
        occupied = np.nonzero(used)[0]
        if len(occupied) == 0:
            return 0
        rows = vals[occupied]
        proto_c = rows[:, SV_PROTO]
        state_c = rows[:, SV_STATE]
        last_c = rows[:, SV_LAST_SEEN].astype(np.int64)
        timeout_c = np.full(len(occupied), UDP_TIMEOUT_S, dtype=np.int64)
        timeout_c[proto_c == PROTO_ICMP] = ICMP_TIMEOUT_S
        timeout_c[proto_c == PROTO_TCP] = np.where(
            state_c[proto_c == PROTO_TCP] == 1,
            TCP_EST_TIMEOUT_S, TCP_TRANSIENT_TIMEOUT_S)
        timeout_c = np.where(state_c == NAT_STATE_CLOSING,
                             np.minimum(timeout_c, TCP_TRANSIENT_TIMEOUT_S),
                             timeout_c)
        for s in occupied[(now - last_c) > timeout_c]:
            v = vals[s]
            key = self.sessions.keys[s].copy()
            src_ip, dst_ip = int(key[0]), int(key[1])
            ports = int(key[2])
            proto_k = int(key[3])
            src_port, dst_port = ports >> 16, ports & 0xFFFF
            nat_ip, nat_port = int(v[SV_NAT_IP]), int(v[SV_NAT_PORT])
            self.sessions.delete(key)
            r_src_port = 0 if proto_k == PROTO_ICMP else dst_port
            self.reverse.delete(self._key(dst_ip, nat_ip, r_src_port, nat_port, proto_k))
            # EIM refcount decrement; free the port when unreferenced
            ekey = (src_ip, src_port, proto_k)
            m = self.eim.get(ekey)
            if m is not None:
                m[2] -= 1
                if m[2] <= 0:
                    self.eim.pop(ekey)
                    self._ext_ports.pop((m[0], m[1], proto_k), None)
            blk = self.blocks.get(src_ip)
            self._log(LOG_SESSION_DELETE, blk["subscriber_id"] if blk else 0,
                      src_ip, nat_ip, src_port, nat_port, dst_ip, dst_port, proto_k, now)
            expired += 1
        return expired

    def subscriber_octets(self, device_vals: np.ndarray | None = None
                          ) -> dict[int, tuple[int, int, int, int]]:
        """Per-subscriber (bytes_in, bytes_out, pkts_in, pkts_out) summed
        over live sessions — the per-subscriber counter feed the reference
        reads for interim accounting. device_vals: engine-fetched
        device-authoritative rows (Engine.fetch_session_vals)."""
        vals = device_vals if device_vals is not None else self.sessions.vals
        occ = np.nonzero(self.sessions.used)[0]
        if len(occ) == 0:
            return {}
        rows = vals[occ]
        ips = rows[:, SV_ORIG_IP].astype(np.int64)
        uniq, inv = np.unique(ips, return_inverse=True)
        out: dict[int, tuple[int, int, int, int]] = {}
        sums = [np.bincount(inv, weights=rows[:, w].astype(np.float64),
                            minlength=len(uniq)).astype(np.int64)
                for w in (SV_BYTES_IN, SV_BYTES_OUT, SV_PKTS_IN, SV_PKTS_OUT)]
        for i, ip in enumerate(uniq):
            out[int(ip)] = (int(sums[0][i]), int(sums[1][i]),
                            int(sums[2][i]), int(sums[3][i]))
        return out

    # -- hairpin / ALG config --
    def add_hairpin_ip(self, ip: int) -> None:
        free = np.nonzero(self.hairpin == 0)[0]
        if len(free) == 0:
            raise RuntimeError("hairpin table full")
        self.hairpin[free[0]] = ip

    def add_alg_port(self, port: int, proto: int) -> None:
        free = np.nonzero(self.alg == 0)[0]
        if len(free) == 0:
            raise RuntimeError("alg table full")
        self.alg[free[0]] = ((port & 0xFFFF) << 16) | (proto & 0xFF)

    # -- device sync --
    def config_array(self) -> np.ndarray:
        return np.array([self.flags, self.port_range[0], self.port_range[1],
                         self.ports_per_subscriber], dtype=np.uint32)

    def device_tables(self) -> NATTables:
        return NATTables(
            sessions=self.sessions.device_state(),
            reverse=self.reverse.device_state(),
            sub_nat=self.sub_nat.device_state(),
            hairpin_ips=jnp.asarray(self.hairpin),
            alg_ports=jnp.asarray(self.alg),
            config=jnp.asarray(self.config_array()),
        )

    def make_updates(self) -> tuple:
        return (
            self.sessions.make_update(self.update_slots),
            self.reverse.make_update(self.update_slots),
            self.sub_nat.make_update(self.update_slots),
            jnp.asarray(self.hairpin),
            jnp.asarray(self.alg),
            jnp.asarray(self.config_array()),
        )

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    _CKPT_TABLES = ("sessions", "reverse", "sub_nat")

    def checkpoint_state(self) -> tuple[dict, dict]:
        """(meta, arrays): the three cuckoo mirrors slot-exact, the dense
        hairpin/alg config, and ALL of the Python allocator bookkeeping —
        block cursors, free lists, EIM refcounts, per-subscriber blocks.
        A restore that kept only the table rows would re-hand out ports
        that live sessions still map (the restore_block hazard)."""
        meta = {
            "geom": {t: getattr(self, t).checkpoint_geom()
                     for t in self._CKPT_TABLES},
            "flags": int(self.flags),
            "port_range": list(self.port_range),
            "ports_per_subscriber": int(self.ports_per_subscriber),
            "public_ips": [int(ip) for ip in self.public_ips],
            "next_block": [[int(ip), int(p)]
                           for ip, p in self._next_block.items()],
            "free_blocks": [[int(ip), [int(s) for s in starts]]
                            for ip, starts in self._free_blocks.items()],
            "ip_round_robin": int(self._ip_round_robin),
            "sub_id_seq": int(self._sub_id_seq),
            "eim": [[int(k[0]), int(k[1]), int(k[2]),
                     int(m[0]), int(m[1]), int(m[2])]
                    for k, m in self.eim.items()],
            "blocks": [[int(ip), int(b["public_ip"]), int(b["port_start"]),
                        int(b["port_end"]), int(b["next_port"]),
                        int(b["subscriber_id"])]
                       for ip, b in self.blocks.items()],
        }
        arrays = {f"{t}.{k}": v
                  for t in self._CKPT_TABLES
                  for k, v in getattr(self, t).checkpoint_arrays().items()}
        arrays["hairpin"] = self.hairpin
        arrays["alg"] = self.alg
        return meta, arrays

    @staticmethod
    def parse_checkpoint_meta(meta: dict) -> dict:
        """Parse/validate the checkpointed allocator bookkeeping into
        plain structures WITHOUT touching self. The restore pre-check
        runs this before any mirror mutates (KeyError/ValueError/
        TypeError propagate to the all-or-nothing gate); restore_state
        applies the result."""
        return {
            "flags": int(meta["flags"]),
            "port_range": (int(meta["port_range"][0]),
                           int(meta["port_range"][1])),
            "ports_per_subscriber": int(meta["ports_per_subscriber"]),
            "public_ips": [int(ip) for ip in meta["public_ips"]],
            "next_block": {int(ip): int(p) for ip, p in meta["next_block"]},
            "free_blocks": {int(ip): [int(s) for s in starts]
                            for ip, starts in meta["free_blocks"]},
            "ip_round_robin": int(meta["ip_round_robin"]),
            "sub_id_seq": int(meta["sub_id_seq"]),
            "eim": {(int(a), int(b), int(c)): [int(d), int(e), int(f)]
                    for a, b, c, d, e, f in meta["eim"]},
            "blocks": {
                int(ip): {"public_ip": int(pub), "port_start": int(start),
                          "port_end": int(end), "next_port": int(nxt),
                          "subscriber_id": int(sid), "private_ip": int(ip)}
                for ip, pub, start, end, nxt, sid in meta["blocks"]},
        }

    def restore_state(self, meta: dict, arrays: dict) -> dict[str, int]:
        """Hydrate from a checkpoint (reject-on-mismatch on table
        geometry). NAT policy knobs (flags, port range, public IPs) come
        from the checkpoint — the restored mappings are only valid under
        the configuration that created them. Caller must follow with a
        full device upload (resync_tables)."""
        parsed = self.parse_checkpoint_meta(meta)  # parse BEFORE mutating
        rows = {}
        for t in self._CKPT_TABLES:
            rows[t] = getattr(self, t).restore_arrays(
                {k: arrays[f"{t}.{k}"] for k in ("keys", "vals", "used")},
                meta["geom"][t])
        self.hairpin[:] = arrays["hairpin"]
        self.alg[:] = arrays["alg"]
        self.flags = parsed["flags"]
        self.port_range = parsed["port_range"]
        self.ports_per_subscriber = parsed["ports_per_subscriber"]
        self.public_ips = parsed["public_ips"]
        self._next_block = parsed["next_block"]
        self._free_blocks = parsed["free_blocks"]
        self._ip_round_robin = parsed["ip_round_robin"]
        self._sub_id_seq = parsed["sub_id_seq"]
        self.eim = parsed["eim"]
        # _ext_ports is derived state: rebuild, never trust two copies
        self._ext_ports = {(m[0], m[1], k[2]): k
                           for k, m in self.eim.items()}
        self.blocks = parsed["blocks"]
        rows["blocks"] = len(self.blocks)
        rows["eim"] = len(self.eim)
        return rows

    def empty_updates(self) -> tuple:
        """No-op table-delta batch (dirty tracking untouched) for the
        scheduler's no-drain bulk steps; pending session deltas stay
        queued for the next drain-cadence step. The scatter buffers come
        from the empty_update caches; hairpin/alg/config are re-read per
        call because the step applies them wholesale (a cached snapshot
        would revert live NAT config between drains)."""
        return (
            self.sessions.empty_update(self.update_slots),
            self.reverse.empty_update(self.update_slots),
            self.sub_nat.empty_update(self.update_slots),
            jnp.asarray(self.hairpin),
            jnp.asarray(self.alg),
            jnp.asarray(self.config_array()),
        )
