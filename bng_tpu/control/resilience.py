"""Partition tolerance: degraded operation when Nexus/RADIUS are down.

Parity: pkg/resilience — Manager partition state machine
(manager.go:221-341 normal/partitioned/recovering), reconciliation with
earlier-timestamp-wins conflict resolution + forced renumber of losers
(manager.go:342-528), ConflictDetector (conflict_detector.go:25,121-233),
PoolMonitor with short-lease activation (pool_monitor.go:20,201-346),
RADIUSHandler degraded auth from cached profiles + offline accounting
buffer (radius_handler.go:52,134-489), RequestQueue (request_queue.go:17).

All loops are tick(now)-driven; health checkers are injectable callables
(the reference's controllable-health-checker test pattern, SURVEY §4.6).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.utils.structlog import ErrorLog


class PartitionState(str, enum.Enum):
    NORMAL = "normal"
    PARTITIONED = "partitioned"
    RECOVERING = "recovering"


@dataclass
class PartitionAllocation:
    """An IP handed out while partitioned (conflict_detector.go role)."""

    subscriber_id: str
    ip: int
    allocated_at: float


@dataclass
class Conflict:
    ip: int
    local: PartitionAllocation
    remote_subscriber: str
    remote_allocated_at: float
    winner: str = ""  # subscriber id
    loser: str = ""


class ConflictDetector:
    """Track partition-time allocations; diff against the central store
    on heal. Parity: conflict_detector.go:25,121-233."""

    def __init__(self):
        self._partition_allocs: dict[int, PartitionAllocation] = {}

    def record(self, subscriber_id: str, ip: int, at: float) -> None:
        self._partition_allocs[ip] = PartitionAllocation(subscriber_id, ip, at)

    def clear(self) -> None:
        self._partition_allocs.clear()

    @property
    def count(self) -> int:
        return len(self._partition_allocs)

    def detect(self, central_lookup: Callable[[int], tuple[str, float] | None]
               ) -> list[Conflict]:
        """For each partition-time allocation, ask the central store who
        else claims that IP. Returns resolved conflicts
        (earlier-timestamp-wins; manager.go:resolveConflict)."""
        conflicts = []
        for ip, local in self._partition_allocs.items():
            remote = central_lookup(ip)
            if remote is None:
                continue
            r_sub, r_at = remote
            if r_sub == local.subscriber_id:
                continue
            c = Conflict(ip, local, r_sub, r_at)
            if r_at <= local.allocated_at:
                c.winner, c.loser = r_sub, local.subscriber_id
            else:
                c.winner, c.loser = local.subscriber_id, r_sub
            conflicts.append(c)
        return conflicts


class PoolLevel(str, enum.Enum):
    """Parity: pool_monitor.go:20 levels."""

    NORMAL = "normal"
    WARNING = "warning"
    CRITICAL = "critical"
    EXHAUSTED = "exhausted"


class PoolMonitor:
    """Utilization watcher; activates short leases under pressure.

    Parity: pool_monitor.go:201-346 — warning 80%, critical 95%,
    exhausted 100%; critical+ switches the DHCP server to short leases so
    churn frees addresses faster during a partition.
    """

    def __init__(self, utilization: Callable[[], float],
                 warning_pct: float = 0.80, critical_pct: float = 0.95,
                 short_lease_s: int = 300,
                 on_level_change: Callable[[PoolLevel], None] | None = None):
        self.utilization = utilization
        self.warning_pct = warning_pct
        self.critical_pct = critical_pct
        self.short_lease_s = short_lease_s
        self.on_level_change = on_level_change
        self.level = PoolLevel.NORMAL

    @property
    def short_lease_active(self) -> bool:
        return self.level in (PoolLevel.CRITICAL, PoolLevel.EXHAUSTED)

    def tick(self, now: float = 0.0) -> PoolLevel:
        u = self.utilization()
        if u >= 1.0:
            new = PoolLevel.EXHAUSTED
        elif u >= self.critical_pct:
            new = PoolLevel.CRITICAL
        elif u >= self.warning_pct:
            new = PoolLevel.WARNING
        else:
            new = PoolLevel.NORMAL
        if new != self.level:
            self.level = new
            if self.on_level_change:
                self.on_level_change(new)
        return self.level


@dataclass
class CachedProfile:
    """RADIUS profile cached from a successful auth
    (radius_handler.go:52 role)."""

    username: str
    policy_name: str = ""
    framed_ip: int = 0
    cached_at: float = 0.0


class DegradedRADIUSHandler:
    """Auth from cache when RADIUS is down; queue reauth + buffer acct.

    Parity: radius_handler.go:134-489 — cache successful auths; during
    partition serve auth decisions from cache (subject to TTL), queue the
    subscriber for re-auth on heal, and buffer accounting records for
    replay.
    """

    def __init__(self, cache_ttl_s: float = 86400.0, max_buffer: int = 10000):
        self.cache: dict[str, CachedProfile] = {}
        self.cache_ttl_s = cache_ttl_s
        self.reauth_queue: list[str] = []
        self.acct_buffer: list[dict] = []
        self.max_buffer = max_buffer
        self.stats = {"cache_hits": 0, "cache_misses": 0, "buffered_acct": 0,
                      "replayed_acct": 0, "reauths": 0}

    def cache_profile(self, p: CachedProfile) -> None:
        self.cache[p.username] = p

    def degraded_auth(self, username: str, now: float) -> CachedProfile | None:
        p = self.cache.get(username)
        if p is None or now - p.cached_at > self.cache_ttl_s:
            self.stats["cache_misses"] += 1
            return None
        self.stats["cache_hits"] += 1
        if username not in self.reauth_queue:
            self.reauth_queue.append(username)
        return p

    def buffer_accounting(self, record: dict) -> bool:
        if len(self.acct_buffer) >= self.max_buffer:
            self.acct_buffer.pop(0)  # oldest-drop (bounded buffer)
        self.acct_buffer.append(record)
        self.stats["buffered_acct"] += 1
        return True

    def replay(self, send: Callable[[dict], bool],
               reauth: Callable[[str], bool] | None = None) -> tuple[int, int]:
        """On heal: flush accounting then re-auth queued subscribers.
        Returns (acct_sent, reauth_done); failures stay queued."""
        sent = 0
        remaining = []
        for rec in self.acct_buffer:
            if send(rec):
                sent += 1
                self.stats["replayed_acct"] += 1
            else:
                remaining.append(rec)
        self.acct_buffer = remaining
        reauthed = 0
        if reauth is not None:
            still = []
            for u in self.reauth_queue:
                if reauth(u):
                    reauthed += 1
                    self.stats["reauths"] += 1
                else:
                    still.append(u)
            self.reauth_queue = still
        return sent, reauthed


class RequestQueue:
    """Bounded FIFO of deferred central-store writes
    (request_queue.go:17 role)."""

    def __init__(self, max_size: int = 10000):
        self._q: list[tuple[str, dict]] = []
        self.max_size = max_size
        self.dropped = 0

    def enqueue(self, kind: str, payload: dict) -> bool:
        if len(self._q) >= self.max_size:
            self.dropped += 1
            return False
        self._q.append((kind, payload))
        return True

    def drain(self, handler: Callable[[str, dict], bool]) -> int:
        done = 0
        remaining = []
        for kind, payload in self._q:
            if handler(kind, payload):
                done += 1
            else:
                remaining.append((kind, payload))
        self._q = remaining
        return done

    def __len__(self) -> int:
        return len(self._q)


@dataclass
class ResilienceEvents:
    partitions: int = 0
    recoveries: int = 0
    conflicts_found: int = 0
    renumbered: int = 0


class ResilienceManager:
    """The partition state machine tying it together.

    Parity: manager.go:22 — checkHealth (:221) drives NORMAL ->
    PARTITIONED when Nexus (and optionally RADIUS) fail; heal drives
    PARTITIONED -> RECOVERING (reconcile: detect + resolve conflicts,
    renumber losers, drain queued writes, replay accounting) -> NORMAL.
    """

    def __init__(
        self,
        nexus_healthy: Callable[[], bool],
        radius_healthy: Callable[[], bool] | None = None,
        check_interval_s: float = 5.0,
        failure_threshold: int = 3,
        central_lookup: Callable[[int], tuple[str, float] | None] | None = None,
        renumber: Callable[[str], bool] | None = None,
        on_state_change: Callable[[PartitionState], None] | None = None,
        probe_clock: Callable[[], float] | None = None,
    ):
        self.nexus_healthy = nexus_healthy
        self.radius_healthy = radius_healthy
        self.check_interval_s = check_interval_s
        self.failure_threshold = failure_threshold
        self.central_lookup = central_lookup
        self.renumber = renumber
        self.on_state_change = on_state_change
        if probe_clock is None:
            import time

            probe_clock = time.monotonic
        self.probe_clock = probe_clock

        self.state = PartitionState.NORMAL
        self.conflicts = ConflictDetector()
        self.radius_handler = DegradedRADIUSHandler()
        self.queue = RequestQueue()
        self.events = ResilienceEvents()
        self._fails = 0
        self._radius_fails = 0
        self.radius_down = False
        self._last_check = 0.0
        self._last_conflicts: list[Conflict] = []
        self._probe_err_log = ErrorLog(
            "resilience", "health probe raised (folded to unhealthy)")

    @property
    def partitioned(self) -> bool:
        return self.state != PartitionState.NORMAL

    @property
    def degraded_auth_active(self) -> bool:
        """Serve auth from cache when RADIUS is unreachable — whether from
        a full Nexus partition or a RADIUS-only outage
        (radius_handler.go's activation condition)."""
        return self.partitioned or self.radius_down

    def record_allocation(self, subscriber_id: str, ip: int, at: float) -> None:
        """DHCP server calls this for allocations made while partitioned."""
        if self.partitioned:
            self.conflicts.record(subscriber_id, ip, at)

    def _set_state(self, s: PartitionState) -> None:
        self.state = s
        if self.on_state_change:
            self.on_state_change(s)

    def tick(self, now: float,
             drain_handler: Callable[[str, dict], bool] | None = None,
             acct_send: Callable[[dict], bool] | None = None) -> PartitionState:
        if now - self._last_check < self.check_interval_s:
            return self.state
        self._last_check = now
        ok = False
        try:
            ok = bool(self.nexus_healthy())
        except Exception as e:
            # a raising probe is a different signal than a clean False —
            # visible (rate-limited), then folded to unhealthy (BNG021)
            self._probe_err_log.report(e, probe="nexus")

        # RADIUS-only outage: degraded auth without a Nexus partition
        if self.radius_healthy is not None:
            r_ok = False
            probe_t0 = self.probe_clock()
            try:
                r_ok = bool(self.radius_healthy())
            except Exception as e:
                self._probe_err_log.report(e, probe="radius")
            probe_wall_s = max(0.0, self.probe_clock() - probe_t0)
            if r_ok:
                self._radius_fails = 0
                if self.radius_down:
                    self.radius_down = False
                    # caller replays buffered accounting via acct_send below
                    if acct_send is not None:
                        self.radius_handler.replay(acct_send)
            else:
                # a probe that STALLED (socket timeout against a
                # black-holed server) already burned the wall-time of
                # that many check intervals — credit them all, or
                # detection takes threshold * stall instead of
                # threshold * interval and degraded auth arrives long
                # after subscribers started timing out
                self._radius_fails += min(
                    self.failure_threshold,
                    1 + int(probe_wall_s // self.check_interval_s))
                if self._radius_fails >= self.failure_threshold:
                    self.radius_down = True

        if self.state == PartitionState.NORMAL:
            if ok:
                self._fails = 0
            else:
                self._fails += 1
                if self._fails >= self.failure_threshold:
                    self._set_state(PartitionState.PARTITIONED)
                    self.events.partitions += 1
        elif self.state == PartitionState.PARTITIONED:
            if ok:
                self._set_state(PartitionState.RECOVERING)
                self._reconcile(now, drain_handler, acct_send)
        return self.state

    def _reconcile(self, now: float,
                   drain_handler: Callable[[str, dict], bool] | None,
                   acct_send: Callable[[dict], bool] | None) -> None:
        """performReconciliation (manager.go:342-528)."""
        if self.central_lookup is not None:
            found = self.conflicts.detect(self.central_lookup)
            self._last_conflicts = found
            self.events.conflicts_found += len(found)
            for c in found:
                if self.renumber is not None and c.loser:
                    if self.renumber(c.loser):
                        self.events.renumbered += 1
        self.conflicts.clear()
        if drain_handler is not None:
            self.queue.drain(drain_handler)
        if acct_send is not None:
            self.radius_handler.replay(acct_send)
        self._fails = 0
        self._set_state(PartitionState.NORMAL)
        self.events.recoveries += 1
