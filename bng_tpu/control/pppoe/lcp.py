"""LCP — link control protocol for PPPoE sessions.

Parity: pkg/pppoe/lcp.go (LCPStateMachine :104, option negotiation
:394-496). Server negotiates MRU 1492 (PPPoE, RFC 2516 §7), announces the
auth protocol (PAP or CHAP-MD5), and exchanges magic numbers.
"""

from __future__ import annotations

import struct

from bng_tpu.control.pppoe.codec import PROTO_CHAP, PROTO_LCP, PROTO_PAP, CPOption
from bng_tpu.control.pppoe.fsm import OptionFSM

OPT_MRU = 1
OPT_AUTH_PROTO = 3
OPT_QUALITY_PROTO = 4
OPT_MAGIC = 5
OPT_PFC = 7
OPT_ACFC = 8

PPPOE_MRU = 1492
CHAP_ALG_MD5 = 5


class LCP(OptionFSM):
    proto = PROTO_LCP
    name = "lcp"

    def __init__(self, magic: int, auth_proto: int = PROTO_CHAP, **kw):
        super().__init__(**kw)
        self.magic = magic & 0xFFFFFFFF
        self.auth_proto = auth_proto  # PROTO_PAP | PROTO_CHAP | 0 (no auth)
        self.peer_magic = 0
        self.peer_mru = PPPOE_MRU
        self.negotiated_auth = 0

    def own_options(self) -> list[CPOption]:
        opts = [CPOption(OPT_MRU, struct.pack(">H", PPPOE_MRU)),
                CPOption(OPT_MAGIC, struct.pack(">I", self.magic))]
        if self.auth_proto == PROTO_PAP:
            opts.append(CPOption(OPT_AUTH_PROTO, struct.pack(">H", PROTO_PAP)))
        elif self.auth_proto == PROTO_CHAP:
            opts.append(CPOption(OPT_AUTH_PROTO,
                                 struct.pack(">HB", PROTO_CHAP, CHAP_ALG_MD5)))
        return opts

    def check_peer_options(self, opts):
        ack, nak, rej = [], [], []
        for o in opts:
            if o.type == OPT_MRU:
                if len(o.data) == 2:
                    mru = struct.unpack(">H", o.data)[0]
                    if mru < 576:  # too small to be useful; nak up to PPPoE MRU
                        nak.append(CPOption(OPT_MRU, struct.pack(">H", PPPOE_MRU)))
                    else:
                        self.peer_mru = min(mru, PPPOE_MRU)
                        ack.append(o)
                else:
                    rej.append(o)
            elif o.type == OPT_MAGIC:
                if len(o.data) == 4:
                    self.peer_magic = struct.unpack(">I", o.data)[0]
                    ack.append(o)
                else:
                    rej.append(o)
            elif o.type in (OPT_PFC, OPT_ACFC):
                # header compression is meaningless over PPPoE; reject
                rej.append(o)
            elif o.type == OPT_AUTH_PROTO:
                # client must not authenticate the server
                rej.append(o)
            else:
                rej.append(o)
        return ack, nak, rej

    def peer_acked(self, opts):
        self.negotiated_auth = self.auth_proto

    def peer_naked(self, opts):
        for o in opts:
            if o.type == OPT_AUTH_PROTO and len(o.data) >= 2:
                want = struct.unpack(">H", o.data[:2])[0]
                # fall back PAP<->CHAP if the client insists (lcp.go behavior:
                # server policy wins only if client supports it)
                if want in (PROTO_PAP, PROTO_CHAP):
                    self.auth_proto = want

    def peer_rejected(self, opts):
        for o in opts:
            if o.type == OPT_AUTH_PROTO:
                # client refuses auth entirely -> keep requiring it; the
                # session will fail authentication instead of skipping it
                pass
