"""PPPoE session state + manager + teardown causes.

Parity: pkg/pppoe/session.go (SessionManager :182, session-ID
allocation) and teardown.go (TerminateCause RFC 2866 values :20-37,
SessionTeardown :113). Sessions advance through phases: discovery ->
lcp -> auth -> network (IPCP/IPV6CP) -> open -> closed.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.control.pppoe.ipcp import IPCP
from bng_tpu.control.pppoe.ipv6cp import IPV6CP
from bng_tpu.control.pppoe.lcp import LCP


class TerminateCause(enum.IntEnum):
    """RFC 2866 Acct-Terminate-Cause (parity: teardown.go:20-37)."""

    USER_REQUEST = 1
    LOST_CARRIER = 2
    LOST_SERVICE = 3
    IDLE_TIMEOUT = 4
    SESSION_TIMEOUT = 5
    ADMIN_RESET = 6
    ADMIN_REBOOT = 7
    PORT_ERROR = 8
    NAS_ERROR = 9
    NAS_REQUEST = 10
    NAS_REBOOT = 11
    PORT_UNNEEDED = 12
    PORT_PREEMPTED = 13
    PORT_SUSPENDED = 14
    SERVICE_UNAVAILABLE = 15
    CALLBACK = 16
    USER_ERROR = 17
    HOST_REQUEST = 18


class Phase(str, enum.Enum):
    DISCOVERY = "discovery"
    LCP = "lcp"
    AUTH = "auth"
    NETWORK = "network"
    OPEN = "open"
    CLOSED = "closed"


@dataclass
class PPPoESession:
    session_id: int
    client_mac: bytes
    phase: Phase = Phase.LCP
    lcp: LCP | None = None
    ipcp: IPCP | None = None
    ipv6cp: IPV6CP | None = None
    username: str = ""
    assigned_ip: int = 0
    chap_ident: int = 0
    chap_challenge: bytes = b""
    created_at: float = 0.0
    last_activity: float = 0.0
    # keepalive (parity: keepalive.go)
    echo_ident: int = 0
    echo_pending: int = 0  # unanswered echo-requests
    last_echo_tx: float = 0.0
    terminate_cause: TerminateCause | None = None
    acct_session_id: str = ""
    radius_attributes: dict = field(default_factory=dict)
    vlans: list[int] = field(default_factory=list)  # S/C tags of the access line

    def touch(self, now: float) -> None:
        self.last_activity = now
        self.echo_pending = 0


class SessionManager:
    """Session-ID allocation + lookup (parity: session.go:182).

    PPPoE session IDs are 16-bit, nonzero, unique per (AC, client MAC).
    Allocation scans from a rolling cursor — same shape as the
    reference's nextSessionID behavior.
    """

    def __init__(self, max_sessions: int = 65535):
        self.max_sessions = min(max_sessions, 0xFFFF)
        self._sessions: dict[int, PPPoESession] = {}
        self._by_mac: dict[bytes, int] = {}
        self._cursor = 0

    def __len__(self) -> int:
        return len(self._sessions)

    def allocate(self, client_mac: bytes, now: float) -> PPPoESession | None:
        if len(self._sessions) >= self.max_sessions:
            return None
        # one session per MAC: replace a stale one (reference tears down
        # the old session on re-PADR)
        old = self._by_mac.get(client_mac)
        if old is not None:
            self.remove(old)
        for _ in range(0xFFFF):
            self._cursor = (self._cursor % 0xFFFF) + 1  # 1..65535
            if self._cursor not in self._sessions:
                break
        else:
            return None
        s = PPPoESession(session_id=self._cursor, client_mac=client_mac,
                         created_at=now, last_activity=now)
        self._sessions[s.session_id] = s
        self._by_mac[client_mac] = s.session_id
        return s

    def get(self, session_id: int) -> PPPoESession | None:
        return self._sessions.get(session_id)

    def by_mac(self, mac: bytes) -> PPPoESession | None:
        sid = self._by_mac.get(mac)
        return self._sessions.get(sid) if sid is not None else None

    def remove(self, session_id: int) -> PPPoESession | None:
        s = self._sessions.pop(session_id, None)
        if s is not None and self._by_mac.get(s.client_mac) == session_id:
            del self._by_mac[s.client_mac]
        return s

    def all(self) -> list[PPPoESession]:
        return list(self._sessions.values())


@dataclass
class TeardownEvent:
    """Handed to accounting/fast-path hooks on session close
    (parity: teardown.go:113 SessionTeardown)."""

    session: PPPoESession
    cause: TerminateCause
    at: float
    session_time_s: float = 0.0

    def __post_init__(self):
        if not self.session_time_s and self.session.created_at:
            self.session_time_s = max(0.0, self.at - self.session.created_at)


TeardownHook = Callable[[TeardownEvent], None]
