"""PAP / CHAP-MD5 authentication for PPPoE sessions.

Parity: pkg/pppoe/auth.go — Authenticator with PAP (:202-298), CHAP MD5
(:323-493), per-MAC rate limiting (:542-564) and password zeroing (:580).
Verification is pluggable: a local secret source or a RADIUS client.
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from dataclasses import dataclass, field
from typing import Callable, Protocol

# PAP codes (RFC 1334)
PAP_AUTH_REQ = 1
PAP_AUTH_ACK = 2
PAP_AUTH_NAK = 3

# CHAP codes (RFC 1994)
CHAP_CHALLENGE = 1
CHAP_RESPONSE = 2
CHAP_SUCCESS = 3
CHAP_FAILURE = 4


def chap_md5(ident: int, secret: bytes, challenge: bytes) -> bytes:
    """RFC 1994 §4.1: MD5(id || secret || challenge)."""
    return hashlib.md5(bytes([ident]) + secret + challenge).digest()


@dataclass
class AuthResult:
    ok: bool
    username: str = ""
    reason: str = ""
    # attributes from RADIUS (Framed-IP-Address, policy name, ...) if any
    attributes: dict = field(default_factory=dict)


class CredentialVerifier(Protocol):
    """Backend check — local secrets or RADIUS.

    verify_pap(username, password) and verify_chap(username, ident,
    challenge, response) return AuthResult. A RADIUS-backed verifier maps
    these onto Access-Request with User-Password or CHAP-Password
    (auth.go's radius calls).
    """

    def verify_pap(self, username: str, password: bytes) -> AuthResult: ...

    def verify_chap(self, username: str, ident: int, challenge: bytes,
                    response: bytes) -> AuthResult: ...


class LocalVerifier:
    """In-memory username->secret table (the reference's local auth mode)."""

    def __init__(self, secrets: dict[str, bytes]):
        self._secrets = secrets

    def verify_pap(self, username: str, password: bytes) -> AuthResult:
        want = self._secrets.get(username)
        ok = want is not None and hmac.compare_digest(want, password)
        return AuthResult(ok=ok, username=username,
                          reason="" if ok else "bad credentials")

    def verify_chap(self, username: str, ident: int, challenge: bytes,
                    response: bytes) -> AuthResult:
        want = self._secrets.get(username)
        if want is None:
            return AuthResult(ok=False, username=username, reason="unknown user")
        ok = hmac.compare_digest(chap_md5(ident, want, challenge), response)
        return AuthResult(ok=ok, username=username,
                          reason="" if ok else "bad chap response")


class RadiusVerifier:
    """CredentialVerifier over a control.radius.client.RadiusClient
    (auth.go's RADIUS mode): PAP maps to User-Password Access-Requests,
    CHAP to CHAP-Password/CHAP-Challenge (client.authenticate_chap).
    RADIUS attributes (Framed-IP, Filter-Id policy, Session-Timeout)
    ride back in AuthResult.attributes for the session-open hooks."""

    def __init__(self, client, mac_source=None):
        self.client = client
        # optional callable returning the CURRENT client MAC for
        # Calling-Station-Id (the PPPoE server verifies per-frame; the
        # verifier protocol doesn't carry the MAC)
        self.mac_source = mac_source or (lambda: b"")

    @staticmethod
    def _result(username: str, res) -> AuthResult:
        if res is None:  # every server timed out — fail closed
            return AuthResult(ok=False, username=username,
                              reason="radius timeout")
        if not res.success:
            return AuthResult(ok=False, username=username,
                              reason=res.reply_message or "radius reject")
        return AuthResult(ok=True, username=username, attributes={
            "framed_ip": res.framed_ip,
            "qos_policy": res.policy_name,
            "session_timeout": res.session_timeout,
            "idle_timeout": res.idle_timeout,
            "radius_class": res.radius_class,
        })

    def verify_pap(self, username: str, password: bytes) -> AuthResult:
        # raw bytes through: PAP passwords are arbitrary octets (RFC 1334)
        res = self.client.authenticate(username, password,
                                       mac=self.mac_source())
        return self._result(username, res)

    def verify_chap(self, username: str, ident: int, challenge: bytes,
                    response: bytes) -> AuthResult:
        res = self.client.authenticate_chap(username, ident, challenge,
                                            response, mac=self.mac_source())
        return self._result(username, res)


@dataclass
class RateLimiter:
    """Per-key auth attempt limiter (parity: auth.go:542-564)."""

    max_attempts: int = 5
    window_s: float = 60.0
    _attempts: dict[str, list[float]] = field(default_factory=dict)

    def allow(self, key: str, now: float) -> bool:
        lst = self._attempts.setdefault(key, [])
        lst[:] = [t for t in lst if now - t < self.window_s]
        if len(lst) >= self.max_attempts:
            return False
        lst.append(now)
        return True

    def reset(self, key: str) -> None:
        self._attempts.pop(key, None)


class PAPHandler:
    """Parses Auth-Request, verifies, emits Ack/Nak body bytes."""

    def __init__(self, verifier: CredentialVerifier,
                 limiter: RateLimiter | None = None):
        self.verifier = verifier
        self.limiter = limiter or RateLimiter()

    def handle(self, body: bytes, key: str, now: float
               ) -> tuple[bytes | None, AuthResult]:
        """body = PAP packet; returns (reply_packet, result)."""
        if len(body) < 4:
            return None, AuthResult(ok=False, reason="truncated")
        code, ident, length = body[0], body[1], struct.unpack(">H", body[2:4])[0]
        if code != PAP_AUTH_REQ or length > len(body):
            return None, AuthResult(ok=False, reason="not an auth-request")
        p = body[4:length]
        if not p:
            return None, AuthResult(ok=False, reason="empty")
        ulen = p[0]
        if 1 + ulen >= len(p):
            return None, AuthResult(ok=False, reason="bad peer-id length")
        username = p[1 : 1 + ulen].decode("utf-8", "replace")
        plen = p[1 + ulen]
        password = bytearray(p[2 + ulen : 2 + ulen + plen])
        try:
            if not self.limiter.allow(key, now):
                res = AuthResult(ok=False, username=username, reason="rate limited")
            else:
                res = self.verifier.verify_pap(username, bytes(password))
        finally:
            for i in range(len(password)):  # zero the secret (auth.go:580)
                password[i] = 0
        msg = b"" if res.ok else res.reason.encode()[:255]
        reply_code = PAP_AUTH_ACK if res.ok else PAP_AUTH_NAK
        reply = struct.pack(">BBH", reply_code, ident, 5 + len(msg)) + \
            bytes([len(msg)]) + msg
        return reply, res


class CHAPHandler:
    """Server-side CHAP: issue challenge, verify response.

    Challenge bytes come from an injected source so tests are
    deterministic (the reference uses crypto/rand).
    """

    def __init__(self, verifier: CredentialVerifier, ac_name: str = "bng-tpu",
                 challenge_source: Callable[[], bytes] | None = None,
                 limiter: RateLimiter | None = None):
        self.verifier = verifier
        self.ac_name = ac_name
        self._mkchallenge = challenge_source or self._default_challenge
        self.limiter = limiter or RateLimiter()
        self._counter = 0

    def _default_challenge(self) -> bytes:
        import os

        return os.urandom(16)

    def make_challenge(self, ident: int) -> tuple[bytes, bytes]:
        """Returns (challenge_value, chap_packet)."""
        val = self._mkchallenge()
        name = self.ac_name.encode()
        body = bytes([len(val)]) + val + name
        pkt = struct.pack(">BBH", CHAP_CHALLENGE, ident, 4 + len(body)) + body
        return val, pkt

    def handle_response(self, body: bytes, challenge: bytes, key: str,
                        now: float) -> tuple[bytes | None, AuthResult]:
        if len(body) < 5:
            return None, AuthResult(ok=False, reason="truncated")
        code, ident, length = body[0], body[1], struct.unpack(">H", body[2:4])[0]
        if code != CHAP_RESPONSE or length > len(body):
            return None, AuthResult(ok=False, reason="not a chap response")
        p = body[4:length]
        if not p:
            return None, AuthResult(ok=False, reason="empty response")
        vlen = p[0]
        if 1 + vlen > len(p):
            return None, AuthResult(ok=False, reason="bad value length")
        value = p[1 : 1 + vlen]
        username = p[1 + vlen :].decode("utf-8", "replace")
        if not self.limiter.allow(key, now):
            res = AuthResult(ok=False, username=username, reason="rate limited")
        else:
            res = self.verifier.verify_chap(username, ident, challenge, value)
        if res.ok:
            msg = b"Welcome"
            reply = struct.pack(">BBH", CHAP_SUCCESS, ident, 4 + len(msg)) + msg
        else:
            msg = res.reason.encode()[:64] or b"Authentication failed"
            reply = struct.pack(">BBH", CHAP_FAILURE, ident, 4 + len(msg)) + msg
        return reply, res
