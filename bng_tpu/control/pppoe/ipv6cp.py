"""IPV6CP — IPv6 interface-identifier negotiation over PPP.

Parity: pkg/pppoe/ipv6cp.go (IPV6CPStateMachine :90): negotiate the
64-bit interface identifier; zero or colliding IIDs are Nak'd with a
server-assigned one. Global addresses then come from SLAAC/DHCPv6 over
the session.
"""

from __future__ import annotations

from bng_tpu.control.pppoe.codec import PROTO_IPV6CP, CPOption
from bng_tpu.control.pppoe.fsm import OptionFSM

OPT_INTERFACE_ID = 1


class IPV6CP(OptionFSM):
    proto = PROTO_IPV6CP
    name = "ipv6cp"

    def __init__(self, our_iid: bytes, client_iid: bytes, **kw):
        super().__init__(**kw)
        assert len(our_iid) == 8 and len(client_iid) == 8
        self.our_iid = our_iid
        self.client_iid = client_iid
        self.client_confirmed_iid = b""

    def own_options(self) -> list[CPOption]:
        return [CPOption(OPT_INTERFACE_ID, self.our_iid)]

    def check_peer_options(self, opts):
        ack, nak, rej = [], [], []
        for o in opts:
            if o.type == OPT_INTERFACE_ID and len(o.data) == 8:
                if o.data != b"\x00" * 8 and o.data != self.our_iid:
                    self.client_confirmed_iid = o.data
                    ack.append(o)
                else:
                    nak.append(CPOption(OPT_INTERFACE_ID, self.client_iid))
            else:
                rej.append(o)
        return ack, nak, rej
