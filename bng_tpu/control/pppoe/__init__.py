"""PPPoE server: discovery, LCP/IPCP/IPV6CP, PAP/CHAP auth, sessions.

Parity: pkg/pppoe (reference's largest package, ~8.2k LoC). The reference
runs over an AF_PACKET raw socket with goroutine loops; here the server is
frames-in/frames-out and tick-driven: the host engine feeds it ethernet
frames (ethertype 0x8863/0x8864) from PASS-verdict lanes and transmits the
frames it returns, and calls tick(now) for keepalive/timeout processing.
"""

from bng_tpu.control.pppoe.codec import (
    ETH_PPPOE_DISCOVERY,
    ETH_PPPOE_SESSION,
    PPPoEPacket,
    Tag,
)
from bng_tpu.control.pppoe.server import PPPoEServer, PPPoEServerConfig
from bng_tpu.control.pppoe.session import PPPoESession, SessionManager, TerminateCause

__all__ = [
    "ETH_PPPOE_DISCOVERY",
    "ETH_PPPOE_SESSION",
    "PPPoEPacket",
    "Tag",
    "PPPoEServer",
    "PPPoEServerConfig",
    "PPPoESession",
    "SessionManager",
    "TerminateCause",
]
