"""IPCP — IPv4 address/DNS negotiation over PPP.

Parity: pkg/pppoe/ipcp.go (IPCPStateMachine :92, IP assignment
negotiation :375-474): the server Naks the client's 0.0.0.0 (or wrong)
IP-Address with the allocated address; DNS options 129/131 are Nak'd
with the configured resolvers.
"""

from __future__ import annotations

import struct

from bng_tpu.control.pppoe.codec import PROTO_IPCP, CPOption
from bng_tpu.control.pppoe.fsm import OptionFSM

OPT_IP_ADDRESSES = 1  # deprecated, reject
OPT_IP_COMPRESSION = 2
OPT_IP_ADDRESS = 3
OPT_PRIMARY_DNS = 129
OPT_SECONDARY_DNS = 131


def _ip4(v: int) -> bytes:
    return struct.pack(">I", v & 0xFFFFFFFF)


class IPCP(OptionFSM):
    proto = PROTO_IPCP
    name = "ipcp"

    def __init__(self, our_ip: int, client_ip: int,
                 dns_primary: int = 0, dns_secondary: int = 0, **kw):
        super().__init__(**kw)
        self.our_ip = our_ip
        self.client_ip = client_ip  # the address we assign
        self.dns_primary = dns_primary
        self.dns_secondary = dns_secondary
        self.client_confirmed_ip = 0

    def own_options(self) -> list[CPOption]:
        return [CPOption(OPT_IP_ADDRESS, _ip4(self.our_ip))]

    def check_peer_options(self, opts):
        ack, nak, rej = [], [], []
        for o in opts:
            if o.type == OPT_IP_ADDRESS and len(o.data) == 4:
                got = struct.unpack(">I", o.data)[0]
                if got == self.client_ip and got != 0:
                    self.client_confirmed_ip = got
                    ack.append(o)
                else:
                    nak.append(CPOption(OPT_IP_ADDRESS, _ip4(self.client_ip)))
            elif o.type == OPT_PRIMARY_DNS and self.dns_primary:
                if len(o.data) == 4 and struct.unpack(">I", o.data)[0] == self.dns_primary:
                    ack.append(o)
                else:
                    nak.append(CPOption(OPT_PRIMARY_DNS, _ip4(self.dns_primary)))
            elif o.type == OPT_SECONDARY_DNS and self.dns_secondary:
                if len(o.data) == 4 and struct.unpack(">I", o.data)[0] == self.dns_secondary:
                    ack.append(o)
                else:
                    nak.append(CPOption(OPT_SECONDARY_DNS, _ip4(self.dns_secondary)))
            else:
                rej.append(o)
        return ack, nak, rej
