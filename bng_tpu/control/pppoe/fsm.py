"""Generic PPP option-negotiation state machine (RFC 1661 §4 subset).

Parity: the shared shape of pkg/pppoe/lcp.go:104 / ipcp.go:92 /
ipv6cp.go:90 — each is the same Configure-Request/Ack/Nak/Reject machine
with protocol-specific option handling. Here that common machine is one
class; LCP/IPCP/IPV6CP subclass it with option policy only.

States (subset of RFC 1661 §4.2 sufficient for a server): CLOSED,
REQ_SENT, ACK_RCVD, ACK_SENT, OPENED, CLOSING. Tick-driven retransmit
with max-configure retry budget (RFC 1661 §4.6 counters).
"""

from __future__ import annotations

from typing import Callable

from bng_tpu.control.pppoe.codec import (
    CP_CODE_REJ,
    CP_CONF_ACK,
    CP_CONF_NAK,
    CP_CONF_REJ,
    CP_CONF_REQ,
    CP_DISCARD_REQ,
    CP_ECHO_REP,
    CP_ECHO_REQ,
    CP_TERM_ACK,
    CP_TERM_REQ,
    CPOption,
    CPPacket,
)

CLOSED = "closed"
REQ_SENT = "req-sent"
ACK_RCVD = "ack-rcvd"
ACK_SENT = "ack-sent"
OPENED = "opened"
CLOSING = "closing"

DEFAULT_RESTART_INTERVAL = 3.0  # RFC 1661 §4.6 Restart timer
DEFAULT_MAX_CONFIGURE = 10  # Max-Configure
DEFAULT_MAX_TERMINATE = 2  # Max-Terminate


class OptionFSM:
    """One PPP control protocol instance for one session.

    Outgoing packets are appended to `self.out` as CPPacket; the session
    layer wraps them in PPP/PPPoE/Ethernet and transmits.
    """

    proto: int = 0  # overridden: PPP protocol number
    name: str = "cp"

    def __init__(self, on_open: Callable[[], None] | None = None,
                 on_close: Callable[[], None] | None = None):
        self.state = CLOSED
        self.out: list[CPPacket] = []
        self.on_open = on_open
        self.on_close = on_close
        self._ident = 0
        self._req_ident = 0
        self._retries = 0
        self._next_resend = 0.0
        self.restart_interval = DEFAULT_RESTART_INTERVAL
        self.max_configure = DEFAULT_MAX_CONFIGURE

    # ---- option policy, overridden per protocol ----

    def own_options(self) -> list[CPOption]:
        """Options for our Configure-Request."""
        return []

    def check_peer_options(self, opts: list[CPOption]) -> tuple[
            list[CPOption], list[CPOption], list[CPOption]]:
        """Split the peer's Configure-Request into (ack, nak, reject)."""
        return opts, [], []

    def peer_acked(self, opts: list[CPOption]) -> None:
        """Peer Configure-Ack'd our request."""

    def peer_naked(self, opts: list[CPOption]) -> None:
        """Peer Configure-Nak'd: adjust our options before resend."""

    def peer_rejected(self, opts: list[CPOption]) -> None:
        """Peer Configure-Reject'd: drop those options before resend."""

    # ---- machine ----

    def _next_ident(self) -> int:
        self._ident = (self._ident + 1) & 0xFF
        return self._ident

    def _send_conf_req(self, now: float) -> None:
        self._req_ident = self._next_ident()
        self.out.append(CPPacket(CP_CONF_REQ, self._req_ident,
                                 options=self.own_options()))
        self._retries += 1
        self._next_resend = now + self.restart_interval

    def open(self, now: float) -> None:
        """Lower layer is up and we want the protocol open (This-Layer-Start)."""
        if self.state in (CLOSED, CLOSING):
            self._retries = 0
            self._send_conf_req(now)
            self.state = REQ_SENT

    def close(self, now: float, send_term: bool = True) -> None:
        if self.state == OPENED and send_term:
            self.out.append(CPPacket(CP_TERM_REQ, self._next_ident()))
            self.state = CLOSING
            self._next_resend = now + self.restart_interval
            self._retries = 0
        else:
            self._to_closed()

    def _to_closed(self) -> None:
        was_open = self.state == OPENED
        self.state = CLOSED
        if was_open and self.on_close:
            self.on_close()

    def _this_layer_up(self) -> None:
        self.state = OPENED
        if self.on_open:
            self.on_open()

    def tick(self, now: float) -> None:
        """Retransmit timers (RFC 1661 §4.6)."""
        if self.state in (REQ_SENT, ACK_RCVD, ACK_SENT) and now >= self._next_resend:
            if self._retries >= self.max_configure:
                self._to_closed()
            else:
                self._send_conf_req(now)
                if self.state == ACK_RCVD:
                    self.state = REQ_SENT  # ack applies to the old request
        elif self.state == CLOSING and now >= self._next_resend:
            if self._retries >= DEFAULT_MAX_TERMINATE:
                self._to_closed()
            else:
                self.out.append(CPPacket(CP_TERM_REQ, self._next_ident()))
                self._retries += 1
                self._next_resend = now + self.restart_interval

    def handle(self, pkt: CPPacket, now: float) -> None:
        code = pkt.code
        if code == CP_CONF_REQ:
            self._rcv_conf_req(pkt, now)
        elif code == CP_CONF_ACK:
            if pkt.identifier != self._req_ident:
                return  # stale ack
            self.peer_acked(pkt.options)
            if self.state == REQ_SENT:
                self.state = ACK_RCVD
            elif self.state == ACK_SENT:
                self._this_layer_up()
        elif code in (CP_CONF_NAK, CP_CONF_REJ):
            if pkt.identifier != self._req_ident:
                return
            if code == CP_CONF_NAK:
                self.peer_naked(pkt.options)
            else:
                self.peer_rejected(pkt.options)
            if self.state in (REQ_SENT, ACK_RCVD, ACK_SENT):
                self._send_conf_req(now)
                if self.state == ACK_RCVD:
                    self.state = REQ_SENT
        elif code == CP_TERM_REQ:
            self.out.append(CPPacket(CP_TERM_ACK, pkt.identifier))
            self._to_closed()
        elif code == CP_TERM_ACK:
            if self.state == CLOSING:
                self._to_closed()
        elif code == CP_ECHO_REQ:
            if self.state == OPENED:
                # magic number in data[:4] is ours in the reply
                self.out.append(CPPacket(CP_ECHO_REP, pkt.identifier,
                                         data=pkt.data))
        elif code in (CP_ECHO_REP, CP_DISCARD_REQ, CP_CODE_REJ):
            pass  # echo replies handled by keepalive layer; others ignored
        else:
            self.out.append(CPPacket(CP_CODE_REJ, self._next_ident(),
                                     data=pkt.encode()[:64]))

    def _rcv_conf_req(self, pkt: CPPacket, now: float) -> None:
        ack, nak, rej = self.check_peer_options(pkt.options)
        if rej:
            self.out.append(CPPacket(CP_CONF_REJ, pkt.identifier, options=rej))
            return
        if nak:
            self.out.append(CPPacket(CP_CONF_NAK, pkt.identifier, options=nak))
            return
        self.out.append(CPPacket(CP_CONF_ACK, pkt.identifier, options=ack))
        if self.state == CLOSED:
            # peer raced ahead of our open(); start our side too
            self._retries = 0
            self._send_conf_req(now)
            self.state = ACK_SENT
        elif self.state == REQ_SENT:
            self.state = ACK_SENT
        elif self.state == ACK_RCVD:
            self._this_layer_up()
        # ACK_SENT/OPENED: re-ack is fine
