"""PPPoE access-concentrator server.

Parity: pkg/pppoe/server.go — receiveLoop dispatch (:263-301), discovery
handlers PADI->PADO / PADR->PADS / PADT (:303-464), session dispatch by
PPP protocol (:466-499), LCP->auth->IPCP progression (:531-852), and
keepalive.go's echo loop (:218-310).

Differences by design (TPU build): no raw socket — the server consumes
ethernet frames from the engine's PASS lanes and returns frames to
transmit; all timing is tick(now)-driven (no goroutines).
"""

from __future__ import annotations

import hashlib
import hmac
import os
import struct
from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.control.pppoe import codec
from bng_tpu.control.pppoe.auth import (
    CHAP_RESPONSE,
    AuthResult,
    CHAPHandler,
    CredentialVerifier,
    PAPHandler,
    RateLimiter,
)
from bng_tpu.control.pppoe.codec import (
    CODE_PADI,
    CODE_PADO,
    CODE_PADR,
    CODE_PADS,
    CODE_PADT,
    CODE_SESSION,
    CP_ECHO_REP,
    CP_ECHO_REQ,
    ETH_PPPOE_DISCOVERY,
    ETH_PPPOE_SESSION,
    PROTO_CHAP,
    PROTO_IPCP,
    PROTO_IPV6CP,
    PROTO_IPV4,
    PROTO_LCP,
    PROTO_PAP,
    CPPacket,
    PPPoEPacket,
    Tag,
    eth_frame,
    find_tag,
    parse_eth_vlan,
    parse_ppp,
    parse_tags,
    ppp_frame,
    serialize_tags,
)
from bng_tpu.control.pppoe.ipcp import IPCP
from bng_tpu.control.pppoe.ipv6cp import IPV6CP
from bng_tpu.control.pppoe.lcp import LCP
from bng_tpu.control.pppoe.session import (
    Phase,
    PPPoESession,
    SessionManager,
    TeardownEvent,
    TerminateCause,
)


@dataclass
class PPPoEServerConfig:
    ac_name: str = "bng-tpu"
    service_name: str = ""  # empty = accept any
    server_mac: bytes = b"\x02\xbb\x00\x00\x00\x01"
    our_ip: int = 0x0A000001  # 10.0.0.1, IPCP our side
    dns_primary: int = 0
    dns_secondary: int = 0
    auth_proto: int = PROTO_CHAP  # PROTO_PAP | PROTO_CHAP | 0
    max_sessions: int = 65535
    echo_interval_s: float = 30.0  # keepalive.go defaults
    echo_max_missed: int = 3
    idle_timeout_s: float = 0.0  # 0 = disabled
    session_timeout_s: float = 0.0
    # half-open sessions (PADR done but never reached OPEN) are reclaimed
    # after this long, else stuck LCP/AUTH floods exhaust the table
    setup_timeout_s: float = 60.0
    cookie_secret: bytes = field(default_factory=lambda: os.urandom(16))


@dataclass
class PPPoEStats:
    padi_rx: int = 0
    pado_tx: int = 0
    padr_rx: int = 0
    pads_tx: int = 0
    padt_rx: int = 0
    padt_tx: int = 0
    sessions_opened: int = 0
    sessions_closed: int = 0
    auth_success: int = 0
    auth_failure: int = 0
    data_frames: int = 0


class PPPoEServer:
    """Frames-in/frames-out PPPoE AC."""

    def __init__(self, config: PPPoEServerConfig, verifier: CredentialVerifier,
                 allocate_ip: Callable[[str, bytes], int | None],
                 release_ip: Callable[[int, bytes], None] | None = None,
                 on_open: Callable[[PPPoESession], None] | None = None,
                 on_close: Callable[[TeardownEvent], None] | None = None,
                 magic_source: Callable[[], int] | None = None,
                 challenge_source: Callable[[], bytes] | None = None):
        self.config = config
        self.sessions = SessionManager(config.max_sessions)
        self.stats = PPPoEStats()
        self.allocate_ip = allocate_ip
        self.release_ip = release_ip
        self.on_open = on_open
        self.on_close = on_close
        self._magic = magic_source or (
            lambda: int.from_bytes(os.urandom(4), "big"))
        limiter = RateLimiter()
        self.pap = PAPHandler(verifier, limiter=limiter)
        self.chap = CHAPHandler(verifier, ac_name=config.ac_name,
                                challenge_source=challenge_source,
                                limiter=limiter)
        self._limiter = limiter
        self._acct_counter = 0
        self._cur_vlans: list[int] = []

    # ---- frame entry point ----

    def handle_frame(self, frame: bytes, now: float) -> list[bytes]:
        try:
            dst, src, etype, payload, vlans = parse_eth_vlan(frame)
        except ValueError:
            return []
        # replies mirror the request's VLAN stack (single-threaded server;
        # _cur_vlans is valid for the duration of this frame)
        self._cur_vlans = vlans
        if etype == ETH_PPPOE_DISCOVERY:
            return self._handle_discovery(src, payload, now)
        if etype == ETH_PPPOE_SESSION:
            return self._handle_session(src, payload, now)
        return []

    # ---- discovery (server.go:303-464) ----

    def _cookie_for(self, mac: bytes) -> bytes:
        return hmac.new(self.config.cookie_secret, mac, hashlib.sha256).digest()[:16]

    def _discovery_reply(self, code: int, dst: bytes, session_id: int,
                         tags: list[Tag], vlans: list[int] | None = None) -> bytes:
        pkt = PPPoEPacket(code=code, session_id=session_id,
                          payload=serialize_tags(tags))
        return eth_frame(dst, self.config.server_mac, ETH_PPPOE_DISCOVERY,
                         pkt.encode(),
                         vlans=vlans if vlans is not None else self._cur_vlans)

    def _handle_discovery(self, src: bytes, payload: bytes, now: float
                          ) -> list[bytes]:
        try:
            pkt = PPPoEPacket.decode(payload)
            tags = parse_tags(pkt.payload)
        except ValueError:
            return []
        if pkt.code == CODE_PADI:
            self.stats.padi_rx += 1
            svc = find_tag(tags, codec.TAG_SERVICE_NAME)
            if (self.config.service_name and svc and svc.value
                    and svc.value.decode("utf-8", "replace") != self.config.service_name):
                err = [Tag(codec.TAG_SERVICE_NAME_ERR,
                           b"service not offered")]
                return [self._discovery_reply(CODE_PADO, src, 0, err)]
            out = [Tag(codec.TAG_AC_NAME, self.config.ac_name.encode()),
                   Tag(codec.TAG_SERVICE_NAME, svc.value if svc else b""),
                   Tag(codec.TAG_AC_COOKIE, self._cookie_for(src))]
            hu = find_tag(tags, codec.TAG_HOST_UNIQ)
            if hu:
                out.append(hu)
            self.stats.pado_tx += 1
            return [self._discovery_reply(CODE_PADO, src, 0, out)]
        if pkt.code == CODE_PADR:
            self.stats.padr_rx += 1
            cookie = find_tag(tags, codec.TAG_AC_COOKIE)
            if cookie is None or not hmac.compare_digest(
                    cookie.value, self._cookie_for(src)):
                err = [Tag(codec.TAG_GENERIC_ERR, b"bad AC-Cookie")]
                return [self._discovery_reply(CODE_PADS, src, 0, err)]
            # re-dial from a MAC with a live session: tear the old one
            # down properly (IP release + accounting stop) before replacing
            old = self.sessions.by_mac(src)
            if old is not None:
                self._close_session(old, TerminateCause.LOST_CARRIER, now,
                                    send_padt=False)
            sess = self.sessions.allocate(src, now)
            if sess is None:
                err = [Tag(codec.TAG_AC_SYSTEM_ERR, b"session table full")]
                return [self._discovery_reply(CODE_PADS, src, 0, err)]
            self._acct_counter += 1
            sess.acct_session_id = f"pppoe-{sess.session_id:04x}-{self._acct_counter}"
            sess.lcp = LCP(magic=self._magic(), auth_proto=self.config.auth_proto)
            sess.phase = Phase.LCP
            sess.vlans = list(self._cur_vlans)
            out = [Tag(codec.TAG_AC_NAME, self.config.ac_name.encode()),
                   Tag(codec.TAG_SERVICE_NAME, b"")]
            hu = find_tag(tags, codec.TAG_HOST_UNIQ)
            if hu:
                out.append(hu)
            self.stats.pads_tx += 1
            frames = [self._discovery_reply(CODE_PADS, src, sess.session_id, out)]
            sess.lcp.open(now)
            frames += self._drain_cp(sess, sess.lcp)
            return frames
        if pkt.code == CODE_PADT:
            self.stats.padt_rx += 1
            sess = self.sessions.get(pkt.session_id)
            if sess is not None and sess.client_mac == src:
                self._close_session(sess, TerminateCause.USER_REQUEST, now,
                                    send_padt=False)
            return []
        return []

    # ---- session phase (server.go:466-852) ----

    def _session_frame(self, sess: PPPoESession, proto: int, body: bytes) -> bytes:
        pkt = PPPoEPacket(code=CODE_SESSION, session_id=sess.session_id,
                          payload=ppp_frame(proto, body))
        return eth_frame(sess.client_mac, self.config.server_mac,
                         ETH_PPPOE_SESSION, pkt.encode(), vlans=sess.vlans)

    def _drain_cp(self, sess: PPPoESession, fsm) -> list[bytes]:
        frames = []
        while fsm.out:
            cp = fsm.out.pop(0)
            frames.append(self._session_frame(sess, fsm.proto, cp.encode()))
        return frames

    def _handle_session(self, src: bytes, payload: bytes, now: float
                        ) -> list[bytes]:
        try:
            pkt = PPPoEPacket.decode(payload)
        except ValueError:
            return []
        if pkt.code != CODE_SESSION:
            return []
        sess = self.sessions.get(pkt.session_id)
        if sess is None or sess.client_mac != src:
            # unknown session: PADT (server.go behavior for stale sessions)
            self.stats.padt_tx += 1
            return [self._discovery_reply(CODE_PADT, src, pkt.session_id,
                                          [Tag(codec.TAG_GENERIC_ERR,
                                               b"unknown session")])]
        try:
            proto, body = parse_ppp(pkt.payload)
        except ValueError:
            return []
        sess.touch(now)
        if proto == PROTO_LCP:
            return self._handle_lcp(sess, body, now)
        if proto == PROTO_PAP and sess.phase == Phase.AUTH:
            return self._handle_pap(sess, body, now)
        if proto == PROTO_CHAP and sess.phase == Phase.AUTH:
            return self._handle_chap(sess, body, now)
        if proto == PROTO_IPCP and sess.ipcp is not None:
            try:
                cp = CPPacket.decode(body)
            except ValueError:
                return []
            sess.ipcp.handle(cp, now)
            return self._drain_cp(sess, sess.ipcp)
        if proto == PROTO_IPV6CP and sess.ipv6cp is not None:
            try:
                cp = CPPacket.decode(body)
            except ValueError:
                return []
            sess.ipv6cp.handle(cp, now)
            return self._drain_cp(sess, sess.ipv6cp)
        if proto in (PROTO_IPV4, codec.PROTO_IPV6):
            self.stats.data_frames += 1
            return []  # data path is the device pipeline's job
        # Protocol-Reject (RFC 1661 §5.7)
        if sess.lcp is not None and sess.lcp.state == "opened":
            rej = CPPacket(codec.CP_PROTO_REJ, 0,
                           data=struct.pack(">H", proto) + body[:64])
            return [self._session_frame(sess, PROTO_LCP, rej.encode())]
        return []

    def _handle_lcp(self, sess: PPPoESession, body: bytes, now: float
                    ) -> list[bytes]:
        if sess.lcp is None:
            return []
        try:
            cp = CPPacket.decode(body)
        except ValueError:
            return []
        if cp.code == CP_ECHO_REP:
            sess.echo_pending = 0
            return []
        was_open = sess.lcp.state == "opened"
        sess.lcp.handle(cp, now)
        frames = self._drain_cp(sess, sess.lcp)
        if sess.lcp.state == "opened" and not was_open:
            frames += self._start_auth(sess, now)
        elif was_open and sess.lcp.state == "closed":
            self._close_session(sess, TerminateCause.USER_REQUEST, now,
                                send_padt=True)
        return frames

    def _start_auth(self, sess: PPPoESession, now: float) -> list[bytes]:
        auth = sess.lcp.auth_proto if sess.lcp else 0
        if auth == 0:
            return self._start_network(sess, "", AuthResult(ok=True), now)
        sess.phase = Phase.AUTH
        if auth == PROTO_CHAP:
            sess.chap_ident = (sess.chap_ident + 1) & 0xFF or 1
            sess.chap_challenge, pkt = self.chap.make_challenge(sess.chap_ident)
            return [self._session_frame(sess, PROTO_CHAP, pkt)]
        return []  # PAP: wait for the client's Auth-Request

    def _auth_done(self, sess: PPPoESession, res: AuthResult, now: float
                   ) -> list[bytes]:
        if not res.ok:
            self.stats.auth_failure += 1
            return self._terminate_frames(sess, TerminateCause.USER_ERROR, now)
        self.stats.auth_success += 1
        # a successful auth clears the attempt budget so legitimately
        # flapping clients are not locked out (limiter counts failures)
        self._limiter.reset(sess.client_mac.hex())
        return self._start_network(sess, res.username, res, now)

    def _handle_pap(self, sess: PPPoESession, body: bytes, now: float
                    ) -> list[bytes]:
        key = sess.client_mac.hex()
        reply, res = self.pap.handle(body, key, now)
        if reply is None:
            return []  # malformed frame: ignore, client will retransmit
        return [self._session_frame(sess, PROTO_PAP, reply)] + \
            self._auth_done(sess, res, now)

    def _handle_chap(self, sess: PPPoESession, body: bytes, now: float
                     ) -> list[bytes]:
        if len(body) >= 1 and body[0] != CHAP_RESPONSE:
            return []
        key = sess.client_mac.hex()
        reply, res = self.chap.handle_response(body, sess.chap_challenge,
                                               key, now)
        if reply is None:
            return []  # malformed frame: ignore, client will retransmit
        return [self._session_frame(sess, PROTO_CHAP, reply)] + \
            self._auth_done(sess, res, now)

    def _start_network(self, sess: PPPoESession, username: str,
                       res: AuthResult, now: float) -> list[bytes]:
        ip = res.attributes.get("framed_ip") or self.allocate_ip(
            username, sess.client_mac)
        if ip is None:
            return self._terminate_frames(sess, TerminateCause.SERVICE_UNAVAILABLE,
                                          now)
        sess.username = username
        sess.assigned_ip = ip
        sess.radius_attributes = res.attributes
        sess.phase = Phase.NETWORK

        def opened():
            if sess.phase != Phase.OPEN:
                sess.phase = Phase.OPEN
                self.stats.sessions_opened += 1
                if self.on_open:
                    self.on_open(sess)

        sess.ipcp = IPCP(our_ip=self.config.our_ip, client_ip=ip,
                         dns_primary=self.config.dns_primary,
                         dns_secondary=self.config.dns_secondary,
                         on_open=opened)
        # IID from MACs (EUI-64-ish, locally administered)
        sess.ipv6cp = IPV6CP(
            our_iid=self.config.server_mac[:3] + b"\xff\xfe" + self.config.server_mac[3:],
            client_iid=sess.client_mac[:3] + b"\xff\xfe" + sess.client_mac[3:])
        sess.ipcp.open(now)
        sess.ipv6cp.open(now)
        return self._drain_cp(sess, sess.ipcp) + self._drain_cp(sess, sess.ipv6cp)

    # ---- teardown (teardown.go) ----

    def _terminate_frames(self, sess: PPPoESession, cause: TerminateCause,
                          now: float) -> list[bytes]:
        frames = []
        if sess.lcp is not None and sess.lcp.state == "opened":
            sess.lcp.close(now)
            frames += self._drain_cp(sess, sess.lcp)
        frames += self._close_session(sess, cause, now, send_padt=True)
        return frames

    def _close_session(self, sess: PPPoESession, cause: TerminateCause,
                       now: float, send_padt: bool) -> list[bytes]:
        frames: list[bytes] = []
        if send_padt:
            self.stats.padt_tx += 1
            frames.append(self._discovery_reply(
                CODE_PADT, sess.client_mac, sess.session_id, []))
        removed = self.sessions.remove(sess.session_id)
        if removed is None:
            return frames
        was_open = sess.phase == Phase.OPEN
        sess.terminate_cause = cause
        sess.phase = Phase.CLOSED
        self.stats.sessions_closed += 1
        if sess.assigned_ip and self.release_ip:
            self.release_ip(sess.assigned_ip, sess.client_mac)
        if self.on_close and was_open:
            # accounting/teardown hooks only for sessions that opened:
            # half-open reclaims have no accounting session to stop
            self.on_close(TeardownEvent(session=sess, cause=cause, at=now))
        return frames

    def terminate(self, session_id: int, cause: TerminateCause, now: float
                  ) -> list[bytes]:
        """Admin/NAS-initiated teardown (CoA Disconnect path)."""
        sess = self.sessions.get(session_id)
        if sess is None:
            return []
        return self._terminate_frames(sess, cause, now)

    # ---- tick: keepalive + timeouts (keepalive.go:218-310) ----

    def tick(self, now: float) -> list[bytes]:
        frames: list[bytes] = []
        for sess in self.sessions.all():
            for fsm in (sess.lcp, sess.ipcp, sess.ipv6cp):
                if fsm is not None:
                    fsm.tick(now)
                    frames += self._drain_cp(sess, fsm)
            # reclaim half-open sessions: PADR done but LCP/AUTH/IPCP never
            # completed (or LCP retried out into CLOSED). Without this, a
            # PADI/PADR flood from distinct MACs pins the session table.
            if sess.phase != Phase.OPEN:
                lcp_dead = sess.lcp is not None and sess.lcp.state == "closed" \
                    and sess.phase in (Phase.LCP, Phase.AUTH)
                if lcp_dead or (self.config.setup_timeout_s and
                                now - sess.created_at >= self.config.setup_timeout_s):
                    frames += self._close_session(
                        sess, TerminateCause.LOST_SERVICE, now, send_padt=True)
                continue
            if sess.phase == Phase.OPEN and sess.lcp is not None:
                cfg = self.config
                if cfg.session_timeout_s and \
                        now - sess.created_at >= cfg.session_timeout_s:
                    frames += self._terminate_frames(
                        sess, TerminateCause.SESSION_TIMEOUT, now)
                    continue
                if cfg.idle_timeout_s and \
                        now - sess.last_activity >= cfg.idle_timeout_s:
                    frames += self._terminate_frames(
                        sess, TerminateCause.IDLE_TIMEOUT, now)
                    continue
                if now - sess.last_echo_tx >= cfg.echo_interval_s:
                    if sess.echo_pending >= cfg.echo_max_missed:
                        frames += self._terminate_frames(
                            sess, TerminateCause.LOST_CARRIER, now)
                        continue
                    sess.echo_ident = (sess.echo_ident + 1) & 0xFF
                    sess.echo_pending += 1
                    sess.last_echo_tx = now
                    echo = CPPacket(CP_ECHO_REQ, sess.echo_ident,
                                    data=struct.pack(">I", sess.lcp.magic))
                    frames.append(self._session_frame(sess, PROTO_LCP,
                                                      echo.encode()))
        return frames
