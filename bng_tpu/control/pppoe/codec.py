"""PPPoE + PPP wire codec.

Parity: pkg/pppoe/protocol.go — PPPoE header/tags (discovery codes,
tag constants :31-40, ParseTags/SerializeTags :162-204) and the PPP
control-protocol packet layout (code, id, length, options) shared by
LCP/IPCP/IPV6CP (lcp.go option codec).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

ETH_PPPOE_DISCOVERY = 0x8863
ETH_PPPOE_SESSION = 0x8864

# PPPoE codes (RFC 2516)
CODE_PADI = 0x09
CODE_PADO = 0x07
CODE_PADR = 0x19
CODE_PADS = 0x65
CODE_PADT = 0xA7
CODE_SESSION = 0x00

# PPPoE tag types (protocol.go:31-40)
TAG_END_OF_LIST = 0x0000
TAG_SERVICE_NAME = 0x0101
TAG_AC_NAME = 0x0102
TAG_HOST_UNIQ = 0x0103
TAG_AC_COOKIE = 0x0104
TAG_VENDOR_SPECIFIC = 0x0105
TAG_RELAY_SESSION_ID = 0x0110
TAG_SERVICE_NAME_ERR = 0x0201
TAG_AC_SYSTEM_ERR = 0x0202
TAG_GENERIC_ERR = 0x0203

# PPP protocol numbers
PROTO_IPV4 = 0x0021
PROTO_IPV6 = 0x0057
PROTO_IPCP = 0x8021
PROTO_IPV6CP = 0x8057
PROTO_LCP = 0xC021
PROTO_PAP = 0xC023
PROTO_CHAP = 0xC223

# PPP control-protocol codes (RFC 1661 §5)
CP_CONF_REQ = 1
CP_CONF_ACK = 2
CP_CONF_NAK = 3
CP_CONF_REJ = 4
CP_TERM_REQ = 5
CP_TERM_ACK = 6
CP_CODE_REJ = 7
CP_PROTO_REJ = 8
CP_ECHO_REQ = 9
CP_ECHO_REP = 10
CP_DISCARD_REQ = 11


@dataclass
class Tag:
    type: int
    value: bytes = b""


@dataclass
class PPPoEPacket:
    """One PPPoE frame (after the Ethernet header)."""

    code: int
    session_id: int = 0
    payload: bytes = b""
    ver_type: int = 0x11

    def encode(self) -> bytes:
        return struct.pack(">BBHH", self.ver_type, self.code, self.session_id,
                           len(self.payload)) + self.payload

    @classmethod
    def decode(cls, data: bytes) -> "PPPoEPacket":
        if len(data) < 6:
            raise ValueError("PPPoE header truncated")
        ver_type, code, sid, length = struct.unpack(">BBHH", data[:6])
        if ver_type != 0x11:
            raise ValueError(f"bad PPPoE ver/type {ver_type:#x}")
        if length > len(data) - 6:
            raise ValueError("PPPoE length exceeds frame")
        return cls(code=code, session_id=sid, payload=data[6 : 6 + length],
                   ver_type=ver_type)


def parse_tags(data: bytes) -> list[Tag]:
    """Parity: ParseTags (protocol.go:162-190); stops at End-Of-List."""
    tags: list[Tag] = []
    off = 0
    while off + 4 <= len(data):
        ttype, tlen = struct.unpack(">HH", data[off : off + 4])
        if ttype == TAG_END_OF_LIST:
            break
        off += 4
        if off + tlen > len(data):
            raise ValueError("tag length exceeds payload")
        tags.append(Tag(ttype, data[off : off + tlen]))
        off += tlen
    return tags


def serialize_tags(tags: list[Tag]) -> bytes:
    out = bytearray()
    for t in tags:
        out += struct.pack(">HH", t.type, len(t.value)) + t.value
    return bytes(out)


def find_tag(tags: list[Tag], ttype: int) -> Tag | None:
    for t in tags:
        if t.type == ttype:
            return t
    return None


@dataclass
class CPOption:
    """One LCP/IPCP/IPV6CP option: type, data (TLV with 2-byte overhead)."""

    type: int
    data: bytes = b""

    def encode(self) -> bytes:
        return bytes([self.type, len(self.data) + 2]) + self.data


@dataclass
class CPPacket:
    """PPP control-protocol packet: code, identifier, body.

    For CONF_* codes the body is an option list; for ECHO_*/TERM_* it is
    opaque data (magic number + payload for echoes).
    """

    code: int
    identifier: int
    options: list[CPOption] = field(default_factory=list)
    data: bytes = b""

    def encode(self) -> bytes:
        if self.code in (CP_CONF_REQ, CP_CONF_ACK, CP_CONF_NAK, CP_CONF_REJ):
            body = b"".join(o.encode() for o in self.options)
        else:
            body = self.data
        return struct.pack(">BBH", self.code, self.identifier, len(body) + 4) + body

    @classmethod
    def decode(cls, data: bytes) -> "CPPacket":
        if len(data) < 4:
            raise ValueError("CP packet truncated")
        code, ident, length = struct.unpack(">BBH", data[:4])
        if length < 4 or length > len(data):
            raise ValueError("bad CP length")
        body = data[4:length]
        pkt = cls(code=code, identifier=ident)
        if code in (CP_CONF_REQ, CP_CONF_ACK, CP_CONF_NAK, CP_CONF_REJ):
            off = 0
            while off + 2 <= len(body):
                otype, olen = body[off], body[off + 1]
                if olen < 2 or off + olen > len(body):
                    raise ValueError("bad CP option length")
                pkt.options.append(CPOption(otype, body[off + 2 : off + olen]))
                off += olen
        else:
            pkt.data = body
        return pkt


def ppp_frame(proto: int, body: bytes) -> bytes:
    """PPP payload inside a PPPoE session frame (no HDLC framing on PPPoE)."""
    return struct.pack(">H", proto) + body


def parse_ppp(payload: bytes) -> tuple[int, bytes]:
    if len(payload) < 2:
        raise ValueError("PPP payload truncated")
    return struct.unpack(">H", payload[:2])[0], payload[2:]


ETH_P_8021Q = 0x8100
ETH_P_8021AD = 0x88A8


def eth_frame(dst: bytes, src: bytes, ethertype: int, payload: bytes,
              vlans: list[int] | None = None) -> bytes:
    """L2 frame; vlans mirror bng_tpu.control.packets.eth_header (QinQ)."""
    hdr = dst + src
    if vlans:
        if len(vlans) == 2:
            hdr += struct.pack(">HH", ETH_P_8021AD, vlans[0])
            hdr += struct.pack(">HH", ETH_P_8021Q, vlans[1])
        else:
            hdr += struct.pack(">HH", ETH_P_8021Q, vlans[0])
    return hdr + struct.pack(">H", ethertype) + payload


def parse_eth(frame: bytes) -> tuple[bytes, bytes, int, bytes]:
    if len(frame) < 14:
        raise ValueError("ethernet frame truncated")
    return frame[0:6], frame[6:12], struct.unpack(">H", frame[12:14])[0], frame[14:]


def parse_eth_vlan(frame: bytes) -> tuple[bytes, bytes, int, bytes, list[int]]:
    """parse_eth that strips 802.1Q/802.1ad tags (subscriber frames are
    typically S/C-tagged; parity with parse_packet_headers'
    VLAN/QinQ handling in the DHCP fast path)."""
    dst, src, etype, payload = parse_eth(frame)
    vlans: list[int] = []
    while etype in (ETH_P_8021Q, ETH_P_8021AD) and len(payload) >= 4:
        tci, etype = struct.unpack(">HH", payload[:4])
        vlans.append(tci & 0x0FFF)
        payload = payload[4:]
    return dst, src, etype, payload, vlans
