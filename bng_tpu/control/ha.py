"""Active/standby HA: session replication, health monitoring, failover.

Parity: pkg/ha — HASyncer (sync.go:77; active serves /sessions full sync
+ /sessions/stream SSE deltas :231-454, standby full-sync + reconnect
with backoff :482-770), SessionState (protocol.go:76-113), SessionStore
(protocol.go:162, store.go:10-62), HealthMonitor (health_monitor.go:79,
:232-415), FailoverController with Normal/FailoverPending/FailedOver/
FailbackPending states and auto-failback (failover.go:137, :305-600).

TPU-build differences: transport is injectable (tests wire two syncers
directly; production uses DCN/HTTP), and all loops are tick(now)-driven.
The role of the standby pod-slice mirroring session tables (SURVEY §2.3).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, asdict
from typing import Callable

from bng_tpu.analysis.sanitize import owned_by
from bng_tpu.chaos.faults import fault_point
from bng_tpu.utils.structlog import ErrorLog


@dataclass
class SessionState:
    """Full subscriber session record (parity: protocol.go:76-113)."""

    session_id: str
    mac: str = ""
    ip: int = 0
    pool_id: int = 0
    circuit_id: str = ""
    username: str = ""
    lease_expiry: float = 0.0
    s_tag: int = 0
    c_tag: int = 0
    nat_public_ip: int = 0
    nat_port_start: int = 0
    nat_port_end: int = 0
    qos_policy: str = ""
    session_kind: str = "ipoe"  # ipoe | pppoe | wifi
    updated_at: float = 0.0

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "SessionState":
        return cls(**{k: v for k, v in d.items() if k in cls.__dataclass_fields__})


class InMemorySessionStore:
    """Parity: ha/store.go:10-62."""

    def __init__(self):
        self._sessions: dict[str, SessionState] = {}

    def put(self, s: SessionState) -> None:
        self._sessions[s.session_id] = s

    def get(self, session_id: str) -> SessionState | None:
        return self._sessions.get(session_id)

    def delete(self, session_id: str) -> bool:
        return self._sessions.pop(session_id, None) is not None

    def all(self) -> list[SessionState]:
        return list(self._sessions.values())

    def __len__(self) -> int:
        return len(self._sessions)


@dataclass
class HAChange:
    """One replication event (the SSE event payload role)."""

    op: str  # "put" | "delete"
    session: SessionState | None = None
    session_id: str = ""
    seq: int = 0


def parse_ha_checkpoint_state(state: dict) -> tuple[int, list[SessionState]]:
    """Checkpoint HA blob -> (seq, SessionState list), touching no
    syncer state. The restore pre-check runs this before any store
    mutation so a corrupt session dict rejects all-or-nothing
    (KeyError/TypeError/ValueError propagate)."""
    sessions = [SessionState.from_dict(d) for d in state.get("sessions", [])]
    return int(state.get("seq", 0)), sessions


class ActiveSyncer:
    """Active side: records changes, serves full syncs + deltas.

    Parity: the active half of HASyncer (sync.go:231-454). Standbys
    subscribe with a callback (the SSE connection role); a bounded replay
    buffer covers reconnect gaps before forcing a full resync.
    """

    def __init__(self, store: InMemorySessionStore, replay_buffer: int = 1024):
        import threading

        self.store = store
        self._seq = 0
        self._replay: list[HAChange] = []
        self._replay_cap = replay_buffer
        self._subscribers: list[Callable[[HAChange], None]] = []
        self.stats = {"changes": 0, "full_syncs": 0, "sink_errors": 0}
        self._sink_err_log = ErrorLog(
            "ha", "replica sink failed; subscriber dropped pending "
            "reconnect full-resync")
        # push_change runs on the main loop; full_sync/replay_since on
        # the cluster listener's HTTP threads. Without this lock a push
        # landing between the snapshot read and the seq read hands a
        # connecting standby "snapshot WITHOUT the session, seq AFTER
        # it" — the delta is skipped and the session is silently absent
        # until its next lifecycle event.
        self._lock = threading.Lock()

    def push_change(self, session: SessionState | None, session_id: str = "") -> None:
        """Parity: HASyncer.PushChange (sync.go:456)."""
        with self._lock:
            return self._push_change_locked(session, session_id)

    def _push_change_locked(self, session, session_id):
        self._seq += 1
        if session is not None:
            self.store.put(session)
            ch = HAChange("put", session=session, seq=self._seq)
        else:
            self.store.delete(session_id)
            ch = HAChange("delete", session_id=session_id, seq=self._seq)
        self._replay.append(ch)
        if len(self._replay) > self._replay_cap:
            self._replay.pop(0)
        self.stats["changes"] += 1
        fp = fault_point("ha.push")
        if fp is not None and fp.kind == "drop_delta":
            # chaos: every replica stream dies mid-event (an SSE
            # connection breaking). The change IS recorded — store +
            # replay buffer — so a reconnecting standby heals via
            # replay_since; only the live delivery is lost.
            self._subscribers.clear()
            return
        for cb in list(self._subscribers):
            # a broken replica sink must never take down the active's
            # session-write path; the subscriber is dropped and will
            # full-resync on reconnect
            try:
                cb(ch)
            except Exception as e:
                self.stats["sink_errors"] += 1
                self._sink_err_log.report(e, seq=ch.seq)
                if cb in self._subscribers:
                    self._subscribers.remove(cb)

    def full_sync(self) -> tuple[list[SessionState], int]:
        """GET /sessions role: snapshot + high-water seq — ATOMIC vs
        push_change (see __init__'s lock note)."""
        with self._lock:
            self.stats["full_syncs"] += 1
            return self.store.all(), self._seq

    def replay_since(self, seq: int) -> list[HAChange] | None:
        """Deltas after `seq`, or None if the gap fell out of the buffer."""
        with self._lock:
            if seq == self._seq:
                return []
            missing = [c for c in self._replay if c.seq > seq]
            if not missing or missing[0].seq != seq + 1:
                return None  # gap: standby must full-sync
            return missing

    # -- checkpoint/warm-restart (control/statestore.py) ---------------
    def checkpoint_state(self) -> dict:
        """Session store + high-water seq, atomically vs push_change —
        the payload a checkpoint carries so a restarted active (or a
        bootstrapping standby) resumes from a consistent cut."""
        with self._lock:
            return {"seq": self._seq,
                    "sessions": [s.to_dict() for s in self.store.all()]}

    parse_checkpoint_state = staticmethod(parse_ha_checkpoint_state)

    def restore_state(self, state: dict) -> int:
        """Hydrate a restarted ACTIVE from a checkpoint. The seq resumes
        at the checkpointed high-water mark so a standby that bootstrapped
        from the same (or older) checkpoint replays forward cleanly; the
        replay buffer starts empty, so any standby further behind gets
        the correct None -> full-resync answer."""
        seq, sessions = parse_ha_checkpoint_state(state)
        with self._lock:
            for s in sessions:
                self.store.put(s)
            self._seq = max(self._seq, seq)
            return len(sessions)

    def subscribe(self, cb: Callable[[HAChange], None]) -> Callable[[], None]:
        self._subscribers.append(cb)

        def cancel():
            if cb in self._subscribers:
                self._subscribers.remove(cb)

        return cancel


@owned_by(None, guard="_lock")
class StandbySyncer:
    """Standby side: full sync then live deltas, reconnect with backoff.

    Parity: standbyLoop (sync.go:495), performFullSync (:538),
    connectToStream (:596). The `transport` returns the active's
    ActiveSyncer-shaped API or raises ConnectionError.

    Thread ownership (BNG060): over the HTTP transport the subscribed
    `_on_change` runs on the SSE reader thread while `tick`
    (reconnect/full-sync) and `checkpoint_state` run on the loop thread
    — `_lock` serializes every touch of the store / `last_seq` /
    `stats`, so a delta can never interleave with a full-sync store
    rebuild or tear a checkpoint snapshot.
    """

    def __init__(self, store: InMemorySessionStore,
                 transport: Callable[[], ActiveSyncer],
                 backoff_initial_s: float = 1.0, backoff_max_s: float = 30.0):
        import threading

        self.store = store
        self.transport = transport
        self.connected = False
        self.last_seq = 0
        self._cancel = None
        self._backoff = backoff_initial_s
        self._backoff_initial = backoff_initial_s
        self._backoff_max = backoff_max_s
        self._next_attempt = 0.0
        self._lock = threading.Lock()
        self.stats = {"full_syncs": 0, "deltas": 0, "reconnects": 0,
                      "bootstraps": 0}

    parse_checkpoint_state = staticmethod(parse_ha_checkpoint_state)

    def bootstrap_state(self, state: dict) -> int:
        """Hydrate from an ActiveSyncer.checkpoint_state() payload BEFORE
        the first connect: the store fills from the snapshot and last_seq
        jumps to its high-water mark, so the first _connect asks
        replay_since(seq) and ships only the delta since the checkpoint —
        full_sync() is the fallback only when the active's replay buffer
        has already wrapped past that seq."""
        seq, sessions = parse_ha_checkpoint_state(state)
        with self._lock:
            for s in sessions:
                self.store.put(s)
            self.last_seq = max(self.last_seq, seq)
            self.stats["bootstraps"] += 1
        return len(sessions)

    def checkpoint_state(self) -> dict:
        """Snapshot the standby's replicated view (its own checkpoints
        make a standby restart a local bootstrap instead of a full
        resync off the active). Under _lock: an SSE delta landing
        mid-snapshot would otherwise pair a new session list with the
        old seq (replay would then skip that delta on bootstrap)."""
        with self._lock:
            return {"seq": self.last_seq,
                    "sessions": [s.to_dict() for s in self.store.all()]}

    def _on_change(self, ch: HAChange) -> None:
        # SSE reader thread (HTTP transport) or loop thread (in-process)
        with self._lock:
            if ch.op == "put":
                self.store.put(ch.session)
            else:
                self.store.delete(ch.session_id)
            self.last_seq = ch.seq
            self.stats["deltas"] += 1

    def _connect(self) -> None:
        fp = fault_point("ha.connect")
        if fp is not None and fp.kind == "fail":
            # chaos: peer timeout — tick()'s backoff path owns recovery
            raise ConnectionError("chaos: injected peer timeout")
        active = self.transport()  # raises ConnectionError when active is down
        replay = active.replay_since(self.last_seq) if self.last_seq else None
        if replay is None:
            sessions, seq = active.full_sync()
            with self._lock:
                self.store._sessions = {s.session_id: s for s in sessions}
                self.last_seq = seq
                self.stats["full_syncs"] += 1
        else:
            for ch in replay:
                self._on_change(ch)
        # Ordering against the stream dying instantly: subscribe()
        # starts the reader thread, whose on_stream_end fires
        # disconnect() possibly BEFORE we return here. `connected`
        # must therefore be set True BEFORE subscribe — then an
        # immediate drop's disconnect() lands after and leaves it
        # False (tick reconnects), instead of us overwriting the drop
        # with a wedged True for a dead stream.
        with self._lock:
            self.connected = True
            self._backoff = self._backoff_initial
        try:
            cancel = active.subscribe(self._on_change)
        except BaseException:
            # a subscribe that never opened must not leave `connected`
            # True — tick()'s backoff owns the retry
            with self._lock:
                self.connected = False
            raise
        with self._lock:
            self._cancel = cancel

    def disconnect(self) -> None:
        # runs on the SSE reader thread too (cli wires it as the HTTP
        # transport's on_stream_end) — _cancel/connected are the same
        # fields the loop's tick/_connect write, so take _lock here as
        # well; unlocked this both races the reconnect path and trips
        # the @owned_by stamp in sanitizer runs, wedging `connected`
        # True forever after a stream drop
        with self._lock:
            if self._cancel:
                self._cancel()
                self._cancel = None
            self.connected = False

    def tick(self, now: float) -> None:
        if self.connected:
            return
        if now < self._next_attempt:
            return
        try:
            self._connect()
            with self._lock:
                self.stats["reconnects"] += 1
        except ConnectionError:
            self._next_attempt = now + self._backoff
            self._backoff = min(self._backoff * 2, self._backoff_max)


# ---------------------------------------------------------------------------
class HealthState(str, enum.Enum):
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    FAILED = "failed"


@dataclass
class HealthEvent:
    state: HealthState
    at: float
    consecutive_failures: int = 0


class HealthMonitor:
    """Probe the peer with failure/recovery thresholds.

    Parity: health_monitor.go:79,232-415 — 1s HTTP probes, N consecutive
    failures -> FAILED, M consecutive successes -> HEALTHY.
    """

    def __init__(self, probe: Callable[[], bool], interval_s: float = 1.0,
                 failure_threshold: int = 3, recovery_threshold: int = 2,
                 on_event: Callable[[HealthEvent], None] | None = None):
        self.probe = probe
        self.interval_s = interval_s
        self.failure_threshold = failure_threshold
        self.recovery_threshold = recovery_threshold
        self.on_event = on_event
        self.state = HealthState.HEALTHY
        self._fails = 0
        self._oks = 0
        self._last_check = 0.0
        self._probe_err_log = ErrorLog("ha", "health probe raised")

    def tick(self, now: float) -> HealthState:
        if now - self._last_check < self.interval_s:
            return self.state
        self._last_check = now
        ok = False
        try:
            ok = bool(self.probe())
        except Exception as e:
            # a RAISING probe is a distinct gray-failure signal from a
            # clean False — log it (rate-limited) before folding to fail
            self._probe_err_log.report(e)
        if ok:
            self._oks += 1
            self._fails = 0
            if self.state == HealthState.FAILED:
                if self._oks >= self.recovery_threshold:
                    self._emit(HealthState.HEALTHY, now)
            elif self.state == HealthState.DEGRADED:
                self.state = HealthState.HEALTHY
        else:
            self._fails += 1
            self._oks = 0
            if self.state != HealthState.FAILED:
                if self._fails >= self.failure_threshold:
                    self._emit(HealthState.FAILED, now)
                else:
                    self.state = HealthState.DEGRADED
        return self.state

    def _emit(self, state: HealthState, now: float) -> None:
        self.state = state
        if self.on_event:
            self.on_event(HealthEvent(state, now, self._fails))


class FailoverState(str, enum.Enum):
    """Parity: failover.go:137 states."""

    NORMAL = "normal"
    FAILOVER_PENDING = "failover_pending"
    FAILED_OVER = "failed_over"
    FAILBACK_PENDING = "failback_pending"


class Role(str, enum.Enum):
    ACTIVE = "active"
    STANDBY = "standby"


class FailoverController:
    """Standby-side promote/failback state machine.

    Parity: failover.go:305-600 — health events drive NORMAL ->
    FAILOVER_PENDING (grace delay) -> FAILED_OVER (promote, role-change
    callback); peer recovery + auto-failback drives FAILED_OVER ->
    FAILBACK_PENDING (stability window) -> NORMAL (demote).
    """

    def __init__(self, role: Role = Role.STANDBY,
                 failover_delay_s: float = 5.0,
                 failback_delay_s: float = 30.0,
                 auto_failback: bool = True,
                 on_role_change: Callable[[Role], None] | None = None):
        self.role = role
        self.state = FailoverState.NORMAL
        self.failover_delay_s = failover_delay_s
        self.failback_delay_s = failback_delay_s
        self.auto_failback = auto_failback
        self.on_role_change = on_role_change
        self._pending_since = 0.0
        self.stats = {"failovers": 0, "failbacks": 0}

    def handle_health_event(self, ev: HealthEvent) -> None:
        """Parity: handleHealthEvent (failover.go:322)."""
        if self.role != Role.STANDBY and self.state not in (
                FailoverState.FAILED_OVER, FailoverState.FAILBACK_PENDING):
            return
        if ev.state == HealthState.FAILED and self.state == FailoverState.NORMAL:
            self.state = FailoverState.FAILOVER_PENDING
            self._pending_since = ev.at
        elif ev.state == HealthState.FAILED and \
                self.state == FailoverState.FAILBACK_PENDING:
            # peer died again before failback completed: stay active
            self.state = FailoverState.FAILED_OVER
        elif ev.state == HealthState.HEALTHY:
            if self.state == FailoverState.FAILOVER_PENDING:
                self.state = FailoverState.NORMAL  # peer came back in time
            elif self.state == FailoverState.FAILED_OVER and self.auto_failback:
                self.state = FailoverState.FAILBACK_PENDING
                self._pending_since = ev.at

    def tick(self, now: float) -> None:
        if self.state == FailoverState.FAILOVER_PENDING and \
                now - self._pending_since >= self.failover_delay_s:
            self._promote()
        elif self.state == FailoverState.FAILBACK_PENDING and \
                now - self._pending_since >= self.failback_delay_s:
            self._demote()

    def _promote(self) -> None:
        """executeFailover (failover.go:400-500)."""
        self.state = FailoverState.FAILED_OVER
        self.role = Role.ACTIVE
        self.stats["failovers"] += 1
        if self.on_role_change:
            self.on_role_change(Role.ACTIVE)

    def _demote(self) -> None:
        self.state = FailoverState.NORMAL
        self.role = Role.STANDBY
        self.stats["failbacks"] += 1
        if self.on_role_change:
            self.on_role_change(Role.STANDBY)

    def force_failover(self) -> None:
        """Operator-initiated (failover.go manual path)."""
        self._promote()

    def force_failback(self) -> None:
        self._demote()
