"""DHCPv6 server: IA_NA address pool + IA_PD prefix delegation.

Parity: pkg/dhcpv6/server.go — Server + handleMessage dispatch
(:18, :420-447), AddressPool/PrefixPool (:196-352),
buildAdvertise/buildReply (:726-966), DUID generation (:1028).

Message I/O is bytes-in/bytes-out: the transport (UDP :547 or the
engine's PASS lanes) hands the server a message payload + client source;
the server returns the reply payload.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.control.dhcpv6 import protocol as p6
from bng_tpu.control.dhcpv6.protocol import (
    DHCPv6Message,
    IAAddress,
    IANA,
    IAPD,
    IAPrefix,
    generate_duid_ll,
)
from bng_tpu.utils.structlog import ErrorLog


class PoolExhausted6(Exception):
    pass


@dataclass
class Lease6:
    duid: bytes
    iaid: int
    address: bytes  # 16B (IA_NA) or prefix (IA_PD)
    prefix_len: int  # 128 for addresses
    expiry: float
    is_pd: bool = False


class AddressPool6:
    """Sequential /64+ address pool (parity: server.go:196-266)."""

    def __init__(self, prefix: str, preferred_lifetime: int = 3600,
                 valid_lifetime: int = 7200):
        self.net = ipaddress.IPv6Network(prefix)
        self.preferred = preferred_lifetime
        self.valid = valid_lifetime
        self._next = 1
        self._free: list[int] = []
        self._allocated: dict[bytes, int] = {}  # address -> offset

    @property
    def size(self) -> int:
        return min(self.net.num_addresses - 1, 1 << 20)

    def allocate(self) -> bytes:
        if self._free:
            off = self._free.pop()
        elif self._next < self.size:
            off = self._next
            self._next += 1
        else:
            raise PoolExhausted6(str(self.net))
        addr = (int(self.net.network_address) + off).to_bytes(16, "big")
        self._allocated[addr] = off
        return addr

    def allocate_specific(self, addr: bytes) -> bool:
        if addr in self._allocated:
            return True
        ip = int.from_bytes(addr, "big")
        off = ip - int(self.net.network_address)
        if not (0 < off < self.size):
            return False
        self._allocated[addr] = off
        self._free = [f for f in self._free if f != off]
        if off >= self._next:
            self._next = off + 1
        return True

    def release(self, addr: bytes) -> None:
        off = self._allocated.pop(addr, None)
        if off is not None:
            self._free.append(off)

    def contains(self, addr: bytes) -> bool:
        return ipaddress.IPv6Address(int.from_bytes(addr, "big")) in self.net


class PrefixPool6:
    """Delegated-prefix pool: carve /N children from a parent prefix
    (parity: server.go:268-352)."""

    def __init__(self, parent: str, delegated_len: int = 56,
                 preferred_lifetime: int = 3600, valid_lifetime: int = 7200):
        self.net = ipaddress.IPv6Network(parent)
        if delegated_len <= self.net.prefixlen:
            raise ValueError("delegated length must be longer than parent")
        self.dlen = delegated_len
        self.preferred = preferred_lifetime
        self.valid = valid_lifetime
        self._next = 0
        self._free: list[int] = []
        self._allocated: dict[bytes, int] = {}
        self.capacity = 1 << (delegated_len - self.net.prefixlen)

    def allocate(self) -> tuple[bytes, int]:
        if self._free:
            idx = self._free.pop()
        elif self._next < self.capacity:
            idx = self._next
            self._next += 1
        else:
            raise PoolExhausted6(str(self.net))
        base = int(self.net.network_address) + (idx << (128 - self.dlen))
        prefix = base.to_bytes(16, "big")
        self._allocated[prefix] = idx
        return prefix, self.dlen

    def release(self, prefix: bytes) -> None:
        idx = self._allocated.pop(prefix, None)
        if idx is not None:
            self._free.append(idx)


@dataclass
class DHCPv6ServerConfig:
    server_mac: bytes = b"\x02\xbb\x00\x00\x00\x01"
    # Reply-source address for framed (on-wire) replies. Empty -> the
    # demux derives the EUI-64 link-local from server_mac (the reference
    # replies from its real bound address, server.go:18; relays need a
    # non-placeholder source or they drop the Relay-Reply).
    server_ip6: bytes = b""
    dns_servers: list[bytes] = field(default_factory=list)  # 16B each
    domain_list: list[str] = field(default_factory=list)
    preference: int = 0
    rapid_commit: bool = True
    t1_fraction: float = 0.5  # T1 = valid * 0.5 (RFC 8415 §21.4 guidance)
    t2_fraction: float = 0.8


@dataclass
class DHCPv6Stats:
    solicit: int = 0
    advertise: int = 0
    request: int = 0
    reply: int = 0
    renew: int = 0
    rebind: int = 0
    release: int = 0
    decline: int = 0
    confirm: int = 0
    info_request: int = 0
    no_addrs: int = 0
    no_binding: int = 0
    relay_forw: int = 0
    relay_repl: int = 0
    # exhaustion split out of no_addrs (which also counts "no pool
    # configured"): an EXHAUSTED pool is a capacity event worth its own
    # counter + rate-limited log, not a config state
    addr_exhausted: int = 0
    pd_exhausted: int = 0


class DHCPv6Server:
    def __init__(self, config: DHCPv6ServerConfig,
                 address_pool: AddressPool6 | None = None,
                 prefix_pool: PrefixPool6 | None = None,
                 clock: Callable[[], float] | None = None,
                 on_lease: Callable[[Lease6], None] | None = None,
                 on_release: Callable[[Lease6], None] | None = None):
        import time

        self.config = config
        self.duid = generate_duid_ll(config.server_mac)
        self.addr_pool = address_pool
        self.prefix_pool = prefix_pool
        self.clock = clock or time.time
        self.on_lease = on_lease
        self.on_release = on_release
        self.stats = DHCPv6Stats()
        # bindings: (duid, iaid, is_pd) -> Lease6
        self.leases: dict[tuple[bytes, int, bool], Lease6] = {}
        self._exhaust_log = ErrorLog(
            "dhcpv6-pool",
            "DHCPv6 pool exhausted — NoAddrsAvail/NoPrefixAvail returned")

    MAX_RELAY_HOPS = 8  # RFC 8415 §7.6 HOP_COUNT_LIMIT (8; RFC 3315's 32 is obsolete)

    # ------------------------------------------------------------------
    def handle_message(self, raw: bytes) -> bytes | None:
        """Dispatch (parity: handleMessage, server.go:420-447). A
        Relay-Forward chain (RFC 8415 §19) is unwrapped to the client
        message and the reply re-wrapped in matching Relay-Replies —
        hop/link/peer copied, Interface-Id echoed verbatim."""
        if raw and raw[0] == p6.RELAY_FORW:
            return self._handle_relay(raw, depth=0)
        try:
            msg = DHCPv6Message.decode(raw)
        except ValueError:
            return None
        if msg.client_duid is None and msg.msg_type != p6.INFORMATION_REQUEST:
            return None
        # RFC 8415 §16: REQUEST/RENEW/RELEASE/DECLINE must carry OUR
        # Server Identifier — another server's Request is discarded
        # (REBIND/CONFIRM/SOLICIT/INFO-REQ have no such requirement)
        if msg.msg_type in (p6.REQUEST, p6.RENEW, p6.RELEASE, p6.DECLINE):
            if msg.server_duid != self.duid.encode():
                return None
        handler = {
            p6.SOLICIT: self._solicit,
            p6.REQUEST: self._request,
            p6.CONFIRM: self._confirm,
            p6.RENEW: self._renew,
            p6.REBIND: self._rebind,
            p6.RELEASE: self._release,
            p6.DECLINE: self._decline,
            p6.INFORMATION_REQUEST: self._info_request,
        }.get(msg.msg_type)
        if handler is None:
            return None
        reply = handler(msg)
        return reply.encode() if reply is not None else None

    def _handle_relay(self, raw: bytes, depth: int) -> bytes | None:
        """Relay-Forward -> process nested message -> Relay-Reply.

        Handles relay chains recursively (relay-of-relay), bounded at
        MAX_RELAY_HOPS. The reply mirrors each level's hop-count,
        link-address and peer-address, and echoes Interface-Id so the
        relay can map the reply to the client-facing interface."""
        if depth >= self.MAX_RELAY_HOPS:
            return None
        try:
            fwd = p6.RelayMessage.decode(raw)
        except ValueError:
            return None
        inner = fwd.get(p6.OPT_RELAY_MSG)
        if not inner:
            return None
        self.stats.relay_forw += 1
        if inner[0] == p6.RELAY_FORW:
            inner_reply = self._handle_relay(inner, depth + 1)
        else:
            inner_reply = self.handle_message(inner)
        if inner_reply is None:
            return None
        reply = p6.RelayMessage(p6.RELAY_REPL, fwd.hop_count,
                                fwd.link_address, fwd.peer_address)
        iface_id = fwd.get(p6.OPT_INTERFACE_ID)
        if iface_id is not None:
            reply.options.append((p6.OPT_INTERFACE_ID, iface_id))
        reply.options.append((p6.OPT_RELAY_MSG, inner_reply))
        self.stats.relay_repl += 1
        return reply.encode()

    # ------------------------------------------------------------------
    def _base_reply(self, msg: DHCPv6Message, msg_type: int) -> DHCPv6Message:
        r = DHCPv6Message(msg_type, msg.transaction_id)
        r.add(p6.OPT_SERVERID, self.duid.encode())
        if msg.client_duid is not None:
            r.add(p6.OPT_CLIENTID, msg.client_duid)
        if self.config.preference and msg_type == p6.ADVERTISE:
            r.add(p6.OPT_PREFERENCE, bytes([self.config.preference]))
        return r

    def _add_global_options(self, r: DHCPv6Message) -> None:
        if self.config.dns_servers:
            r.add(p6.OPT_DNS_SERVERS, b"".join(self.config.dns_servers))
        if self.config.domain_list:
            out = bytearray()
            for d in self.config.domain_list:
                for label in d.rstrip(".").split("."):
                    out += bytes([len(label)]) + label.encode()
                out += b"\x00"
            r.add(p6.OPT_DOMAIN_LIST, bytes(out))

    def _t12(self, valid: int) -> tuple[int, int]:
        return (int(valid * self.config.t1_fraction),
                int(valid * self.config.t2_fraction))

    def _grant_na(self, duid: bytes, ia: IANA, commit: bool) -> IANA:
        """Allocate (or look up) an address for one IA_NA."""
        if self.addr_pool is None:
            out = IANA(ia.iaid)
            out.status = (p6.STATUS_NO_ADDRS_AVAIL, "no address pool")
            self.stats.no_addrs += 1
            return out
        key = (duid, ia.iaid, False)
        lease = self.leases.get(key)
        now = self.clock()
        pool = self.addr_pool
        if lease is None:
            try:
                addr = pool.allocate()
            except PoolExhausted6 as e:
                out = IANA(ia.iaid)
                out.status = (p6.STATUS_NO_ADDRS_AVAIL, "pool exhausted")
                self.stats.no_addrs += 1
                self.stats.addr_exhausted += 1
                self._exhaust_log.report(e, ia="na", iaid=ia.iaid)
                return out
            lease = Lease6(duid, ia.iaid, addr, 128, now + pool.valid)
            if commit:
                self.leases[key] = lease
                if self.on_lease:
                    self.on_lease(lease)
            else:
                pool.release(addr)  # advertise only: do not hold
        else:
            lease.expiry = now + pool.valid
        t1, t2 = self._t12(pool.valid)
        out = IANA(ia.iaid, t1, t2)
        out.addresses.append(IAAddress(lease.address, pool.preferred, pool.valid))
        return out

    def _grant_pd(self, duid: bytes, ia: IAPD, commit: bool) -> IAPD:
        if self.prefix_pool is None:
            out = IAPD(ia.iaid)
            out.status = (p6.STATUS_NO_PREFIX_AVAIL, "no prefix pool")
            self.stats.no_addrs += 1
            return out
        key = (duid, ia.iaid, True)
        lease = self.leases.get(key)
        now = self.clock()
        pool = self.prefix_pool
        if lease is None:
            try:
                prefix, plen = pool.allocate()
            except PoolExhausted6 as e:
                out = IAPD(ia.iaid)
                out.status = (p6.STATUS_NO_PREFIX_AVAIL, "pool exhausted")
                self.stats.no_addrs += 1
                self.stats.pd_exhausted += 1
                self._exhaust_log.report(e, ia="pd", iaid=ia.iaid)
                return out
            lease = Lease6(duid, ia.iaid, prefix, plen, now + pool.valid, is_pd=True)
            if commit:
                self.leases[key] = lease
                if self.on_lease:
                    self.on_lease(lease)
            else:
                pool.release(prefix)
        else:
            lease.expiry = now + pool.valid
        t1, t2 = self._t12(pool.valid)
        out = IAPD(ia.iaid, t1, t2)
        out.prefixes.append(IAPrefix(lease.address, lease.prefix_len,
                                     pool.preferred, pool.valid))
        return out

    # ------------------------------------------------------------------
    def _solicit(self, msg: DHCPv6Message) -> DHCPv6Message:
        """SOLICIT -> ADVERTISE (or REPLY with rapid commit);
        parity: buildAdvertise server.go:726-830."""
        self.stats.solicit += 1
        duid = msg.client_duid
        rapid = self.config.rapid_commit and msg.has_rapid_commit()
        r = self._base_reply(msg, p6.REPLY if rapid else p6.ADVERTISE)
        if rapid:
            r.add(p6.OPT_RAPID_COMMIT, b"")
            self.stats.reply += 1
        else:
            self.stats.advertise += 1
        for ia in msg.ia_nas():
            r.add_ia_na(self._grant_na(duid, ia, commit=rapid))
        for ia in msg.ia_pds():
            r.add_ia_pd(self._grant_pd(duid, ia, commit=rapid))
        self._add_global_options(r)
        return r

    def _request(self, msg: DHCPv6Message) -> DHCPv6Message:
        """REQUEST -> REPLY with committed bindings
        (parity: buildReply server.go:832-966)."""
        self.stats.request += 1
        self.stats.reply += 1
        duid = msg.client_duid
        r = self._base_reply(msg, p6.REPLY)
        for ia in msg.ia_nas():
            r.add_ia_na(self._grant_na(duid, ia, commit=True))
        for ia in msg.ia_pds():
            r.add_ia_pd(self._grant_pd(duid, ia, commit=True))
        self._add_global_options(r)
        return r

    def _confirm(self, msg: DHCPv6Message) -> DHCPv6Message:
        """CONFIRM: are the client's addresses still on-link?"""
        self.stats.confirm += 1
        r = self._base_reply(msg, p6.REPLY)
        on_link = True
        for ia in msg.ia_nas():
            for a in ia.addresses:
                if self.addr_pool is None or not self.addr_pool.contains(a.address):
                    on_link = False
        if on_link:
            r.add_status(p6.STATUS_SUCCESS, "all addresses on-link")
        else:
            r.add_status(p6.STATUS_NOT_ON_LINK, "address not on-link")
        return r

    def _extend(self, msg: DHCPv6Message, require_binding: bool) -> DHCPv6Message:
        """RENEW (binding required) / REBIND (recreate allowed)."""
        duid = msg.client_duid
        r = self._base_reply(msg, p6.REPLY)
        now = self.clock()
        for ia in msg.ia_nas():
            key = (duid, ia.iaid, False)
            lease = self.leases.get(key)
            if lease is None:
                if require_binding:
                    out = IANA(ia.iaid)
                    out.status = (p6.STATUS_NO_BINDING, "no binding")
                    self.stats.no_binding += 1
                    r.add_ia_na(out)
                    continue
                # REBIND after state loss: re-confirm the address the
                # client presents if it's ours and free (RFC 8415 §18.3.5)
                kept = self._rebind_keep(duid, ia, now)
                r.add_ia_na(kept if kept is not None
                            else self._grant_na(duid, ia, commit=True))
                continue
            pool = self.addr_pool
            lease.expiry = now + pool.valid
            t1, t2 = self._t12(pool.valid)
            out = IANA(ia.iaid, t1, t2)
            out.addresses.append(IAAddress(lease.address, pool.preferred, pool.valid))
            r.add_ia_na(out)
        for ia in msg.ia_pds():
            key = (duid, ia.iaid, True)
            lease = self.leases.get(key)
            if lease is None:
                if require_binding:
                    out = IAPD(ia.iaid)
                    out.status = (p6.STATUS_NO_BINDING, "no binding")
                    self.stats.no_binding += 1
                    r.add_ia_pd(out)
                    continue
                r.add_ia_pd(self._grant_pd(duid, ia, commit=True))
                continue
            pool = self.prefix_pool
            lease.expiry = now + pool.valid
            t1, t2 = self._t12(pool.valid)
            out = IAPD(ia.iaid, t1, t2)
            out.prefixes.append(IAPrefix(lease.address, lease.prefix_len,
                                         pool.preferred, pool.valid))
            r.add_ia_pd(out)
        self._add_global_options(r)
        return r

    def _rebind_keep(self, duid: bytes, ia: IANA, now: float) -> IANA | None:
        """Keep the client's presented address across server state loss."""
        if self.addr_pool is None:
            return None
        for a in ia.addresses:
            if self.addr_pool.contains(a.address) and \
                    self.addr_pool.allocate_specific(a.address):
                pool = self.addr_pool
                lease = Lease6(duid, ia.iaid, a.address, 128, now + pool.valid)
                self.leases[(duid, ia.iaid, False)] = lease
                if self.on_lease:
                    self.on_lease(lease)
                t1, t2 = self._t12(pool.valid)
                out = IANA(ia.iaid, t1, t2)
                out.addresses.append(IAAddress(a.address, pool.preferred, pool.valid))
                return out
        return None

    def _renew(self, msg: DHCPv6Message) -> DHCPv6Message:
        self.stats.renew += 1
        self.stats.reply += 1
        return self._extend(msg, require_binding=True)

    def _rebind(self, msg: DHCPv6Message) -> DHCPv6Message:
        self.stats.rebind += 1
        self.stats.reply += 1
        return self._extend(msg, require_binding=False)

    def _release(self, msg: DHCPv6Message) -> DHCPv6Message:
        self.stats.release += 1
        self.stats.reply += 1
        duid = msg.client_duid
        r = self._base_reply(msg, p6.REPLY)
        for ia in msg.ia_nas():
            self._drop_binding(duid, ia.iaid, is_pd=False)
        for ia in msg.ia_pds():
            self._drop_binding(duid, ia.iaid, is_pd=True)
        r.add_status(p6.STATUS_SUCCESS, "released")
        return r

    def _decline(self, msg: DHCPv6Message) -> DHCPv6Message:
        """Client saw a conflict: take the address out of service."""
        self.stats.decline += 1
        self.stats.reply += 1
        duid = msg.client_duid
        r = self._base_reply(msg, p6.REPLY)
        for ia in msg.ia_nas():
            key = (duid, ia.iaid, False)
            lease = self.leases.pop(key, None)
            if lease is not None and self.addr_pool is not None:
                # do NOT return to free list (conflict): just forget it
                self.addr_pool._allocated.pop(lease.address, None)
        r.add_status(p6.STATUS_SUCCESS, "declined")
        return r

    def _info_request(self, msg: DHCPv6Message) -> DHCPv6Message:
        self.stats.info_request += 1
        self.stats.reply += 1
        r = self._base_reply(msg, p6.REPLY)
        self._add_global_options(r)
        return r

    # ------------------------------------------------------------------
    def _drop_binding(self, duid: bytes, iaid: int, is_pd: bool) -> None:
        lease = self.leases.pop((duid, iaid, is_pd), None)
        if lease is None:
            return
        if is_pd and self.prefix_pool is not None:
            self.prefix_pool.release(lease.address)
        elif not is_pd and self.addr_pool is not None:
            self.addr_pool.release(lease.address)
        if self.on_release:
            self.on_release(lease)

    def cleanup_expired(self, now: float | None = None,
                        max_reaps: int | None = None) -> int:
        """Expired-binding sweep. `max_reaps` bounds one sweep's teardown
        work (same expiry-batching contract as the v4 server): leftovers
        stay expired and the next sweep reaps them."""
        now = now if now is not None else self.clock()
        dead = []
        for k, l in self.leases.items():
            if l.expiry < now:
                dead.append(k)
                if max_reaps is not None and len(dead) >= max_reaps:
                    break
        for duid, iaid, is_pd in dead:
            self._drop_binding(duid, iaid, is_pd)
        return len(dead)
