"""DHCPv6 server: wire codec + IA_NA address / IA_PD prefix delegation.

Parity: pkg/dhcpv6 (from-scratch codec + server, reference
protocol.go:166-453 / server.go). Handles SOLICIT/REQUEST/CONFIRM/RENEW/
REBIND/RELEASE/DECLINE/INFORMATION-REQUEST with IA_NA pools and IA_PD
prefix pools, rapid commit, and status codes.
"""

from bng_tpu.control.dhcpv6.protocol import (
    DHCPv6Message,
    DUID,
    IAAddress,
    IANA,
    IAPD,
    IAPrefix,
    generate_duid_ll,
)
from bng_tpu.control.dhcpv6.server import (
    AddressPool6,
    DHCPv6Server,
    DHCPv6ServerConfig,
    PrefixPool6,
)

__all__ = [
    "DHCPv6Message",
    "DUID",
    "IAAddress",
    "IANA",
    "IAPD",
    "IAPrefix",
    "generate_duid_ll",
    "AddressPool6",
    "DHCPv6Server",
    "DHCPv6ServerConfig",
    "PrefixPool6",
]
