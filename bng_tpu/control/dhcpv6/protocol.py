"""DHCPv6 wire codec (RFC 8415).

Parity: pkg/dhcpv6/protocol.go:166-453 — message header (type +
transaction-id), TLV options, DUID, IA_NA/IA_PD containers with nested
IAAddress/IAPrefix options, status codes.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

# message types (RFC 8415 §7.3)
SOLICIT = 1
ADVERTISE = 2
REQUEST = 3
CONFIRM = 4
RENEW = 5
REBIND = 6
REPLY = 7
RELEASE = 8
DECLINE = 9
RECONFIGURE = 10
INFORMATION_REQUEST = 11
RELAY_FORW = 12
RELAY_REPL = 13

# option codes (RFC 8415 §21)
OPT_CLIENTID = 1
OPT_SERVERID = 2
OPT_RELAY_MSG = 9  # RFC 8415 §21.10 — the encapsulated client message
OPT_INTERFACE_ID = 18  # RFC 8415 §21.18 — echoed verbatim in the reply
OPT_IA_NA = 3
OPT_IA_TA = 4
OPT_IAADDR = 5
OPT_ORO = 6
OPT_PREFERENCE = 7
OPT_ELAPSED_TIME = 8
OPT_UNICAST = 12
OPT_STATUS_CODE = 13
OPT_RAPID_COMMIT = 14
OPT_DNS_SERVERS = 23
OPT_DOMAIN_LIST = 24
OPT_IA_PD = 25
OPT_IAPREFIX = 26

# status codes (RFC 8415 §21.13)
STATUS_SUCCESS = 0
STATUS_UNSPEC_FAIL = 1
STATUS_NO_ADDRS_AVAIL = 2
STATUS_NO_BINDING = 3
STATUS_NOT_ON_LINK = 4
STATUS_USE_MULTICAST = 5
STATUS_NO_PREFIX_AVAIL = 6

# DUID types (RFC 8415 §11)
DUID_LLT = 1
DUID_EN = 2
DUID_LL = 3


@dataclass
class DUID:
    duid_type: int
    data: bytes  # type-specific body

    def encode(self) -> bytes:
        return struct.pack(">H", self.duid_type) + self.data

    @classmethod
    def decode(cls, raw: bytes) -> "DUID":
        if len(raw) < 2:
            raise ValueError("DUID truncated")
        return cls(struct.unpack(">H", raw[:2])[0], raw[2:])


def generate_duid_ll(mac: bytes, hw_type: int = 1) -> DUID:
    """DUID-LL from a MAC (parity: server.go:1028 GenerateDUID)."""
    return DUID(DUID_LL, struct.pack(">H", hw_type) + mac)


@dataclass
class IAAddress:
    """IA Address option (RFC 8415 §21.6)."""

    address: bytes  # 16 bytes
    preferred: int = 0
    valid: int = 0
    options: list[tuple[int, bytes]] = field(default_factory=list)

    def encode(self) -> bytes:
        body = self.address + struct.pack(">II", self.preferred, self.valid)
        body += encode_options(self.options)
        return body

    @classmethod
    def decode(cls, raw: bytes) -> "IAAddress":
        if len(raw) < 24:
            raise ValueError("IAADDR truncated")
        pref, valid = struct.unpack(">II", raw[16:24])
        return cls(raw[:16], pref, valid, decode_options(raw[24:]))


@dataclass
class IAPrefix:
    """IA Prefix option (RFC 8415 §21.22)."""

    prefix: bytes  # 16 bytes
    prefix_len: int = 0
    preferred: int = 0
    valid: int = 0
    options: list[tuple[int, bytes]] = field(default_factory=list)

    def encode(self) -> bytes:
        body = struct.pack(">IIB", self.preferred, self.valid, self.prefix_len)
        body += self.prefix + encode_options(self.options)
        return body

    @classmethod
    def decode(cls, raw: bytes) -> "IAPrefix":
        if len(raw) < 25:
            raise ValueError("IAPREFIX truncated")
        pref, valid, plen = struct.unpack(">IIB", raw[:9])
        return cls(raw[9:25], plen, pref, valid, decode_options(raw[25:]))


@dataclass
class IANA:
    """IA_NA container (RFC 8415 §21.4)."""

    iaid: int
    t1: int = 0
    t2: int = 0
    addresses: list[IAAddress] = field(default_factory=list)
    status: tuple[int, str] | None = None

    def encode(self) -> bytes:
        body = struct.pack(">III", self.iaid, self.t1, self.t2)
        for a in self.addresses:
            enc = a.encode()
            body += struct.pack(">HH", OPT_IAADDR, len(enc)) + enc
        if self.status is not None:
            s = struct.pack(">H", self.status[0]) + self.status[1].encode()
            body += struct.pack(">HH", OPT_STATUS_CODE, len(s)) + s
        return body

    @classmethod
    def decode(cls, raw: bytes) -> "IANA":
        if len(raw) < 12:
            raise ValueError("IA_NA truncated")
        iaid, t1, t2 = struct.unpack(">III", raw[:12])
        ia = cls(iaid, t1, t2)
        for code, data in decode_options(raw[12:]):
            if code == OPT_IAADDR:
                ia.addresses.append(IAAddress.decode(data))
            elif code == OPT_STATUS_CODE and len(data) >= 2:
                ia.status = (struct.unpack(">H", data[:2])[0],
                             data[2:].decode("utf-8", "replace"))
        return ia


@dataclass
class IAPD:
    """IA_PD container (RFC 8415 §21.21)."""

    iaid: int
    t1: int = 0
    t2: int = 0
    prefixes: list[IAPrefix] = field(default_factory=list)
    status: tuple[int, str] | None = None

    def encode(self) -> bytes:
        body = struct.pack(">III", self.iaid, self.t1, self.t2)
        for p in self.prefixes:
            enc = p.encode()
            body += struct.pack(">HH", OPT_IAPREFIX, len(enc)) + enc
        if self.status is not None:
            s = struct.pack(">H", self.status[0]) + self.status[1].encode()
            body += struct.pack(">HH", OPT_STATUS_CODE, len(s)) + s
        return body

    @classmethod
    def decode(cls, raw: bytes) -> "IAPD":
        if len(raw) < 12:
            raise ValueError("IA_PD truncated")
        iaid, t1, t2 = struct.unpack(">III", raw[:12])
        ia = cls(iaid, t1, t2)
        for code, data in decode_options(raw[12:]):
            if code == OPT_IAPREFIX:
                ia.prefixes.append(IAPrefix.decode(data))
            elif code == OPT_STATUS_CODE and len(data) >= 2:
                ia.status = (struct.unpack(">H", data[:2])[0],
                             data[2:].decode("utf-8", "replace"))
        return ia


def encode_options(options: list[tuple[int, bytes]]) -> bytes:
    out = bytearray()
    for code, data in options:
        out += struct.pack(">HH", code, len(data)) + data
    return bytes(out)


def decode_options(raw: bytes) -> list[tuple[int, bytes]]:
    out = []
    off = 0
    while off + 4 <= len(raw):
        code, length = struct.unpack(">HH", raw[off : off + 4])
        off += 4
        if off + length > len(raw):
            raise ValueError("option length exceeds buffer")
        out.append((code, raw[off : off + length]))
        off += length
    return out


@dataclass
class DHCPv6Message:
    msg_type: int
    transaction_id: int  # 24-bit
    options: list[tuple[int, bytes]] = field(default_factory=list)

    def encode(self) -> bytes:
        hdr = struct.pack(">I", (self.msg_type << 24) | (self.transaction_id & 0xFFFFFF))
        return hdr + encode_options(self.options)

    @classmethod
    def decode(cls, raw: bytes) -> "DHCPv6Message":
        if len(raw) < 4:
            raise ValueError("DHCPv6 message truncated")
        word = struct.unpack(">I", raw[:4])[0]
        return cls(word >> 24, word & 0xFFFFFF, decode_options(raw[4:]))

    # -- helpers --
    def get(self, code: int) -> bytes | None:
        for c, d in self.options:
            if c == code:
                return d
        return None

    def get_all(self, code: int) -> list[bytes]:
        return [d for c, d in self.options if c == code]

    def add(self, code: int, data: bytes) -> None:
        self.options.append((code, data))

    @property
    def client_duid(self) -> bytes | None:
        return self.get(OPT_CLIENTID)

    @property
    def server_duid(self) -> bytes | None:
        return self.get(OPT_SERVERID)

    def ia_nas(self) -> list[IANA]:
        return [IANA.decode(d) for d in self.get_all(OPT_IA_NA)]

    def ia_pds(self) -> list[IAPD]:
        return [IAPD.decode(d) for d in self.get_all(OPT_IA_PD)]

    def has_rapid_commit(self) -> bool:
        return self.get(OPT_RAPID_COMMIT) is not None

    def add_ia_na(self, ia: IANA) -> None:
        self.add(OPT_IA_NA, ia.encode())

    def add_ia_pd(self, ia: IAPD) -> None:
        self.add(OPT_IA_PD, ia.encode())

    def add_status(self, code: int, msg: str = "") -> None:
        self.add(OPT_STATUS_CODE, struct.pack(">H", code) + msg.encode())


@dataclass
class RelayMessage:
    """RFC 8415 §9: Relay-Forward/Relay-Reply framing.

    Parity: the reference defines the same shape (protocol.go:104-111)
    — hop-count + link-address + peer-address + options, with the
    client's message nested in OPT_RELAY_MSG (possibly through a chain
    of relays). The fixed header is 34 bytes vs the client messages' 4.
    """

    msg_type: int  # RELAY_FORW | RELAY_REPL
    hop_count: int
    link_address: bytes  # 16
    peer_address: bytes  # 16
    options: list[tuple[int, bytes]] = field(default_factory=list)

    def encode(self) -> bytes:
        if len(self.link_address) != 16 or len(self.peer_address) != 16:
            raise ValueError("relay addresses must be 16 bytes")
        return (bytes([self.msg_type, self.hop_count & 0xFF])
                + self.link_address + self.peer_address
                + encode_options(self.options))

    @classmethod
    def decode(cls, raw: bytes) -> "RelayMessage":
        if len(raw) < 34:
            raise ValueError("relay message truncated")
        if raw[0] not in (RELAY_FORW, RELAY_REPL):
            raise ValueError(f"not a relay message: type {raw[0]}")
        return cls(raw[0], raw[1], raw[2:18], raw[18:34],
                   decode_options(raw[34:]))

    def get(self, code: int) -> bytes | None:
        for c, d in self.options:
            if c == code:
                return d
        return None
