"""CLSet CRDT replicated store — the distributed control-plane state layer.

Role parity: pkg/nexus/clset.go (CLSetStore), pkg/nexus/clset_store.go
(DistributedStore modes memory/read/write), pkg/nexus/crdt_backend.go
(gossip backend + membership). The reference vendors a stubbed "CLSet"
library and gets the real one from libp2p-land; here the CRDT itself is
implemented: a **causal-length set** keyed KV store (Elvinger/Shapiro
family — per key a causal length counter whose parity encodes presence),
which is the published CRDT the reference's library names.

Per key we keep (cl, ts, node, value):

    cl odd  = present, cl even = absent/tombstone
    local set():    absent -> cl+1 (flip to present)
                    present -> cl+2 (new observation, dominates a
                                     concurrent delete of the old one)
    local delete(): present -> cl+1 (flip to absent); absent -> no-op
    merge(remote):  keep the entry with the greater (cl, ts, node)
                    triple — higher causal length always wins; ties
                    break by timestamp then node id.

merge() is commutative, associative and idempotent, so any two replicas
that exchange entries converge to identical state regardless of delivery
order or repetition — the partition/heal property the round-2 verdict
demanded. Anti-entropy is digest-based (two rounds: digest -> missing
entries) over an injectable transport; control/cluster_http.py gives it a
real HTTP wire.

No background thread by default: call tick() from the runtime loop (the
engine's slow path cadence), or start_sync() for a daemon thread matching
the reference's 5s syncLoop.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable

__all__ = [
    "CLSetStore", "DistributedStore", "Entry", "ReadOnlyNodeError",
    "MODE_MEMORY", "MODE_READ", "MODE_WRITE",
]


class ReadOnlyNodeError(Exception):
    """Write attempted on a read-mode node (clset_store.go ErrReadOnlyNode)."""


@dataclass(frozen=True)
class Entry:
    """One replicated key's state. Tombstones are Entries with even cl."""

    cl: int  # causal length; odd = present
    ts: int  # wall-clock ns at the writing node (tie-break only)
    node: str  # writing node id (final tie-break)
    value: bytes | None  # None iff tombstone

    @property
    def present(self) -> bool:
        return self.cl % 2 == 1

    def dominates(self, other: "Entry") -> bool:
        return (self.cl, self.ts, self.node) > (other.cl, other.ts, other.node)


class CLSetStore:
    """Replicated KV store with the MemoryStore surface (get/put/delete/
    list/watch) plus CRDT merge + digest anti-entropy.

    Watch callbacks fire for both local mutations and remote merges, like
    the reference's insert/update/delete hooks (crdt_backend.go:100-140).
    """

    def __init__(self, node_id: str, namespace: str = "nexus",
                 clock_ns: Callable[[], int] = time.time_ns):
        if not node_id:
            raise ValueError("node_id required")
        self.node_id = node_id
        self.namespace = namespace
        self._clock_ns = clock_ns
        self._entries: dict[str, Entry] = {}
        self._watchers: list[tuple[str, Callable[[str, bytes | None], None]]] = []
        self._lock = threading.RLock()

    # ---- MemoryStore surface ----
    def get(self, key: str) -> bytes | None:
        with self._lock:
            e = self._entries.get(key)
            return e.value if e is not None and e.present else None

    def put(self, key: str, value: bytes) -> None:
        if isinstance(value, str):  # defensive: stores hold bytes
            value = value.encode()
        with self._lock:
            cur = self._entries.get(key)
            cl = 1 if cur is None else (cur.cl + 2 if cur.present else cur.cl + 1)
            self._entries[key] = Entry(cl, self._clock_ns(), self.node_id, bytes(value))
        self._notify(key, bytes(value))

    def delete(self, key: str) -> bool:
        with self._lock:
            cur = self._entries.get(key)
            if cur is None or not cur.present:
                return False
            self._entries[key] = Entry(cur.cl + 1, self._clock_ns(), self.node_id, None)
        self._notify(key, None)
        return True

    def list(self, prefix: str) -> dict[str, bytes]:
        with self._lock:
            return {k: e.value for k, e in self._entries.items()
                    if e.present and k.startswith(prefix)}

    def watch(self, prefix: str, cb: Callable[[str, bytes | None], None]) -> None:
        self._watchers.append((prefix, cb))

    def _notify(self, key: str, value: bytes | None) -> None:
        for prefix, cb in self._watchers:
            if key.startswith(prefix):
                cb(key, value)

    # ---- CRDT machinery ----
    def digest(self) -> dict[str, tuple[int, int, str]]:
        """Compact replica summary: key -> (cl, ts, node)."""
        with self._lock:
            return {k: (e.cl, e.ts, e.node) for k, e in self._entries.items()}

    def entries_for(self, keys) -> dict[str, tuple[int, int, str, bytes | None]]:
        with self._lock:
            return {k: (e.cl, e.ts, e.node, e.value)
                    for k, e in ((k, self._entries.get(k)) for k in keys)
                    if e is not None}

    def missing_from(self, remote_digest: dict[str, tuple[int, int, str]]) -> list[str]:
        """Keys where the remote replica dominates (we need their entries)."""
        out = []
        with self._lock:
            for k, (cl, ts, node) in remote_digest.items():
                cur = self._entries.get(k)
                if cur is None or Entry(cl, ts, node, None).dominates(cur):
                    out.append(k)
        return out

    def dominated_by_local(self, remote_digest: dict[str, tuple[int, int, str]]) -> list[str]:
        """Keys where WE dominate (the remote needs our entries)."""
        out = []
        with self._lock:
            for k, e in self._entries.items():
                r = remote_digest.get(k)
                if r is None or e.dominates(Entry(r[0], r[1], r[2], None)):
                    out.append(k)
        return out

    def merge_entries(self, entries: dict[str, tuple[int, int, str, bytes | None]]) -> int:
        """Apply remote entries; returns how many changed local state.

        Commutative + idempotent: an entry applies only if it dominates."""
        changed = []
        with self._lock:
            for k, (cl, ts, node, value) in entries.items():
                cand = Entry(cl, ts, node,
                             None if value is None else bytes(value))
                cur = self._entries.get(k)
                if cur is None or cand.dominates(cur):
                    self._entries[k] = cand
                    changed.append((k, cand.value if cand.present else None))
        for k, v in changed:
            self._notify(k, v)
        return len(changed)

    def sync_with(self, peer: "CLSetStore | object") -> int:
        """Two-round digest anti-entropy against a peer (a CLSetStore or a
        transport proxy exposing digest/entries_for/merge_entries).

        Returns entries changed locally. After A.sync_with(B) both replicas
        hold identical state for every key either side knew."""
        remote_digest = peer.digest()
        want = self.missing_from(remote_digest)
        got = peer.entries_for(want)
        changed = self.merge_entries(got)
        theirs = self.dominated_by_local(remote_digest)
        peer.merge_entries(self.entries_for(theirs))
        return changed

    def prune_tombstones(self, max_age_ns: int, now_ns: int | None = None) -> int:
        """Drop tombstones older than max_age_ns. Returns how many.

        Safety contract: the prune horizon must exceed the longest
        partition you intend to heal from — a replica that was isolated
        longer than this and still holds the key PRESENT will resurrect it
        on re-merge (the standard CRDT garbage-collection tradeoff; the
        reference's badger-backed CLSet keeps tombstones subject to the
        datastore's own GC). DistributedStore applies a 24h default."""
        now_ns = self._clock_ns() if now_ns is None else now_ns
        cutoff = now_ns - max_age_ns
        with self._lock:
            dead = [k for k, e in self._entries.items()
                    if not e.present and e.ts < cutoff]
            for k in dead:
                del self._entries[k]
        return len(dead)

    # ---- stats ----
    def key_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values() if e.present)

    def tombstone_count(self) -> int:
        with self._lock:
            return sum(1 for e in self._entries.values() if not e.present)


MODE_MEMORY = "memory"
MODE_READ = "read"
MODE_WRITE = "write"


@dataclass
class ClusterMember:
    node_id: str
    node_name: str
    last_seen: float
    active: bool = True
    mode: str = MODE_WRITE


class DistributedStore:
    """Mode-aware cluster store (clset_store.go StoreMode semantics).

    memory — local-only CLSetStore, no peers (dev/tests).
    read   — receives merges, serves reads; put/delete raise
             ReadOnlyNodeError (renew-only OLT-BNG nodes).
    write  — full read/write; joins the hashring (owns pool ranges) so
             allocators can place ownership deterministically.

    Peers are injectable sync targets: objects with digest/entries_for/
    merge_entries (another DistributedStore.store, or an HTTP proxy from
    control/cluster_http.py). Membership heartbeats ride the CRDT itself
    under <ns>/_members/, so liveness converges with the data.
    """

    MEMBER_PREFIX = "_members/"

    def __init__(self, node_id: str, mode: str = MODE_MEMORY,
                 node_name: str = "BNG", namespace: str = "nexus",
                 peer_ttl: float = 30.0, sync_interval: float = 5.0,
                 tombstone_ttl: float = 86400.0,
                 clock: Callable[[], float] = time.time,
                 ring=None):
        if mode not in (MODE_MEMORY, MODE_READ, MODE_WRITE):
            raise ValueError(f"unknown store mode {mode!r}")
        self.node_id = node_id
        self.node_name = node_name
        self.mode = mode
        self.peer_ttl = peer_ttl
        self.sync_interval = sync_interval
        self.tombstone_ttl = tombstone_ttl
        self.clock = clock
        self.store = CLSetStore(node_id, namespace=namespace,
                                clock_ns=lambda: int(clock() * 1e9))
        self._peers: list[object] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

        # write-mode nodes join the rendezvous ring (own pool ranges);
        # ring is the mutable node set consulted by rendezvous_owner
        self.ring: set[str] | None = None
        if mode == MODE_WRITE:
            self.ring = set(ring) if ring is not None else set()
            self.ring.add(node_id)
        self._heartbeat()

    # ---- MemoryStore surface (mode-gated writes) ----
    def get(self, key: str) -> bytes | None:
        return self.store.get(key)

    def list(self, prefix: str) -> dict[str, bytes]:
        return self.store.list(prefix)

    def watch(self, prefix: str, cb) -> None:
        self.store.watch(prefix, cb)

    def put(self, key: str, value: bytes) -> None:
        if self.mode == MODE_READ:
            raise ReadOnlyNodeError(f"put({key!r}) on read-mode node {self.node_id}")
        self.store.put(key, value)

    def delete(self, key: str) -> bool:
        if self.mode == MODE_READ:
            raise ReadOnlyNodeError(f"delete({key!r}) on read-mode node {self.node_id}")
        return self.store.delete(key)

    # ---- cluster plumbing ----
    def add_peer(self, peer) -> None:
        """peer: a sync target (DistributedStore, CLSetStore, or transport
        proxy with digest/entries_for/merge_entries)."""
        if isinstance(peer, DistributedStore):
            peer = peer.store
        self._peers.append(peer)

    def _heartbeat(self) -> None:
        key = f"{self.MEMBER_PREFIX}{self.node_id}"
        val = f"{self.node_name}:{self.mode}:{self.clock():.3f}".encode()
        # membership updates bypass the read-only gate: liveness is not data
        self.store.put(key, val)

    def members(self) -> dict[str, ClusterMember]:
        now = self.clock()
        out: dict[str, ClusterMember] = {}
        for k, v in self.store.list(self.MEMBER_PREFIX).items():
            node = k[len(self.MEMBER_PREFIX):]
            try:
                name, mode, ts = v.decode().rsplit(":", 2)
                last = float(ts)
            except ValueError:
                name, mode, last = v.decode(), MODE_WRITE, 0.0
            out[node] = ClusterMember(node, name, last,
                                      active=(now - last) <= self.peer_ttl,
                                      mode=mode)
        return out

    def tick(self) -> int:
        """One anti-entropy round: heartbeat, sync every peer, GC old
        tombstones (see CLSetStore.prune_tombstones' safety contract).
        Returns entries changed locally."""
        self._heartbeat()
        changed = 0
        for p in list(self._peers):
            try:
                changed += self.store.sync_with(p)
            except Exception:  # a dead peer must not stall the loop
                continue
        self.store.prune_tombstones(int(self.tombstone_ttl * 1e9))
        return changed

    def start_sync(self) -> None:
        """Daemon sync thread at sync_interval (clset.go syncLoop parity)."""
        if self._thread is not None:
            return
        def loop():
            while not self._stop.wait(self.sync_interval):
                self.tick()
        self._thread = threading.Thread(target=loop, daemon=True,
                                        name=f"clset-sync-{self.node_id}")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.sync_interval)
            self._thread = None

    # ---- hashring ownership (write mode) ----
    def owner_of(self, key: str) -> str | None:
        if self.ring is None:
            return self.node_id if self.mode != MODE_READ else None
        from bng_tpu.parallel.hashring import rendezvous_owner

        return rendezvous_owner(sorted(self.ring), key)

    def owns(self, key: str) -> bool:
        return self.owner_of(key) == self.node_id

    def join_member_ring(self) -> None:
        """Refresh the local ring view from active cluster members.

        Membership rides the CRDT, so after anti-entropy every write node
        computes the same ring — deterministic ownership without consensus."""
        if self.ring is None:
            return
        for m in self.members().values():
            if m.active and m.mode == MODE_WRITE:
                self.ring.add(m.node_id)
            else:
                self.ring.discard(m.node_id)
        self.ring.add(self.node_id)
