"""Checkpoint file store + background cadence — the statestore half of
warm restart.

`runtime/checkpoint.py` owns WHAT a snapshot contains and the binary
format; this module owns the file lifecycle around it:

- `CheckpointStore`: a directory of versioned `ckpt-<seq>.bngckpt`
  files. Writes go to a temp file in the same directory and land with
  one atomic `os.replace` (a crash mid-write can never shadow the last
  good checkpoint); loads walk newest-first and skip corrupt files (the
  reject comes from `decode_checkpoint`'s checksum/schema gates), so a
  torn newest file degrades to the previous snapshot, not to a crash.

- `PeriodicCheckpointer`: the background cadence `bng run
  --checkpoint-interval-s` drives from the 1 Hz tick (plus the SIGTERM
  snapshot). A failing save bumps the failure counter AND emits a
  rate-limited structlog event (utils.structlog.RateLimiter — the same
  token bucket that guards the slow-path error log): a wedged disk must
  be visible in the logs without turning the tick loop into a firehose.

HA wiring: a standby passes its `StandbySyncer` as the `ha` target of
`restore_checkpoint` — `bootstrap_state()` hydrates the session store
and jumps `last_seq` to the checkpoint's high-water mark, so the first
connect catches up via `replay_since(seq)` and only falls back to
`full_sync()` when the active's replay buffer has wrapped past it.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Callable, NamedTuple

from bng_tpu.chaos.faults import mutate_point
from bng_tpu.runtime.checkpoint import (Checkpoint, CheckpointError,
                                        decode_checkpoint, encode_checkpoint,
                                        verify_checkpoint_bytes)
from bng_tpu.utils.structlog import RateLimiter, get_logger

CKPT_SUFFIX = ".bngckpt"
_CKPT_PREFIX = "ckpt-"


class CheckpointInfo(NamedTuple):
    """One store entry. list() fully validates each file (header CRC +
    payload CRC) — the inventory's error column is trustworthy, at the
    cost of reading the kept files (bounded by the retention policy)."""

    path: str
    seq: int
    created_at: float
    node_id: str
    bytes: int
    error: str | None  # non-None: file exists but would be rejected


class CheckpointStore:
    """Versioned, atomically-replaced checkpoint files in one directory.

    Single-writer by design: seq assignment (next_seq at save time) and
    the atomic replace assume ONE process snapshots into a directory —
    the `bng run` daemon. `bng checkpoint save` against the same dir
    while a daemon runs would write a fresh process's (staler) state
    under the newest seq; the CLI warns about exactly that."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path_for(self, seq: int) -> Path:
        return self.root / f"{_CKPT_PREFIX}{seq:012d}{CKPT_SUFFIX}"

    def _candidates(self) -> list[Path]:
        """Checkpoint files, newest seq first (name-encoded, zero-padded
        so lexical order IS seq order). Files whose name doesn't parse
        as a seq are ignored — a stray `ckpt-latest.bngckpt` copy must
        not shadow the real newest or collapse next_seq."""
        return sorted((p for p in
                       self.root.glob(f"{_CKPT_PREFIX}*{CKPT_SUFFIX}")
                       if self._seq_of(p) >= 0), reverse=True)

    @staticmethod
    def _seq_of(path: Path) -> int:
        try:
            return int(path.name[len(_CKPT_PREFIX) : -len(CKPT_SUFFIX)])
        except ValueError:
            return -1

    def has_checkpoints(self) -> bool:
        """Any candidate files on disk — a zero-read cold-start probe
        (whether the newest is restorable is load_latest's call)."""
        return bool(self._candidates())

    def next_seq(self) -> int:
        """Monotonic sequence number for the next save (max on disk + 1,
        so restarts never reuse a seq even after a restore)."""
        cands = self._candidates()
        return (self._seq_of(cands[0]) + 1) if cands else 1

    def save(self, ckpt: Checkpoint) -> Path:
        """Encode + write atomically; returns the final path."""
        # chaos hook: truncation/bit-flip corrupts the bytes that land
        # on disk (the decoder must reject them later); io_error raises
        # before any file exists (the failure-counter path)
        data = mutate_point("ckpt.write", encode_checkpoint(ckpt))
        final = self._path_for(ckpt.seq)
        tmp = self.root / f".tmp-{final.name}.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, final)
        finally:
            if tmp.exists():  # failed before the rename
                tmp.unlink(missing_ok=True)
        # fsync the directory so the rename itself survives power loss
        # (best effort: not every filesystem supports O_DIRECTORY opens)
        try:
            dfd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
        return final

    def load(self, path: str | os.PathLike) -> Checkpoint:
        """Decode one specific file (CheckpointError on any corruption)."""
        try:
            # chaos hook: read-side corruption (bad disk / torn page) —
            # the decoder's CRC gates must reject, never half-hydrate
            data = mutate_point("ckpt.read", Path(path).read_bytes())
        except OSError as e:
            raise CheckpointError(f"cannot read checkpoint {path}: {e}") from e
        return decode_checkpoint(data)

    def load_latest(self) -> tuple[Checkpoint, Path]:
        """Newest restorable checkpoint. A corrupt newer file is skipped
        (with its error collected) in favor of an older good one; raises
        CheckpointError when the store holds nothing restorable."""
        errors = []
        for path in self._candidates():
            try:
                return self.load(path), path
            except CheckpointError as e:
                errors.append(f"{path.name}: {e}")
        if errors:
            raise CheckpointError(
                "no restorable checkpoint in "
                f"{self.root}: {'; '.join(errors)}")
        raise CheckpointError(f"no checkpoints in {self.root}")

    def list(self) -> list[CheckpointInfo]:
        """Inventory, newest first (the `checkpoint info` feed): headers
        plus the checksum gate, no array materialization. Corrupt files
        appear with their rejection reason."""
        out = []
        for path in self._candidates():
            size = 0
            try:
                size = path.stat().st_size
                header, _ = verify_checkpoint_bytes(path.read_bytes())
                meta = header.get("meta", {})
                out.append(CheckpointInfo(
                    str(path), int(meta.get("seq", self._seq_of(path))),
                    float(meta.get("created_at", 0.0)),
                    str(meta.get("node_id", "")), size, None))
            except (CheckpointError, OSError) as e:
                # vanished mid-listing (concurrent prune) or unreadable:
                # flag it, never crash the inventory
                out.append(CheckpointInfo(str(path), self._seq_of(path),
                                          0.0, "", size, str(e)))
        return out

    def prune(self, keep: int = 3) -> int:
        """Drop all but the newest `keep` checkpoints; returns removed
        count. Corrupt files older than the cut go too."""
        removed = 0
        for path in self._candidates()[max(keep, 1):]:
            path.unlink(missing_ok=True)
            removed += 1
        return removed


class PeriodicCheckpointer:
    """Cadence + bookkeeping around a snapshot function.

    `snapshot_fn(seq, now) -> Checkpoint` is the composition root's
    closure (it quiesces the scheduler and collects the app's
    components); this class owns WHEN it runs, the retention policy, the
    stats the bng_ckpt_* metric families scrape, and the rate-limited
    failure log.
    """

    def __init__(self, store: CheckpointStore,
                 snapshot_fn: Callable[[int, float], Checkpoint],
                 interval_s: float = 0.0, keep: int = 3, metrics=None,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.snapshot_fn = snapshot_fn
        self.interval_s = float(interval_s)
        self.keep = keep
        self.metrics = metrics
        self.clock = clock
        # staleness origin before the FIRST success: an unwritable dir
        # from boot must read as a GROWING age, not a perpetually-fresh 0
        self.started_at = clock()
        self._last_attempt = 0.0
        self._log = get_logger("checkpoint")
        self._err_limit = RateLimiter(rate=1 / 30.0, burst=3)
        self.stats = {"saves": 0, "failures": 0, "last_success_t": 0.0,
                      "last_bytes": 0, "last_duration_s": 0.0,
                      "last_seq": 0, "last_error": ""}

    def due(self, now: float) -> bool:
        return (self.interval_s > 0
                and now - self._last_attempt >= self.interval_s)

    def tick(self, now: float | None = None) -> Path | None:
        """Background-cadence entry (the 1 Hz app tick): save when due,
        NEVER raise — a checkpoint failure must not take down the
        dataplane loop it rides on. Failures count + rate-limited log."""
        now = now if now is not None else self.clock()
        if not self.due(now):
            return None
        self._last_attempt = now
        try:
            return self.save_now(reason="interval")
        except Exception as e:  # noqa: BLE001 — disk/encode faults land here
            self._on_failure(e)
            return None

    def save_now(self, reason: str = "manual") -> Path:
        """Snapshot + write + prune (exceptions propagate — CLI verbs and
        SIGTERM want the error; tick() wraps this)."""
        t0 = self.clock()
        seq = self.store.next_seq()
        ckpt = self.snapshot_fn(seq, t0)
        path = self.store.save(ckpt)
        dt = self.clock() - t0
        size = path.stat().st_size
        s = self.stats
        s["saves"] += 1
        s["last_success_t"] = t0
        s["last_bytes"] = size
        s["last_duration_s"] = dt
        s["last_seq"] = seq
        s["last_error"] = ""
        if self.metrics is not None:
            self.metrics.ckpt_duration.observe(dt, reason=reason)
        self._log.info("checkpoint saved", seq=seq, reason=reason,
                       bytes=size, duration_ms=round(dt * 1e3, 1))
        self.store.prune(self.keep)
        return path

    def _on_failure(self, exc: Exception) -> None:
        self.stats["failures"] += 1
        self.stats["last_error"] = f"{type(exc).__name__}: {exc}"
        ok, suppressed = self._err_limit.allow()
        if ok:
            self._log.error("background checkpoint failed",
                            error=self.stats["last_error"],
                            failures=self.stats["failures"],
                            suppressed=suppressed,
                            exc_info=(type(exc), exc, exc.__traceback__))
