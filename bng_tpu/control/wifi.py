"""WiFi gateway operating mode: captive-portal session flow for WiFi.

Parity: pkg/wifi — OperatingMode + Config with DefaultWiFiConfig /
DefaultOLTBNGConfig (gateway.go:27-100), Session + states (:102-149),
Manager create/renew/authenticate/release (:222-365), by-IP index (:374),
traffic stats (:400), grace period + NeedsAuthentication (:416-444),
Stats (:446-470).

Same BNG stack, different deployment: WiFi mode allocates on DHCP
DISCOVER and deallocates on lease expiry; OLT-BNG mode allocates after
RADIUS auth and deallocates on session termination.
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum


class OperatingMode(str, Enum):
    OLT_BNG = "olt_bng"
    WIFI_GATEWAY = "wifi_gateway"


class WiFiSessionState(str, Enum):
    NEW = "new"
    GRACE_PERIOD = "grace_period"
    AUTHENTICATED = "authenticated"
    ACTIVE = "active"
    EXPIRED = "expired"


@dataclass
class WiFiConfig:
    mode: OperatingMode = OperatingMode.WIFI_GATEWAY
    allocation_trigger: str = "dhcp_discover"  # or "radius_auth"
    deallocation_trigger: str = "lease_expiry"  # or "session_termination"
    lease_duration: float = 1800.0
    nexus_enabled: bool = False
    pon_enabled: bool = False
    pppoe_enabled: bool = False
    captive_portal_enabled: bool = True
    captive_portal_url: str = ""
    grace_period: float = 300.0


def default_wifi_config() -> WiFiConfig:
    """gateway.go:73-86."""
    return WiFiConfig()


def default_olt_bng_config() -> WiFiConfig:
    """gateway.go:88-100."""
    return WiFiConfig(
        mode=OperatingMode.OLT_BNG,
        allocation_trigger="radius_auth",
        deallocation_trigger="session_termination",
        lease_duration=86400.0,
        nexus_enabled=True,
        pon_enabled=True,
        pppoe_enabled=True,
        captive_portal_enabled=False,
    )


@dataclass
class WiFiSession:
    id: str
    mac: str
    ip: str = ""
    hostname: str = ""
    pool_id: int = 0
    state: WiFiSessionState = WiFiSessionState.NEW
    authenticated: bool = False
    auth_method: str = ""
    user_identity: str = ""
    created_at: float = 0.0
    lease_expiry: float = 0.0
    authenticated_at: float = 0.0
    grace_period_ends: float = 0.0
    last_renewal: float = 0.0
    lease_duration: float = 0.0
    bytes_in: int = 0
    bytes_out: int = 0
    packets_in: int = 0
    packets_out: int = 0
    vendor_class: str = ""
    user_class: str = ""


class WiFiGatewayManager:
    """WiFi gateway session manager (gateway.go:151-470)."""

    def __init__(self, config: WiFiConfig | None = None, clock=time.time):
        self.config = config or default_wifi_config()
        self._clock = clock
        self._lock = threading.Lock()
        self._sessions: dict[str, WiFiSession] = {}  # mac -> session
        self._by_ip: dict[str, str] = {}  # ip -> mac
        self.on_session_create = None
        self.on_session_auth = None
        self.on_session_expire = None

    def create_session(self, mac: str, hostname: str = "", pool_id: int = 0,
                       ip: str = "") -> WiFiSession:
        """DHCP DISCOVER arrival (gateway.go:222-278)."""
        now = self._clock()
        with self._lock:
            existing = self._sessions.get(mac)
            if existing is not None:
                existing.last_renewal = now
                existing.lease_expiry = now + self.config.lease_duration
                if hostname:
                    existing.hostname = hostname
                if ip and ip != existing.ip:
                    if existing.ip:
                        self._by_ip.pop(existing.ip, None)
                    existing.ip = ip
                    self._by_ip[ip] = mac
                return existing
            s = WiFiSession(
                id=uuid.uuid4().hex[:16], mac=mac, ip=ip, hostname=hostname,
                pool_id=pool_id, created_at=now, last_renewal=now,
                lease_duration=self.config.lease_duration,
                lease_expiry=now + self.config.lease_duration,
            )
            if self.config.captive_portal_enabled:
                s.state = WiFiSessionState.GRACE_PERIOD
                s.grace_period_ends = now + self.config.grace_period
            else:
                s.state = WiFiSessionState.ACTIVE
                s.authenticated = True
            self._sessions[mac] = s
            if ip:
                self._by_ip[ip] = mac
        if self.on_session_create:
            self.on_session_create(s)
        return s

    def renew_session(self, mac: str) -> None:
        """DHCP renewal (gateway.go:280-301)."""
        now = self._clock()
        with self._lock:
            s = self._sessions.get(mac)
            if s is None:
                raise KeyError(f"no session for {mac}")
            s.last_renewal = now
            s.lease_expiry = now + s.lease_duration

    def authenticate_session(self, mac: str, auth_method: str,
                             user_identity: str) -> None:
        """Captive portal success (gateway.go:303-333)."""
        now = self._clock()
        with self._lock:
            s = self._sessions.get(mac)
            if s is None:
                raise KeyError(f"no session for {mac}")
            s.authenticated = True
            s.auth_method = auth_method
            s.user_identity = user_identity
            s.authenticated_at = now
            s.state = WiFiSessionState.AUTHENTICATED
            s.grace_period_ends = 0.0
        if self.on_session_auth:
            self.on_session_auth(s)

    def release_session(self, mac: str) -> None:
        with self._lock:
            s = self._sessions.pop(mac, None)
            if s is not None and s.ip:
                self._by_ip.pop(s.ip, None)

    def get_session(self, mac: str) -> WiFiSession | None:
        with self._lock:
            return self._sessions.get(mac)

    def get_session_by_ip(self, ip: str) -> WiFiSession | None:
        with self._lock:
            mac = self._by_ip.get(ip)
            return self._sessions.get(mac) if mac else None

    def list_sessions(self) -> list[WiFiSession]:
        with self._lock:
            return list(self._sessions.values())

    def update_traffic_stats(self, mac: str, bytes_in: int, bytes_out: int,
                             packets_in: int, packets_out: int) -> None:
        with self._lock:
            s = self._sessions.get(mac)
            if s is None:
                return
            s.bytes_in += bytes_in
            s.bytes_out += bytes_out
            s.packets_in += packets_in
            s.packets_out += packets_out
            if s.state == WiFiSessionState.AUTHENTICATED:
                s.state = WiFiSessionState.ACTIVE

    def is_in_grace_period(self, mac: str) -> bool:
        now = self._clock()
        with self._lock:
            s = self._sessions.get(mac)
            return (s is not None and s.state == WiFiSessionState.GRACE_PERIOD
                    and now < s.grace_period_ends)

    def needs_authentication(self, mac: str) -> bool:
        if not self.config.captive_portal_enabled:
            return False
        with self._lock:
            s = self._sessions.get(mac)
            return s is None or not s.authenticated

    def expire_sessions(self) -> int:
        """Sweep lease-expired and grace-period-overrun sessions."""
        now = self._clock()
        expired = []
        lease_driven = self.config.deallocation_trigger == "lease_expiry"
        with self._lock:
            for mac, s in list(self._sessions.items()):
                # In session-termination mode (OLT-BNG) authenticated sessions
                # outlive the DHCP lease; RADIUS teardown releases them.
                lease_out = (s.lease_expiry and now >= s.lease_expiry
                             and (lease_driven or not s.authenticated))
                grace_out = (s.state == WiFiSessionState.GRACE_PERIOD
                             and not s.authenticated
                             and now >= s.grace_period_ends)
                if lease_out or grace_out:
                    s.state = WiFiSessionState.EXPIRED
                    del self._sessions[mac]
                    if s.ip:
                        self._by_ip.pop(s.ip, None)
                    expired.append(s)
        if self.on_session_expire:
            for s in expired:
                self.on_session_expire(s)
        return len(expired)

    def stats(self) -> dict:
        now = self._clock()
        with self._lock:
            out = {
                "active_sessions": len(self._sessions),
                "authenticated_sessions": 0,
                "grace_period_sessions": 0,
                "total_bytes_in": 0,
                "total_bytes_out": 0,
            }
            for s in self._sessions.values():
                if s.authenticated:
                    out["authenticated_sessions"] += 1
                if (s.state == WiFiSessionState.GRACE_PERIOD
                        and now < s.grace_period_ends):
                    out["grace_period_sessions"] += 1
                out["total_bytes_in"] += s.bytes_in
                out["total_bytes_out"] += s.bytes_out
            return out
