"""Real HTTP/SSE transports for the distributed control plane.

Round-2 verdict missing #2: HA sync, peer pool and the Nexus allocator
had injectable-callable transports only — two `bng-tpu run` processes
could not talk. This module gives each its wire, stdlib-only (the
environment pins dependencies):

  server (ClusterServer, one listener per node):
    GET  /health                       liveness
    GET  /ha/sessions                  HA full sync  (pkg/ha/sync.go:231)
    GET  /ha/replay?since=N            HA delta replay (410 = gap, resync)
    GET  /ha/stream?since=N            HA SSE delta stream (sync.go:304)
    POST /pool/allocate {subscriber_id}   peer pool     (pkg/pool/peer.go:633)
    POST /pool/release  {subscriber_id}
    GET  /pool/get?subscriber_id=
    GET  /pool/status
    POST /crdt/digest                  CLSet anti-entropy (control/crdt.py)
    POST /crdt/entries {keys}
    POST /crdt/merge  {entries}
    POST /api/v1/allocate              Nexus allocator (nexus/http_allocator.go)
    GET  /api/v1/allocations/<id>
    DELETE /api/v1/allocations/<id>
    GET  /api/v1/pools

  client proxies, shaped exactly like the in-process objects the
  consumers already accept:
    HTTPActiveProxy   -> StandbySyncer transport   (full_sync/replay/subscribe)
    HTTPPeerProxy     -> PeerPool transport        (_allocate_local/.../status)
    HTTPStorePeer     -> DistributedStore.add_peer (digest/entries/merge)
    http_nexus_transport(url) -> HTTPAllocator transport callable

Every proxy raises ConnectionError on transport failure, which is the
signal the consumers' failover paths already handle (backoff reconnect,
ranked failover, skipped anti-entropy round).
"""

from __future__ import annotations

import base64
import json
import queue
import threading
import ssl
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable
from urllib.parse import parse_qs, urlparse

from bng_tpu.control.ha import ActiveSyncer, HAChange, SessionState
from bng_tpu.control.ztp_tls import CertificateValidationError
from bng_tpu.utils.structlog import ErrorLog
from bng_tpu.control.peerpool import PeerPool, PeerPoolError

__all__ = [
    "ClusterServer", "HTTPActiveProxy", "HTTPPeerProxy", "HTTPStorePeer",
    "http_nexus_transport",
]

_TIMEOUT = 3.0


def _b64(v: bytes | None) -> str | None:
    return None if v is None else base64.b64encode(v).decode()


def _unb64(v: str | None) -> bytes | None:
    return None if v is None else base64.b64decode(v)


def _change_dict(ch: HAChange) -> dict:
    return {
        "op": ch.op, "seq": ch.seq, "session_id": ch.session_id,
        "session": ch.session.to_dict() if ch.session is not None else None,
    }


def _change_from(d: dict) -> HAChange:
    sess = d.get("session")
    return HAChange(d["op"],
                    session=SessionState.from_dict(sess) if sess else None,
                    session_id=d.get("session_id", ""), seq=d["seq"])


class ClusterServer:
    """One node's control-plane listener. Mount the services the node runs;
    unmounted paths 404. start() binds (port=0 picks a free port)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, tls=None):
        """tls: ztp_tls.ServerTLSConfig — the listener speaks TLS
        (+ mutual TLS when the config carries a client CA). Plaintext
        when None. Parity: pkg/ha/sync.go:151-185's TLS/mTLS modes on
        the session-replication wire."""
        self.host = host
        self.port = port
        self.tls = tls
        self.ha: ActiveSyncer | None = None
        self.pool: PeerPool | None = None
        self.store = None  # CLSetStore / DistributedStore
        self.allocator = None  # object with allocate/lookup/release/pool_info
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._closing = threading.Event()  # terminates live SSE streams

    # ---- service mounting ----
    def mount_ha(self, active: ActiveSyncer) -> "ClusterServer":
        self.ha = active
        return self

    def mount_pool(self, pool: PeerPool) -> "ClusterServer":
        self.pool = pool
        return self

    def mount_store(self, store) -> "ClusterServer":
        from bng_tpu.control.crdt import DistributedStore

        self.store = store.store if isinstance(store, DistributedStore) else store
        return self

    def mount_allocator(self, allocator) -> "ClusterServer":
        """allocator: .allocate(subscriber_id, pool_hint) -> ip_str | None,
        .lookup(id) -> ip_str | None, .release(id) -> bool,
        .pool_info() -> dict."""
        self.allocator = allocator
        return self

    # ---- lifecycle ----
    def start(self) -> "ClusterServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            daemon_threads = True

            def log_message(self, *a):  # quiet
                pass

            # -- helpers --
            def _json(self, status: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> dict:
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n == 0:
                    return {}
                return json.loads(self.rfile.read(n) or b"{}")

            # -- routes --
            def do_GET(self):
                u = urlparse(self.path)
                q = parse_qs(u.query)
                try:
                    if u.path == "/health":
                        return self._json(200, {"ok": True})
                    if u.path == "/ha/sessions" and outer.ha:
                        sessions, seq = outer.ha.full_sync()
                        return self._json(200, {
                            "sessions": [s.to_dict() for s in sessions],
                            "seq": seq})
                    if u.path == "/ha/replay" and outer.ha:
                        since = int(q.get("since", ["0"])[0])
                        replay = outer.ha.replay_since(since)
                        if replay is None:
                            return self._json(410, {"error": "gap"})
                        return self._json(200, {
                            "changes": [_change_dict(c) for c in replay]})
                    if u.path == "/ha/stream" and outer.ha:
                        return self._stream(int(q.get("since", ["0"])[0]))
                    if u.path == "/pool/get" and outer.pool:
                        # local-slice read only (peer.go /get): the CALLER
                        # does the owner-chasing; answering with pool.get()
                        # here could recurse across peers
                        sid = q.get("subscriber_id", [""])[0]
                        return self._json(
                            200, {"value": outer.pool.by_subscriber.get(sid)})
                    if u.path == "/pool/status" and outer.pool:
                        return self._json(200, outer.pool.status())
                    if u.path.startswith("/api/v1/allocations/") and outer.allocator:
                        ip = outer.allocator.lookup(u.path.rsplit("/", 1)[1])
                        if ip is None:
                            return self._json(404, {})
                        return self._json(200, {"ip": ip})
                    if u.path == "/api/v1/pools" and outer.allocator:
                        return self._json(200, outer.allocator.pool_info())
                    if (u.path.startswith("/api/v1/allocation-by-ip/")
                            and outer.allocator):
                        # heal-time conflict detection asks who the
                        # CENTRAL store thinks owns an IP
                        # (conflict_detector.go:121-233's central view)
                        fn = getattr(outer.allocator, "lookup_by_ip", None)
                        if fn is None:
                            return self._json(404, {})
                        got = fn(u.path.rsplit("/", 1)[1])
                        if got is None:
                            return self._json(404, {})
                        sid, at = got
                        return self._json(200, {"subscriber_id": sid,
                                                "allocated_at": at})
                    return self._json(404, {"error": "not found"})
                except BrokenPipeError:
                    raise
                except Exception as e:  # route errors become 500s, not crashes
                    return self._json(500, {"error": f"{type(e).__name__}: {e}"})

            def do_POST(self):
                u = urlparse(self.path)
                try:
                    body = self._body()
                    if u.path == "/pool/allocate" and outer.pool:
                        try:
                            ip = outer.pool._allocate_local(body["subscriber_id"])
                            return self._json(200, {"value": ip})
                        except PeerPoolError as e:
                            return self._json(409, {"error": str(e)})
                    if u.path == "/pool/release" and outer.pool:
                        ok = outer.pool._release_local(body["subscriber_id"])
                        return self._json(200, {"ok": ok})
                    if u.path == "/crdt/digest" and outer.store:
                        return self._json(200, {"digest": {
                            k: list(v) for k, v in outer.store.digest().items()}})
                    if u.path == "/crdt/entries" and outer.store:
                        ent = outer.store.entries_for(body.get("keys", []))
                        return self._json(200, {"entries": {
                            k: [cl, ts, node, _b64(val)]
                            for k, (cl, ts, node, val) in ent.items()}})
                    if u.path == "/crdt/merge" and outer.store:
                        entries = {
                            k: (cl, ts, node, _unb64(val))
                            for k, (cl, ts, node, val) in body.get("entries", {}).items()}
                        return self._json(200, {
                            "changed": outer.store.merge_entries(entries)})
                    if u.path == "/api/v1/allocate" and outer.allocator:
                        ip = outer.allocator.allocate(body.get("subscriber_id", ""),
                                                      body.get("pool", ""))
                        if ip is None:
                            return self._json(404, {})
                        return self._json(200, {"ip": ip})
                    return self._json(404, {"error": "not found"})
                except BrokenPipeError:
                    raise
                except Exception as e:
                    return self._json(500, {"error": f"{type(e).__name__}: {e}"})

            def do_DELETE(self):
                u = urlparse(self.path)
                try:
                    if u.path.startswith("/api/v1/allocations/") and outer.allocator:
                        ok = outer.allocator.release(u.path.rsplit("/", 1)[1])
                        return self._json(200 if ok else 404, {"ok": ok})
                    return self._json(404, {"error": "not found"})
                except Exception as e:
                    return self._json(500, {"error": f"{type(e).__name__}: {e}"})

            # -- SSE (sync.go:304 handleSessionStream) --
            def _stream(self, since: int) -> None:
                ha = outer.ha
                # ORDER MATTERS: subscribe FIRST, take the replay snapshot
                # SECOND. A delta pushed between the two lands in the live
                # queue (and possibly also in the replay); the seq filter
                # below dedups the overlap. Snapshot-then-subscribe would
                # silently lose exactly that window (code-review r3).
                ch_q: "queue.Queue[HAChange]" = queue.Queue(maxsize=4096)
                overflow = threading.Event()

                def enqueue(ch: HAChange) -> None:
                    # NEVER raise into the active's push_change: a stalled
                    # standby loses its stream (it will reconnect and
                    # resync), the active keeps serving
                    try:
                        ch_q.put_nowait(ch)
                    except queue.Full:
                        overflow.set()

                cancel = ha.subscribe(enqueue)
                # everything from here runs under the finally that cancels
                # the subscription — a client that dies during the header
                # write must not leak its callback on the active
                last_seq = since
                idle = 0.0
                try:
                    # always consult replay — even at since=0: a standby
                    # that full-synced a FRESH active (seq 0) must still
                    # receive deltas from the sync-to-connect window
                    replay = ha.replay_since(since)
                    if replay is None:
                        return self._json(410, {"error": "gap"})
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    for ch in replay or []:
                        self._emit(ch)
                        last_seq = max(last_seq, ch.seq)
                    # poll at 1s so server close() ends the stream promptly
                    # (shutdown() only stops the accept loop — live handler
                    # threads would otherwise hold their sockets open and
                    # standbys would never see the active die)
                    while not outer._closing.is_set() and not overflow.is_set():
                        try:
                            ch = ch_q.get(timeout=1.0)
                        except queue.Empty:
                            idle += 1.0
                            if idle >= 15.0:
                                self.wfile.write(b": keepalive\n\n")
                                self.wfile.flush()
                                idle = 0.0
                            continue
                        if ch.seq <= last_seq:
                            continue
                        self._emit(ch)
                        last_seq = ch.seq
                        idle = 0.0
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away
                finally:
                    cancel()

            def _emit(self, ch: HAChange) -> None:
                data = json.dumps(_change_dict(ch))
                self.wfile.write(f"data: {data}\n\n".encode())
                self.wfile.flush()

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.tls is not None:
            from bng_tpu.control.ztp_tls import build_server_ssl_context

            ctx = build_server_ssl_context(self.tls)
            # handshake OFF the accept loop: with do_handshake_on_connect
            # a half-open client (no ClientHello) would block accept()
            # forever and wedge the whole control plane; deferred, the
            # handshake runs in the per-connection handler thread on the
            # first read (ThreadingHTTPServer), one thread per client
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name=f"cluster-http-{self.port}")
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        scheme = "https" if self.tls is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"

    def close(self) -> None:
        self._closing.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


# ---------------------------------------------------------------------------
# client-side proxies
# ---------------------------------------------------------------------------
def _make_pinned_https_connection(tls_cfg, ssl_ctx):
    """An http.client.HTTPSConnection subclass whose connect() runs
    ztp_tls verification on the presented chain BEFORE any request bytes
    are sent (the VerifyPeerCertificate role, tls.go:208-275) and
    performs SNI against cfg.server_name when set (peer dialed by IP,
    cert names a host)."""
    import http.client
    import socket as _socket

    from bng_tpu.control.ztp_tls import verify_wrapped_socket

    class Conn(http.client.HTTPSConnection):
        def connect(self):
            sock = _socket.create_connection(
                (self.host, self.port), self.timeout)
            if self._tunnel_host:  # pragma: no cover — no proxies here
                self.sock = sock
                self._tunnel()
                sock = self.sock
            sn = tls_cfg.server_name or self.host
            self.sock = ssl_ctx.wrap_socket(sock, server_hostname=sn)
            verify_wrapped_socket(self.sock, tls_cfg)  # raises pre-request

    return Conn


def make_cluster_opener(tls_cfg) -> "urllib.request.OpenerDirector":
    """An urllib opener whose https connections enforce the cluster TLS
    config (pinning + optional mTLS client identity). Used for every
    proxy request AND the SSE stream, so no wire path escapes the
    verification."""
    from bng_tpu.control.ztp_tls import build_ssl_context

    ctx = build_ssl_context(tls_cfg)
    conn_cls = _make_pinned_https_connection(tls_cfg, ctx)

    class Handler(urllib.request.HTTPSHandler):
        def https_open(self, req):
            return self.do_open(conn_cls, req)

    return urllib.request.build_opener(Handler())


def _req(method: str, url: str, body: dict | None = None,
         timeout: float = _TIMEOUT, opener=None) -> tuple[int, dict]:
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type": "application/json"})
    open_ = opener.open if opener is not None else urllib.request.urlopen
    try:
        with open_(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read() or b"{}")
        except Exception:
            return e.code, {}
    except CertificateValidationError:
        # already a ConnectionError (by design) AND carries the why —
        # don't flatten it into a generic transport failure
        raise
    except (urllib.error.URLError, TimeoutError, ConnectionError, OSError,
            ssl.SSLError) as e:
        # urllib wraps OSError-derived refusals (CertificateValidation-
        # Error included) into URLError(reason=...): unwrap so the why
        # survives to callers that assert on it
        reason = getattr(e, "reason", None)
        if isinstance(reason, CertificateValidationError):
            raise reason
        raise ConnectionError(f"{method} {url}: {e}") from e


class HTTPActiveProxy:
    """StandbySyncer transport target: the active node over HTTP+SSE.

    full_sync/replay_since are plain GETs; subscribe() opens the SSE
    stream in a reader thread and invokes the callback per delta. When
    the stream drops, on_stream_end fires (wire it to standby.disconnect
    so the tick loop reconnects with backoff)."""

    def __init__(self, url: str, on_stream_end: Callable[[], None] | None = None,
                 tls=None):
        """tls: ztp_tls.TLSConfig — verify (and pin) the active's cert on
        every request including the SSE stream; carries our client
        identity when the active demands mTLS."""
        self.url = url.rstrip("/")
        self.on_stream_end = on_stream_end
        self._opener = make_cluster_opener(tls) if tls is not None else None
        self._stream_err_log = ErrorLog(
            "cluster", "SSE stream died; standby will reconnect")
        self._seen_seq = 0  # high-water mark from full_sync/replay_since
        # fail fast like an in-process transport: unreachable = raise now
        status, _ = self._req("GET", f"{self.url}/health")
        if status != 200:
            raise ConnectionError(f"active unhealthy: {status}")

    def _req(self, method, url, body=None, timeout=_TIMEOUT):
        return _req(method, url, body, timeout, opener=self._opener)

    def full_sync(self):
        status, body = self._req("GET", f"{self.url}/ha/sessions")
        if status != 200:
            raise ConnectionError(f"full_sync {status}")
        self._seen_seq = body["seq"]
        return ([SessionState.from_dict(d) for d in body["sessions"]], body["seq"])

    def replay_since(self, seq: int):
        status, body = self._req("GET", f"{self.url}/ha/replay?since={seq}")
        if status == 410:
            return None
        if status != 200:
            raise ConnectionError(f"replay {status}")
        changes = [_change_from(d) for d in body["changes"]]
        self._seen_seq = max([seq] + [c.seq for c in changes])
        return changes

    def subscribe(self, cb: Callable[[HAChange], None]) -> Callable[[], None]:
        stop = threading.Event()
        since = self._seen_seq

        def reader():
            from bng_tpu.analysis.sanitize import ctx_enter

            ctx_enter("ha-sync")
            try:
                # since = the snapshot's high-water seq: the server replays
                # anything newer into the stream, so the window between the
                # sync GET and this connect cannot drop deltas
                req = urllib.request.Request(f"{self.url}/ha/stream?since={since}")
                open_ = (self._opener.open if self._opener is not None
                         else urllib.request.urlopen)
                with open_(req, timeout=60.0) as r:
                    for raw in r:
                        if stop.is_set():
                            return
                        line = raw.decode().strip()
                        if line.startswith("data: "):
                            cb(_change_from(json.loads(line[6:])))
            except Exception as e:  # noqa: BLE001 — any stream failure
                # means reconnect; the reason still matters (TLS reject
                # vs timeout vs bad payload diagnose very differently)
                self._stream_err_log.report(e, since=since)
            finally:
                if not stop.is_set() and self.on_stream_end is not None:
                    self.on_stream_end()

        t = threading.Thread(target=reader, daemon=True, name="ha-sse-reader")
        t.start()

        def cancel():
            stop.set()

        return cancel


class _RemoteBySubscriber:
    """Read-only mapping shim: PeerPool.get() reads
    `transport(node).by_subscriber.get(sid)` on the in-process transport;
    over HTTP that dict access becomes one GET."""

    def __init__(self, proxy: "HTTPPeerProxy"):
        self._proxy = proxy

    def get(self, subscriber_id: str):
        return self._proxy.get(subscriber_id)


class HTTPPeerProxy:
    """PeerPool transport target: a remote peer's local pool slice."""

    def __init__(self, url: str, tls=None):
        self.url = url.rstrip("/")
        self._opener = make_cluster_opener(tls) if tls is not None else None
        self.by_subscriber = _RemoteBySubscriber(self)

    def _req(self, method, url, body=None, timeout=_TIMEOUT):
        return _req(method, url, body, timeout, opener=self._opener)

    def _allocate_local(self, subscriber_id: str) -> int:
        status, body = self._req("POST", f"{self.url}/pool/allocate",
                            {"subscriber_id": subscriber_id})
        if status == 200:
            return body["value"]
        if status == 409:
            raise PeerPoolError(body.get("error", "allocate failed"))
        raise ConnectionError(f"allocate {status}")

    def _release_local(self, subscriber_id: str) -> bool:
        status, body = self._req("POST", f"{self.url}/pool/release",
                            {"subscriber_id": subscriber_id})
        if status != 200:
            raise ConnectionError(f"release {status}")
        return body["ok"]

    def get(self, subscriber_id: str):
        # ids are free-form operator strings (circuit IDs etc.) — quote them
        sid = urllib.parse.quote(subscriber_id, safe="")
        status, body = self._req("GET", f"{self.url}/pool/get?subscriber_id={sid}")
        if status != 200:
            raise ConnectionError(f"get {status}")
        return body["value"]

    def status(self) -> dict:
        status, body = self._req("GET", f"{self.url}/pool/status")
        if status != 200:
            raise ConnectionError(f"status {status}")
        return body


class HTTPStorePeer:
    """DistributedStore.add_peer target: remote CLSet over HTTP."""

    def __init__(self, url: str, tls=None):
        self.url = url.rstrip("/")
        self._opener = make_cluster_opener(tls) if tls is not None else None

    def _req(self, method, url, body=None, timeout=_TIMEOUT):
        return _req(method, url, body, timeout, opener=self._opener)

    def digest(self):
        status, body = self._req("POST", f"{self.url}/crdt/digest", {})
        if status != 200:
            raise ConnectionError(f"digest {status}")
        return {k: tuple(v) for k, v in body["digest"].items()}

    def entries_for(self, keys):
        status, body = self._req("POST", f"{self.url}/crdt/entries",
                            {"keys": list(keys)})
        if status != 200:
            raise ConnectionError(f"entries {status}")
        return {k: (cl, ts, node, _unb64(val))
                for k, (cl, ts, node, val) in body["entries"].items()}

    def merge_entries(self, entries) -> int:
        wire = {k: [cl, ts, node, _b64(val)]
                for k, (cl, ts, node, val) in entries.items()}
        status, body = self._req("POST", f"{self.url}/crdt/merge", {"entries": wire})
        if status != 200:
            raise ConnectionError(f"merge {status}")
        return body["changed"]


def http_nexus_transport(url: str, tls=None) -> Callable:
    """HTTPAllocator-shaped transport: (method, path, body) -> (status, body)."""
    base = url.rstrip("/")
    opener = make_cluster_opener(tls) if tls is not None else None

    def transport(method: str, path: str, body: dict | None):
        return _req(method, f"{base}{path}", body, opener=opener)

    return transport
