"""On-OLT agent daemon: bootstrap -> watch Nexus -> local caches.

Parity: pkg/agent — Agent state machine + loops (agent.go:41-313),
subscriber/NTE/ISP local caches with by-MAC / by-NTE lookups
(agent.go:315-455), ISP churn events (agent.go:389-412), heartbeat loop
(agent.go:255-300), health snapshot (agent.go:457-486), bootstrap
integration via ztp.BootstrapClient (bootstrap.go:62-340).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum

from bng_tpu.control.nexus import (ISPConfigEntity, NTEEntity, NexusClient,
                                   SubscriberEntity)


class AgentState(str, Enum):
    """types.go:10-40."""

    INIT = "init"
    BOOTSTRAPPING = "bootstrapping"
    SYNCING = "syncing"
    ONLINE = "online"
    DEGRADED = "degraded"
    STOPPED = "stopped"


@dataclass
class AgentConfig:
    """agent.go:16-39."""

    device_id: str = ""
    heartbeat_interval: float = 30.0
    sync_interval: float = 60.0
    degraded_after: float = 90.0  # missed-heartbeat window


class Agent:
    """agent.go:41-486. The nexus client is injected; watchers keep the
    local caches warm so the dataplane never blocks on Nexus."""

    def __init__(self, config: AgentConfig, nexus: NexusClient,
                 bootstrap_client=None, clock=time.time):
        """bootstrap_client: an optional ztp.BootstrapClient — when given,
        start() runs the full registration flow first (the agent's TLS
        bootstrap variant, pkg/agent/bootstrap.go:62-340, typically over
        ztp.make_https_transport's pinned channel) and adopts the
        returned DeviceConfig (node identity, partner, pools)."""
        self.config = config
        self.nexus = nexus
        self._bootstrap = bootstrap_client
        self.device_config = None  # ztp.DeviceConfig after bootstrap
        self._clock = clock
        self._lock = threading.Lock()
        self._state = AgentState.INIT
        self._started_at = 0.0
        self._last_heartbeat_ok = 0.0
        self._subscribers: dict[str, SubscriberEntity] = {}
        self._by_mac: dict[str, str] = {}
        self._by_nte: dict[str, str] = {}
        self._ntes: dict[str, NTEEntity] = {}
        self._isps: dict[str, ISPConfigEntity] = {}
        self.on_state_change = None
        self.on_config_change = None
        self.on_isp_churn = None
        self.stats = {"heartbeats": 0, "heartbeat_failures": 0,
                      "subscriber_updates": 0, "nte_updates": 0,
                      "isp_churns": 0, "bootstrapped": 0,
                      "bootstrap_failures": 0}

    # -- state ----------------------------------------------------------

    @property
    def state(self) -> AgentState:
        with self._lock:
            return self._state

    def _set_state(self, new: AgentState) -> None:
        with self._lock:
            old, self._state = self._state, new
        if old != new and self.on_state_change:
            self.on_state_change(old, new)

    def is_online(self) -> bool:
        return self.state == AgentState.ONLINE

    def uptime(self) -> float:
        return self._clock() - self._started_at if self._started_at else 0.0

    # -- lifecycle ------------------------------------------------------

    def start(self, bootstrap_deadline: float | None = None) -> None:
        """Synchronous start: bootstrap -> full sync -> watch. The
        composition root drives heartbeat()/tick() on its scheduler
        (the reference's goroutine loops, agent.go:216-313)."""
        self._started_at = self._clock()
        self._set_state(AgentState.BOOTSTRAPPING)
        if self._bootstrap is not None:
            # registration poll (pending/backoff handled by the client);
            # the returned DeviceConfig is this agent's durable identity.
            # A failed bootstrap must not leave a live-looking agent stuck
            # in 'bootstrapping': transition to DEGRADED, then re-raise.
            try:
                dev = self._bootstrap.bootstrap(deadline=bootstrap_deadline)
            except BaseException:
                self.stats["bootstrap_failures"] += 1
                self._set_state(AgentState.DEGRADED)
                raise
            self.device_config = dev
            if dev.node_id:
                self.config.device_id = dev.node_id
            self.stats["bootstrapped"] = 1
        self._set_state(AgentState.SYNCING)
        self._full_sync()
        self._watch()
        self._last_heartbeat_ok = self._clock()
        self._set_state(AgentState.ONLINE)

    def stop(self) -> None:
        self._set_state(AgentState.STOPPED)

    def _full_sync(self) -> None:
        for sid, sub in self.nexus.subscribers.list().items():
            self._put_subscriber(sid, sub)
        for nid, nte in self.nexus.ntes.list().items():
            with self._lock:
                self._ntes[nid] = nte
        for iid, isp in self.nexus.isps.list().items():
            with self._lock:
                self._isps[iid] = isp

    def _watch(self) -> None:
        self.nexus.subscribers.watch(self._on_subscriber)
        self.nexus.ntes.watch(self._on_nte)
        self.nexus.isps.watch(self._on_isp)

    # -- heartbeat (agent.go:255-300) -----------------------------------

    def heartbeat(self) -> bool:
        try:
            self.nexus.heartbeat(self.config.device_id)
            self._last_heartbeat_ok = self._clock()
            self.stats["heartbeats"] += 1
            if self.state == AgentState.DEGRADED:
                self._set_state(AgentState.ONLINE)
            return True
        except Exception:
            self.stats["heartbeat_failures"] += 1
            self.tick()
            return False

    def tick(self) -> None:
        """Degrade when heartbeats stop landing."""
        if (self.state == AgentState.ONLINE
                and self._clock() - self._last_heartbeat_ok
                > self.config.degraded_after):
            self._set_state(AgentState.DEGRADED)

    # -- cache maintenance ---------------------------------------------

    def _put_subscriber(self, sid: str, sub: SubscriberEntity) -> None:
        with self._lock:
            old = self._subscribers.get(sid)
            self._subscribers[sid] = sub
            if sub.mac:
                self._by_mac[sub.mac.lower()] = sid
            if sub.nte_id:
                self._by_nte[sub.nte_id] = sid
        self.stats["subscriber_updates"] += 1
        if (old is not None and old.isp_id and sub.isp_id
                and old.isp_id != sub.isp_id):
            self.stats["isp_churns"] += 1
            if self.on_isp_churn:
                self.on_isp_churn(sid, old.isp_id, sub.isp_id)
        if self.on_config_change:
            self.on_config_change("subscriber", sid)

    def _on_subscriber(self, sid: str, sub: SubscriberEntity | None) -> None:
        if sub is None:
            self.remove_subscriber(sid)
        else:
            self._put_subscriber(sid, sub)

    def _on_nte(self, nid: str, nte: NTEEntity | None) -> None:
        with self._lock:
            if nte is None:
                self._ntes.pop(nid, None)
            else:
                self._ntes[nid] = nte
        self.stats["nte_updates"] += 1

    def _on_isp(self, iid: str, isp: ISPConfigEntity | None) -> None:
        with self._lock:
            if isp is None:
                self._isps.pop(iid, None)
            else:
                self._isps[iid] = isp

    def remove_subscriber(self, sid: str) -> None:
        with self._lock:
            sub = self._subscribers.pop(sid, None)
            if sub is not None:
                if sub.mac and self._by_mac.get(sub.mac.lower()) == sid:
                    del self._by_mac[sub.mac.lower()]
                serial = sub.nte_id
                if serial and self._by_nte.get(serial) == sid:
                    del self._by_nte[serial]

    # -- lookups (agent.go:315-455) -------------------------------------

    def get_subscriber(self, sid: str) -> SubscriberEntity | None:
        with self._lock:
            return self._subscribers.get(sid)

    def get_subscriber_by_mac(self, mac: str) -> SubscriberEntity | None:
        with self._lock:
            sid = self._by_mac.get(mac.lower())
            return self._subscribers.get(sid) if sid else None

    def get_subscriber_by_nte(self, serial: str) -> SubscriberEntity | None:
        with self._lock:
            sid = self._by_nte.get(serial)
            return self._subscribers.get(sid) if sid else None

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subscribers)

    def subscriber_count_by_isp(self) -> dict[str, int]:
        with self._lock:
            out: dict[str, int] = {}
            for sub in self._subscribers.values():
                if sub.isp_id:
                    out[sub.isp_id] = out.get(sub.isp_id, 0) + 1
            return out

    def get_nte(self, serial: str) -> NTEEntity | None:
        with self._lock:
            return self._ntes.get(serial)

    def nte_count(self) -> int:
        with self._lock:
            return len(self._ntes)

    def get_isp_config(self, isp_id: str) -> ISPConfigEntity | None:
        with self._lock:
            return self._isps.get(isp_id)

    def health(self) -> dict:
        """agent.go:457-486."""
        return {
            "state": self.state.value,
            "device_id": self.config.device_id,
            "uptime_s": self.uptime(),
            "subscribers": self.subscriber_count(),
            "ntes": self.nte_count(),
            "last_heartbeat_age_s": (self._clock() - self._last_heartbeat_ok
                                     if self._last_heartbeat_ok else -1),
            **self.stats,
        }
