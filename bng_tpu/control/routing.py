"""Multi-ISP routing: platform abstraction, BGP/BFD via FRR, subscriber routes.

Parity: pkg/routing — RoutingPlatform interface (manager.go:159-179) with
an in-memory stub (netlink_stub.go:13; the Linux netlink impl is a thin
adapter the composition root supplies), Manager with upstreams / ISP
tables / policy routing / ECMP / health checks (manager.go:15-663),
BGPController driving FRR through a pluggable vtysh executor
(bgp.go:18-848: neighbors :219-321, announce/withdraw :323-399, max-paths
:431, per-neighbor BFD :451, route-maps :490, table import :517, config
generation :758-817), BFDManager (bfd.go:19-430), SubscriberRouteManager
injecting per-subscriber /32s with BGP communities by class and a retry
queue (subscriber_routes.go:16-668).

All FRR interaction goes through `executor(command) -> str` so everything
runs hermetically; production wires `lambda c: subprocess.run(["vtysh",
"-c", c], ...)` exactly like bgp.go:554-578.
"""

from __future__ import annotations

import ipaddress
import json
import threading
import time
from dataclasses import dataclass, field
from enum import Enum


# ---------------------------------------------------------------------------
# Platform abstraction (manager.go:117-190)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class NextHop:
    gateway: str
    interface: str = ""
    weight: int = 1


@dataclass(frozen=True)
class Route:
    destination: str  # CIDR
    gateway: str = ""
    interface: str = ""
    table: int = 254  # main
    metric: int = 0
    nexthops: tuple = ()  # ECMP


@dataclass(frozen=True)
class PolicyRule:
    priority: int
    table: int
    src: str = ""  # CIDR
    dst: str = ""
    fwmark: int = 0


@dataclass
class InterfaceInfo:
    name: str
    index: int = 0
    mtu: int = 1500
    hwaddr: str = ""
    up: bool = True
    addresses: list[str] = field(default_factory=list)


class StubPlatform:
    """In-memory RoutingPlatform (netlink_stub.go:13): a route/rule table
    that behaves observably like the netlink one. ping() consults a
    settable reachability map."""

    def __init__(self):
        self._lock = threading.Lock()
        self.routes: dict[int, list[Route]] = {}
        self.rules: list[PolicyRule] = []
        self.interfaces: dict[str, InterfaceInfo] = {
            "lo": InterfaceInfo(name="lo", index=1)}
        self.reachable: dict[str, float] = {}  # ip -> rtt seconds

    def add_route(self, route: Route) -> None:
        with self._lock:
            table = self.routes.setdefault(route.table, [])
            if route in table:
                raise FileExistsError(f"route exists: {route}")
            table.append(route)

    def delete_route(self, route: Route) -> None:
        with self._lock:
            table = self.routes.get(route.table, [])
            try:
                table.remove(route)
            except ValueError:
                raise FileNotFoundError(f"no such route: {route}") from None

    def get_routes(self, table: int) -> list[Route]:
        with self._lock:
            return list(self.routes.get(table, []))

    def flush_table(self, table: int) -> None:
        with self._lock:
            self.routes[table] = []

    def add_rule(self, rule: PolicyRule) -> None:
        with self._lock:
            if rule in self.rules:
                raise FileExistsError(f"rule exists: {rule}")
            self.rules.append(rule)
            self.rules.sort(key=lambda r: r.priority)

    def delete_rule(self, rule: PolicyRule) -> None:
        with self._lock:
            try:
                self.rules.remove(rule)
            except ValueError:
                raise FileNotFoundError(f"no such rule: {rule}") from None

    def get_rules(self) -> list[PolicyRule]:
        with self._lock:
            return list(self.rules)

    def get_interface(self, name: str) -> InterfaceInfo:
        with self._lock:
            if name not in self.interfaces:
                raise FileNotFoundError(f"no such interface: {name}")
            return self.interfaces[name]

    def set_interface_up(self, name: str) -> None:
        self.get_interface(name).up = True

    def set_interface_down(self, name: str) -> None:
        self.get_interface(name).up = False

    def ping(self, target: str, timeout: float = 1.0) -> float:
        with self._lock:
            rtt = self.reachable.get(target)
        if rtt is None or rtt > timeout:
            raise TimeoutError(f"ping {target} timed out")
        return rtt


class IPRoute2Platform:
    """Linux RoutingPlatform over iproute2 (`ip -j`) subprocesses.

    The real-kernel counterpart of StubPlatform — the role of the
    reference's NetlinkPlatform (pkg/routing/netlink_linux.go:20-442),
    using the `ip(8)` CLI's JSON output instead of a netlink library
    (pyroute2 is not in the image; iproute2 is, and its -j output is the
    stable programmatic interface). Same observable contract as the stub:
    FileExistsError on duplicate adds, FileNotFoundError on missing
    deletes/interfaces, TimeoutError from ping.

    `runner` is injectable for hermetic tests; production uses
    subprocess.run. Requires CAP_NET_ADMIN for mutations.
    """

    def __init__(self, runner=None, ip_binary: str = "ip",
                 timeout: float = 5.0):
        import subprocess

        self._ip = ip_binary
        self._timeout = timeout
        self._runner = runner or (lambda args: subprocess.run(
            args, capture_output=True, text=True, timeout=self._timeout))

    # -- plumbing ----------------------------------------------------------
    def _run(self, *args: str, check: bool = True) -> str:
        res = self._runner([self._ip, *args])
        if check and res.returncode != 0:
            err = (res.stderr or res.stdout or "").strip()
            low = err.lower()
            if "file exists" in low:
                raise FileExistsError(err)
            if ("no such" in low or "not found" in low
                    or "cannot find" in low or "does not exist" in low):
                raise FileNotFoundError(err)
            raise RuntimeError(f"ip {' '.join(args)}: rc="
                               f"{res.returncode}: {err[:200]}")
        return res.stdout

    def _json(self, *args: str):
        out = self._run("-j", *args)
        return json.loads(out) if out.strip() else []

    @staticmethod
    def _route_args(route: Route) -> list[str]:
        args = [route.destination, "table", str(route.table)]
        if route.nexthops:  # ECMP (netlink_linux.go multipath role)
            for nh in route.nexthops:
                args.append("nexthop")
                if nh.gateway:
                    args += ["via", nh.gateway]
                if nh.interface:
                    args += ["dev", nh.interface]
                args += ["weight", str(max(1, nh.weight))]
            return args
        if route.gateway:
            args += ["via", route.gateway]
        if route.interface:
            args += ["dev", route.interface]
        if route.metric:
            args += ["metric", str(route.metric)]
        return args

    # -- routes ------------------------------------------------------------
    def add_route(self, route: Route) -> None:
        self._run("route", "add", *self._route_args(route))

    def delete_route(self, route: Route) -> None:
        self._run("route", "del", *self._route_args(route))

    def get_routes(self, table: int) -> list[Route]:
        routes = []
        for r in self._json("route", "show", "table", str(table)):
            nexthops = tuple(
                NextHop(gateway=nh.get("gateway", ""),
                        interface=nh.get("dev", ""),
                        weight=int(nh.get("weight", 1)))
                for nh in r.get("nexthops", ()))
            dst = r.get("dst", "")
            if dst == "default":
                dst = "0.0.0.0/0"
            elif "/" not in dst:
                dst += "/32"
            routes.append(Route(
                destination=dst, gateway=r.get("gateway", ""),
                interface=r.get("dev", ""), table=table,
                metric=int(r.get("metric", 0)), nexthops=nexthops))
        return routes

    def flush_table(self, table: int) -> None:
        self._run("route", "flush", "table", str(table), check=False)

    # -- policy rules (ip rule) --------------------------------------------
    @staticmethod
    def _rule_args(rule: PolicyRule) -> list[str]:
        args = ["priority", str(rule.priority)]
        args += ["from", rule.src or "all"]
        if rule.dst:
            args += ["to", rule.dst]
        if rule.fwmark:
            args += ["fwmark", str(rule.fwmark)]
        args += ["table", str(rule.table)]
        return args

    def add_rule(self, rule: PolicyRule) -> None:
        # the kernel rejects exact duplicates with EEXIST ("File exists"),
        # which _run maps to the stub's FileExistsError contract — no
        # O(total rules) pre-scan per subscriber rule
        self._run("rule", "add", *self._rule_args(rule))

    def delete_rule(self, rule: PolicyRule) -> None:
        # the kernel's own ENOENT ("No such file or directory") maps to
        # FileNotFoundError in _run — no O(total rules) pre-scan needed
        self._run("rule", "del", *self._rule_args(rule))

    def get_rules(self) -> list[PolicyRule]:
        rules = []
        for r in self._json("rule", "show"):
            table = r.get("table", "")
            if not str(table).isdigit():
                continue  # local/main/default system tables
            src = r.get("src", "")
            if src in ("all", ""):
                src = ""
            else:  # iproute2 omits srclen for /32: normalize to CIDR
                src += f"/{r.get('srclen', 32)}"
            dst = r.get("dst", "")
            if dst:
                dst += f"/{r.get('dstlen', 32)}"
            rules.append(PolicyRule(
                priority=int(r.get("priority", 0)), table=int(table),
                src=src, dst=dst, fwmark=int(r.get("fwmark", "0x0"), 16)
                if isinstance(r.get("fwmark"), str) else int(r.get("fwmark", 0))))
        return rules

    # -- interfaces --------------------------------------------------------
    def get_interface(self, name: str) -> InterfaceInfo:
        links = self._json("link", "show", "dev", name)
        if not links:
            raise FileNotFoundError(f"no such interface: {name}")
        link = links[0]
        addrs = []
        for a in self._json("addr", "show", "dev", name):
            for ai in a.get("addr_info", ()):
                addrs.append(f"{ai['local']}/{ai['prefixlen']}")
        return InterfaceInfo(
            name=name, index=int(link.get("ifindex", 0)),
            mtu=int(link.get("mtu", 1500)),
            hwaddr=link.get("address", ""),
            up="UP" in link.get("flags", ()), addresses=addrs)

    def set_interface_up(self, name: str) -> None:
        self._run("link", "set", "dev", name, "up")

    def set_interface_down(self, name: str) -> None:
        self._run("link", "set", "dev", name, "down")

    # -- health ------------------------------------------------------------
    def ping(self, target: str, timeout: float = 1.0) -> float:
        """ICMP echo probe — raw-socket first (the reference's approach,
        netlink_linux.go:237; needs CAP_NET_RAW), ping(8) as the unprivileged
        fallback. Returns RTT seconds, raises TimeoutError on no reply."""
        import os
        import socket
        import struct

        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_RAW,
                              socket.IPPROTO_ICMP)
        except PermissionError:
            return self._ping_binary(target, timeout)
        from bng_tpu.control.packets import checksum16

        try:
            s.settimeout(timeout)
            # ident+seq+random token: a reply only counts if it echoes THIS
            # probe's token AND comes from the probed address — a late
            # reply from a previous (slower) target must never validate a
            # dead upstream (review r4)
            ident = os.getpid() & 0xFFFF
            seq = next(_PING_SEQ) & 0xFFFF
            token = os.urandom(8)
            payload = struct.pack("!HH", ident, seq) + token
            csum = checksum16(struct.pack("!BBH", 8, 0, 0) + payload)
            pkt = struct.pack("!BBH", 8, 0, csum) + payload
            t0 = time.monotonic()
            s.sendto(pkt, (target, 0))
            deadline = t0 + timeout
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(f"ping {target} timed out")
                s.settimeout(remaining)
                try:
                    data, addr = s.recvfrom(2048)
                except (socket.timeout, TimeoutError):
                    raise TimeoutError(f"ping {target} timed out") from None
                if addr[0] != target:
                    continue
                # strip the IP header; match echo-reply + ident/seq/token
                ihl = (data[0] & 0x0F) * 4
                icmp = data[ihl:]
                if (len(icmp) >= 16 and icmp[0] == 0
                        and icmp[4:8] == payload[:4]
                        and icmp[8:16] == token):
                    return time.monotonic() - t0
        finally:
            s.close()

    @staticmethod
    def _ping_binary(target: str, timeout: float) -> float:
        import subprocess

        t0 = time.monotonic()
        try:
            res = subprocess.run(
                ["ping", "-c", "1", "-W", str(max(1, int(timeout))), target],
                capture_output=True, text=True, timeout=timeout + 2)
        except (subprocess.TimeoutExpired, FileNotFoundError):
            raise TimeoutError(f"ping {target} unavailable/timed out") from None
        if res.returncode != 0:
            raise TimeoutError(f"ping {target} failed: rc={res.returncode}")
        return time.monotonic() - t0


# monotone ICMP sequence across all platform instances in this process
import itertools as _itertools

_PING_SEQ = _itertools.count(1)


def vtysh_executor(binary: str = "vtysh", timeout: float = 10.0,
                   runner=None):
    """Real FRR executor: `vtysh -c <line> -c <line> ...` subprocesses.

    Parity: the reference builds exactly this command per call
    (pkg/routing/bgp.go:554-578, wired in cmd/bng/main.go:884-940).
    BGPController hands multi-line configs as newline-joined strings;
    each line becomes its own -c argument, matching vtysh semantics.
    Returns stdout; raises RuntimeError on nonzero rc so controller state
    never silently diverges from FRR.
    """
    import subprocess

    # bounded argv: a bulk inject/withdraw at 1M-subscriber scale would
    # otherwise exceed ARG_MAX (execve E2BIG). Chunks re-enter config mode
    # so each invocation is a complete vtysh session.
    MAX_LINES = 400

    def _invoke(lines: list[str]) -> str:
        args = [binary]
        for line in lines:
            args += ["-c", line]
        run = runner or (lambda a: subprocess.run(
            a, capture_output=True, text=True, timeout=timeout))
        res = run(args)
        if res.returncode != 0:
            err = (res.stderr or res.stdout or "").strip()
            raise RuntimeError(f"vtysh rc={res.returncode}: {err[:200]}")
        return res.stdout

    # vtysh context-entering prefixes: a chunk boundary inside one of
    # these blocks must REPLAY the block entry (advisor r4: replaying only
    # the initial preamble re-entered the FIRST router context for lines
    # belonging to a LATER one)
    ENTER = ("router ", "address-family ", "interface ", "route-map ",
             "vrf ")

    def execute(command: str) -> str:
        lines = command.split("\n")
        if len(lines) <= MAX_LINES:
            return _invoke(lines)
        out = []
        stack: list[str] = []  # live context path, outermost first
        chunk: list[str] = []

        def depth(entry: str) -> int:
            s = entry.strip()
            if s.startswith("configure"):
                return 0
            return 2 if s.startswith("address-family ") else 1

        def track(line: str) -> None:
            s = line.strip()
            if s.startswith("configure"):
                stack.clear()
                stack.append(line)
            elif any(s.startswith(p) for p in ENTER):
                # vtysh implicitly leaves a sibling stanza when the next
                # one opens (consecutive `interface X` blocks carry no
                # `exit`): pop to ABOVE this line's depth, then push —
                # bounds the stack at [configure, level-1, addr-family]
                d = depth(line)
                while stack and depth(stack[-1]) >= d:
                    stack.pop()
                stack.append(line)
            elif s in ("end", "quit"):
                stack.clear()  # back to exec mode
            elif s in ("exit", "exit-address-family", "exit-vrf"):
                if stack:
                    stack.pop()

        for line in lines:
            if not chunk and stack:
                chunk.extend(stack)  # re-enter the CURRENT context
            chunk.append(line)
            track(line)
            if len(chunk) >= MAX_LINES:
                out.append(_invoke(chunk))
                chunk = []
        if chunk:
            out.append(_invoke(chunk))
        return "".join(out)

    return execute


class LinkState(str, Enum):
    UNKNOWN = "unknown"
    UP = "up"
    DOWN = "down"


@dataclass
class Upstream:
    """manager.go:75-101: one ISP uplink."""

    name: str
    interface: str = ""
    gateway: str = ""
    table: int = 0
    health_target: str = ""  # IP pinged by the health checker
    weight: int = 1
    state: LinkState = LinkState.UNKNOWN
    consecutive_failures: int = 0
    last_rtt: float = 0.0


@dataclass
class RoutingConfig:
    """manager.go:46-73."""

    default_table: int = 254
    enable_ecmp: bool = True
    enable_policy_routing: bool = True
    health_check_interval: float = 5.0
    health_check_timeout: float = 1.0
    failure_threshold: int = 3


class RoutingManager:
    """manager.go:15-663."""

    def __init__(self, config: RoutingConfig | None = None, platform=None):
        self.config = config or RoutingConfig()
        self.platform = platform or StubPlatform()
        self._lock = threading.Lock()
        self._upstreams: dict[str, Upstream] = {}
        self.on_upstream_up = None
        self.on_upstream_down = None
        self.stats = {"routes_added": 0, "routes_deleted": 0,
                      "rules_added": 0, "failovers": 0, "health_checks": 0}

    # -- upstreams (manager.go:258-343) ---------------------------------

    def add_upstream(self, upstream: Upstream) -> None:
        with self._lock:
            if upstream.name in self._upstreams:
                raise ValueError(f"upstream {upstream.name} exists")
            self._upstreams[upstream.name] = upstream
        if upstream.table and upstream.gateway:
            self.create_isp_table(upstream.name, upstream.table,
                                  upstream.gateway, upstream.interface)

    def remove_upstream(self, name: str) -> None:
        with self._lock:
            up = self._upstreams.pop(name, None)
        if up is not None and up.table:
            self.platform.flush_table(up.table)

    def get_upstream(self, name: str) -> Upstream | None:
        with self._lock:
            return self._upstreams.get(name)

    def list_upstreams(self) -> list[Upstream]:
        with self._lock:
            return list(self._upstreams.values())

    # -- routes (manager.go:345-519) ------------------------------------

    def set_default_gateway(self, gateway: str, interface: str = "") -> None:
        self.add_route(Route(destination="0.0.0.0/0", gateway=gateway,
                             interface=interface,
                             table=self.config.default_table))

    def set_default_gateway_ecmp(self, nexthops: list[NextHop]) -> None:
        """manager.go:360-375."""
        if not self.config.enable_ecmp:
            raise ValueError("ECMP disabled")
        self.add_route(Route(destination="0.0.0.0/0",
                             table=self.config.default_table,
                             nexthops=tuple(nexthops)))

    def add_route(self, route: Route) -> None:
        self.platform.add_route(route)
        self.stats["routes_added"] += 1

    def delete_route(self, route: Route) -> None:
        self.platform.delete_route(route)
        self.stats["routes_deleted"] += 1

    def add_policy_rule(self, rule: PolicyRule) -> None:
        if not self.config.enable_policy_routing:
            raise ValueError("policy routing disabled")
        self.platform.add_rule(rule)
        self.stats["rules_added"] += 1

    # -- per-ISP tables (manager.go:521-572) -----------------------------

    def create_isp_table(self, isp_id: str, table_id: int, gateway: str,
                         interface: str = "") -> None:
        """Default route in the ISP's table; subscribers are steered with
        per-source rules."""
        self.platform.add_route(Route(destination="0.0.0.0/0", gateway=gateway,
                                      interface=interface, table=table_id))

    def route_subscriber_to_isp(self, subscriber_ip: str, table_id: int,
                                priority: int = 1000) -> PolicyRule:
        rule = PolicyRule(priority=priority, table=table_id,
                          src=f"{subscriber_ip}/32")
        self.add_policy_rule(rule)
        return rule

    def unroute_subscriber(self, subscriber_ip: str, table_id: int,
                           priority: int = 1000) -> None:
        self.platform.delete_rule(PolicyRule(priority=priority, table=table_id,
                                             src=f"{subscriber_ip}/32"))

    # -- health checking (manager.go:592-640) ---------------------------

    def check_health(self) -> None:
        """One sweep of all upstream health targets."""
        for up in self.list_upstreams():
            if not up.health_target:
                continue
            self.stats["health_checks"] += 1
            try:
                up.last_rtt = self.platform.ping(
                    up.health_target, self.config.health_check_timeout)
                up.consecutive_failures = 0
                if up.state != LinkState.UP:
                    up.state = LinkState.UP
                    if self.on_upstream_up:
                        self.on_upstream_up(up.name)
            except Exception:
                up.consecutive_failures += 1
                if (up.state != LinkState.DOWN and up.consecutive_failures
                        >= self.config.failure_threshold):
                    up.state = LinkState.DOWN
                    self.stats["failovers"] += 1
                    if self.on_upstream_down:
                        self.on_upstream_down(up.name)

    def routing_stats(self) -> dict:
        with self._lock:
            ups = sum(1 for u in self._upstreams.values()
                      if u.state == LinkState.UP)
            return dict(self.stats, upstreams=len(self._upstreams),
                        upstreams_up=ups)


# ---------------------------------------------------------------------------
# BGP via FRR (bgp.go)
# ---------------------------------------------------------------------------

class BGPState(str, Enum):
    IDLE = "Idle"
    CONNECT = "Connect"
    ACTIVE = "Active"
    OPENSENT = "OpenSent"
    OPENCONFIRM = "OpenConfirm"
    ESTABLISHED = "Established"


def parse_bgp_state(s: str) -> BGPState:
    """bgp.go:118-136."""
    try:
        return BGPState(s.strip().capitalize().replace("Opensent", "OpenSent")
                        .replace("Openconfirm", "OpenConfirm"))
    except ValueError:
        return BGPState.IDLE


@dataclass
class BGPNeighbor:
    """bgp.go:68-96."""

    address: str
    remote_as: int
    description: str = ""
    state: BGPState = BGPState.IDLE
    bfd_enabled: bool = False
    next_hop_self: bool = False
    route_map_in: str = ""
    route_map_out: str = ""
    table_id: int = 0
    prefixes_received: int = 0
    uptime_s: float = 0.0


@dataclass
class BGPConfig:
    """bgp.go:38-66."""

    local_as: int = 65000
    router_id: str = ""
    poll_interval: float = 10.0


@dataclass
class BGPAnnouncement:
    prefix: str
    route_map: str = ""
    communities: list[str] = field(default_factory=list)


class BGPController:
    """bgp.go:18-848 with `executor(command) -> str` instead of vtysh."""

    def __init__(self, config: BGPConfig, executor):
        self.config = config
        self._exec = executor
        self._lock = threading.Lock()
        self._neighbors: dict[str, BGPNeighbor] = {}
        self._announcements: dict[str, BGPAnnouncement] = {}
        self.on_neighbor_up = None
        self.on_neighbor_down = None
        self.stats = {"commands": 0, "neighbor_transitions": 0}

    def _vtysh(self, command: str) -> str:
        self.stats["commands"] += 1
        return self._exec(command)

    def _conf(self, *lines: str) -> str:
        return self._vtysh("configure terminal\n" + "\n".join(lines))

    # -- neighbors (bgp.go:219-321) --------------------------------------

    def add_neighbor(self, neighbor: BGPNeighbor) -> None:
        with self._lock:
            if neighbor.address in self._neighbors:
                raise ValueError(f"neighbor {neighbor.address} exists")
            self._neighbors[neighbor.address] = neighbor
        lines = [f"router bgp {self.config.local_as}",
                 f"neighbor {neighbor.address} remote-as {neighbor.remote_as}"]
        if neighbor.description:
            lines.append(f"neighbor {neighbor.address} description "
                         f"{neighbor.description}")
        if neighbor.bfd_enabled:
            lines.append(f"neighbor {neighbor.address} bfd")
        lines += ["address-family ipv4 unicast",
                  f"neighbor {neighbor.address} activate"]
        if neighbor.next_hop_self:
            lines.append(f"neighbor {neighbor.address} next-hop-self")
        if neighbor.route_map_in:
            lines.append(f"neighbor {neighbor.address} route-map "
                         f"{neighbor.route_map_in} in")
        if neighbor.route_map_out:
            lines.append(f"neighbor {neighbor.address} route-map "
                         f"{neighbor.route_map_out} out")
        lines.append("exit-address-family")
        self._conf(*lines)

    def remove_neighbor(self, address: str) -> None:
        with self._lock:
            if self._neighbors.pop(address, None) is None:
                raise KeyError(address)
        self._conf(f"router bgp {self.config.local_as}",
                   f"no neighbor {address}")

    def get_neighbor(self, address: str) -> BGPNeighbor | None:
        with self._lock:
            return self._neighbors.get(address)

    def list_neighbors(self) -> list[BGPNeighbor]:
        with self._lock:
            return list(self._neighbors.values())

    # -- prefixes (bgp.go:323-399) ---------------------------------------

    def announce_prefix(self, prefix: str,
                        opts: BGPAnnouncement | None = None) -> None:
        ipaddress.ip_network(prefix)  # validate
        ann = opts or BGPAnnouncement(prefix=prefix)
        ann.prefix = prefix
        with self._lock:
            self._announcements[prefix] = ann
        self._conf(f"router bgp {self.config.local_as}",
                   "address-family ipv4 unicast",
                   f"network {prefix}"
                   + (f" route-map {ann.route_map}" if ann.route_map else ""),
                   "exit-address-family")

    def withdraw_prefix(self, prefix: str) -> None:
        with self._lock:
            if self._announcements.pop(prefix, None) is None:
                raise KeyError(prefix)
        self._conf(f"router bgp {self.config.local_as}",
                   "address-family ipv4 unicast",
                   f"no network {prefix}",
                   "exit-address-family")

    def list_announcements(self) -> list[BGPAnnouncement]:
        with self._lock:
            return list(self._announcements.values())

    # -- knobs (bgp.go:431-552) ------------------------------------------

    def enable_max_paths(self, max_paths: int) -> None:
        if not 1 <= max_paths <= 64:
            raise ValueError("max_paths out of range")
        self._conf(f"router bgp {self.config.local_as}",
                   "address-family ipv4 unicast",
                   f"maximum-paths {max_paths}",
                   "exit-address-family")

    def configure_bfd(self, address: str, min_rx: int = 300, min_tx: int = 300,
                      multiplier: int = 3) -> None:
        self._conf("bfd", f"peer {address}",
                   f"receive-interval {min_rx}",
                   f"transmit-interval {min_tx}",
                   f"detect-multiplier {multiplier}", "no shutdown")
        n = self.get_neighbor(address)
        if n is not None:
            n.bfd_enabled = True

    def create_route_map(self, name: str, seq: int, action: str,
                         match_clauses: list[str] | None = None,
                         set_clauses: list[str] | None = None) -> None:
        lines = [f"route-map {name} {action} {seq}"]
        lines += [f"match {m}" for m in (match_clauses or [])]
        lines += [f"set {s}" for s in (set_clauses or [])]
        self._conf(*lines)

    def set_neighbor_route_table(self, address: str, table_id: int) -> None:
        """bgp.go:517-552: import neighbor routes into an ISP table."""
        n = self.get_neighbor(address)
        if n is None:
            raise KeyError(address)
        n.table_id = table_id
        self._conf(f"router bgp {self.config.local_as}",
                   "address-family ipv4 unicast",
                   f"table-map isp-table-{table_id}",
                   "exit-address-family")

    def clear_neighbor(self, address: str, soft: bool = False) -> None:
        self._vtysh(f"clear bgp {address}" + (" soft" if soft else ""))

    # -- status (bgp.go:402-428, :580-756) -------------------------------

    def refresh_neighbors(self) -> None:
        """Poll FRR state JSON and fire up/down callbacks."""
        raw = self._vtysh("show bgp ipv4 unicast summary json")
        try:
            data = json.loads(raw)
        except (ValueError, TypeError):
            return
        peers = data.get("peers", data.get("ipv4Unicast", {}).get("peers", {}))
        for addr, info in peers.items():
            n = self.get_neighbor(addr)
            if n is None:
                continue
            new_state = parse_bgp_state(str(info.get("state", "Idle")))
            n.prefixes_received = int(info.get("pfxRcd", 0) or 0)
            if new_state != n.state:
                self.stats["neighbor_transitions"] += 1
                old, n.state = n.state, new_state
                if new_state == BGPState.ESTABLISHED and self.on_neighbor_up:
                    self.on_neighbor_up(addr)
                elif (old == BGPState.ESTABLISHED
                      and self.on_neighbor_down):
                    self.on_neighbor_down(addr)

    def summary(self) -> dict:
        with self._lock:
            est = sum(1 for n in self._neighbors.values()
                      if n.state == BGPState.ESTABLISHED)
            return {"local_as": self.config.local_as,
                    "neighbors": len(self._neighbors),
                    "established": est,
                    "announcements": len(self._announcements)}

    # -- config generation (bgp.go:758-817) ------------------------------

    def generate_config(self) -> str:
        with self._lock:
            out = ["! BGP configuration generated by bng-tpu", "!",
                   f"router bgp {self.config.local_as}"]
            if self.config.router_id:
                out.append(f" bgp router-id {self.config.router_id}")
            out += [" no bgp default ipv4-unicast",
                    " bgp bestpath as-path multipath-relax", "!"]
            for n in self._neighbors.values():
                out.append(f" neighbor {n.address} remote-as {n.remote_as}")
                if n.description:
                    out.append(f" neighbor {n.address} description "
                               f"{n.description}")
                if n.bfd_enabled:
                    out.append(f" neighbor {n.address} bfd")
            out += ["!", " address-family ipv4 unicast"]
            out += [f"  network {a.prefix}"
                    for a in self._announcements.values()]
            for n in self._neighbors.values():
                out.append(f"  neighbor {n.address} activate")
                if n.next_hop_self:
                    out.append(f"  neighbor {n.address} next-hop-self")
                if n.route_map_in:
                    out.append(f"  neighbor {n.address} route-map "
                               f"{n.route_map_in} in")
                if n.route_map_out:
                    out.append(f"  neighbor {n.address} route-map "
                               f"{n.route_map_out} out")
            out += [" exit-address-family", "!"]
            return "\n".join(out) + "\n"

    def write_config(self) -> None:
        self._vtysh("write memory")


# ---------------------------------------------------------------------------
# BFD via FRR (bfd.go)
# ---------------------------------------------------------------------------

class BFDState(str, Enum):
    ADMIN_DOWN = "admin_down"
    DOWN = "down"
    INIT = "init"
    UP = "up"


@dataclass
class BFDPeer:
    """bfd.go:88-119."""

    address: str
    min_rx_ms: int = 300
    min_tx_ms: int = 300
    detect_multiplier: int = 3
    multihop: bool = False
    state: BFDState = BFDState.DOWN
    linked_bgp_as: int = 0


@dataclass
class BFDConfig:
    """bfd.go:38-86."""

    min_rx_ms: int = 300
    min_tx_ms: int = 300
    detect_multiplier: int = 3


def aggressive_bfd_config() -> BFDConfig:
    """bfd.go:80-86: ~50ms detection for fast failover."""
    return BFDConfig(min_rx_ms=50, min_tx_ms=50, detect_multiplier=3)


class BFDManager:
    """bfd.go:19-430."""

    def __init__(self, config: BFDConfig | None = None, executor=None):
        self.config = config or BFDConfig()
        self._exec = executor or (lambda c: "")
        self._lock = threading.Lock()
        self._peers: dict[str, BFDPeer] = {}
        self.on_peer_up = None
        self.on_peer_down = None

    def add_peer(self, address: str, min_rx: int | None = None,
                 min_tx: int | None = None, detect_mult: int | None = None,
                 multihop: bool = False) -> BFDPeer:
        peer = BFDPeer(address=address,
                       min_rx_ms=min_rx or self.config.min_rx_ms,
                       min_tx_ms=min_tx or self.config.min_tx_ms,
                       detect_multiplier=detect_mult
                       or self.config.detect_multiplier,
                       multihop=multihop)
        with self._lock:
            if address in self._peers:
                raise ValueError(f"BFD peer {address} exists")
            self._peers[address] = peer
        self._exec("configure terminal\nbfd\n"
                   f"peer {address}{' multihop' if multihop else ''}\n"
                   f"receive-interval {peer.min_rx_ms}\n"
                   f"transmit-interval {peer.min_tx_ms}\n"
                   f"detect-multiplier {peer.detect_multiplier}\nno shutdown")
        return peer

    def remove_peer(self, address: str) -> None:
        with self._lock:
            if self._peers.pop(address, None) is None:
                raise KeyError(address)
        self._exec(f"configure terminal\nbfd\nno peer {address}")

    def link_to_bgp_neighbor(self, bgp_as: int, address: str) -> None:
        """bfd.go:317-348."""
        peer = self.get_peer(address) or self.add_peer(address)
        peer.linked_bgp_as = bgp_as
        self._exec(f"configure terminal\nrouter bgp {bgp_as}\n"
                   f"neighbor {address} bfd")

    def unlink_from_bgp_neighbor(self, bgp_as: int, address: str) -> None:
        peer = self.get_peer(address)
        if peer is not None:
            peer.linked_bgp_as = 0
        self._exec(f"configure terminal\nrouter bgp {bgp_as}\n"
                   f"no neighbor {address} bfd")

    def get_peer(self, address: str) -> BFDPeer | None:
        with self._lock:
            return self._peers.get(address)

    def list_peers(self) -> list[BFDPeer]:
        with self._lock:
            return list(self._peers.values())

    def refresh_peers(self) -> None:
        """Poll `show bfd peers json` and fire transitions (bfd.go:401+)."""
        raw = self._exec("show bfd peers json")
        try:
            data = json.loads(raw)
        except (ValueError, TypeError):
            return
        for entry in data if isinstance(data, list) else []:
            addr = entry.get("peer", "")
            peer = self.get_peer(addr)
            if peer is None:
                continue
            new = BFDState(entry.get("status", "down").lower()) \
                if entry.get("status", "").lower() in \
                ("admin_down", "down", "init", "up") else BFDState.DOWN
            if new != peer.state:
                old, peer.state = peer.state, new
                if new == BFDState.UP and self.on_peer_up:
                    self.on_peer_up(addr)
                elif old == BFDState.UP and self.on_peer_down:
                    self.on_peer_down(addr)

    def bfd_stats(self) -> dict:
        with self._lock:
            return {"peers": len(self._peers),
                    "up": sum(1 for p in self._peers.values()
                              if p.state == BFDState.UP)}


# ---------------------------------------------------------------------------
# Per-subscriber route injection (subscriber_routes.go)
# ---------------------------------------------------------------------------

@dataclass
class SubscriberRoute:
    """subscriber_routes.go:88-97."""

    session_id: str
    subscriber_id: str
    ip: str
    subscriber_class: str = ""
    community: str = ""
    injected_at: float = 0.0


@dataclass
class SubscriberRouteConfig:
    """subscriber_routes.go:39-86."""

    enabled: bool = True
    communities_by_class: dict[str, str] = field(default_factory=lambda: {
        "residential": "65000:100",
        "business": "65000:200",
        "wholesale": "65000:300",
    })
    default_community: str = "65000:100"
    graceful_shutdown_community: str = "65535:0"  # RFC 8326
    max_retries: int = 3


class SubscriberRouteManager:
    """subscriber_routes.go:16-668: /32 injection with communities, retry
    queue, bulk ops, reconcile."""

    def __init__(self, config: SubscriberRouteConfig | None = None,
                 executor=None, clock=time.time):
        self.config = config or SubscriberRouteConfig()
        self._exec = executor or (lambda c: "")
        self._clock = clock
        self._lock = threading.Lock()
        self._routes: dict[str, SubscriberRoute] = {}  # session_id ->
        self._by_ip: dict[str, str] = {}
        self._retry: list[tuple[str, SubscriberRoute, int]] = []  # (op, rt, n)
        self.stats = {"injected": 0, "withdrawn": 0, "failed": 0,
                      "retried": 0, "retry_errors": 0}

    def _community_for(self, subscriber_class: str) -> str:
        return self.config.communities_by_class.get(
            subscriber_class, self.config.default_community)

    def inject_route(self, session_id: str, subscriber_id: str, ip: str,
                     subscriber_class: str = "") -> SubscriberRoute:
        """subscriber_routes.go:183-272."""
        if not self.config.enabled:
            raise ValueError("subscriber routes disabled")
        ipaddress.ip_address(ip)
        route = SubscriberRoute(
            session_id=session_id, subscriber_id=subscriber_id, ip=ip,
            subscriber_class=subscriber_class,
            community=self._community_for(subscriber_class),
            injected_at=self._clock())
        try:
            self._exec(
                "configure terminal\n"
                f"ip route {ip}/32 Null0 tag 500\n"
                f"route-map SUBSCRIBER-{route.community.replace(':', '-')} "
                "permit 10\n"
                f"set community {route.community}")
        except Exception:
            self.stats["failed"] += 1
            with self._lock:
                self._retry.append(("inject", route, 0))
            raise
        with self._lock:
            self._routes[session_id] = route
            self._by_ip[ip] = session_id
            self.stats["injected"] += 1
        return route

    def withdraw_route(self, session_id: str) -> None:
        """subscriber_routes.go:274-366."""
        with self._lock:
            route = self._routes.pop(session_id, None)
            if route is not None:
                self._by_ip.pop(route.ip, None)
        if route is None:
            raise KeyError(session_id)
        try:
            self._exec("configure terminal\n"
                       f"no ip route {route.ip}/32 Null0 tag 500")
        except Exception:
            self.stats["failed"] += 1
            with self._lock:
                self._retry.append(("withdraw", route, 0))
            return
        with self._lock:
            self.stats["withdrawn"] += 1

    def bulk_inject(self, routes: list[SubscriberRoute]) -> int:
        """subscriber_routes.go:368-425: one config session for N routes."""
        lines = ["configure terminal"]
        for r in routes:
            r.community = r.community or self._community_for(r.subscriber_class)
            lines.append(f"ip route {r.ip}/32 Null0 tag 500")
        self._exec("\n".join(lines))
        with self._lock:
            for r in routes:
                r.injected_at = self._clock()
                self._routes[r.session_id] = r
                self._by_ip[r.ip] = r.session_id
            self.stats["injected"] += len(routes)
        return len(routes)

    def bulk_withdraw(self) -> int:
        """subscriber_routes.go:427-482: graceful-shutdown everything."""
        with self._lock:
            routes = list(self._routes.values())
            self._routes.clear()
            self._by_ip.clear()
        if not routes:
            return 0
        lines = ["configure terminal"]
        lines += [f"no ip route {r.ip}/32 Null0 tag 500" for r in routes]
        self._exec("\n".join(lines))
        with self._lock:
            self.stats["withdrawn"] += len(routes)
        return len(routes)

    def retry_pending(self) -> int:
        """One pass of the retry worker (subscriber_routes.go:599-668)."""
        with self._lock:
            pending, self._retry = self._retry, []
        done = 0
        for op, route, attempts in pending:
            if attempts >= self.config.max_retries:
                continue
            try:
                if op == "inject":
                    self.inject_route(route.session_id, route.subscriber_id,
                                      route.ip, route.subscriber_class)
                else:
                    with self._lock:
                        self._routes[route.session_id] = route
                        self._by_ip[route.ip] = route.session_id
                    self.withdraw_route(route.session_id)
                done += 1
                self.stats["retried"] += 1
            except Exception:
                # still failing: requeue with the attempt count bumped,
                # and count it — an install that never converges must
                # show up in stats, not just sit in the retry deque
                self.stats["retry_errors"] += 1
                with self._lock:
                    self._retry.append((op, route, attempts + 1))
        return done

    def get_active_routes(self) -> list[SubscriberRoute]:
        with self._lock:
            return list(self._routes.values())

    def get_route_by_ip(self, ip: str) -> SubscriberRoute | None:
        with self._lock:
            sid = self._by_ip.get(ip)
            return self._routes.get(sid) if sid else None

    def route_stats(self) -> dict:
        with self._lock:
            return dict(self.stats, active=len(self._routes))
