"""Device -> Nexus authentication: none / PSK(HMAC) / mTLS.

Parity: pkg/deviceauth — Authenticator interface + mode dispatch
(types.go:194, authenticator.go:16-40), DeviceIdentity read from DMI//sys
(authenticator.go:137-259), NoneAuthenticator (authenticator.go:42-134),
PSKAuthenticator with HMAC-SHA256 signed headers + server-side verify with
timestamp-skew check (psk.go:35-301), MTLSAuthenticator with cert loading,
fingerprinting, expiry checks and rotation reload (mtls.go:20-418),
AuthenticatedTransport header injection (transport.go:8-110).

The mTLS cert expiry check uses a minimal DER walk (stdlib has no X.509
parser); CSR generation shells out to the openssl binary the way the
reference drives FRR via vtysh.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import os
import re
import subprocess
import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum

MAX_TIMESTAMP_SKEW = 300.0  # psk.go MaxTimestampSkew
PSK_TIMESTAMP_HEADER = "X-Device-Timestamp"
PSK_SIGNATURE_HEADER = "X-Device-Signature"


class AuthMode(str, Enum):
    NONE = "none"
    PSK = "psk"
    MTLS = "mtls"


@dataclass
class DeviceIdentity:
    """types.go:122-147."""

    device_id: str = ""
    serial: str = ""
    mac: str = ""
    model: str = ""
    firmware: str = ""


@dataclass
class AuthResult:
    success: bool
    mode: AuthMode
    identity: DeviceIdentity | None = None
    error: str = ""


def sanitize_id(s: str) -> str:
    """authenticator.go:251-259: keep [a-zA-Z0-9-_], lowercase."""
    return re.sub(r"[^a-zA-Z0-9_-]", "-", s).lower()


def generate_device_id(serial: str, mac: str) -> str:
    """authenticator.go:233-249: stable ID from serial+mac."""
    if serial:
        return "dev-" + sanitize_id(serial)
    if mac:
        return "dev-" + sanitize_id(mac.replace(":", ""))
    return "dev-" + uuid.uuid4().hex[:12]


def read_device_identity(sys_root: str = "/") -> DeviceIdentity:
    """Detect serial/MAC/model from DMI + sysfs (authenticator.go:137-231).
    sys_root is injectable so tests provide a fake /sys tree."""
    def _read(path: str) -> str:
        try:
            with open(os.path.join(sys_root, path.lstrip("/"))) as f:
                return f.read().strip()
        except OSError:
            return ""

    serial = (_read("/sys/class/dmi/id/product_serial")
              or _read("/sys/class/dmi/id/board_serial")
              or _read("/etc/machine-id"))
    model = _read("/sys/class/dmi/id/product_name")
    mac = ""
    net_dir = os.path.join(sys_root, "sys/class/net")
    try:
        for iface in sorted(os.listdir(net_dir)):
            if iface == "lo":
                continue
            addr = _read(f"/sys/class/net/{iface}/address")
            if addr and addr != "00:00:00:00:00:00":
                mac = addr
                break
    except OSError:
        pass
    return DeviceIdentity(device_id=generate_device_id(serial, mac),
                          serial=serial, mac=mac, model=model)


class NoneAuthenticator:
    """Pass-through: identity headers only (authenticator.go:42-134)."""

    def __init__(self, identity: DeviceIdentity | None = None):
        self.identity = identity or DeviceIdentity(
            device_id=generate_device_id("", ""))

    @property
    def mode(self) -> AuthMode:
        return AuthMode.NONE

    def authenticate(self) -> AuthResult:
        return AuthResult(True, self.mode, self.identity)

    def http_headers(self) -> dict[str, str]:
        h = {"X-Device-ID": self.identity.device_id}
        if self.identity.serial:
            h["X-Device-Serial"] = self.identity.serial
        return h

    def tls_config(self):
        return None

    def close(self) -> None:
        pass


class PSKAuthenticator:
    """HMAC-SHA256 pre-shared-key auth (psk.go:35-301).

    Headers carry a signature over "device_id:timestamp", never the PSK.
    The server derives the same signature from the shared key.
    """

    def __init__(self, psk: str | bytes = "", psk_file: str = "",
                 identity: DeviceIdentity | None = None, clock=time.time):
        if psk_file:
            with open(psk_file) as f:
                psk = f.read().strip()
        if isinstance(psk, str):
            psk = psk.encode()
        if len(psk) < 16:
            raise ValueError("PSK must be at least 16 characters")
        self._psk = psk
        self._clock = clock
        self._lock = threading.Lock()
        self.identity = identity or DeviceIdentity(
            device_id=generate_device_id("", ""))

    @property
    def mode(self) -> AuthMode:
        return AuthMode.PSK

    def authenticate(self) -> AuthResult:
        return AuthResult(True, self.mode, self.identity)

    def sign_message(self, message: str) -> str:
        with self._lock:
            return hmac.new(self._psk, message.encode(), hashlib.sha256).hexdigest()

    @staticmethod
    def _fmt_ts(t: float) -> str:
        return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(t))

    def http_headers(self) -> dict[str, str]:
        """psk.go:192-227."""
        h = {"X-Device-ID": self.identity.device_id}
        if self.identity.serial:
            h["X-Device-Serial"] = self.identity.serial
        if self.identity.mac:
            h["X-Device-MAC"] = self.identity.mac
        ts = self._fmt_ts(self._clock())
        h[PSK_TIMESTAMP_HEADER] = ts
        h[PSK_SIGNATURE_HEADER] = self.sign_message(
            f"{self.identity.device_id}:{ts}")
        return h

    def verify_signature(self, device_id: str, timestamp: str,
                         signature: str) -> None:
        """Server side (psk.go:266-291). Raises ValueError on failure."""
        try:
            ts = time.mktime(time.strptime(timestamp, "%Y-%m-%dT%H:%M:%SZ")) \
                - time.timezone
        except ValueError as e:
            raise ValueError(f"invalid timestamp format: {e}") from e
        if abs(self._clock() - ts) > MAX_TIMESTAMP_SKEW:
            raise ValueError("timestamp skew too large")
        expected = self.sign_message(f"{device_id}:{timestamp}")
        if not hmac.compare_digest(signature, expected):
            raise ValueError("signature mismatch")

    def rotate_psk(self, new_psk: str) -> None:
        if len(new_psk) < 16:
            raise ValueError("new PSK must be at least 16 characters")
        with self._lock:
            self._psk = new_psk.encode()

    def tls_config(self):
        return None

    def close(self) -> None:
        self._psk = b"\x00" * len(self._psk)  # zero like psk.go Close


# -- minimal X.509 DER helpers (expiry + subject CN) --------------------

def _pem_to_der(pem: str) -> bytes:
    body = re.search(r"-----BEGIN CERTIFICATE-----(.*?)-----END CERTIFICATE-----",
                     pem, re.S)
    if not body:
        raise ValueError("no certificate in PEM")
    return base64.b64decode("".join(body.group(1).split()))


def _der_iter(data: bytes, off: int = 0):
    """Yield (tag, start, end) for each TLV at one DER level."""
    while off < len(data):
        tag = data[off]
        length = data[off + 1]
        off += 2
        if length & 0x80:
            n = length & 0x7F
            length = int.from_bytes(data[off:off + n], "big")
            off += n
        yield tag, off, off + length
        off += length


def cert_not_after(pem: str) -> float:
    """Extract notAfter from an X.509 PEM (mtls.go:322-341 role)."""
    der = _pem_to_der(pem)
    # Certificate ::= SEQUENCE { tbsCertificate, sigAlg, sig }
    _, s, e = next(_der_iter(der))
    cert_body = der[s:e]
    _, ts0, te0 = next(_der_iter(cert_body))  # tbsCertificate
    tbs = cert_body[ts0:te0]
    tbs_fields = list(_der_iter(tbs))
    # tbs: [0] version?, serial, sigAlg, issuer, validity(SEQ), subject, ...
    idx = 0
    if tbs_fields and tbs_fields[0][0] == 0xA0:
        idx = 1
    validity = tbs_fields[idx + 3]  # serial, sigAlg, issuer, then validity
    vdata = tbs[validity[1]:validity[2]]
    times = list(_der_iter(vdata))
    tag, ts, te = times[1]  # notAfter
    raw = vdata[ts:te].decode()
    if tag == 0x17:  # UTCTime YYMMDDHHMMSSZ
        year = int(raw[:2])
        year += 2000 if year < 50 else 1900
        raw = f"{year}{raw[2:]}"
    return time.mktime(time.strptime(raw, "%Y%m%d%H%M%SZ")) - time.timezone


def cert_fingerprint(pem: str) -> str:
    return hashlib.sha256(_pem_to_der(pem)).hexdigest()


class MTLSAuthenticator:
    """Mutual-TLS device auth with rotation reload (mtls.go:20-418)."""

    def __init__(self, cert_file: str, key_file: str, ca_file: str = "",
                 identity: DeviceIdentity | None = None, clock=time.time):
        self.cert_file = cert_file
        self.key_file = key_file
        self.ca_file = ca_file
        self._clock = clock
        self._lock = threading.Lock()
        self._fingerprint = ""
        self._not_after = 0.0
        self._mtime = 0.0
        self.identity = identity or DeviceIdentity()
        self.reload_certificates()
        if not self.identity.device_id:
            cn = self._subject_cn()
            self.identity.device_id = generate_device_id(cn, "")

    @property
    def mode(self) -> AuthMode:
        return AuthMode.MTLS

    def reload_certificates(self) -> None:
        """mtls.go:86-123, :343-360."""
        with open(self.cert_file) as f:
            pem = f.read()
        with self._lock:
            self._fingerprint = cert_fingerprint(pem)
            self._not_after = cert_not_after(pem)
            self._mtime = os.path.getmtime(self.cert_file)

    def _subject_cn(self) -> str:
        try:
            out = subprocess.run(
                ["openssl", "x509", "-in", self.cert_file, "-noout", "-subject"],
                capture_output=True, text=True, timeout=10, check=True).stdout
            m = re.search(r"CN\s*=\s*([^,/\n]+)", out)
            return m.group(1).strip() if m else ""
        except Exception:
            return ""

    def maybe_rotate(self) -> bool:
        """Rotation watcher body (mtls.go:287-320): reload on file change."""
        try:
            if os.path.getmtime(self.cert_file) != self._mtime:
                self.reload_certificates()
                return True
        except OSError:
            pass
        return False

    def authenticate(self) -> AuthResult:
        if self.expires_within(0):
            return AuthResult(False, self.mode, self.identity,
                              error="certificate expired")
        return AuthResult(True, self.mode, self.identity)

    def expires_within(self, seconds: float) -> bool:
        """mtls.go:408-417."""
        with self._lock:
            return self._clock() + seconds >= self._not_after

    @property
    def fingerprint(self) -> str:
        with self._lock:
            return self._fingerprint

    def http_headers(self) -> dict[str, str]:
        return {"X-Device-ID": self.identity.device_id,
                "X-Device-Cert-Fingerprint": self.fingerprint}

    def tls_config(self):
        """Build an ssl.SSLContext loaded with the client pair."""
        import ssl
        ctx = ssl.create_default_context(
            cafile=self.ca_file or None,
            purpose=ssl.Purpose.SERVER_AUTH)
        ctx.load_cert_chain(self.cert_file, self.key_file)
        return ctx

    def generate_csr(self, cn: str, out_dir: str) -> tuple[str, str]:
        """CSR + fresh key via openssl (mtls.go:362-406). Returns paths."""
        key = os.path.join(out_dir, "device.key")
        csr = os.path.join(out_dir, "device.csr")
        subprocess.run(
            ["openssl", "req", "-new", "-newkey", "ec", "-pkeyopt",
             "ec_paramgen_curve:P-256", "-nodes", "-keyout", key,
             "-subj", f"/CN={cn}", "-out", csr],
            capture_output=True, timeout=30, check=True)
        return csr, key

    def close(self) -> None:
        pass


def new_authenticator(mode: AuthMode | str, **kw):
    """Dispatch like authenticator.go:16-40."""
    mode = AuthMode(mode)
    if mode == AuthMode.NONE:
        return NoneAuthenticator(**kw)
    if mode == AuthMode.PSK:
        return PSKAuthenticator(**kw)
    return MTLSAuthenticator(**kw)


class AuthenticatedTransport:
    """Header-injecting request wrapper (transport.go:8-110). Wraps any
    transport callable (method, url, headers, body) -> response."""

    def __init__(self, base, authenticator):
        self._base = base
        self._auth = authenticator

    def __call__(self, method: str, url: str, headers: dict | None = None,
                 body: bytes | None = None):
        h = dict(headers or {})
        h.update(self._auth.http_headers())
        return self._base(method, url, h, body)
