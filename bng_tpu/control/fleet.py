"""Slow-path fleet: sharded multi-worker control-plane service.

The reference BNG sustains 50k+ DHCP req/s because its slow path is
concurrent Go (pkg/dhcp/server.go:302 onward — one goroutine per
request); ours was a single GIL thread behind the engine's PASS lanes.
This module re-hosts that concurrency as a shared-nothing worker fleet:

- **Sharding**: frames are steered to workers by FNV-1a32(src MAC) —
  bit-for-bit the hash the ring classifier uses for DHCP control frames
  (runtime/ring.py shard_of, bngring.h spec), so one subscriber's whole
  DORA lands on ONE worker (the SO_REUSEPORT + consistent-hash role).
  No lock is ever taken on the per-frame path.

- **Workers**: each worker owns a full `SlowPathDemux` + `DHCPServer`
  stack and allocates from per-worker *lease slices* carved out of the
  parent `PoolManager` — addresses a worker holds are marked allocated
  in the parent pool, so two workers can never hand out the same IP.
  Slice refill (batched, low-watermark-triggered) is the only
  cross-worker coordination, and it happens between batches, never
  mid-frame.

- **Single-writer tables**: workers never touch the device tables.
  Their DHCP servers write to a `TableEventLog` recorder; the parent
  replays the events into the real `FastPathTables` host mirror, which
  the engine's existing bounded update drain ships to HBM — the same
  single-writer discipline every other table producer follows.

- **Admission**: an `AdmissionController` (control/admission.py) sheds
  DHCP-correctly in front of the inboxes — DISCOVERs first, never a
  REQUEST whose OFFER we already sent, never a half-allocation.

Execution modes:
  - ``process`` — one OS process per worker (multiprocessing, spawn by
    default): real CPU parallelism for the Python slow path. Workers
    are built IN the child from a picklable `FleetSpec`. Standard
    spawn rules apply: an embedding *script* must guard its
    entrypoint with ``if __name__ == '__main__'`` (module entrypoints
    like ``python -m bng_tpu.cli`` are fine as-is). Parents whose
    __main__ is not importable at all (stdin, REPL) automatically fall
    back to fork; BNG_FLEET_START or start_method overrides.
  - ``inline`` — same sharding/admission/slice machinery, handlers run
    synchronously in the caller; deterministic (tests, workers=1).

A worker that dies mid-flight (IPC error) loses only its own shard's
lanes for that batch — clients retransmit — and is counted in
`worker_failures`; other shards and later batches are unaffected.

The fleet's `handle_batch` is the engine's `slow_path_batch` hook:
fan-out by shard, fan-in with replies re-merged in lane (ring) order.
"""

from __future__ import annotations

import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from bng_tpu.chaos import faults
from bng_tpu.chaos.faults import fault_point
from bng_tpu.control import dhcp_codec
from bng_tpu.telemetry import spans as tele
from bng_tpu.telemetry.hist import LatencyHist
from bng_tpu.control.admission import (AdmissionConfig, AdmissionController,
                                       peek_reply)
from bng_tpu.control.pool import PoolExhaustedError, PoolManager
from bng_tpu.runtime import hostpath
from bng_tpu.runtime.ring import classify_dhcp
from bng_tpu.utils.net import fnv1a32, prefix_to_mask
from bng_tpu.utils.structlog import SlowPathErrorLog, get_logger
from bng_tpu.analysis.sanitize import ctx_enter, owned_by


def shard_for_mac(mac: bytes, n_workers: int) -> int:
    """Worker owning a client MAC — the ring classifier's DHCP-control
    steering hash (shard_of's fnv1a32(frame[6:12]) fallback), so the
    host ring, the sharded cluster and the fleet all agree on owners."""
    if n_workers <= 1:
        return 0
    return fnv1a32(mac[:6]) % n_workers


def shard_for_frame(frame: bytes, n_workers: int) -> int:
    """Worker for a slow-path frame: by source MAC (frame[6:12])."""
    if n_workers <= 1 or len(frame) < 12:
        return 0
    return fnv1a32(frame[6:12]) % n_workers


# ---------------------------------------------------------------------------
# picklable worker construction spec
# ---------------------------------------------------------------------------

@dataclass
class FleetPoolSpec:
    """Per-pool config a worker needs to build reply options + validate
    addresses. Mirrors control.pool.Pool's config surface (no state)."""

    pool_id: int
    network: int
    prefix_len: int
    gateway: int
    dns_primary: int = 0
    dns_secondary: int = 0
    lease_time: int = 3600
    client_class: int = 0


@dataclass
class FleetSpec:
    """Everything a child process needs to build its worker stack."""

    server_mac: bytes
    server_ip: int
    pools: list = field(default_factory=list)  # [FleetPoolSpec]
    lease_time_cap: int | None = None
    slice_size: int = 1024
    low_watermark: int = 256
    # RADIUS fan-out (ISSUE 19): each worker builds its OWN RadiusClient
    # from these picklable RadiusServerConfig entries — auth runs on the
    # shard that owns the subscriber's MAC (auth affinity = DHCP
    # affinity, both the same FNV-1a32 hash), so no cross-worker lock
    # and no parent round-trip on the DORA path
    radius_servers: list = field(default_factory=list)
    radius_nas_id: str = "bng-tpu"
    radius_nas_ip: int = 0
    # central Nexus allocation (ISSUE 20): worker lease-authority routes
    # through the shared store PER SHARD — each worker builds its own
    # HTTPAllocator + ResilienceManager from these picklable fields
    # (the radius_servers mold), so a configured nexus_url no longer
    # force-disables the fleet. nexus_tls is a ztp_tls.TLSConfig
    # (string/list dataclass — picklable) or None for plaintext.
    nexus_url: str = ""
    nexus_node_id: str = "bng-tpu"
    nexus_tls: object = None

    @staticmethod
    def from_pool_manager(server_mac: bytes, server_ip: int,
                          pools: PoolManager, **kw) -> "FleetSpec":
        specs = [FleetPoolSpec(
            pool_id=p.pool_id, network=p.network, prefix_len=p.prefix_len,
            gateway=p.gateway, dns_primary=p.dns_primary,
            dns_secondary=p.dns_secondary, lease_time=p.lease_time,
            client_class=p.client_class) for p in pools.pools.values()]
        return FleetSpec(server_mac=server_mac, server_ip=server_ip,
                         pools=specs, **kw)


# ---------------------------------------------------------------------------
# worker-side pools: lease slices
# ---------------------------------------------------------------------------

class SlicePool:
    """Worker-side view of one pool: full config, but allocation is
    restricted to the address slices the parent granted. Duck-types the
    Pool surface DHCPServer consumes."""

    def __init__(self, spec: FleetPoolSpec,
                 on_exhausted: Callable[[int], None] | None = None):
        # called once when allocate() drains the slice dry: the worker's
        # synchronous refill hook (mid-batch exhaustion must be able to
        # pull a new slice, not silently drop the tail of a batch)
        self.on_exhausted = on_exhausted
        self.pool_id = spec.pool_id
        self.prefix_len = spec.prefix_len
        self.gateway = spec.gateway
        self.dns_primary = spec.dns_primary
        self.dns_secondary = spec.dns_secondary
        self.lease_time = spec.lease_time
        self.client_class = spec.client_class
        mask = prefix_to_mask(spec.prefix_len)
        self.network = spec.network & mask
        self.first = self.network + 1
        self.last = (self.network | (~mask & 0xFFFFFFFF)) - 1
        self._free: deque[int] = deque()
        self._granted: set[int] = set()
        self._allocated: dict[int, str] = {}
        self._declined: set[int] = set()

    def grant(self, ips) -> int:
        added = 0
        for ip in ips:
            if ip not in self._granted:
                self._granted.add(ip)
                self._free.append(ip)
                added += 1
        return added

    @property
    def free_count(self) -> int:
        return (len(self._granted) - len(self._allocated)
                - len(self._declined & self._granted))

    @property
    def used(self) -> int:
        return len(self._allocated)

    def allocate(self, owner: str) -> int:
        for attempt in (0, 1):
            while self._free:
                ip = self._free.popleft()
                # revoked (no longer granted), re-claimed or declined
                # addresses may still sit in the free deque — skip them
                if (ip not in self._granted or ip in self._allocated
                        or ip in self._declined):
                    continue
                self._allocated[ip] = owner
                return ip
            if attempt == 0 and self.on_exhausted is not None:
                self.on_exhausted(self.pool_id)  # may grant a new slice
        raise PoolExhaustedError(
            f"worker slice of pool {self.pool_id} exhausted")

    def revoke(self, ip: int) -> bool:
        """Withdraw an un-leased address from this slice (restore-time
        ownership transfer). Active allocations are never revoked."""
        if ip in self._allocated:
            return False
        self._granted.discard(ip)
        return True

    def allocate_specific(self, ip: int, owner: str) -> bool:
        # the granted set is the correctness boundary: an address another
        # worker owns is simply not grantable here, so a cross-shard
        # REQUEST NAKs instead of double-allocating
        if ip not in self._granted or ip in self._declined:
            return False
        cur = self._allocated.get(ip)
        if cur is not None and cur != owner:
            return False
        self._allocated[ip] = owner
        return True

    def release(self, ip: int) -> bool:
        if ip in self._allocated:
            del self._allocated[ip]
            self._free.append(ip)
            return True
        return False

    def decline(self, ip: int) -> None:
        self._allocated.pop(ip, None)
        self._declined.add(ip)

    def contains(self, ip: int) -> bool:
        # FULL pool range, not just granted slices: pool_for_ip must
        # find the owning pool for renewals/validation; allocate_specific
        # still enforces the granted boundary
        return self.first <= ip <= self.last


class WorkerPools:
    """PoolManager-shaped registry over a worker's SlicePools."""

    def __init__(self, specs: list[FleetPoolSpec],
                 on_exhausted: Callable[[int], None] | None = None):
        self.pools: dict[int, SlicePool] = {
            s.pool_id: SlicePool(s, on_exhausted) for s in specs}

    def classify(self, client_class: int = 0):
        best = None
        for p in self.pools.values():
            if p.client_class == client_class:
                return p
            if p.client_class == 0 and best is None:
                best = p
        return best

    def pool_for_ip(self, ip: int):
        for p in self.pools.values():
            if p.contains(ip):
                return p
        return None


# ---------------------------------------------------------------------------
# single-writer table relay
# ---------------------------------------------------------------------------

class TableEventLog:
    """FastPathTables-shaped recorder: workers call the same methods the
    DHCP server calls on the real tables; the calls are logged as
    picklable events the PARENT replays into the host mirror — keeping
    the device tables single-writer."""

    _METHODS = ("add_subscriber", "remove_subscriber",
                "add_circuit_id_subscriber", "remove_circuit_id_subscriber",
                "add_vlan_subscriber", "remove_vlan_subscriber")

    def __init__(self):
        self.events: list = []

    def __getattr__(self, name):
        if name not in self._METHODS:
            raise AttributeError(name)

        def record(*args, **kwargs):
            self.events.append(("fastpath", name, args, kwargs))
        return record

    def drain(self) -> list:
        out, self.events = self.events, []
        return out


def apply_table_events(events: list, table_sink, qos_hook=None,
                       nat_hook=None, lease_hook=None) -> None:
    """Replay worker events into the parent-side sinks (the single
    writer). Unknown event kinds are ignored — forward compatibility
    across worker versions mid-rolling-restart."""
    for ev in events:
        kind = ev[0]
        if kind == "fastpath":
            if table_sink is not None:
                getattr(table_sink, ev[1])(*ev[2], **ev[3])
        elif kind == "qos":
            if qos_hook is not None:
                qos_hook(ev[1], ev[2])
        elif kind == "nat":
            if nat_hook is not None:
                nat_hook(ev[1], ev[2])
        elif kind == "lease":
            if lease_hook is not None:
                lease_hook(ev[1], ev[2], ev[3])


# ---------------------------------------------------------------------------
# the worker (runs in-child for process mode, in-parent for inline)
# ---------------------------------------------------------------------------

class _WorkerNexusAllocator:
    """DHCPServer's int-contract adapter over a worker-local
    HTTPAllocator (the cli `_NexusAlloc` twin, one per shard):
    partitioned -> None so the local slice answers immediately instead
    of eating a central-store timeout per DISCOVER."""

    def __init__(self, allocator, resilience):
        self.allocator = allocator
        self.resilience = resilience
        self.release_errors = 0

    def allocate(self, owner: str):
        if self.resilience.partitioned:
            return None
        try:
            ip = self.allocator.allocate(owner)
        except Exception:  # network lane: any failure = local fallback
            return None
        if not ip:
            return None
        from bng_tpu.utils.net import ip_to_u32

        return ip_to_u32(ip)

    def release(self, owner: str) -> None:
        if self.resilience.partitioned:
            return  # heal-time reconciliation covers it — no timeout
            # per expired lease during an outage
        try:
            self.allocator.release(owner)
        except Exception:  # heal-time reconciliation sweeps leaked IPs
            self.release_errors += 1


class FleetWorker:
    """One shard: demux + DHCP server + slice pools, shared-nothing."""

    def __init__(self, spec: FleetSpec, worker_id: int, n_workers: int,
                 clock: Callable[[], float] | None = None):
        from bng_tpu.control.dhcp_server import DHCPServer
        from bng_tpu.control.slowpath import SlowPathDemux

        self.spec = spec
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.clock = clock or time.time
        self.tables = TableEventLog()
        # set by the execution context (fleet for inline, _worker_main
        # for process): called when a slice runs dry MID-batch so the
        # tail of the batch can still allocate. None = rely on the
        # between-batch watermark refill only.
        self.refill_now: Callable[[int], None] | None = None
        self.pools = WorkerPools(spec.pools, self._on_slice_exhausted)
        self._events: list = []
        # per-worker RADIUS lane: own client socket, own degraded-auth
        # cache. The MAC that steered the frame here is the MAC being
        # authenticated, so the cache is shard-complete by construction.
        self.radius = None
        self._radius_degraded = None
        self.auth_requests = 0
        self.auth_degraded = 0
        if spec.radius_servers:
            from bng_tpu.control.radius.client import RadiusClient
            from bng_tpu.control.resilience import DegradedRADIUSHandler

            self.radius = RadiusClient(
                servers=list(spec.radius_servers),
                nas_identifier=spec.radius_nas_id,
                nas_ip=spec.radius_nas_ip, clock=self.clock)
            self._radius_degraded = DegradedRADIUSHandler()
        # per-worker Nexus lane (ISSUE 20): the shard that owns the MAC
        # allocates against the shared store under its own node id —
        # no parent round-trip on the DORA path. While partitioned the
        # adapter answers None and DHCP falls back to the local slice
        # (the resilience FSM owns retry cadence, not a per-DISCOVER
        # timeout).
        self.nexus = None
        self.nexus_resilience = None
        allocator = None
        if spec.nexus_url:
            from bng_tpu.control.cluster_http import http_nexus_transport
            from bng_tpu.control.nexus import HTTPAllocator
            from bng_tpu.control.resilience import ResilienceManager

            self.nexus = HTTPAllocator(
                spec.nexus_url,
                http_nexus_transport(spec.nexus_url, tls=spec.nexus_tls),
                node_id=f"{spec.nexus_node_id}-w{worker_id}")
            self.nexus_resilience = ResilienceManager(
                nexus_healthy=self.nexus.health_check)
            allocator = _WorkerNexusAllocator(self.nexus,
                                              self.nexus_resilience)
        self.server = DHCPServer(
            server_mac=spec.server_mac, server_ip=spec.server_ip,
            allocator=allocator,
            pool_manager=self.pools, fastpath_tables=self.tables,
            qos_hook=lambda ip, pol: self._events.append(("qos", ip, pol)),
            nat_hook=lambda ip, now: self._events.append(("nat", ip, now)),
            accounting_hook=self._lease_event,
            authenticator=(self._radius_auth if self.radius is not None
                           else None),
            lease_time_cap=spec.lease_time_cap, clock=self.clock)
        self.demux = SlowPathDemux(dhcp=self.server, clock=self.clock)
        # mac_u64s whose lease ENDED (release/expiry/replacement) since
        # the last report — the admission controller's is_known feedback
        self._released: list[int] = []
        self.frames = 0
        self.batches = 0
        self.errors = 0
        self.busy_s = 0.0
        # per-frame handler latency histogram, shipped in the stats
        # payload and merged into the parent tracer's `worker` stage
        # (telemetry/hist.py — merge is counter addition, so worker
        # order never matters). Built only when telemetry is armed in
        # the parent: process-mode children inherit BNG_TELEMETRY=1
        # (exported by SlowPathFleet before spawning), inline workers
        # see the parent's armed tracer directly.
        self._lat_hist = (LatencyHist()
                          if (tele.enabled()
                              or os.environ.get("BNG_TELEMETRY") == "1")
                          else None)

    def _on_slice_exhausted(self, pool_id: int) -> None:
        if self.refill_now is not None:
            self.refill_now(pool_id)

    def _lease_event(self, event: str, lease, sid: str) -> None:
        if event == "stop":
            # RELEASE produces no reply frame, so the reply peek can
            # never observe it — report ended leases explicitly or the
            # admission controller's known-client set grows forever
            self._released.append(int.from_bytes(lease.mac[:6], "big"))
        self._events.append(("lease", event, {
            "mac": lease.mac.hex(), "ip": lease.ip, "pool_id": lease.pool_id,
            "expiry": lease.expiry, "username": lease.username,
            "qos_policy": lease.qos_policy}, sid))

    # -- RADIUS fan-out (worker-local auth + CoA actions) -----------------

    def _radius_auth(self, username="", password="", mac=b"",
                     circuit_id=b"", **kw):
        """Worker-shard authenticator (the cli closure's fleet twin):
        auth over this worker's own RadiusClient, degraded fallback from
        the worker-local profile cache on full-timeout — an outage must
        not evict paying subscribers, and a REJECT is never cached."""
        self.auth_requests += 1
        res = self.radius.authenticate(username, password, mac=mac,
                                       circuit_id=circuit_id)
        key = username or mac.hex()
        if res is None:
            cached = self._radius_degraded.degraded_auth(key, self.clock())
            if cached is not None:
                self.auth_degraded += 1
                return {"qos_policy": cached.policy_name,
                        "framed_ip": cached.framed_ip}
            return None
        if not res.success:
            return None
        from bng_tpu.control.resilience import CachedProfile

        self._radius_degraded.cache_profile(CachedProfile(
            username=key, policy_name=res.policy_name,
            framed_ip=res.framed_ip, cached_at=self.clock()))
        profile = {"qos_policy": res.policy_name,
                   "framed_ip": res.framed_ip, **res.attributes}
        if res.session_timeout:
            profile["lease_time"] = res.session_timeout
        return profile

    def handle_coa(self, action: str, mac_u64: int = 0, ip: int = 0,
                   session_id: str = "", policy_name: str = "") -> dict:
        """CoA/Disconnect actions against THIS shard's lease book.
        `locate` finds without mutating (the fleet's steering probe);
        `qos` re-plans a live lease; `disconnect` force-expires it. The
        mutations ride the same drained event stream as DHCP handling,
        so the parent's single-writer replay sees them in order."""
        lease = None
        if mac_u64:
            lease = self.server.leases.get(mac_u64)
        if lease is None and (ip or session_id):
            for cand in self.server.leases.values():
                if (ip and cand.ip == ip) or \
                        (session_id and cand.session_id == session_id):
                    lease = cand
                    break
        out = {"found": lease is not None, "ip": 0, "events": [],
               "releases": [], "stats": None}
        if lease is None:
            return out
        out["ip"] = lease.ip
        if action == "qos":
            lease.qos_policy = policy_name
            self._events.append(("qos", lease.ip, policy_name))
            # re-push through the lease-event seam so HA replication
            # sees the new plan — else failover restores pre-CoA QoS
            self._lease_event("renew", lease, lease.session_id)
        elif action == "disconnect":
            lease.expiry = 0
            self.server.cleanup_expired(1)  # reaps only the forced lease
        out["events"] = self.tables.drain() + self._drain_events()
        out["releases"] = self._drain_released()
        out["stats"] = self._stats()
        return out

    # -- batch handling ---------------------------------------------------

    def handle_batch(self, items: list, now: float | None = None) -> dict:
        """[(lane, frame)] -> {"results", "events", "offers", "acks",
        "releases", "pending", "refill", "stats"}. One poison frame must
        not kill the worker or shift any other lane's result."""
        t0 = time.perf_counter()
        if self.nexus_resilience is not None:
            # drive the partition FSM here (the worker's only periodic
            # entry point); check_interval_s gates the actual probes so
            # this is a float compare per batch, not an HTTP call
            self.nexus_resilience.tick(self.clock())
        results = []
        offers, acks, releases = [], [], []
        hist = self._lat_hist
        if hist is None and tele.enabled():
            # armed after construction (inline workers share the parent
            # interpreter): start recording from this batch on
            hist = self._lat_hist = LatencyHist()
        for lane, frame in items:
            reply = None
            tf = time.perf_counter() if hist is not None else 0.0
            try:
                reply = self.demux(frame)
            except Exception:  # noqa: BLE001 — untrusted wire input
                self.errors += 1
            if hist is not None:
                hist.record((time.perf_counter() - tf) * 1e6)
            if reply is not None:
                peek = peek_reply(reply)
                if peek is not None:
                    if peek[0] == dhcp_codec.OFFER:
                        offers.append(peek[1])
                    elif peek[0] == dhcp_codec.ACK:
                        acks.append(peek[1])
            results.append((lane, reply))
        self.frames += len(items)
        self.batches += 1
        self.busy_s += time.perf_counter() - t0
        releases += self._drain_released()
        return {
            "results": results,
            "events": self.tables.drain() + self._drain_events(),
            "offers": offers, "acks": acks, "releases": releases,
            "pending": self.demux.drain_pending(),
            "refill": self._refill_wanted(),
            "stats": self._stats(),
        }

    def _drain_events(self) -> list:
        out, self._events = self._events, []
        return out

    def _drain_released(self) -> list:
        out, self._released = self._released, []
        return out

    def _refill_wanted(self) -> list:
        """[(pool_id, want)] for slices under the low watermark."""
        want = []
        for pid, p in self.pools.pools.items():
            free = p.free_count
            if free < self.spec.low_watermark:
                want.append((pid, self.spec.slice_size - free))
        return want

    def apply_grant(self, grants: list) -> None:
        for pid, ips in grants:
            p = self.pools.pools.get(pid)
            if p is not None:
                p.grant(ips)

    def expire(self, now: int, max_reaps: int | None = None) -> dict:
        n = self.server.cleanup_expired(now, max_reaps=max_reaps)
        return {"expired": n,
                "events": self.tables.drain() + self._drain_events(),
                "releases": self._drain_released(),
                "stats": self._stats()}

    def _stats(self) -> dict:
        out = {
            "frames": self.frames, "batches": self.batches,
            "errors": self.errors, "busy_s": self.busy_s,
            "leases": len(self.server.leases),
            "demux": dict(self.demux.stats),
            "slice_free": {pid: p.free_count
                           for pid, p in self.pools.pools.items()},
            # slice exhaustion (refill couldn't keep up / parent pool
            # dry) surfaces through the server's counted degradations
            "pool_exhausted": self.server.stats.pool_exhausted,
        }
        if self.radius is not None:
            out["radius"] = dict(self.radius.stats)
            out["auth_requests"] = self.auth_requests
            out["auth_degraded"] = self.auth_degraded
        if self._lat_hist is not None and self._lat_hist.n:
            # ship-and-reset: the parent folds each shipped delta into
            # its tracer (merge = addition, so deltas compose exactly)
            out["lat_hist"] = self._lat_hist.to_dict()
            self._lat_hist = LatencyHist()
        return out

    # -- checkpoint -------------------------------------------------------

    def export_state(self) -> dict:
        return self.server.export_leases()

    def export_transfer(self) -> dict:
        """Live-transfer state (fleet resize / rolling restart): the
        checkpoint lease book PLUS the in-flight DORA state (un-ACKed
        OFFERs — a checkpoint drops them because a restart client just
        re-DISCOVERs, but a live transition must not strand a client
        whose OFFER is outstanding) and the granted slice map (so the
        parent can release un-held addresses / re-grant verbatim)."""
        st = self.server.export_leases()
        st["offers"] = self.server.export_offers()
        st["granted"] = {int(pid): sorted(int(i) for i in p._granted)
                         for pid, p in self.pools.pools.items()}
        return st

    def restore_state(self, state: dict) -> int:
        """Hydrate the lease book (and, for live transfers, the in-flight
        OFFER state). `revoke` lists every restored address fleet-wide:
        whichever worker's INITIAL slice happened to cover an address
        withdraws it first (ownership moves to the lease's hash-owner),
        then the owner grants + re-claims its own leases — so a fresh
        DORA can never double-assign a restored subscriber's address."""
        for ip in state.get("revoke", ()):
            pool = self.pools.pool_for_ip(int(ip))
            if pool is not None:
                pool.revoke(int(ip))
        ips = [int(d["ip"]) for d in state.get("leases", [])]
        ips += [int(o["ip"]) for o in state.get("offers", [])]
        for ip in ips:
            pool = self.pools.pool_for_ip(ip)
            if pool is not None:
                pool.grant([ip])
        restored = self.server.restore_leases(state)
        restored += self.server.restore_offers(state.get("offers", []))
        return restored


def _worker_main(conn, spec: FleetSpec, worker_id: int,
                 n_workers: int) -> None:
    """Child-process loop: message-driven, never dies on handler input
    (per-frame isolation lives in FleetWorker.handle_batch)."""
    ctx_enter("worker")
    worker = FleetWorker(spec, worker_id, n_workers)

    def refill_now(pool_id: int) -> None:
        # mid-batch synchronous refill: the parent is blocked in its
        # gather loop for this worker and answers refill_req inline
        # (always with a grant message, possibly empty), so this recv
        # cannot deadlock
        conn.send(("refill_req", [(pool_id, spec.slice_size)]))
        tag, payload = conn.recv()
        if tag == "grant":
            worker.apply_grant(payload)

    worker.refill_now = refill_now
    try:
        while True:
            try:
                msg = conn.recv()
            except EOFError:
                break
            kind = msg[0]
            if kind == "batch":
                conn.send(("result", worker.handle_batch(msg[1], msg[2])))
            elif kind == "grant":
                worker.apply_grant(msg[1])
            elif kind == "expire":
                conn.send(("expired", worker.expire(
                    msg[1], msg[2] if len(msg) > 2 else None)))
            elif kind == "export":
                conn.send(("state", worker.export_state()))
            elif kind == "export_transfer":
                conn.send(("state", worker.export_transfer()))
            elif kind == "restore":
                conn.send(("restored", worker.restore_state(msg[1])))
            elif kind == "coa":
                conn.send(("coa", worker.handle_coa(msg[1], **msg[2])))
            elif kind == "stop":
                break
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# the fleet (parent side)
# ---------------------------------------------------------------------------

@owned_by("loop", attrs=None)
class SlowPathFleet:
    """N shared-nothing slow-path workers behind admission control.

    Ownership (BNG_SANITIZE): every mutation belongs to the loop
    context — transitions (resize/rolling restart) run on the loop
    thread via the OpsController drain, reads from the ctl/scrape
    threads go through stats_snapshot()/busy_seconds_total() under the
    app's _ctl. The @owned_by stamp turns a reintroduced cross-context
    reach-in (the pre-PR-7 `_pending`/`_dead` class) into a loud
    OwnershipViolation in sanitizer runs.

    `handle_batch` is the engine's `slow_path_batch` hook: it fans a
    slow-lane batch out to the owning workers, fans replies back in
    **re-merged in lane order**, replays worker table events into the
    parent's single-writer host mirrors, and services lease-slice
    refills — the only cross-worker coordination point.
    """

    def __init__(self, spec: FleetSpec, n_workers: int, pools: PoolManager,
                 mode: str = "process",
                 admission: AdmissionConfig | None = None,
                 table_sink=None, qos_hook=None, nat_hook=None,
                 lease_hook=None,
                 fallback: Callable[[bytes], bytes | None] | None = None,
                 start_method: str | None = None,
                 clock: Callable[[], float] | None = None,
                 worker_factory: Callable[[int, int], FleetWorker] | None = None):
        if mode not in ("process", "inline"):
            raise ValueError(f"fleet mode {mode!r}: expected "
                             f"'process' or 'inline'")
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.spec = spec
        self.n = n_workers
        self.pools = pools
        self.mode = mode
        self.clock = clock or time.time
        self.admission = AdmissionController(admission, clock=self.clock)
        self.table_sink = table_sink
        self.qos_hook = qos_hook
        self.nat_hook = nat_hook
        self.lease_hook = lease_hook
        self.fallback = fallback
        # host-path snapshot (ISSUE 14): vector = batched classify /
        # steer / admit pre-pass in handle_batch; resolved once at
        # construction like Engine.table_impl
        self.host_path = hostpath.resolved_host_path()
        self._vec = self.host_path == "vector"
        self.refills = 0
        self.refill_ips_granted = 0
        self.fallback_frames = 0
        self.fallback_errors = 0
        self._fallback_err_log = SlowPathErrorLog("fleet-fallback")
        self.batches = 0
        self.worker_failures = 0  # dead-worker batch losses (IPC errors)
        # CoA fan-out (ISSUE 19): found on the steered shard / relayed
        # to another shard (missteered — no MAC in the request, or the
        # lease moved) / not found anywhere
        self.coa_handled = 0
        self.coa_relayed = 0
        self.coa_misses = 0
        # workers killed by the chaos harness (fleet.scatter `kill`):
        # process mode terminates the child AND marks it here so the
        # maintenance fan-outs stop talking to a dead pipe; inline mode
        # uses the mark alone (deterministic scenarios)
        self._dead: set[int] = set()
        self.start_method = None  # set for process mode below
        self._pending: list[bytes] = []
        self._last_stats: list[dict] = [{} for _ in range(n_workers)]
        # monotonic fold of dead worker sets' slice-exhaustion counts:
        # per-worker ServerStats restart at 0 on resize/rolling restart,
        # and a counter metric fed from live stats alone would move
        # BACKWARD across a transition (same ship-and-reset discipline
        # as the worker latency histograms)
        self.pool_exhausted_folded = 0
        self._procs: list = []
        self._conns: list = []
        self._inline: list[FleetWorker] = []
        self._worker_factory = worker_factory
        self._mp_ctx = None
        # zero-downtime transition counters (bng_ops_* families)
        self.resizes = 0
        self.rolling_restarts = 0
        if mode == "process":
            import multiprocessing as mp
            import sys

            method = start_method or os.environ.get("BNG_FLEET_START")
            if method is None:
                # spawn re-imports the parent's __main__ in the child;
                # when __main__ is not importable (stdin scripts, REPLs:
                # __file__ == '<stdin>' or missing) every child dies at
                # startup with FileNotFoundError — fall back to fork,
                # which needs no re-import
                main = sys.modules.get("__main__")
                spec_name = getattr(getattr(main, "__spec__", None),
                                    "name", None)
                main_file = getattr(main, "__file__", None)
                spawn_safe = (spec_name is not None or main_file is None
                              or os.path.exists(main_file))
                method = "spawn" if spawn_safe else "fork"
            self._mp_ctx = mp.get_context(method)
            self.start_method = method
        self._spawn_workers()
        self._initial_grant()

    # -- worker lifecycle (shared by __init__, resize, rolling restart) --

    def _make_inline(self, i: int) -> FleetWorker:
        make = self._worker_factory or (
            lambda w, n: FleetWorker(self.spec, w, n, clock=self.clock))
        worker = make(i, self.n)
        worker.refill_now = (lambda pid, _w=i: self._refill_sync(_w, pid))
        return worker

    def _spawn_one(self, i: int) -> tuple:
        """(process, conn) for worker slot i — caller owns the telemetry
        env window (see _spawn_workers)."""
        parent, child = self._mp_ctx.Pipe(duplex=True)
        p = self._mp_ctx.Process(target=_worker_main,
                                 args=(child, self.spec, i, self.n),
                                 daemon=True,
                                 name=f"bng-slowpath-w{i}")
        p.start()
        child.close()
        return p, parent

    class _telemetry_env:
        """Children build their own per-frame latency histograms only
        when the parent traces — env is the only channel that survives
        both spawn and fork. Set ONLY around the worker starts and
        restored after: a leaked BNG_TELEMETRY=1 would force-arm every
        later BNGApp in this process and make every later fleet's
        workers pay armed per-frame costs forever."""

        def __enter__(self):
            self.was = os.environ.get("BNG_TELEMETRY")
            self.set = tele.enabled()
            if self.set:
                os.environ["BNG_TELEMETRY"] = "1"
            return self

        def __exit__(self, *exc):
            # every child inherited its env at start(); restore ours even
            # when a spawn fails mid-loop (a leaked armed flag outlives
            # this fleet, per the warning above)
            if self.set:
                if self.was is None:
                    os.environ.pop("BNG_TELEMETRY", None)
                else:
                    os.environ["BNG_TELEMETRY"] = self.was

    def _spawn_workers(self) -> None:
        """Build a fresh worker set for the CURRENT self.n."""
        if self.mode == "inline":
            self._inline = [self._make_inline(i) for i in range(self.n)]
            return
        with self._telemetry_env():
            for i in range(self.n):
                p, conn = self._spawn_one(i)
                self._procs.append(p)
                self._conns.append(conn)

    def _stop_worker(self, w: int) -> None:
        """Tear down one worker slot (process mode: stop + join; inline:
        the object is simply replaced)."""
        if self.mode == "inline":
            return
        conn, p = self._conns[w], self._procs[w]
        try:
            conn.send(("stop",))
        except (BrokenPipeError, OSError):
            pass
        p.join(timeout=5)
        if p.is_alive():
            p.terminate()
        try:
            conn.close()
        except OSError:
            pass

    def _stop_workers(self) -> None:
        for w in range(len(self._procs)):
            self._stop_worker(w)
        self._procs.clear()
        self._conns.clear()
        self._inline.clear()

    # -- lease-slice coordination (the parent pools stay the authority) --

    def _carve(self, pool_id: int, want: int, worker: int) -> list[int]:
        """Claim up to `want` addresses from the parent pool for a
        worker. Claimed addresses are marked allocated in the parent
        (owner 'fleet:wN'), so cross-worker double allocation is
        structurally impossible."""
        pool = self.pools.pools.get(pool_id)
        if pool is None:
            return []
        out = []
        owner = f"fleet:w{worker}"
        for _ in range(want):
            try:
                out.append(pool.allocate(owner))
            except PoolExhaustedError:
                break
        return out

    def _initial_grant(self) -> None:
        for pid, pool in self.pools.pools.items():
            # fair first carve: don't let worker 0 drain a small pool
            per = max(1, min(self.spec.slice_size,
                             max(0, pool.size - pool.used) // self.n))
            for w in range(self.n):
                ips = self._carve(pid, per, w)
                if ips:
                    self._grant(w, [(pid, ips)])

    def _initial_grant_for(self, w: int) -> None:
        """Fresh initial slices for ONE worker slot (rolling restart of a
        worker whose book was lost with its process)."""
        for pid, pool in self.pools.pools.items():
            per = max(1, min(self.spec.slice_size,
                             max(0, pool.size - pool.used) // self.n))
            ips = self._carve(pid, per, w)
            if ips:
                self._grant(w, [(pid, ips)])

    def _grant(self, worker: int, grants: list) -> None:
        self.refill_ips_granted += sum(len(ips) for _, ips in grants)
        if self.mode == "inline":
            self._inline[worker].apply_grant(grants)
        else:
            self._conns[worker].send(("grant", grants))

    def _service_refill(self, worker: int, wanted: list) -> None:
        grants = self._carve_grants(worker, wanted)
        if grants:
            self.refills += 1
            self._grant(worker, grants)

    def _carve_grants(self, worker: int, wanted: list) -> list:
        grants = []
        for pid, want in wanted:
            ips = self._carve(pid, want, worker)
            if ips:
                grants.append((pid, ips))
        return grants

    def _refill_sync(self, worker: int, pool_id: int) -> None:
        """Inline-mode mid-batch refill (the worker's slice ran dry)."""
        grants = self._carve_grants(worker, [(pool_id,
                                              self.spec.slice_size)])
        if grants:
            self.refills += 1
            self.refill_ips_granted += sum(len(i) for _p, i in grants)
            self._inline[worker].apply_grant(grants)

    def _gather(self, worker: int, expect: str):
        """Receive one `expect`-tagged message from a worker process,
        servicing mid-batch refill_req messages inline. The reply to a
        refill_req is ALWAYS a grant (possibly empty) — the child blocks
        on it."""
        conn = self._conns[worker]
        while True:
            tag, payload = conn.recv()
            if tag == "refill_req":
                grants = self._carve_grants(worker, payload)
                if grants:
                    self.refills += 1
                    self.refill_ips_granted += sum(
                        len(i) for _p, i in grants)
                conn.send(("grant", grants))
                continue
            if tag != expect:
                raise RuntimeError(
                    f"fleet worker {worker}: unexpected reply {tag!r} "
                    f"(wanted {expect!r})")
            return payload

    # -- chaos harness hooks (bng_tpu/chaos/faults.py) --------------------

    def _scatter_fault(self, w: int, groups: dict,
                       now: float | None = None) -> bool:
        """fault_point('fleet.scatter') on the per-worker batch dispatch
        — the pipe protocol's failure surface. Returns True when this
        worker's batch is LOST (kill / drop_batch / already dead);
        dup_batch and reorder mutate the delivery and the batch still
        runs. Disarmed cost: one no-op call per worker-group."""
        fp = fault_point("fleet.scatter")
        if fp is not None:
            if fp.kind == "kill":
                self._kill_worker(w)
            elif fp.kind == "drop_batch":
                self._note_worker_failure(w)
                return True
            elif fp.kind == "reorder":
                # pipe reorder: lanes arrive at the worker out of order;
                # the parent's lane-sorted re-merge must absorb it
                groups[w] = list(reversed(groups[w]))
            elif fp.kind == "dup_batch" and self.mode == "inline" \
                    and w not in self._dead:
                # at-least-once delivery: the worker handles the batch
                # twice. The duplicate's table events / admission
                # feedback absorb normally (idempotent upserts); its
                # replies are superseded by the second pass.
                self._absorb(w, self._inline[w].handle_batch(
                    list(groups[w]),
                    now if now is not None else self.clock()))
        if w in self._dead:
            self._note_worker_failure(w)
            return True
        return False

    def _kill_worker(self, w: int) -> None:
        """The chaos `kill` fault: a real terminate in process mode (the
        pipe dies mid-protocol — the existing IPC-failure handling owns
        the fallout), a permanent dead-mark in inline mode. Either way
        the worker's shard loses service until a restart; its carved
        slices stay allocated in the parent pool, so no other worker can
        ever double-assign its addresses."""
        self._dead.add(w)
        if self.mode == "process":
            try:
                self._procs[w].terminate()
                self._procs[w].join(timeout=2)
            except (OSError, ValueError):
                pass

    # -- the hot path -----------------------------------------------------

    def handle_batch(self, items: list, now: float | None = None) -> list:
        """[(lane, frame)] or [(lane, frame, enq_t)] -> [(lane, reply)]
        in ascending lane order. Shed frames return (lane, None)."""
        now = now if now is not None else self.clock()
        self.batches += 1
        groups: dict[int, list] = {}
        results: list[tuple[int, bytes | None]] = []
        shed_n = 0
        t0 = tele.t()
        if self._vec and len(items) > 1 and not faults.any_armed():
            shed_n = self._admit_vec(items, now, groups, results)
        else:
            depth: dict[int, int] = {}
            for item in items:
                lane, frame = item[0], item[1]
                enq_t = item[2] if len(item) > 2 else None
                if self.fallback is not None and not classify_dhcp(frame):
                    # non-DHCPv4 slow traffic (v6 / SLAAC / PPPoE /
                    # poison) stays on the parent's demux — the fleet
                    # shards DHCPv4
                    self.fallback_frames += 1
                    try:
                        results.append((lane, self.fallback(frame)))
                    except Exception as e:  # noqa: BLE001 — untrusted wire input
                        self.fallback_errors += 1
                        self._fallback_err_log.report(e, lane=lane)
                        results.append((lane, None))
                    continue
                w = shard_for_frame(frame, self.n)
                ok, _reason = self.admission.admit(
                    frame, depth.get(w, 0), now, enq_t)
                if not ok:
                    shed_n += 1
                    results.append((lane, None))
                    continue
                groups.setdefault(w, []).append((lane, frame))
                depth[w] = depth.get(w, 0) + 1
        tele.lap(tele.ADMIT, t0)
        tele.add(shed=shed_n)
        t0 = tele.t()
        if groups:
            if self.mode == "inline":
                for w in sorted(groups):
                    if self._scatter_fault(w, groups, now):
                        results.extend((lane, None)
                                       for lane, _f in groups[w])
                        continue
                    out = self._inline[w].handle_batch(groups[w], now)
                    results.extend(self._absorb(w, out))
            else:
                # scatter first so every child computes concurrently,
                # THEN gather. A dead worker (IPC error) loses only ITS
                # lanes — the client retransmits; other shards and later
                # batches are unaffected.
                sent = []
                for w in sorted(groups):
                    if self._scatter_fault(w, groups, now):
                        results.extend((lane, None)
                                       for lane, _f in groups[w])
                        continue
                    try:
                        self._conns[w].send(("batch", groups[w], now))
                        sent.append(w)
                    except (OSError, ValueError):
                        self._note_worker_failure(w)
                        results.extend((lane, None)
                                       for lane, _f in groups[w])
                for w in sent:
                    try:
                        results.extend(self._absorb(
                            w, self._gather(w, "result")))
                    except (OSError, EOFError):
                        self._note_worker_failure(w)
                        results.extend((lane, None)
                                       for lane, _f in groups[w])
        tele.lap(tele.FLEET, t0)
        results.sort(key=lambda t: t[0])
        return results

    def _admit_vec(self, items: list, now: float, groups: dict,
                   results: list) -> int:
        """Vectorized classify->shard->admit pre-pass (ISSUE 14): one
        packed matrix, one classify_dhcp_batch for the fallback demux,
        one FNV pass for worker steering, one admit_batch for the
        admission verdicts — bit-identical to the per-frame loop
        (pinned by tests/test_hostpath.py), with per-frame Python left
        only where a handler must run per frame (the fallback demux and
        the worker scatter protocol). Returns the shed count."""
        frames = [item[1] for item in items]
        lens = hostpath.frame_lens(frames)
        buf = None
        if self.fallback is not None:
            # the fallback demux needs the classifier, which needs the
            # packed matrix; without a fallback nothing here reads a
            # payload byte (admit_batch packs its breached subset
            # lazily), so the matrix is never built
            buf = np.empty((len(frames), max(int(lens.max()), 1)),
                           dtype=np.uint8)
            hostpath.pack_into(frames, buf,
                               np.empty((len(frames),), np.uint32),
                               lens=lens)
            dhcp_m = hostpath.classify_dhcp_batch(buf, lens) != 0
        else:
            dhcp_m = np.ones(len(frames), dtype=bool)
        if self.n > 1:
            if buf is not None:
                mac6 = buf[:, 6:12]
            elif int(lens.min()) >= 12:
                # steering needs ONLY frame[6:12]: one join of 6-byte
                # slices beats packing whole payloads
                mac6 = np.frombuffer(
                    b"".join([f[6:12] for f in frames]),
                    dtype=np.uint8).reshape(len(frames), 6)
            else:
                mac6 = np.zeros((len(frames), 6), dtype=np.uint8)
                for i in np.nonzero(lens >= 12)[0].tolist():
                    mac6[i] = np.frombuffer(frames[i][6:12], np.uint8)
            workers = (hostpath.fnv1a32_cols(mac6)
                       % np.uint32(self.n)).astype(np.int64)
            workers[lens < 12] = 0  # shard_for_frame's runt guard
        else:
            workers = np.zeros(len(frames), dtype=np.int64)
        all_dhcp = bool(dhcp_m.all())
        di = np.arange(len(frames)) if all_dhcp else np.nonzero(dhcp_m)[0]
        enq = None
        if len(items[0]) > 2 and len(di):
            enq = (np.fromiter((it[2] for it in items), dtype=np.float64,
                               count=len(items)) if all_dhcp else
                   np.fromiter((items[i][2] for i in di.tolist()),
                               dtype=np.float64, count=len(di)))
        admitted = self.admission.admit_batch(
            frames if all_dhcp else [frames[i] for i in di.tolist()],
            workers if all_dhcp else workers[di],
            None if buf is None else (buf if all_dhcp else buf[di]),
            lens if all_dhcp else lens[di], now, enq)
        shed_n = 0
        if admitted.all() and self.n == 1:
            # the unpressured single-worker fast path: ONE group append
            g = groups.setdefault(0, [])
            g.extend((items[i][0], frames[i]) for i in di.tolist())
        else:
            wl = workers.tolist()
            al = admitted.tolist()
            for k, i in enumerate(di.tolist()):
                if al[k]:
                    groups.setdefault(wl[i], []).append(
                        (items[i][0], frames[i]))
                else:
                    shed_n += 1
                    results.append((items[i][0], None))
        for i in np.nonzero(~dhcp_m)[0].tolist():
            lane, frame = items[i][0], frames[i]
            self.fallback_frames += 1
            try:
                results.append((lane, self.fallback(frame)))
            except Exception as e:  # noqa: BLE001 — untrusted wire input
                self.fallback_errors += 1
                self._fallback_err_log.report(e, lane=lane)
                results.append((lane, None))
        return shed_n

    def _note_worker_failure(self, w: int) -> None:
        """One dead/failed worker batch: counted AND surfaced to the
        flight recorder (gray failures hide in counters; a worker death
        must leave the last-N batch evidence on disk)."""
        self.worker_failures += 1
        tele.trigger("worker_death", f"worker {w} lost a batch")

    def _absorb(self, worker: int, out: dict) -> list:
        """Fold one worker's batch result into parent state (events ->
        single-writer tables, offer/ack feedback -> admission, refill
        service, pending frames) and return its lane results."""
        apply_table_events(out["events"], self.table_sink,
                          self.qos_hook, self.nat_hook, self.lease_hook)
        # releases BEFORE offers/acks: a lease replaced within the batch
        # emits stop(old) + ACK(new) for one MAC — the re-lease must win
        for mac in out["releases"]:
            self.admission.note_release(mac)
        for mac in out["offers"]:
            self.admission.note_offer(mac)
        for mac in out["acks"]:
            self.admission.note_ack(mac)
        self._pending.extend(out["pending"])
        if out["refill"]:
            self._service_refill(worker, out["refill"])
        self._last_stats[worker] = out["stats"]
        tr = tele.tracer()
        if tr is not None and "lat_hist" in out["stats"]:
            # cross-process histogram merge: the worker's per-frame
            # handler-latency delta folds into the parent's `worker`
            # stage (merge = counter addition — worker order never
            # changes the distribution)
            tr.merge_stage(tele.WORKER, out["stats"]["lat_hist"])
        return out["results"]

    def handle_frame(self, frame: bytes) -> bytes | None:
        """Single-frame facade (the plain `slow_path` signature)."""
        out = self.handle_batch([(0, frame)])
        return out[0][1] if out else None

    def drain_pending(self) -> list[bytes]:
        """Extra frames beyond one-reply-per-input (the demux pending
        contract), merged in worker-arrival order — deterministic
        because workers are gathered in index order."""
        out, self._pending = self._pending, []
        return out

    def requeue(self, frames: list[bytes], front: bool = False) -> None:
        """Public re-queue onto the pending queue (the drain_pending
        counterpart): the composition root puts back frames it could not
        TX-inject this beat (`front=True` preserves wire order) instead
        of reaching into the private list."""
        if front:
            self._pending[:0] = frames
        else:
            self._pending.extend(frames)

    # -- CoA fan-out ------------------------------------------------------

    def _coa_one(self, w: int, action: str, kw: dict) -> dict | None:
        """One shard's CoA verdict, with its event stream folded through
        the parent's single-writer replay (same discipline as batches)."""
        try:
            if self.mode == "inline":
                out = self._inline[w].handle_coa(action, **kw)
            else:
                self._conns[w].send(("coa", action, kw))
                out = self._gather(w, "coa")
        except (OSError, EOFError):
            self._note_worker_failure(w)
            return None
        apply_table_events(out["events"], self.table_sink,
                          self.qos_hook, self.nat_hook, self.lease_hook)
        for mac in out["releases"]:
            self.admission.note_release(mac)
        if out["stats"] is not None:
            self._last_stats[w] = out["stats"]
        return out

    def handle_coa(self, action: str, mac: bytes = b"", ip: int = 0,
                   session_id: str = "", policy_name: str = "") -> dict:
        """Route a CoA/Disconnect action to the owning shard. With a MAC
        the steering hash names the owner directly (auth affinity = DHCP
        affinity = CoA affinity); otherwise — or when the steered shard
        misses — the remaining shards are probed in index order and a
        hit counts as a relay. Returns {found, ip, worker, relayed}."""
        kw = {"mac_u64": int.from_bytes(mac[:6].rjust(6, b"\0"), "big")
              if mac else 0,
              "ip": ip, "session_id": session_id,
              "policy_name": policy_name}
        steered = shard_for_mac(mac, self.n) if mac else 0
        order = [steered] + [w for w in range(self.n) if w != steered]
        for w in order:
            if w in self._dead:
                continue
            out = self._coa_one(w, action, kw)
            if out is None or not out["found"]:
                continue
            relayed = bool(mac) and w != steered
            self.coa_handled += 1
            if relayed:
                self.coa_relayed += 1
            return {"found": True, "ip": out["ip"], "worker": w,
                    "relayed": relayed}
        self.coa_misses += 1
        return {"found": False, "ip": 0, "worker": -1, "relayed": False}

    # -- maintenance ------------------------------------------------------

    def expire(self, now: int, max_reaps: int | None = None) -> int:
        """Lease-expiry sweep across every worker (the parent tick's
        cleanup_expired role). `max_reaps` is a PER-WORKER teardown
        bound (each worker's sweep is its own serial section; bounding
        per shard keeps the tick budget proportional to fleet width the
        same way batch handling is)."""
        total = 0
        if self.mode == "inline":
            for w, worker in enumerate(self._inline):
                if w in self._dead:
                    continue
                out = worker.expire(now, max_reaps)
                total += self._absorb_expire(w, out)
        else:
            sent = []
            for w, conn in enumerate(self._conns):
                if w in self._dead:
                    continue
                try:
                    conn.send(("expire", now, max_reaps))
                    sent.append(w)
                except (OSError, ValueError):
                    self._note_worker_failure(w)
            for w in sent:
                try:
                    total += self._absorb_expire(w,
                                                 self._gather(w, "expired"))
                except (OSError, EOFError):
                    self._note_worker_failure(w)
        return total

    def _absorb_expire(self, worker: int, out: dict) -> int:
        apply_table_events(out["events"], self.table_sink,
                          self.qos_hook, self.nat_hook, self.lease_hook)
        for mac in out.get("releases", ()):
            self.admission.note_release(mac)
        self._last_stats[worker] = out["stats"]
        tr = tele.tracer()
        if tr is not None and "lat_hist" in out["stats"]:
            # the worker ships-and-resets its histogram with EVERY stats
            # payload — an expire-path delta dropped here would be lost
            tr.merge_stage(tele.WORKER, out["stats"]["lat_hist"])
        return out["expired"]

    # -- checkpoint (runtime/checkpoint.py 'fleet' component) -------------

    def export_state(self) -> dict:
        """Per-worker lease books for the checkpoint payload. Slice
        free-lists are transient (like the server's _offers) — on
        restore, workers get fresh slices and each restored lease's IP
        is re-claimed explicitly."""
        if self.mode == "inline":
            # dead (chaos-killed) inline workers keep their books in
            # memory — a checkpoint still captures their leases
            workers = [dict(w.export_state(), worker_id=i)
                       for i, w in enumerate(self._inline)]
        else:
            # a KNOWN-dead process's book is gone: snapshot the
            # survivors rather than failing the whole checkpoint. A
            # LIVE worker's IPC failure still raises — a silently
            # partial snapshot saved as good would un-claim a whole
            # shard's addresses on restore (double-allocation), which is
            # strictly worse than keeping the previous good checkpoint.
            workers = []
            for w, conn in enumerate(self._conns):
                if w in self._dead:
                    continue
                conn.send(("export",))
                workers.append(dict(self._gather(w, "state"),
                                    worker_id=w))
        return {"n_workers": self.n, "workers": workers}

    @staticmethod
    def parse_state(state: dict) -> int:
        """Dry-parse (the restore pre-check role): raises on a corrupt
        fleet blob, touches nothing. Returns the total lease count."""
        from bng_tpu.control.dhcp_server import DHCPServer

        total = 0
        for wstate in state["workers"]:
            _seq, leases = DHCPServer.parse_lease_state(wstate)
            total += len(leases)
        return total

    def restore_state(self, state: dict) -> int:
        """Re-shard the checkpointed lease books onto the CURRENT worker
        count (the MAC hash decides, so a changed --slowpath-workers
        still lands every subscriber on its new owner), claim each
        lease's IP in the parent pool, and hydrate the owners."""
        return self._hydrate_books(state["workers"])

    def _hydrate_books(self, books: list[dict]) -> int:
        """The shared re-shard + hydrate core: checkpoint restore and
        live resize both route every lease (and, for live transfers,
        every in-flight OFFER) to its MAC-hash owner at the CURRENT
        worker count — bit-for-bit the ring classifier's steering hash,
        so restore-time and resize-time ownership can never diverge."""
        per_worker: list[dict] = [
            {"session_seq": 0, "leases": [], "offers": []}
            for _ in range(self.n)]
        all_ips: list[int] = []
        for wstate in books:
            seq = int(wstate.get("session_seq", 0))
            for d in wstate.get("leases", []):
                mac = bytes.fromhex(d["mac"])
                w = shard_for_mac(mac, self.n)
                per_worker[w]["leases"].append(d)
                per_worker[w]["session_seq"] = max(
                    per_worker[w]["session_seq"], seq)
                all_ips.append(int(d["ip"]))
            for o in wstate.get("offers", []):
                w = shard_for_mac(bytes.fromhex(o["mac"]), self.n)
                per_worker[w]["offers"].append(o)
                all_ips.append(int(o["ip"]))
        restored = 0
        for w, wstate in enumerate(per_worker):
            for ip in ([int(d["ip"]) for d in wstate["leases"]]
                       + [int(o["ip"]) for o in wstate["offers"]]):
                # parent-side ownership transfer: the address may sit in
                # ANOTHER worker's initial free slice — release that
                # claim, then re-claim for the lease's hash-owner, so it
                # is out of every other worker's reach before the owner
                # re-leases it (the workers revoke their side below)
                pool = self.pools.pool_for_ip(ip)
                if pool is None:
                    continue
                owner_tag = f"fleet:w{w}"
                cur = pool._allocated.get(ip)
                if cur is not None and cur != owner_tag:
                    pool.release(ip)
                pool.allocate_specific(ip, owner_tag)
            # every worker gets the full revoke list: initial slices are
            # carved before restore, so any worker may hold any address
            wstate["revoke"] = all_ips
            if self.mode == "inline":
                restored += self._inline[w].restore_state(wstate)
            elif w not in self._dead:
                # a chaos-killed process can't hydrate its shard; the
                # parent-side claims above still protect every restored
                # address from double-allocation (service degraded,
                # consistency intact)
                self._conns[w].send(("restore", wstate))
        if self.mode == "process":
            for w in range(self.n):
                if w not in self._dead:
                    restored += self._gather(w, "restored")
        return restored

    # -- zero-downtime operations (ROADMAP [ops-refactor]) ----------------

    def _export_transfer(self, w: int) -> dict | None:
        """One worker's live-transfer state, or None when the book is
        unknowable (dead process — its carved addresses stay allocated
        in the parent pool, so consistency survives the loss). Inline
        dead-marked workers keep their books in memory, so a transition
        HEALS them: the state moves, the subscriber never notices."""
        if self.mode == "inline":
            return dict(self._inline[w].export_transfer(), worker_id=w)
        if w in self._dead:
            return None
        try:
            self._conns[w].send(("export_transfer",))
            return dict(self._gather(w, "state"), worker_id=w)
        except (OSError, EOFError, BrokenPipeError):
            self._note_worker_failure(w)
            return None

    def resize(self, n_new: int) -> dict:
        """Live fleet elasticity: grow/shrink to `n_new` workers at a
        batch boundary (caller serializes against handle_batch), without
        dropping in-flight DORAs.

        Drain-then-transfer, transactional: phase 1 reads every knowable
        worker book + offer set (abortable — a chaos `fail` here leaves
        the old fleet serving untouched); phase 2 stops the old workers
        and releases their un-held slice addresses back to the parent
        pool; phase 3 builds the new worker set with fresh initial
        slices; phase 4 re-shards every lease AND every un-ACKed OFFER
        onto its new MAC-hash owner (the checkpoint-restore discipline),
        transferring parent-pool ownership address by address. The
        admission controller is parent-side state and rides through
        unchanged, so REQUEST-after-OFFER protection holds ACROSS the
        transition. Returns the transition report (bng_ops_* feed)."""
        if n_new < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_new}")
        t_all = time.perf_counter()
        report: dict = {"op": "fleet_resize", "from": self.n, "to": n_new}
        if n_new == self.n:
            report.update(outcome="noop", duration_s=0.0)
            return report
        # phase 1 — drain-then-transfer (read-only, abortable)
        t0 = tele.t()
        states: list[dict] = []
        lost: list[int] = []
        for w in range(self.n):
            fp = fault_point("fleet.resize")
            if fp is not None:
                if fp.kind == "kill":
                    self._kill_worker(w)
                elif fp.kind == "fail":
                    report.update(
                        outcome="aborted",
                        error="chaos: injected resize failure",
                        duration_s=time.perf_counter() - t_all)
                    return report
            st = self._export_transfer(w)
            if st is None:
                lost.append(w)
            else:
                states.append(st)
        tele.lap(tele.OPS, t0)
        # phase 2 — commit: stop the old fleet; un-held slice addresses
        # go back to the parent pool (a lost book's grants are unknowable
        # and stay allocated: consistency over reclamation)
        t0 = tele.t()
        self._stop_workers()
        held = {int(d["ip"]) for st in states for d in st["leases"]}
        held |= {int(o["ip"]) for st in states
                 for o in st.get("offers", [])}
        freed = 0
        for st in states:
            for pid, ips in st.get("granted", {}).items():
                pool = self.pools.pools.get(int(pid))
                if pool is None:
                    continue
                for ip in ips:
                    if int(ip) not in held and pool.release(int(ip)):
                        freed += 1
        # phase 3 — the new worker set + initial slices at the new count
        try:
            self.n = n_new
            self._dead.clear()
            self._fold_exhaustion()
            self._last_stats = [{} for _ in range(n_new)]
            self._spawn_workers()
            self._initial_grant()
            tele.lap(tele.OPS, t0)
            # phase 4 — re-shard + hydrate (checkpoint-restore hash)
            t0 = tele.t()
            restored = self._hydrate_books(states)
            tele.lap(tele.OPS, t0)
        except Exception as e:  # noqa: BLE001
            # past the commit point the old fleet is GONE and `states`
            # is the only copy of every lease and in-flight OFFER —
            # "transactional" must not end at phase 2. Salvage: rebuild
            # the smallest viable worker set and hydrate the exported
            # books into it (fd/process pressure that failed an N-worker
            # spawn usually still admits one; shard count changing again
            # is fine — _hydrate_books re-routes by the same hash).
            report.update(outcome="failed",
                          error=f"{type(e).__name__}: {e}"[:300])
            log = get_logger("fleet.resize")
            log.error("resize failed past commit point, salvaging",
                      to=n_new, error=report["error"],
                      books=len(states))
            for fallback in dict.fromkeys((n_new, 1)):
                try:
                    self._stop_workers()
                    self.n = fallback
                    self._dead.clear()
                    self._fold_exhaustion()
                    self._last_stats = [{} for _ in range(fallback)]
                    self._spawn_workers()
                    self._initial_grant()
                    restored = self._hydrate_books(states)
                except Exception as e2:  # noqa: BLE001 — next size down
                    log.error("salvage attempt failed", workers=fallback,
                              error=f"{type(e2).__name__}: {e2}")
                    continue
                self.resizes += 1
                report.update(
                    outcome="salvaged", to=fallback, restored=restored,
                    leases_moved=sum(len(s["leases"]) for s in states),
                    offers_moved=sum(len(s.get("offers", ()))
                                     for s in states),
                    slices_freed=freed, lost_workers=sorted(lost))
                break
            report["duration_s"] = time.perf_counter() - t_all
            return report
        self.resizes += 1
        report.update(
            outcome="ok", restored=restored,
            leases_moved=sum(len(s["leases"]) for s in states),
            offers_moved=sum(len(s.get("offers", ())) for s in states),
            slices_freed=freed, lost_workers=sorted(lost),
            duration_s=time.perf_counter() - t_all)
        return report

    def rolling_restart(self) -> dict:
        """Replace every worker one shard at a time under the same
        drain-then-transfer discipline as resize — the live-deploy /
        leak-recovery verb. Same worker count, same shard map: each
        worker's book, offer set and granted slices move verbatim into
        a fresh worker in the same slot (parent-pool owner tags never
        change), so no re-shard and no cross-shard transfer happens. A
        dead-marked process worker's book is gone — its replacement
        starts empty on fresh slices (subscribers re-DORA; the lost
        slices stay allocated: consistency over reclamation) — while a
        dead-marked INLINE worker's book is still in memory, so the
        rotation heals it with zero subscriber impact."""
        t_all = time.perf_counter()
        report: dict = {"op": "fleet_rolling_restart", "workers": self.n}
        replaced: list[int] = []
        healed: list[int] = []
        lost: list[int] = []
        moved = 0
        for w in range(self.n):
            fp = fault_point("fleet.restart")
            if fp is not None:
                if fp.kind == "kill":
                    self._kill_worker(w)
                elif fp.kind == "fail":
                    report.update(
                        outcome="aborted",
                        error="chaos: injected restart failure",
                        replaced=replaced, healed=healed, lost=lost,
                        leases_moved=moved,
                        duration_s=time.perf_counter() - t_all)
                    return report
            t0 = tele.t()
            was_dead = w in self._dead
            st = self._export_transfer(w)
            self._stop_worker(w)
            if self.mode == "inline":
                self._inline[w] = self._make_inline(w)
            else:
                with self._telemetry_env():
                    p, conn = self._spawn_one(w)
                self._procs[w], self._conns[w] = p, conn
            self._dead.discard(w)
            self.pool_exhausted_folded += int(
                self._last_stats[w].get("pool_exhausted", 0) or 0)
            self._last_stats[w] = {}
            if st is None:
                # fresh slices so the shard serves again
                self._initial_grant_for(w)
                lost.append(w)
                tele.lap(tele.OPS, t0)
                continue
            grants = [(int(pid), [int(i) for i in ips])
                      for pid, ips in st.pop("granted", {}).items()]
            if grants:
                self._grant(w, grants)
            st["revoke"] = []
            if self.mode == "inline":
                moved += self._inline[w].restore_state(st)
            else:
                self._conns[w].send(("restore", st))
                moved += self._gather(w, "restored")
            (healed if was_dead else replaced).append(w)
            tele.lap(tele.OPS, t0)
        self.rolling_restarts += 1
        report.update(outcome="ok", replaced=replaced, healed=healed,
                      lost=lost, leases_moved=moved,
                      duration_s=time.perf_counter() - t_all)
        return report

    # -- observability ----------------------------------------------------

    def _fold_exhaustion(self) -> None:
        """Absorb the outgoing worker set's slice-exhaustion counts into
        the monotonic fold — call exactly once per teardown, BEFORE the
        per-worker stats reset."""
        self.pool_exhausted_folded += sum(
            int(w.get("pool_exhausted", 0) or 0)
            for w in self._last_stats if w)

    def pool_exhausted_total(self) -> int:
        """Monotonic slice-exhaustion count across worker generations:
        folded dead-set counts + the live workers' latest payloads (the
        counter-metric read — never moves backward over a transition)."""
        return self.pool_exhausted_folded + sum(
            int(w.get("pool_exhausted", 0) or 0)
            for w in self._last_stats if w)

    def busy_seconds_total(self) -> float:
        """Cumulative handler-busy seconds across the worker set (from
        the latest per-worker stats payloads) — the autoscaler's load
        signal: sampled on a cadence, the delta over wall time is the
        fleet's mean busy fraction."""
        return sum(float(w.get("busy_s", 0.0))
                   for w in self._last_stats if w)

    def stats_snapshot(self) -> dict:
        return {
            "workers": self.n,
            "mode": self.mode,
            "start_method": self.start_method,
            "worker_failures": self.worker_failures,
            "dead_workers": sorted(self._dead),
            "batches": self.batches,
            "resizes": self.resizes,
            "rolling_restarts": self.rolling_restarts,
            "refills": self.refills,
            "refill_ips_granted": self.refill_ips_granted,
            "fallback_frames": self.fallback_frames,
            "fallback_errors": self.fallback_errors,
            "coa_handled": self.coa_handled,
            "coa_relayed": self.coa_relayed,
            "coa_misses": self.coa_misses,
            "per_worker": list(self._last_stats),
            "pool_exhausted_total": self.pool_exhausted_total(),
            "admission": self.admission.stats_snapshot(),
        }

    def close(self) -> None:
        if self.mode == "inline":
            return
        for conn in self._conns:
            try:
                conn.send(("stop",))
            except (BrokenPipeError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()
        self._procs.clear()
