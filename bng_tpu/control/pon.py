"""PON/OLT NTE (ONT) lifecycle: discovery -> provisioning -> connected.

Parity: pkg/pon — NTEState (manager.go:14-39), DiscoveryEvent /
ProvisioningResult (manager.go:41-57), Manager with HandleDiscovery queue
(manager.go:188-214), handleDiscoveryEvent + provisionNTE (VLAN alloc via
Nexus, QoS profile, approval gating) (manager.go:216-379),
handleNexusNTEChange reacting to approval flips (manager.go:381-396),
HandleDisconnect (manager.go:398-427), stats (manager.go:460-495).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from enum import Enum

from bng_tpu.control.nexus import NexusClient, NTEEntity


class NTEState(str, Enum):
    UNKNOWN = "unknown"
    DISCOVERED = "discovered"
    PENDING_APPROVAL = "pending_approval"
    PROVISIONING = "provisioning"
    CONNECTED = "connected"
    DISCONNECTED = "disconnected"
    FAILED = "failed"


@dataclass
class DiscoveryEvent:
    """manager.go:41-47: an ONT appeared on an OLT port."""

    serial: str
    olt_id: str = ""
    olt_port: int = 0
    model: str = ""
    timestamp: float = 0.0


@dataclass
class QoSProfile:
    """manager.go:80-84."""

    name: str = "default"
    down_mbps: int = 100
    up_mbps: int = 20


@dataclass
class ProvisioningResult:
    """manager.go:49-57."""

    serial: str
    success: bool = False
    s_tag: int = 0
    c_tag: int = 0
    qos_profile: str = ""
    error: str = ""


@dataclass
class PONConfig:
    """manager.go:59-97."""

    auto_provision: bool = True
    default_qos: QoSProfile = field(default_factory=QoSProfile)
    require_approval: bool = True


class PONManager:
    """manager.go:99-495. vlan_allocator: nexus.VLANAllocator-compatible
    (.allocate(id) -> (s_tag, c_tag))."""

    def __init__(self, config: PONConfig, nexus: NexusClient,
                 vlan_allocator=None, clock=time.time):
        self.config = config
        self.nexus = nexus
        self.vlans = vlan_allocator
        self._clock = clock
        self._lock = threading.Lock()
        self._states: dict[str, NTEState] = {}
        self._pending: dict[str, DiscoveryEvent] = {}
        self.on_discovered = None
        self.on_provisioned = None
        self.on_disconnected = None
        self.stats = {"discovered": 0, "provisioned": 0, "failed": 0,
                      "disconnected": 0, "pending": 0}
        self.nexus.ntes.watch(self._on_nexus_nte_change)

    # -- discovery (manager.go:188-277) ---------------------------------

    def handle_discovery(self, event: DiscoveryEvent) -> ProvisioningResult | None:
        event.timestamp = event.timestamp or self._clock()
        with self._lock:
            self._states[event.serial] = NTEState.DISCOVERED
            self.stats["discovered"] += 1
        if self.on_discovered:
            self.on_discovered(event)

        nte = self._find_nte(event.serial)
        if nte is None:
            # Unknown ONT: register as pending in Nexus, hold locally.
            self.nexus.ntes.put(event.serial, NTEEntity(
                id=event.serial, serial=event.serial, model=event.model,
                olt_id=event.olt_id, state="discovered", approved=False))
            return self._hold_pending(event)
        if self.config.require_approval and not nte.approved:
            return self._hold_pending(event)
        if not self.config.auto_provision:
            return self._hold_pending(event)
        return self.provision(event)

    def _hold_pending(self, event: DiscoveryEvent) -> None:
        with self._lock:
            if event.serial not in self._pending:
                self.stats["pending"] += 1
            self._pending[event.serial] = event
            self._states[event.serial] = NTEState.PENDING_APPROVAL
        return None

    def _find_nte(self, serial: str) -> NTEEntity | None:
        nte = self.nexus.ntes.get(serial)
        if nte is not None:
            return nte
        for n in self.nexus.ntes.list().values():
            if n.serial == serial:
                return n
        return None

    # -- provisioning (manager.go:279-379) ------------------------------

    def provision(self, event: DiscoveryEvent) -> ProvisioningResult:
        serial = event.serial
        with self._lock:
            # Leave pending before writing back to Nexus: the ntes.put below
            # re-fires our own watcher, which must not re-enter provision.
            if self._pending.pop(serial, None) is not None:
                self.stats["pending"] -= 1
            self._states[serial] = NTEState.PROVISIONING
        nte = self._find_nte(serial)
        if nte is None:
            return self._fail(serial, "NTE vanished during provisioning")
        s_tag, c_tag = nte.s_tag, nte.c_tag
        if not (s_tag or c_tag):
            if self.vlans is None:
                return self._fail(serial, "no VLAN assignment and no allocator")
            pair = self.vlans.allocate(serial)
            if pair is None:
                return self._fail(serial, "VLAN space exhausted")
            s_tag, c_tag = pair
        nte.s_tag, nte.c_tag = s_tag, c_tag
        nte.state = "connected"
        self.nexus.ntes.put(nte.id, nte)
        result = ProvisioningResult(
            serial=serial, success=True, s_tag=s_tag, c_tag=c_tag,
            qos_profile=self.config.default_qos.name)
        with self._lock:
            self._states[serial] = NTEState.CONNECTED
            self.stats["provisioned"] += 1
        if self.on_provisioned:
            self.on_provisioned(result)
        return result

    def _fail(self, serial: str, error: str) -> ProvisioningResult:
        with self._lock:
            self._states[serial] = NTEState.FAILED
            self.stats["failed"] += 1
        result = ProvisioningResult(serial=serial, success=False, error=error)
        if self.on_provisioned:
            self.on_provisioned(result)
        return result

    # -- nexus reaction (manager.go:381-396) ----------------------------

    def _on_nexus_nte_change(self, nte_id: str, nte: NTEEntity | None) -> None:
        if nte is None:
            return
        with self._lock:
            pending = self._pending.get(nte.serial or nte_id)
        if pending is not None and nte.approved:
            self.provision(pending)

    # -- disconnect (manager.go:398-427) --------------------------------

    def handle_disconnect(self, serial: str) -> None:
        with self._lock:
            self._states[serial] = NTEState.DISCONNECTED
            self.stats["disconnected"] += 1
        nte = self._find_nte(serial)
        if nte is not None:
            nte.state = "disconnected"
            self.nexus.ntes.put(nte.id, nte)
        if self.on_disconnected:
            self.on_disconnected(serial)

    # -- queries (manager.go:429-495) -----------------------------------

    def get_state(self, serial: str) -> NTEState:
        with self._lock:
            return self._states.get(serial, NTEState.UNKNOWN)

    def list_connected(self) -> list[str]:
        with self._lock:
            return [s for s, st in self._states.items()
                    if st == NTEState.CONNECTED]

    def list_pending(self) -> list[DiscoveryEvent]:
        with self._lock:
            return list(self._pending.values())

    def get_stats(self) -> dict:
        with self._lock:
            return dict(self.stats)
