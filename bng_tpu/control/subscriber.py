"""Subscriber session lifecycle orchestrator (IPoE/PPPoE/WiFi-agnostic).

Parity: pkg/subscriber — Manager (manager.go:36) with CreateSession
(:106), Authenticate (:179), AssignAddress (:296), walled-garden set/clear
(:389-456), TerminateSession (:457); Session + states + events
(types.go:42-237). Pluggable Authenticator + AddressAllocator, event
emission, idle cleanup tick.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Callable


class SessionState(str, Enum):
    CREATED = "created"
    AUTHENTICATING = "authenticating"
    AUTHENTICATED = "authenticated"
    ADDRESS_ASSIGNED = "address_assigned"
    ACTIVE = "active"
    WALLED_GARDEN = "walled_garden"
    TERMINATING = "terminating"
    TERMINATED = "terminated"


class SessionKind(str, Enum):
    IPOE = "ipoe"
    PPPOE = "pppoe"
    WIFI = "wifi"


@dataclass
class Session:
    id: str
    kind: SessionKind
    mac: str = ""
    circuit_id: str = ""
    username: str = ""
    state: SessionState = SessionState.CREATED
    ip: str = ""
    subscriber_id: str = ""
    created_at: float = 0.0
    last_activity: float = 0.0
    walled: bool = False
    attributes: dict = field(default_factory=dict)


@dataclass
class SessionEvent:
    session_id: str
    event: str
    at: float
    detail: dict = field(default_factory=dict)


class SubscriberManager:
    def __init__(
        self,
        authenticator: Callable[[Session], dict | None] | None = None,
        allocator=None,  # .allocate(sid)/.release(sid)
        walled_garden=None,  # .add(session)/.remove(session)
        event_sink: Callable[[SessionEvent], None] | None = None,
        idle_timeout_s: float = 3600,
        clock=time.time,
    ):
        self.authenticator = authenticator
        self.allocator = allocator
        self.walled_garden = walled_garden
        self.event_sink = event_sink
        self.idle_timeout_s = idle_timeout_s
        self.clock = clock
        self.sessions: dict[str, Session] = {}
        self._by_mac: dict[str, str] = {}
        self._seq = 0

    def _emit(self, session: Session, event: str, **detail) -> None:
        if self.event_sink:
            self.event_sink(SessionEvent(session.id, event, self.clock(), detail))

    # -- lifecycle (manager.go:106-486) --
    def create_session(self, kind: SessionKind, mac: str = "", circuit_id: str = "",
                       username: str = "") -> Session:
        now = self.clock()
        self._seq += 1
        s = Session(id=f"{kind.value}-{int(now):x}-{self._seq:06x}", kind=kind,
                    mac=mac.lower(), circuit_id=circuit_id, username=username,
                    created_at=now, last_activity=now)
        self.sessions[s.id] = s
        if mac:
            self._by_mac[s.mac] = s.id
        self._emit(s, "created")
        return s

    def authenticate(self, session_id: str) -> bool:
        s = self._get(session_id)
        s.state = SessionState.AUTHENTICATING
        profile = self.authenticator(s) if self.authenticator else {}
        if profile is None:
            # auth failed -> walled garden, not termination (manager.go:389)
            self.set_walled_garden(session_id)
            self._emit(s, "auth_failed")
            return False
        s.attributes.update(profile or {})
        s.subscriber_id = (profile or {}).get("subscriber_id", s.mac or s.username)
        s.state = SessionState.AUTHENTICATED
        self._emit(s, "authenticated")
        return True

    def assign_address(self, session_id: str) -> str | None:
        s = self._get(session_id)
        if self.allocator is None:
            return None
        ip = self.allocator.allocate(s.subscriber_id or s.mac)
        if ip is None:
            self._emit(s, "address_exhausted")
            return None
        s.ip = ip
        s.state = SessionState.ADDRESS_ASSIGNED
        self._emit(s, "address_assigned", ip=ip)
        return ip

    def activate(self, session_id: str) -> None:
        s = self._get(session_id)
        if s.walled:
            self.clear_walled_garden(session_id)
        s.state = SessionState.ACTIVE
        self._emit(s, "active")

    def set_walled_garden(self, session_id: str) -> None:
        s = self._get(session_id)
        s.walled = True
        s.state = SessionState.WALLED_GARDEN
        if self.walled_garden is not None:
            self.walled_garden.add(s)
        self._emit(s, "walled_garden")

    def clear_walled_garden(self, session_id: str) -> None:
        s = self._get(session_id)
        s.walled = False
        if self.walled_garden is not None:
            self.walled_garden.remove(s)
        self._emit(s, "walled_garden_cleared")

    def touch(self, session_id: str) -> None:
        s = self.sessions.get(session_id)
        if s:
            s.last_activity = self.clock()

    def terminate(self, session_id: str, reason: str = "user") -> bool:
        s = self.sessions.get(session_id)
        if s is None:
            return False
        s.state = SessionState.TERMINATING
        if s.walled and self.walled_garden is not None:
            self.walled_garden.remove(s)
        if s.ip and self.allocator is not None:
            self.allocator.release(s.subscriber_id or s.mac)
        s.state = SessionState.TERMINATED
        self._emit(s, "terminated", reason=reason)
        del self.sessions[s.id]
        self._by_mac.pop(s.mac, None)
        return True

    # -- queries --
    def by_mac(self, mac: str) -> Session | None:
        sid = self._by_mac.get(mac.lower())
        return self.sessions.get(sid) if sid else None

    def _get(self, session_id: str) -> Session:
        s = self.sessions.get(session_id)
        if s is None:
            raise KeyError(f"no session {session_id}")
        return s

    # -- idle sweep (manager.go idle cleanup) --
    def cleanup_idle(self, now: float | None = None) -> int:
        now = now if now is not None else self.clock()
        dead = [sid for sid, s in self.sessions.items()
                if now - s.last_activity > self.idle_timeout_s]
        for sid in dead:
            self.terminate(sid, reason="idle_timeout")
        return len(dead)
