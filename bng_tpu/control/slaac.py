"""SLAAC Router Advertisement daemon (radvd role).

Parity: pkg/slaac/radvd.go — Server (:49), buildRA (:315-378),
prefix/RDNSS/DNSSL options (:380-457); types.go EUI-64 (:124-148) and
stable-privacy address generation (:150).

Tick-driven: tick(now) emits periodic RAs; handle_rs() answers router
solicitations. Frames are full Ethernet+IPv6+ICMPv6 with checksum, ready
for the engine's TX path.
"""

from __future__ import annotations

import hashlib
import struct
from dataclasses import dataclass, field

# ICMPv6 types
ICMP6_RS = 133
ICMP6_RA = 134

# NDP option types
NDP_OPT_SRC_LLADDR = 1
NDP_OPT_PREFIX_INFO = 3
NDP_OPT_MTU = 5
NDP_OPT_RDNSS = 25
NDP_OPT_DNSSL = 31

ALL_NODES_MAC = bytes.fromhex("333300000001")
ALL_NODES_IP6 = bytes.fromhex("ff020000000000000000000000000001")


def eui64_iid(mac: bytes) -> bytes:
    """EUI-64 interface identifier (parity: types.go:124-148)."""
    return bytes([mac[0] ^ 0x02]) + mac[1:3] + b"\xff\xfe" + mac[3:6]


def eui64_address(prefix: bytes, mac: bytes) -> bytes:
    """prefix(8B used) + EUI-64 iid."""
    return prefix[:8] + eui64_iid(mac)


def stable_privacy_iid(prefix: bytes, mac: bytes, secret: bytes,
                       dad_counter: int = 0) -> bytes:
    """RFC 7217 stable-privacy IID (parity: types.go:150)."""
    h = hashlib.sha256(prefix[:8] + mac + struct.pack(">I", dad_counter) + secret).digest()
    iid = bytearray(h[:8])
    iid[0] &= ~0x02  # clear universal/local bit
    return bytes(iid)


def link_local(mac: bytes) -> bytes:
    return bytes.fromhex("fe80000000000000") + eui64_iid(mac)


def _icmp6_checksum(src: bytes, dst: bytes, payload: bytes) -> int:
    """ICMPv6 checksum over the IPv6 pseudo-header (RFC 8200 §8.1)."""
    pseudo = src + dst + struct.pack(">I", len(payload)) + b"\x00\x00\x00\x3a"
    data = pseudo + payload
    if len(data) & 1:
        data += b"\x00"
    s = sum(struct.unpack(f">{len(data) // 2}H", data))
    while s >> 16:
        s = (s & 0xFFFF) + (s >> 16)
    return (~s) & 0xFFFF


@dataclass
class PrefixConfig:
    """One advertised prefix (parity: radvd.go Prefix config)."""

    prefix: bytes  # 16 bytes
    prefix_len: int = 64
    on_link: bool = True
    autonomous: bool = True  # A flag: SLAAC allowed
    valid_lifetime: int = 86400
    preferred_lifetime: int = 14400


@dataclass
class SLAACConfig:
    server_mac: bytes = b"\x02\xbb\x00\x00\x00\x01"
    prefixes: list[PrefixConfig] = field(default_factory=list)
    managed: bool = False  # M flag: addresses via DHCPv6
    other_config: bool = False  # O flag: other config via DHCPv6
    router_lifetime: int = 1800
    reachable_time_ms: int = 0
    retrans_timer_ms: int = 0
    cur_hop_limit: int = 64
    mtu: int = 0  # 0 = don't advertise
    rdnss: list[bytes] = field(default_factory=list)  # 16B each
    rdnss_lifetime: int = 3600
    dnssl: list[str] = field(default_factory=list)
    interval_s: float = 200.0  # MaxRtrAdvInterval default range


@dataclass
class SLAACStats:
    ra_sent: int = 0
    rs_received: int = 0
    periodic: int = 0


class SLAACServer:
    def __init__(self, config: SLAACConfig):
        self.config = config
        self.stats = SLAACStats()
        self._last_ra = 0.0
        self.ll_addr = link_local(config.server_mac)

    # ---- option builders (parity: radvd.go:380-457) ----
    def _prefix_option(self, p: PrefixConfig) -> bytes:
        flags = (0x80 if p.on_link else 0) | (0x40 if p.autonomous else 0)
        return struct.pack(">BBBBIII", NDP_OPT_PREFIX_INFO, 4, p.prefix_len,
                           flags, p.valid_lifetime, p.preferred_lifetime,
                           0) + p.prefix

    def _rdnss_option(self) -> bytes:
        n = len(self.config.rdnss)
        length = 1 + 2 * n
        return struct.pack(">BBHI", NDP_OPT_RDNSS, length, 0,
                           self.config.rdnss_lifetime) + b"".join(self.config.rdnss)

    def _dnssl_option(self) -> bytes:
        out = bytearray()
        for d in self.config.dnssl:
            for label in d.rstrip(".").split("."):
                out += bytes([len(label)]) + label.encode()
            out += b"\x00"
        pad = (-len(out)) % 8
        out += b"\x00" * pad
        # RFC 6106 §5.2: length in 8-octet units incl. the 8-byte header
        length = 1 + len(out) // 8
        return struct.pack(">BBHI", NDP_OPT_DNSSL, length, 0,
                           self.config.rdnss_lifetime) + bytes(out)

    def build_ra(self) -> bytes:
        """ICMPv6 RA payload (parity: buildRA radvd.go:315-378)."""
        c = self.config
        flags = (0x80 if c.managed else 0) | (0x40 if c.other_config else 0)
        body = struct.pack(">BBHBBHII", ICMP6_RA, 0, 0, c.cur_hop_limit,
                           flags, c.router_lifetime,
                           c.reachable_time_ms, c.retrans_timer_ms)
        # source link-layer address option
        body += struct.pack(">BB", NDP_OPT_SRC_LLADDR, 1) + c.server_mac
        if c.mtu:
            body += struct.pack(">BBHI", NDP_OPT_MTU, 1, 0, c.mtu)
        for p in c.prefixes:
            body += self._prefix_option(p)
        if c.rdnss:
            body += self._rdnss_option()
        if c.dnssl:
            body += self._dnssl_option()
        return body

    def build_ra_frame(self, dst_mac: bytes = ALL_NODES_MAC,
                       dst_ip: bytes = ALL_NODES_IP6) -> bytes:
        """Full Ethernet+IPv6+ICMPv6 RA frame with checksum."""
        payload = bytearray(self.build_ra())
        csum = _icmp6_checksum(self.ll_addr, dst_ip, bytes(payload))
        payload[2:4] = struct.pack(">H", csum)
        ip6 = struct.pack(">IHBB", 0x60000000, len(payload), 58, 255)
        ip6 += self.ll_addr + dst_ip
        eth = dst_mac + self.config.server_mac + b"\x86\xdd"
        return eth + ip6 + bytes(payload)

    # ---- RS handling + periodic ticks ----
    def handle_rs(self, src_mac: bytes, src_ip: bytes) -> bytes:
        """Solicited RA: unicast if the client has a source address
        (parity: radvd.go solicited path)."""
        self.stats.rs_received += 1
        self.stats.ra_sent += 1
        unspecified = src_ip == b"\x00" * 16
        if unspecified:
            return self.build_ra_frame()
        return self.build_ra_frame(dst_mac=src_mac, dst_ip=src_ip)

    def handle_frame(self, frame: bytes) -> bytes | None:
        """Engine PASS-lane entry: answer RS frames."""
        if len(frame) < 54 + 4 or frame[12:14] != b"\x86\xdd":
            return None
        if frame[20] != 58:  # next header ICMPv6
            return None
        icmp_off = 54
        if frame[icmp_off] != ICMP6_RS:
            return None
        return self.handle_rs(frame[6:12], frame[22:38])

    def tick(self, now: float) -> list[bytes]:
        # first tick always advertises (radvd sends initial RAs on start)
        if self._last_ra == 0.0 or now - self._last_ra >= self.config.interval_s:
            self._last_ra = now
            self.stats.ra_sent += 1
            self.stats.periodic += 1
            return [self.build_ra_frame()]
        return []
