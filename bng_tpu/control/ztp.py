"""Zero-touch provisioning: Nexus discovery + bootstrap registration.

Parity: pkg/ztp — DHCP-based Nexus discovery via Option 224 (simple
string) then Option 43 Type-1 vendor TLV (client.go:50-143),
BootstrapClient.Bootstrap / registerAndWait poll loop with exponential
backoff and pending->configured states (bootstrap.go:103-338), serial/MAC/
model detection from DMI //sys (bootstrap.go:340-448), TLS cert pinning
(tls.go:20-527, fingerprint pinning here via deviceauth.cert_fingerprint).

Transport is a pluggable callable (so tests run hermetically); the real
one POSTs JSON to https://nexus/api/v1/bootstrap.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from bng_tpu.control.deviceauth import DeviceIdentity, read_device_identity
from bng_tpu.utils.structlog import ErrorLog

OPTION_NEXUS_URL = 224  # private-use simple string
OPTION_VENDOR = 43  # vendor TLV; sub-type 1 = Nexus URL


def extract_nexus_url(options: dict[int, bytes]) -> str:
    """client.go:101-117: Option 224 first, then 43/Type-1."""
    raw = options.get(OPTION_NEXUS_URL)
    if raw:
        return raw.decode(errors="replace")
    vendor = options.get(OPTION_VENDOR)
    if vendor:
        return parse_vendor_options(vendor)
    return ""


def parse_vendor_options(data: bytes) -> str:
    """client.go:122-141: TLV walk; sub-type 1 carries the URL."""
    i = 0
    while i + 2 <= len(data):
        sub_type, sub_len = data[i], data[i + 1]
        i += 2
        if i + sub_len > len(data):
            break
        if sub_type == 1:
            return data[i:i + sub_len].decode(errors="replace")
        i += sub_len
    return ""


def build_vendor_option(nexus_url: str) -> bytes:
    """Server-side helper: encode the Option 43 TLV the probe parses."""
    url = nexus_url.encode()
    return bytes([1, len(url)]) + url


@dataclass
class ZTPResult:
    """client.go Result: the lease + discovered URL."""

    ip: str = ""
    mask: str = ""
    gateway: str = ""
    dns: list[str] = field(default_factory=list)
    lease_time: int = 0
    nexus_url: str = ""


def discover_from_lease(ip: str = "", mask: str = "", gateway: str = "",
                        dns: list[str] | None = None, lease_time: int = 0,
                        options: dict[int, bytes] | None = None) -> ZTPResult:
    """Assemble a discovery result from a decoded DHCP ACK
    (client.go:50-99; the wire exchange itself runs through
    bng_tpu.control.dhcp_codec in the composition root)."""
    return ZTPResult(ip=ip, mask=mask, gateway=gateway, dns=list(dns or []),
                     lease_time=lease_time,
                     nexus_url=extract_nexus_url(options or {}))


@dataclass
class BootstrapConfig:
    """bootstrap.go:23-48."""

    nexus_url: str = ""
    initial_backoff: float = 1.0
    max_backoff: float = 60.0
    max_retries: int = 0  # 0 = wait forever
    poll_interval: float = 5.0
    pin_fingerprint: str = ""  # expected server cert SHA-256 (tls.go pinning)
    tls: "object | None" = None  # ztp_tls.TLSConfig (None = plaintext/dev)


def make_https_transport(config: BootstrapConfig):
    """Pinning-enforcing HTTPS transport for BootstrapClient (the
    bootstrap.go:449-464 POST through tls.go's BuildTLSConfig channel).

    Uses config.tls (a ztp_tls.TLSConfig) when set; a bare
    pin_fingerprint becomes the classic TOFU bootstrap config
    (self-signed Nexus, no CA yet, SHA-256 pin mandatory)."""
    import json as _json

    from bng_tpu.control import ztp_tls

    tls_cfg = config.tls
    if tls_cfg is None:
        if not config.pin_fingerprint:
            raise ValueError("https transport needs tls config or a pin")
        tls_cfg = ztp_tls.TLSConfig(require_valid_chain=False,
                                    pinned_certs=[config.pin_fingerprint])

    def transport(req: BootstrapRequest) -> dict:
        body = _json.dumps({"serial": req.serial, "mac": req.mac,
                            "model": req.model,
                            "firmware": req.firmware}).encode()
        status, parsed, _warnings = ztp_tls.https_get_json(
            config.nexus_url.rstrip("/") + "/api/v1/bootstrap/register",
            tls_cfg, method="POST", body=body,
            headers={"Content-Type": "application/json"})
        # anything but 200/201 is an error (bootstrap.go:327): a 403
        # "unknown serial" must surface, not masquerade as pending
        if status not in (200, 201) or parsed is None:
            detail = ""
            if isinstance(parsed, dict):
                detail = f": {parsed.get('error') or parsed.get('message', '')}"
            raise ConnectionError(f"nexus bootstrap HTTP {status}{detail}")
        return parsed

    return transport


@dataclass
class BootstrapRequest:
    serial: str
    mac: str
    model: str = ""
    firmware: str = ""


@dataclass
class DeviceConfig:
    """bootstrap.go:92-101: what an approved device receives."""

    node_id: str = ""
    site_id: str = ""
    role: str = ""
    partner: dict = field(default_factory=dict)
    pools: list[dict] = field(default_factory=list)
    cluster: dict = field(default_factory=dict)
    timestamp: float = 0.0


class BootstrapPending(Exception):
    def __init__(self, retry_after: float = 0.0, message: str = ""):
        super().__init__(message or "registration pending approval")
        self.retry_after = retry_after


class BootstrapClient:
    """Registration poll loop (bootstrap.go:103-338).

    transport: Callable[[BootstrapRequest], dict] posting to Nexus and
    returning the decoded response. Expected keys: status
    ("configured"|"pending"), node_id, site_id, role, partner, pools,
    cluster, retry_after. Raises on network failure.
    """

    def __init__(self, config: BootstrapConfig, transport,
                 identity: DeviceIdentity | None = None,
                 sys_root: str = "/", clock=time.time, sleep=time.sleep):
        self.config = config
        self._transport = transport
        self._clock = clock
        self._sleep = sleep
        self.identity = identity or read_device_identity(sys_root)
        self.attempts = 0
        self._bootstrap_err_log = ErrorLog(
            "ztp", "bootstrap attempt failed; backing off")

    def detect_system_info(self) -> BootstrapRequest:
        """bootstrap.go:181-217."""
        ident = self.identity
        return BootstrapRequest(serial=ident.serial, mac=ident.mac,
                                model=ident.model, firmware=ident.firmware)

    def register_once(self) -> DeviceConfig:
        """One registration attempt (bootstrap.go:449-464)."""
        resp = self._transport(self.detect_system_info())
        self.attempts += 1
        if resp.get("status") == "configured":
            return DeviceConfig(
                node_id=resp.get("node_id", ""), site_id=resp.get("site_id", ""),
                role=resp.get("role", ""), partner=resp.get("partner", {}),
                pools=resp.get("pools", []), cluster=resp.get("cluster", {}),
                timestamp=self._clock())
        raise BootstrapPending(retry_after=float(resp.get("retry_after", 0)),
                               message=resp.get("message", ""))

    def bootstrap(self, deadline: float | None = None) -> DeviceConfig:
        """Register and wait for approval (bootstrap.go:155-338):
        network errors retry with exponential backoff; 'pending' retries
        after the server-suggested delay; backoff resets after any
        successful exchange."""
        backoff = self.config.initial_backoff
        retries = 0
        while True:
            if deadline is not None and self._clock() >= deadline:
                raise TimeoutError("bootstrap deadline exceeded")
            try:
                return self.register_once()
            except BootstrapPending as pending:
                retries += 1
                if self.config.max_retries and retries >= self.config.max_retries:
                    raise TimeoutError(
                        f"max retries ({self.config.max_retries}) exceeded "
                        "waiting for configuration") from pending
                self._sleep(pending.retry_after or backoff)
                backoff = self.config.initial_backoff  # reset after contact
            except TimeoutError:
                raise
            except Exception as e:
                # transient bootstrap failure: visible per retry (ZTP
                # hangs are diagnosed from exactly these lines), then
                # backed off and retried
                self._bootstrap_err_log.report(e, backoff_s=backoff)
                self._sleep(backoff)
                backoff = min(backoff * 2, self.config.max_backoff)
