"""DNS forwarder/interceptor for the walled garden.

Parity: pkg/dns — Resolver.Resolve pipeline rate-limit -> intercept ->
walled-garden -> cache -> forward -> DNS64 (resolver.go:116-210), rule
matching with exact/suffix/wildcard (:468-491), redirect/NXDOMAIN/CNAME
responses (:493-531), walled-garden client redirect (:533-554), DNS64
AAAA synthesis from A (:556-596), LRU cache with TTL clamps + negative
cache (cache.go:10-199), per-client-IP token-bucket rate limiting
(resolver.go:623-708), stats (types.go:134-171).

The upstream forwarder is pluggable (a callable), so the resolver is
fully testable without a network — the same inversion the reference gets
from its stub platform pattern (SURVEY.md §4.6).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from enum import Enum

from bng_tpu.utils.structlog import ErrorLog

# DNS constants (types.go:173-221)
TYPE_A = 1
TYPE_NS = 2
TYPE_CNAME = 5
TYPE_AAAA = 28
TYPE_PTR = 12
TYPE_MX = 15
TYPE_TXT = 16
TYPE_SRV = 33
CLASS_IN = 1

RCODE_SUCCESS = 0
RCODE_FORMAT_ERROR = 1
RCODE_SERVER_FAILURE = 2
RCODE_NAME_ERROR = 3  # NXDOMAIN
RCODE_REFUSED = 5

_TYPE_NAMES = {TYPE_A: "A", TYPE_AAAA: "AAAA", TYPE_CNAME: "CNAME",
               TYPE_PTR: "PTR", TYPE_MX: "MX", TYPE_TXT: "TXT"}


def type_string(t: int) -> str:
    return _TYPE_NAMES.get(t, f"TYPE{t}")


class InterceptAction(str, Enum):
    ALLOW = "allow"
    BLOCK = "block"  # NXDOMAIN
    REDIRECT = "redirect"  # answer with a configured IP
    CNAME = "cname"


@dataclass
class Query:
    name: str
    qtype: int = TYPE_A
    qclass: int = CLASS_IN
    source: str = ""  # client IP


@dataclass
class Record:
    name: str
    rtype: int
    rclass: int = CLASS_IN
    ttl: int = 0
    ipv4: str = ""
    ipv6: str = ""
    target: str = ""  # CNAME/NS/PTR target
    # verbatim rdata for other types (TXT, MX, SRV, ...): the wire codec
    # stores a decompressed copy so non-address records survive the
    # forward path instead of being silently dropped
    rdata: bytes = b""


@dataclass
class Response:
    query: Query
    answers: list[Record] = field(default_factory=list)
    rcode: int = RCODE_SUCCESS
    cached: bool = False


@dataclass
class InterceptRule:
    """types.go:223-249."""

    domain: str = ""
    domain_suffix: str = ""
    exact: bool = False
    action: InterceptAction = InterceptAction.ALLOW
    redirect_ip: str = ""
    cname: str = ""


@dataclass
class DNSConfig:
    """types.go:9-79 defaults."""

    upstreams: list[str] = field(default_factory=lambda: ["8.8.8.8:53", "1.1.1.1:53"])
    timeout: float = 5.0
    cache_size: int = 10_000
    min_ttl: int = 60
    max_ttl: int = 86_400
    negative_ttl: int = 300
    dns64_enabled: bool = False
    dns64_prefix: str = "64:ff9b::"  # RFC 6052 well-known /96
    walled_garden_redirect_ip: str = "10.255.255.1"
    rate_limit_qps: int = 100
    rate_limit_burst: int = 200


def cache_key(name: str, qtype: int, qclass: int) -> str:
    """cache.go:196-199."""
    return f"{name.lower().rstrip('.')}/{qtype}/{qclass}"


class DNSCache:
    """LRU cache with TTL clamping + negative cache (cache.go:10-199)."""

    def __init__(self, max_size: int, min_ttl: int, max_ttl: int,
                 negative_ttl: int, clock=time.time):
        self.max_size = max_size
        self.min_ttl = min_ttl
        self.max_ttl = max_ttl
        self.negative_ttl = negative_ttl
        self._clock = clock
        self._lock = threading.Lock()
        self._items: OrderedDict[str, tuple[float, Response | None]] = OrderedDict()
        self._hits = 0
        self._misses = 0
        self._evictions = 0

    def get(self, key: str) -> tuple[Response | None, bool]:
        """Returns (response, found). A found None response = negative hit."""
        now = self._clock()
        with self._lock:
            item = self._items.get(key)
            if item is None:
                self._misses += 1
                return None, False
            expires, resp = item
            if now >= expires:
                del self._items[key]
                self._misses += 1
                return None, False
            self._items.move_to_end(key)
            self._hits += 1
            return resp, True

    def set(self, key: str, response: Response) -> None:
        ttl = min(r.ttl for r in response.answers) if response.answers else 0
        ttl = max(self.min_ttl, min(self.max_ttl, ttl))
        self._put(key, self._clock() + ttl, response)

    def set_negative(self, key: str) -> None:
        self._put(key, self._clock() + self.negative_ttl, None)

    def _put(self, key: str, expires: float, resp: Response | None) -> None:
        with self._lock:
            if key in self._items:
                self._items.move_to_end(key)
            self._items[key] = (expires, resp)
            while len(self._items) > self.max_size:
                self._items.popitem(last=False)
                self._evictions += 1

    def delete(self, key: str) -> None:
        with self._lock:
            self._items.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._items.clear()

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    def cleanup(self) -> int:
        now = self._clock()
        with self._lock:
            dead = [k for k, (exp, _) in self._items.items() if now >= exp]
            for k in dead:
                del self._items[k]
            return len(dead)

    def stats(self) -> dict:
        with self._lock:
            total = self._hits + self._misses
            return {"size": len(self._items), "hits": self._hits,
                    "misses": self._misses, "evictions": self._evictions,
                    "hit_rate": self._hits / total if total else 0.0}


class _RateBucket:
    __slots__ = ("tokens", "last")

    def __init__(self, tokens: float, last: float):
        self.tokens = tokens
        self.last = last


class Resolver:
    """The resolve pipeline (resolver.go:116-210)."""

    def __init__(self, config: DNSConfig | None = None, forwarder=None,
                 clock=time.time):
        """forwarder: Callable[[Query], Response] hitting the upstreams."""
        self.config = config or DNSConfig()
        self._forward = forwarder
        self._clock = clock
        self._lock = threading.Lock()
        self.cache = DNSCache(self.config.cache_size, self.config.min_ttl,
                              self.config.max_ttl, self.config.negative_ttl,
                              clock=clock)
        self._rules: list[InterceptRule] = []
        self._walled_clients: set[str] = set()
        self._buckets: dict[str, _RateBucket] = {}
        self._stats = {"queries": 0, "cache_hits": 0, "intercepted": 0,
                       "walled_garden_redirects": 0, "forwarded": 0,
                       "rate_limited": 0, "dns64_synthesized": 0,
                       "errors": 0, "dns64_errors": 0}
        self._dns64_err_log = ErrorLog(
            "dns", "DNS64 synthesis failed; empty AAAA passed through")

    # -- config surface -------------------------------------------------

    def add_intercept_rule(self, rule: InterceptRule) -> None:
        with self._lock:
            self._rules.append(rule)

    def remove_intercept_rule(self, domain: str) -> bool:
        with self._lock:
            before = len(self._rules)
            self._rules = [r for r in self._rules
                           if r.domain != domain and r.domain_suffix != domain]
            return len(self._rules) != before

    def add_walled_garden_client(self, ip: str) -> None:
        with self._lock:
            self._walled_clients.add(ip)

    def remove_walled_garden_client(self, ip: str) -> bool:
        with self._lock:
            had = ip in self._walled_clients
            self._walled_clients.discard(ip)
            return had

    def is_in_walled_garden(self, ip: str) -> bool:
        with self._lock:
            return ip in self._walled_clients

    # -- the pipeline ---------------------------------------------------

    def resolve(self, query: Query) -> Response:
        with self._lock:
            self._stats["queries"] += 1

        # 1. rate limit (resolver.go:131-137)
        if query.source and not self._check_rate_limit(query.source):
            with self._lock:
                self._stats["rate_limited"] += 1
            return Response(query=query, rcode=RCODE_REFUSED)

        # 2. interception rules (resolver.go:140-147)
        action, resp = self._check_intercept(query)
        if action != InterceptAction.ALLOW:
            with self._lock:
                self._stats["intercepted"] += 1
            return resp

        # 3. walled garden clients get the portal for everything
        #    (resolver.go:150-157)
        if query.source and self.is_in_walled_garden(query.source):
            with self._lock:
                self._stats["walled_garden_redirects"] += 1
            return self._walled_garden_answer(query)

        # 4. cache (resolver.go:160-170)
        key = cache_key(query.name, query.qtype, query.qclass)
        cached, found = self.cache.get(key)
        if found:
            with self._lock:
                self._stats["cache_hits"] += 1
            if cached is None:  # negative hit
                return Response(query=query, rcode=RCODE_NAME_ERROR, cached=True)
            return Response(query=query, answers=cached.answers,
                            rcode=cached.rcode, cached=True)

        # 5. forward (resolver.go:173-186)
        if self._forward is None:
            with self._lock:
                self._stats["errors"] += 1
            return Response(query=query, rcode=RCODE_SERVER_FAILURE)
        try:
            resp = self._forward(query)
        except Exception:
            with self._lock:
                self._stats["errors"] += 1
            return Response(query=query, rcode=RCODE_SERVER_FAILURE)
        with self._lock:
            self._stats["forwarded"] += 1

        # 6. DNS64: empty AAAA answer -> synthesize from A (resolver.go:189-199)
        if (self.config.dns64_enabled and query.qtype == TYPE_AAAA
                and not resp.answers and resp.rcode == RCODE_SUCCESS):
            try:
                synth = self._apply_dns64(query)
            except Exception as e:
                # a broken upstream A answer must not kill the resolve,
                # but silent DNS64 breakage hides v6-only outage (BNG021)
                synth = None
                self._stats["dns64_errors"] += 1
                self._dns64_err_log.report(e, qname=query.name)
            if synth is not None:
                resp = synth

        # cache positive + negative outcomes (resolver.go:202-207)
        if resp.answers:
            self.cache.set(key, resp)
        elif resp.rcode == RCODE_NAME_ERROR:
            self.cache.set_negative(key)
        return resp

    # -- pieces ---------------------------------------------------------

    def _check_intercept(self, query: Query):
        with self._lock:
            rules = list(self._rules)
        for rule in rules:
            if not _match_rule(rule, query.name):
                continue
            if rule.action == InterceptAction.BLOCK:
                return rule.action, Response(query=query, rcode=RCODE_NAME_ERROR)
            if rule.action == InterceptAction.REDIRECT:
                return rule.action, _redirect_response(query, rule.redirect_ip)
            if rule.action == InterceptAction.CNAME:
                rec = Record(name=query.name, rtype=TYPE_CNAME,
                             rclass=query.qclass, ttl=300, target=rule.cname)
                return rule.action, Response(query=query, answers=[rec])
        return InterceptAction.ALLOW, None

    def _walled_garden_answer(self, query: Query) -> Response:
        if query.qtype in (TYPE_A, TYPE_AAAA):
            return _redirect_response(query, self.config.walled_garden_redirect_ip)
        return Response(query=query, rcode=RCODE_NAME_ERROR)

    def _apply_dns64(self, query: Query) -> Response | None:
        a_resp = self._forward(Query(name=query.name, qtype=TYPE_A,
                                     qclass=query.qclass, source=query.source))
        if not a_resp.answers:
            return None
        out = Response(query=query)
        for ans in a_resp.answers:
            if ans.rtype != TYPE_A or not ans.ipv4:
                continue
            out.answers.append(Record(
                name=ans.name, rtype=TYPE_AAAA, rclass=ans.rclass, ttl=ans.ttl,
                ipv6=dns64_synthesize(self.config.dns64_prefix, ans.ipv4)))
        if out.answers:
            with self._lock:
                self._stats["dns64_synthesized"] += len(out.answers)
            return out
        return None

    def _check_rate_limit(self, ip: str) -> bool:
        """Token bucket per client IP (resolver.go:623-643)."""
        now = self._clock()
        qps, burst = self.config.rate_limit_qps, self.config.rate_limit_burst
        if qps <= 0:
            return True
        with self._lock:
            b = self._buckets.get(ip)
            if b is None:
                self._buckets[ip] = _RateBucket(burst - 1.0, now)
                return True
            b.tokens = min(burst, b.tokens + (now - b.last) * qps)
            b.last = now
            if b.tokens >= 1.0:
                b.tokens -= 1.0
                return True
            return False

    def cleanup_rate_limiter(self, idle: float = 300.0) -> int:
        now = self._clock()
        with self._lock:
            dead = [ip for ip, b in self._buckets.items() if now - b.last > idle]
            for ip in dead:
                del self._buckets[ip]
            return len(dead)

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats, cache=self.cache.stats())


def _match_rule(rule: InterceptRule, domain: str) -> bool:
    """resolver.go:468-491: exact / suffix / domain+subdomain wildcard."""
    d = domain.lower().rstrip(".")
    if rule.exact:
        return d == rule.domain.lower().rstrip(".")
    if rule.domain_suffix:
        return d.endswith(rule.domain_suffix.lower().rstrip("."))
    if rule.domain:
        base = rule.domain.lower().rstrip(".")
        return d == base or d.endswith("." + base)
    return False


def _redirect_response(query: Query, ip: str) -> Response:
    rec = Record(name=query.name, rtype=query.qtype, rclass=query.qclass, ttl=300)
    if query.qtype == TYPE_A:
        rec.ipv4 = ip
    elif query.qtype == TYPE_AAAA:
        rec.ipv6 = ip if ":" in ip else dns64_synthesize("64:ff9b::", ip)
    return Response(query=query, answers=[rec])


def dns64_synthesize(prefix: str, ipv4: str) -> str:
    """RFC 6052 /96 synthesis: prefix::a.b.c.d embedded in the low 32 bits."""
    a, b, c, d = (int(x) for x in ipv4.split("."))
    base = prefix.rstrip(":") + "::"
    return f"{base}{(a << 8) | b:x}:{(c << 8) | d:x}"
