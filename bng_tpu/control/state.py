"""In-memory indexed state store — the embedded single-node alternative to
Nexus.

Parity: pkg/state/store.go — Store (:15) with subscriber/lease/pool/
session/NAT-binding records, by-MAC/by-IP/by-NTE indexes (:148-856),
FindPoolForSubscriber class matching (:356), pool name lookups (:330),
lease renew (:547), session activity accounting (:705), NAT-binding
endpoint lookups incl. by-public (the LEA-query shape, :803-833), list/
update CRUD, store stats (:129), and TTL cleanup — both explicit sweeps
and the background loops behind start()/stop() (:100-127, :858-1024).
Types: pkg/state/types.go:9-330.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field


def _locked(fn):
    """Store methods run under one re-entrant lock — the reference store
    is mutex-guarded throughout (store.go uses sync.RWMutex), and the
    background sweep thread would otherwise race foreground CRUD
    (dict-changed-during-iteration kills the sweeper silently)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **k):
        with self._lock:
            return fn(self, *a, **k)

    return wrapper


@dataclass
class Subscriber:
    id: str
    mac: str = ""
    circuit_id: str = ""
    nte_id: str = ""
    client_class: int = 0
    isp_id: str = ""
    enabled: bool = True
    meta: dict = field(default_factory=dict)


@dataclass
class LeaseRecord:
    ip: str
    subscriber_id: str
    mac: str
    expires_at: float
    pool_id: str = ""


@dataclass
class PoolRecord:
    id: str
    cidr: str
    name: str = ""
    client_class: int = 0
    isp_id: str = ""
    enabled: bool = True


@dataclass
class SessionRecord:
    id: str
    subscriber_id: str
    ip: str = ""
    mac: str = ""
    started_at: float = 0.0
    last_seen: float = 0.0
    kind: str = "ipoe"  # ipoe | pppoe | wifi
    state: str = "active"
    bytes_in: int = 0
    bytes_out: int = 0


@dataclass
class NATBinding:
    private_ip: str
    public_ip: str
    port_start: int
    port_end: int
    subscriber_id: str = ""


class Store:
    def __init__(self, clock=time.time, lease_sweep_interval: float = 60.0,
                 session_idle_s: float = 3600.0):
        self.clock = clock
        self.lease_sweep_interval = lease_sweep_interval
        self.session_idle_s = session_idle_s
        self.subscribers: dict[str, Subscriber] = {}
        self.leases: dict[str, LeaseRecord] = {}  # by ip
        self.pools: dict[str, PoolRecord] = {}
        self.sessions: dict[str, SessionRecord] = {}
        self.nat_bindings: dict[str, NATBinding] = {}  # by private ip
        # indexes
        self._sub_by_mac: dict[str, str] = {}
        self._sub_by_cid: dict[str, str] = {}
        self._sub_by_nte: dict[str, set[str]] = {}
        self._sess_by_sub: dict[str, set[str]] = {}
        self._sess_by_mac: dict[str, str] = {}
        self._sess_by_ip: dict[str, str] = {}
        self._lease_by_mac: dict[str, str] = {}
        self._pool_by_name: dict[str, str] = {}
        # public ip -> sorted [(port_start, port_end, private_ip)] blocks
        self._nat_by_public: dict[str, list] = {}
        self._counters = {"leases_expired": 0, "sessions_reaped": 0}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- subscribers --
    @_locked
    def put_subscriber(self, s: Subscriber) -> None:
        old = self.subscribers.get(s.id)
        if old:
            self._unindex_subscriber(old)
        self.subscribers[s.id] = s
        if s.mac:
            self._sub_by_mac[s.mac.lower()] = s.id
        if s.circuit_id:
            self._sub_by_cid[s.circuit_id] = s.id
        if s.nte_id:
            self._sub_by_nte.setdefault(s.nte_id, set()).add(s.id)

    @_locked
    def get_subscriber(self, sub_id: str) -> Subscriber | None:
        return self.subscribers.get(sub_id)

    @_locked
    def subscriber_by_mac(self, mac: str) -> Subscriber | None:
        sid = self._sub_by_mac.get(mac.lower())
        return self.subscribers.get(sid) if sid else None

    @_locked
    def subscriber_by_circuit_id(self, cid: str) -> Subscriber | None:
        sid = self._sub_by_cid.get(cid)
        return self.subscribers.get(sid) if sid else None

    @_locked
    def subscribers_by_nte(self, nte_id: str) -> list[Subscriber]:
        return [self.subscribers[s] for s in self._sub_by_nte.get(nte_id, ())]

    @_locked
    def delete_subscriber(self, sub_id: str) -> bool:
        s = self.subscribers.pop(sub_id, None)
        if s is None:
            return False
        self._unindex_subscriber(s)
        return True

    def _unindex_subscriber(self, s: Subscriber) -> None:
        # ownership-guarded like every other index teardown: a MAC or
        # circuit-id reassigned to another subscriber must keep ITS entry
        if s.mac and self._sub_by_mac.get(s.mac.lower()) == s.id:
            del self._sub_by_mac[s.mac.lower()]
        if s.circuit_id and self._sub_by_cid.get(s.circuit_id) == s.id:
            del self._sub_by_cid[s.circuit_id]
        if s.nte_id:
            self._sub_by_nte.get(s.nte_id, set()).discard(s.id)

    # -- leases --
    @_locked
    def put_lease(self, l: LeaseRecord) -> None:
        self.leases[l.ip] = l
        self._lease_by_mac[l.mac.lower()] = l.ip

    @_locked
    def lease_by_ip(self, ip: str) -> LeaseRecord | None:
        return self.leases.get(ip)

    @_locked
    def lease_by_mac(self, mac: str) -> LeaseRecord | None:
        ip = self._lease_by_mac.get(mac.lower())
        return self.leases.get(ip) if ip else None

    @_locked
    def delete_lease(self, ip: str) -> bool:
        l = self.leases.pop(ip, None)
        if l is None:
            return False
        if self._lease_by_mac.get(l.mac.lower()) == ip:
            del self._lease_by_mac[l.mac.lower()]
        return True

    @_locked
    def update_subscriber(self, s: Subscriber) -> None:
        """Update-only variant (store.go:225): missing id is an error —
        a typo'd update must not silently create a ghost subscriber."""
        if s.id not in self.subscribers:
            raise KeyError(f"subscriber {s.id!r} not found")
        self.put_subscriber(s)

    @_locked
    def list_subscribers(self) -> list[Subscriber]:
        return list(self.subscribers.values())

    # -- pools --
    @_locked
    def put_pool(self, p: PoolRecord) -> None:
        old = self.pools.get(p.id)
        if old and old.name and self._pool_by_name.get(old.name) == p.id:
            self._pool_by_name.pop(old.name)
        self.pools[p.id] = p
        if p.name:
            self._pool_by_name[p.name] = p.id

    @_locked
    def get_pool(self, pool_id: str) -> PoolRecord | None:
        return self.pools.get(pool_id)

    @_locked
    def pool_by_name(self, name: str) -> PoolRecord | None:
        pid = self._pool_by_name.get(name)
        return self.pools.get(pid) if pid else None

    @_locked
    def list_pools(self) -> list[PoolRecord]:
        return list(self.pools.values())

    @_locked
    def delete_pool(self, pool_id: str) -> bool:
        p = self.pools.pop(pool_id, None)
        if p is None:
            return False
        if p.name and self._pool_by_name.get(p.name) == pool_id:
            del self._pool_by_name[p.name]
        return True

    @_locked
    def find_pool_for_subscriber(self, sub: Subscriber) -> PoolRecord | None:
        """Class/ISP matching (parity: FindPoolForSubscriber, store.go:356):
        exact class+isp > class > isp > any-enabled."""
        best, best_score = None, -1
        for p in self.pools.values():
            if not p.enabled:
                continue
            score = 0
            if p.client_class and p.client_class != sub.client_class:
                continue
            if p.isp_id and p.isp_id != sub.isp_id:
                continue
            score = (2 if p.client_class else 0) + (1 if p.isp_id else 0)
            if score > best_score:
                best, best_score = p, score
        return best

    # -- leases (cont.) --
    @_locked
    def renew_lease(self, ip: str, duration_s: float,
                    now: float | None = None) -> bool:
        """store.go:547: extend from NOW, not from the old expiry."""
        l = self.leases.get(ip)
        if l is None:
            return False
        l.expires_at = (now if now is not None else self.clock()) + duration_s
        return True

    @_locked
    def list_leases(self) -> list[LeaseRecord]:
        return list(self.leases.values())

    # -- sessions --
    @_locked
    def put_session(self, s: SessionRecord) -> None:
        old = self.sessions.get(s.id)
        if old:
            self._unindex_session(old)
        self.sessions[s.id] = s
        self._sess_by_sub.setdefault(s.subscriber_id, set()).add(s.id)
        if s.mac:
            self._sess_by_mac[s.mac.lower()] = s.id
        if s.ip:
            self._sess_by_ip[s.ip] = s.id

    def _unindex_session(self, s: SessionRecord) -> None:
        self._sess_by_sub.get(s.subscriber_id, set()).discard(s.id)
        if s.mac and self._sess_by_mac.get(s.mac.lower()) == s.id:
            del self._sess_by_mac[s.mac.lower()]
        if s.ip and self._sess_by_ip.get(s.ip) == s.id:
            del self._sess_by_ip[s.ip]

    @_locked
    def sessions_for(self, subscriber_id: str) -> list[SessionRecord]:
        return [self.sessions[i] for i in self._sess_by_sub.get(subscriber_id, ())]

    @_locked
    def session_by_mac(self, mac: str) -> SessionRecord | None:
        sid = self._sess_by_mac.get(mac.lower())
        return self.sessions.get(sid) if sid else None

    @_locked
    def session_by_ip(self, ip: str) -> SessionRecord | None:
        sid = self._sess_by_ip.get(ip)
        return self.sessions.get(sid) if sid else None

    @_locked
    def update_session_activity(self, session_id: str, bytes_in: int = 0,
                                bytes_out: int = 0,
                                now: float | None = None) -> bool:
        """store.go:705: accounting tick — counters accumulate and
        last_seen advances (keeps the idle reaper away)."""
        s = self.sessions.get(session_id)
        if s is None:
            return False
        s.bytes_in += bytes_in
        s.bytes_out += bytes_out
        s.last_seen = now if now is not None else self.clock()
        return True

    @_locked
    def list_sessions(self) -> list[SessionRecord]:
        return list(self.sessions.values())

    @_locked
    def delete_session(self, session_id: str) -> bool:
        s = self.sessions.pop(session_id, None)
        if s is None:
            return False
        self._unindex_session(s)
        return True

    # -- NAT bindings --
    @_locked
    def put_nat_binding(self, b: NATBinding) -> None:
        """Port-BLOCK bindings (RFC 6431): the by-public index is an
        interval list per public IP (bisect on block start), not one
        entry per port — a /26 pool of 1024-port blocks would otherwise
        carry millions of index entries."""
        import bisect

        old = self.nat_bindings.get(b.private_ip)
        if old:
            self.delete_nat_binding(old.private_ip)
        self.nat_bindings[b.private_ip] = b
        blocks = self._nat_by_public.setdefault(b.public_ip, [])
        bisect.insort(blocks, (b.port_start, b.port_end, b.private_ip))

    @_locked
    def nat_binding(self, private_ip: str) -> NATBinding | None:
        return self.nat_bindings.get(private_ip)

    @_locked
    def nat_binding_by_public(self, public_ip: str,
                              port: int) -> NATBinding | None:
        """Reverse lookup by public endpoint — the LEA-request shape
        (store.go:819-833; same query pkg/nat's compliance log answers)."""
        blocks = self._nat_by_public.get(public_ip, [])
        i = bisect.bisect_right(blocks, (port, float("inf"), "")) - 1
        if i >= 0:
            start, end, priv = blocks[i]
            if start <= port <= end:
                return self.nat_bindings.get(priv)
        return None

    @_locked
    def delete_nat_binding(self, private_ip: str) -> bool:
        b = self.nat_bindings.pop(private_ip, None)
        if b is None:
            return False
        blocks = self._nat_by_public.get(b.public_ip, [])
        try:
            blocks.remove((b.port_start, b.port_end, b.private_ip))
        except ValueError:
            pass
        return True

    # -- stats (store.go:129-146) --
    @_locked
    def stats(self) -> dict:
        return {
            "subscribers": len(self.subscribers),
            "leases": len(self.leases),
            "pools": len(self.pools),
            "sessions": len(self.sessions),
            "nat_bindings": len(self.nat_bindings),
            **self._counters,
        }

    # -- background cleanup loops (store.go:100-127, 858-1024) --
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return  # one sweeper; a second start() must not orphan it
        self._stop.clear()
        self._thread = threading.Thread(target=self._cleanup_loop,
                                        daemon=True, name="bng-state-sweep")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def _cleanup_loop(self) -> None:
        while not self._stop.wait(self.lease_sweep_interval):
            self.cleanup_expired_leases()
            self.cleanup_idle_sessions(self.session_idle_s)

    # -- cleanup sweeps (parity: store.go:858-1024) --
    @_locked
    def cleanup_expired_leases(self, now: float | None = None) -> int:
        now = now if now is not None else self.clock()
        dead = [ip for ip, l in self.leases.items() if l.expires_at < now]
        for ip in dead:
            self.delete_lease(ip)
        self._counters["leases_expired"] += len(dead)
        return len(dead)

    @_locked
    def cleanup_idle_sessions(self, idle_s: float, now: float | None = None) -> int:
        now = now if now is not None else self.clock()
        dead = [i for i, s in self.sessions.items() if now - s.last_seen > idle_s]
        for i in dead:
            self.delete_session(i)
        self._counters["sessions_reaped"] += len(dead)
        return len(dead)
