"""In-memory indexed state store — the embedded single-node alternative to
Nexus.

Parity: pkg/state/store.go — Store (:15) with subscriber/lease/pool/
session/NAT-binding records, by-MAC/by-IP/by-NTE indexes (:148-856),
FindPoolForSubscriber class matching (:356), TTL cleanup sweeps
(:858-1024, explicit tick here). Types: pkg/state/types.go:9-330.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class Subscriber:
    id: str
    mac: str = ""
    circuit_id: str = ""
    nte_id: str = ""
    client_class: int = 0
    isp_id: str = ""
    enabled: bool = True
    meta: dict = field(default_factory=dict)


@dataclass
class LeaseRecord:
    ip: str
    subscriber_id: str
    mac: str
    expires_at: float
    pool_id: str = ""


@dataclass
class PoolRecord:
    id: str
    cidr: str
    client_class: int = 0
    isp_id: str = ""
    enabled: bool = True


@dataclass
class SessionRecord:
    id: str
    subscriber_id: str
    ip: str = ""
    mac: str = ""
    started_at: float = 0.0
    last_seen: float = 0.0
    kind: str = "ipoe"  # ipoe | pppoe | wifi
    state: str = "active"


@dataclass
class NATBinding:
    private_ip: str
    public_ip: str
    port_start: int
    port_end: int
    subscriber_id: str = ""


class Store:
    def __init__(self, clock=time.time):
        self.clock = clock
        self.subscribers: dict[str, Subscriber] = {}
        self.leases: dict[str, LeaseRecord] = {}  # by ip
        self.pools: dict[str, PoolRecord] = {}
        self.sessions: dict[str, SessionRecord] = {}
        self.nat_bindings: dict[str, NATBinding] = {}  # by private ip
        # indexes
        self._sub_by_mac: dict[str, str] = {}
        self._sub_by_cid: dict[str, str] = {}
        self._sub_by_nte: dict[str, set[str]] = {}
        self._sess_by_sub: dict[str, set[str]] = {}
        self._lease_by_mac: dict[str, str] = {}

    # -- subscribers --
    def put_subscriber(self, s: Subscriber) -> None:
        old = self.subscribers.get(s.id)
        if old:
            self._sub_by_mac.pop(old.mac.lower(), None)
            self._sub_by_cid.pop(old.circuit_id, None)
            if old.nte_id:
                self._sub_by_nte.get(old.nte_id, set()).discard(s.id)
        self.subscribers[s.id] = s
        if s.mac:
            self._sub_by_mac[s.mac.lower()] = s.id
        if s.circuit_id:
            self._sub_by_cid[s.circuit_id] = s.id
        if s.nte_id:
            self._sub_by_nte.setdefault(s.nte_id, set()).add(s.id)

    def get_subscriber(self, sub_id: str) -> Subscriber | None:
        return self.subscribers.get(sub_id)

    def subscriber_by_mac(self, mac: str) -> Subscriber | None:
        sid = self._sub_by_mac.get(mac.lower())
        return self.subscribers.get(sid) if sid else None

    def subscriber_by_circuit_id(self, cid: str) -> Subscriber | None:
        sid = self._sub_by_cid.get(cid)
        return self.subscribers.get(sid) if sid else None

    def subscribers_by_nte(self, nte_id: str) -> list[Subscriber]:
        return [self.subscribers[s] for s in self._sub_by_nte.get(nte_id, ())]

    def delete_subscriber(self, sub_id: str) -> bool:
        s = self.subscribers.pop(sub_id, None)
        if s is None:
            return False
        self._sub_by_mac.pop(s.mac.lower(), None)
        self._sub_by_cid.pop(s.circuit_id, None)
        if s.nte_id:
            self._sub_by_nte.get(s.nte_id, set()).discard(sub_id)
        return True

    # -- leases --
    def put_lease(self, l: LeaseRecord) -> None:
        self.leases[l.ip] = l
        self._lease_by_mac[l.mac.lower()] = l.ip

    def lease_by_ip(self, ip: str) -> LeaseRecord | None:
        return self.leases.get(ip)

    def lease_by_mac(self, mac: str) -> LeaseRecord | None:
        ip = self._lease_by_mac.get(mac.lower())
        return self.leases.get(ip) if ip else None

    def delete_lease(self, ip: str) -> bool:
        l = self.leases.pop(ip, None)
        if l is None:
            return False
        if self._lease_by_mac.get(l.mac.lower()) == ip:
            del self._lease_by_mac[l.mac.lower()]
        return True

    # -- pools --
    def put_pool(self, p: PoolRecord) -> None:
        self.pools[p.id] = p

    def find_pool_for_subscriber(self, sub: Subscriber) -> PoolRecord | None:
        """Class/ISP matching (parity: FindPoolForSubscriber, store.go:356):
        exact class+isp > class > isp > any-enabled."""
        best, best_score = None, -1
        for p in self.pools.values():
            if not p.enabled:
                continue
            score = 0
            if p.client_class and p.client_class != sub.client_class:
                continue
            if p.isp_id and p.isp_id != sub.isp_id:
                continue
            score = (2 if p.client_class else 0) + (1 if p.isp_id else 0)
            if score > best_score:
                best, best_score = p, score
        return best

    # -- sessions --
    def put_session(self, s: SessionRecord) -> None:
        self.sessions[s.id] = s
        self._sess_by_sub.setdefault(s.subscriber_id, set()).add(s.id)

    def sessions_for(self, subscriber_id: str) -> list[SessionRecord]:
        return [self.sessions[i] for i in self._sess_by_sub.get(subscriber_id, ())]

    def delete_session(self, session_id: str) -> bool:
        s = self.sessions.pop(session_id, None)
        if s is None:
            return False
        self._sess_by_sub.get(s.subscriber_id, set()).discard(session_id)
        return True

    # -- NAT bindings --
    def put_nat_binding(self, b: NATBinding) -> None:
        self.nat_bindings[b.private_ip] = b

    def nat_binding(self, private_ip: str) -> NATBinding | None:
        return self.nat_bindings.get(private_ip)

    # -- cleanup sweeps (parity: store.go:858-1024) --
    def cleanup_expired_leases(self, now: float | None = None) -> int:
        now = now if now is not None else self.clock()
        dead = [ip for ip, l in self.leases.items() if l.expires_at < now]
        for ip in dead:
            self.delete_lease(ip)
        return len(dead)

    def cleanup_idle_sessions(self, idle_s: float, now: float | None = None) -> int:
        now = now if now is not None else self.clock()
        dead = [i for i, s in self.sessions.items() if now - s.last_seen > idle_s]
        for i in dead:
            self.delete_session(i)
        return len(dead)
