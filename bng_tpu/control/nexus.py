"""Nexus coordination client + embeddable store stack.

Parity: pkg/nexus — Store interface (store.go:13), MemoryStore (:43),
TypedStore[T] (:129), entities (:211-291), Client with watchers +
deterministic hashring IP allocation (client.go:47-577), HTTPAllocator
REST client (http_allocator.go:95-541), VLANAllocator (vlan.go:46-270).

The HTTP transport is injectable (tests run against an in-memory server;
SURVEY.md §4.6 httpmock pattern). The hashring allocation is the same
algorithm the device uses for shard routing — one placement function
across the whole system.
"""

from __future__ import annotations

import json
import time
from dataclasses import asdict, dataclass, field
from typing import Callable, Generic, TypeVar

from bng_tpu.parallel.hashring import hashring_allocate


class ErrNoAllocation(Exception):
    """Parity: http_allocator.go:226."""


# ---------------------------------------------------------------------------
# Store
# ---------------------------------------------------------------------------
class MemoryStore:
    """KV store with prefix listing and change watchers (store.go:13-120)."""

    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._watchers: list[tuple[str, Callable[[str, bytes | None], None]]] = []

    def get(self, key: str) -> bytes | None:
        return self._data.get(key)

    def put(self, key: str, value: bytes) -> None:
        self._data[key] = value
        self._notify(key, value)

    def delete(self, key: str) -> bool:
        if key in self._data:
            del self._data[key]
            self._notify(key, None)
            return True
        return False

    def list(self, prefix: str) -> dict[str, bytes]:
        return {k: v for k, v in self._data.items() if k.startswith(prefix)}

    def watch(self, prefix: str,
              cb: Callable[[str, bytes | None], None]) -> Callable[[], None]:
        """Subscribe to changes under `prefix`. Returns a cancel
        callable (idempotent); watchers fire in registration order."""
        entry = (prefix, cb)
        self._watchers.append(entry)

        def cancel():
            if entry in self._watchers:
                self._watchers.remove(entry)

        return cancel

    def _notify(self, key: str, value: bytes | None) -> None:
        for prefix, cb in list(self._watchers):
            if key.startswith(prefix):
                cb(key, value)


T = TypeVar("T")


class TypedStore(Generic[T]):
    """Typed veneer over a KV store (store.go:129-205)."""

    def __init__(self, store, prefix: str, cls: type[T]):
        self.store = store
        self.prefix = prefix.rstrip("/") + "/"
        self.cls = cls

    def _key(self, id_: str) -> str:
        return self.prefix + id_

    def get(self, id_: str) -> T | None:
        raw = self.store.get(self._key(id_))
        return self.cls(**json.loads(raw)) if raw else None

    def put(self, id_: str, obj: T) -> None:
        self.store.put(self._key(id_), json.dumps(asdict(obj)).encode())

    def delete(self, id_: str) -> bool:
        return self.store.delete(self._key(id_))

    def list(self) -> dict[str, T]:
        return {
            k[len(self.prefix):]: self.cls(**json.loads(v))
            for k, v in self.store.list(self.prefix).items()
        }

    def watch(self, cb: Callable[[str, T | None], None]) -> Callable[[], None]:
        def wrapped(key: str, value: bytes | None):
            id_ = key[len(self.prefix):]
            cb(id_, self.cls(**json.loads(value)) if value else None)

        return self.store.watch(self.prefix, wrapped)


# ---------------------------------------------------------------------------
# Entities (store.go:211-291)
# ---------------------------------------------------------------------------
@dataclass
class SubscriberEntity:
    id: str
    mac: str = ""
    circuit_id: str = ""
    nte_id: str = ""
    isp_id: str = ""
    client_class: int = 0
    qos_policy: str = ""
    enabled: bool = True
    static_ip: str = ""


@dataclass
class NTEEntity:
    id: str
    serial: str = ""
    model: str = ""
    olt_id: str = ""
    state: str = "discovered"  # discovered|provisioning|connected|disconnected
    s_tag: int = 0
    c_tag: int = 0
    approved: bool = False


@dataclass
class ISPConfigEntity:
    id: str
    name: str = ""
    as_number: int = 0
    route_table: int = 0
    pools: list = field(default_factory=list)


@dataclass
class IPPoolEntity:
    id: str
    cidr: str = ""
    gateway: str = ""
    isp_id: str = ""
    client_class: int = 0
    lease_time: int = 3600


@dataclass
class DeviceEntity:
    id: str
    serial: str = ""
    mac: str = ""
    model: str = ""
    state: str = "pending"  # pending|approved|rejected
    last_heartbeat: float = 0.0


# ---------------------------------------------------------------------------
# Client with hashring allocation (client.go)
# ---------------------------------------------------------------------------
class NexusClient:
    """Coordination client over a Store (embedded or remote-backed).

    AllocateIPForSubscriber parity (client.go:487-577): deterministic
    hash(subscriberID+attempt) probing over the pool, claim via the store.
    """

    def __init__(self, store=None, node_id: str = "bng0", clock=time.time):
        self.store = store if store is not None else MemoryStore()
        self.node_id = node_id
        self.clock = clock
        self.subscribers = TypedStore(self.store, "subscribers", SubscriberEntity)
        self.ntes = TypedStore(self.store, "ntes", NTEEntity)
        self.isps = TypedStore(self.store, "isps", ISPConfigEntity)
        self.pools = TypedStore(self.store, "pools", IPPoolEntity)
        self.devices = TypedStore(self.store, "devices", DeviceEntity)

    # -- subscriber lookup (client.go:459) --
    def get_subscriber_by_mac(self, mac: str) -> SubscriberEntity | None:
        mac = mac.lower()
        for sub in self.subscribers.list().values():
            if sub.mac.lower() == mac:
                return sub
        return None

    def get_subscriber_by_circuit_id(self, cid: str) -> SubscriberEntity | None:
        for sub in self.subscribers.list().values():
            if sub.circuit_id == cid:
                return sub
        return None

    # -- heartbeat --
    def heartbeat(self, device_id: str) -> None:
        d = self.devices.get(device_id)
        if d:
            d.last_heartbeat = self.clock()
            self.devices.put(device_id, d)

    # -- hashring IP allocation (client.go:487-577) --
    def allocate_ip(self, subscriber_id: str, pool_id: str) -> str | None:
        import ipaddress

        pool = self.pools.get(pool_id)
        if pool is None:
            return None
        net = ipaddress.ip_network(pool.cidr, strict=False)
        size = net.num_addresses - 2 if net.version == 4 and net.num_addresses > 2 else net.num_addresses
        base = int(net.network_address) + (1 if net.version == 4 else 0)

        existing_key = f"allocations/{pool_id}/by-sub/{subscriber_id}"
        existing = self.store.get(existing_key)
        if existing:
            return existing.decode()

        def is_free(idx: int) -> bool:
            ip = str(ipaddress.ip_address(base + idx))
            return self.store.get(f"allocations/{pool_id}/by-ip/{ip}") is None

        idx = hashring_allocate(subscriber_id, size, is_free)
        if idx is None:
            return None
        ip = str(ipaddress.ip_address(base + idx))
        self.store.put(f"allocations/{pool_id}/by-ip/{ip}", subscriber_id.encode())
        self.store.put(existing_key, ip.encode())
        return ip

    def release_ip(self, subscriber_id: str, pool_id: str) -> bool:
        key = f"allocations/{pool_id}/by-sub/{subscriber_id}"
        ip_raw = self.store.get(key)
        if ip_raw is None:
            return False
        self.store.delete(key)
        self.store.delete(f"allocations/{pool_id}/by-ip/{ip_raw.decode()}")
        return True


# ---------------------------------------------------------------------------
# HTTP allocator (http_allocator.go)
# ---------------------------------------------------------------------------
class HTTPAllocator:
    """REST allocate/lookup/release against a central Nexus.

    transport(method, path, body_dict) -> (status, body_dict); production
    wires an HTTP session, tests wire a fake (http_allocator_test parity).
    """

    def __init__(self, base_url: str, transport, node_id: str = "bng0"):
        self.base_url = base_url.rstrip("/")
        self.transport = transport
        self.node_id = node_id
        self.stats = {"allocations": 0, "failures": 0, "releases": 0}

    def allocate(self, subscriber_id: str, pool_hint: str = "") -> str | None:
        status, body = self.transport("POST", "/api/v1/allocate", {
            "subscriber_id": subscriber_id, "node_id": self.node_id,
            "pool": pool_hint,
        })
        if status == 200 and body.get("ip"):
            self.stats["allocations"] += 1
            return body["ip"]
        if status == 404:
            raise ErrNoAllocation(subscriber_id)
        self.stats["failures"] += 1
        if status >= 500:
            raise ConnectionError(f"nexus {status}")
        return None

    def lookup(self, subscriber_id: str) -> str | None:
        status, body = self.transport("GET", f"/api/v1/allocations/{subscriber_id}", None)
        if status == 200:
            return body.get("ip")
        if status == 404:
            return None
        raise ConnectionError(f"nexus {status}")

    def lookup_by_ip(self, ip: str) -> tuple[str, float] | None:
        """Who does the CENTRAL store think owns this IP? -> (subscriber,
        allocated_at) — the heal-time conflict-detection query
        (conflict_detector.go:121-233's central view)."""
        status, body = self.transport(
            "GET", f"/api/v1/allocation-by-ip/{ip}", None)
        if status == 200 and body.get("subscriber_id"):
            return body["subscriber_id"], float(body.get("allocated_at", 0))
        if status == 404:
            return None
        raise ConnectionError(f"nexus {status}")

    def release(self, subscriber_id: str) -> bool:
        status, _ = self.transport("DELETE", f"/api/v1/allocations/{subscriber_id}", None)
        ok = status in (200, 204)
        if ok:
            self.stats["releases"] += 1
        return ok

    def get_pool_info(self) -> dict:
        status, body = self.transport("GET", "/api/v1/pools", None)
        if status != 200:
            raise ConnectionError(f"nexus {status}")
        return body

    def health_check(self) -> bool:
        try:
            status, _ = self.transport("GET", "/health", None)
            return status == 200
        except Exception:
            return False


# ---------------------------------------------------------------------------
# VLAN allocator (vlan.go:46-270)
# ---------------------------------------------------------------------------
class VLANAllocator:
    """S-TAG/C-TAG assignment for QinQ deployments."""

    def __init__(self, s_tag_range=(100, 4000), c_tag_range=(1, 4094)):
        self.s_range = s_tag_range
        self.c_range = c_tag_range
        self._assigned: dict[str, tuple[int, int]] = {}
        self._used: set[tuple[int, int]] = set()
        self._next_s = s_tag_range[0]
        self._next_c = c_tag_range[0]

    def allocate(self, subscriber_id: str) -> tuple[int, int] | None:
        if subscriber_id in self._assigned:
            return self._assigned[subscriber_id]
        s, c = self._next_s, self._next_c
        span_c = self.c_range[1] - self.c_range[0] + 1
        for _ in range(span_c * (self.s_range[1] - self.s_range[0] + 1)):
            if (s, c) not in self._used:
                self._assigned[subscriber_id] = (s, c)
                self._used.add((s, c))
                self._advance()
                return s, c
            s, c = self._peek_next(s, c)
        return None

    def _advance(self):
        self._next_s, self._next_c = self._peek_next(self._next_s, self._next_c)

    def _peek_next(self, s, c):
        c += 1
        if c > self.c_range[1]:
            c = self.c_range[0]
            s += 1
            if s > self.s_range[1]:
                s = self.s_range[0]
        return s, c

    def release(self, subscriber_id: str) -> bool:
        pair = self._assigned.pop(subscriber_id, None)
        if pair is None:
            return False
        self._used.discard(pair)
        return True

    def lookup(self, subscriber_id: str) -> tuple[int, int] | None:
        return self._assigned.get(subscriber_id)
