"""NAT ALGs: FTP and SIP payload rewriting for punted flows.

Parity: pkg/nat/alg.go — ALGHandler registry keyed by well-known port
(alg.go:1-136), FTPALG with PORT / EPRT outbound rewrite and PASV / EPSV
inbound handling + data-connection pre-mapping (alg.go:138-351), SIPALG
line-based Via/Contact/SDP address rewrite (alg.go:353-441).

Device side: the NAT44 kernel detects control-protocol ports and punts
those packets (bpf/nat44.c:616-641 -> ops.nat44 ALG trigger verdict); the
host rewrites payloads here and pre-installs data-connection mappings via
the NATManager before re-injecting.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Callable

FTP_PORT = 21
SIP_PORT = 5060


@dataclass
class ALGConnection:
    """One NAT'd control connection (alg.go ALGConnection)."""

    private_ip: str
    private_port: int
    public_ip: str
    public_port: int
    protocol: int = 6


# mapper: (private_ip, private_port) -> (public_ip, public_port) or None
Mapper = Callable[[str, int], "tuple[str, int] | None"]


class FTPALG:
    """alg.go:138-351."""

    # PORT h1,h2,h3,h4,p1,p2
    PORT_RE = re.compile(r"(?i)(PORT)\s+(\d+),(\d+),(\d+),(\d+),(\d+),(\d+)")
    # 227 Entering Passive Mode (h1,h2,h3,h4,p1,p2)
    PASV_RE = re.compile(r"(227\s+[^(]*)\((\d+),(\d+),(\d+),(\d+),(\d+),(\d+)\)")
    # EPRT |1|ip|port|
    EPRT_RE = re.compile(r"(?i)(EPRT)\s+\|1\|([^|]+)\|(\d+)\|")
    # 229 Entering Extended Passive Mode (|||port|)
    EPSV_RE = re.compile(r"229\s+[^(]*\(\|\|\|(\d+)\|\)")

    name = "FTP"

    def __init__(self, mapper: Mapper):
        self._map = mapper
        self.stats = {"port_rewrites": 0, "pasv_rewrites": 0,
                      "eprt_rewrites": 0, "epsv_mappings": 0, "failures": 0}

    def process_outbound(self, conn: ALGConnection, data: bytes) -> bytes:
        """Client->server: rewrite announced private endpoints to public."""
        text = data.decode("latin-1")
        lines = text.split("\r\n")
        modified = False
        for i, line in enumerate(lines):
            m = self.PORT_RE.search(line)
            if m:
                new = self._rewrite_port(conn, m)
                if new is not None:
                    lines[i] = new
                    modified = True
                continue
            m = self.EPRT_RE.search(line)
            if m:
                new = self._rewrite_eprt(conn, m)
                if new is not None:
                    lines[i] = new
                    modified = True
        return "\r\n".join(lines).encode("latin-1") if modified else data

    def process_inbound(self, conn: ALGConnection, data: bytes) -> bytes:
        """Server->client: rewrite 227 PASV bodies that leak the private
        address (NAT'd FTP server case); pre-map 229 EPSV data ports."""
        text = data.decode("latin-1")
        lines = text.split("\r\n")
        modified = False
        for i, line in enumerate(lines):
            m = self.PASV_RE.search(line)
            if m:
                new = self._rewrite_pasv(conn, m)
                if new is not None:
                    lines[i] = new
                    modified = True
                continue
            m = self.EPSV_RE.search(line)
            if m:
                # EPSV carries no IP; just pre-map the data port.
                if self._map(conn.private_ip, int(m.group(1))):
                    self.stats["epsv_mappings"] += 1
        return "\r\n".join(lines).encode("latin-1") if modified else data

    @staticmethod
    def _decode_hostport(groups) -> tuple[str, int]:
        h = ".".join(groups[:4])
        return h, int(groups[4]) * 256 + int(groups[5])

    @staticmethod
    def _encode_hostport(ip: str, port: int) -> str:
        return ",".join(ip.split(".")) + f",{port >> 8},{port & 0xFF}"

    def _rewrite_port(self, conn: ALGConnection, m: re.Match) -> str | None:
        ip, port = self._decode_hostport(m.groups()[1:])
        if ip != conn.private_ip:
            return None
        mapped = self._map(ip, port)
        if mapped is None:
            self.stats["failures"] += 1
            return None
        self.stats["port_rewrites"] += 1
        return m.string[:m.start()] + \
            f"{m.group(1)} {self._encode_hostport(*mapped)}" + \
            m.string[m.end():]

    def _rewrite_eprt(self, conn: ALGConnection, m: re.Match) -> str | None:
        ip, port = m.group(2), int(m.group(3))
        if ip != conn.private_ip:
            return None
        mapped = self._map(ip, port)
        if mapped is None:
            self.stats["failures"] += 1
            return None
        self.stats["eprt_rewrites"] += 1
        return m.string[:m.start()] + \
            f"{m.group(1)} |1|{mapped[0]}|{mapped[1]}|" + m.string[m.end():]

    def _rewrite_pasv(self, conn: ALGConnection, m: re.Match) -> str | None:
        ip, port = self._decode_hostport(m.groups()[1:])
        if ip != conn.private_ip:
            return None
        mapped = self._map(ip, port)
        if mapped is None:
            self.stats["failures"] += 1
            return None
        self.stats["pasv_rewrites"] += 1
        return m.string[:m.start()] + \
            f"{m.group(1)}({self._encode_hostport(*mapped)})" + \
            m.string[m.end():]


class SIPALG:
    """alg.go:353-441: rewrite private<->public addresses in SIP headers
    (Via/Contact/From/To) and SDP bodies (c=/o=/m= lines)."""

    name = "SIP"

    def __init__(self, mapper: Mapper | None = None):
        self._map = mapper
        self.stats = {"rewrites": 0, "media_mappings": 0}

    _SDP_MEDIA_RE = re.compile(r"^m=(audio|video)\s+(\d+)\s", re.M)

    def _rewrite(self, conn: ALGConnection, data: bytes,
                 old_ip: str, new_ip: str) -> bytes:
        text = data.decode("latin-1")
        if old_ip not in text:
            return data
        out = text.replace(old_ip, new_ip)
        self.stats["rewrites"] += out.count(new_ip)
        return out.encode("latin-1")

    def process_outbound(self, conn: ALGConnection, data: bytes) -> bytes:
        out = self._rewrite(conn, data, conn.private_ip, conn.public_ip)
        # Pre-map announced RTP media ports so inbound audio flows.
        if self._map is not None:
            for m in self._SDP_MEDIA_RE.finditer(out.decode("latin-1")):
                if self._map(conn.private_ip, int(m.group(2))):
                    self.stats["media_mappings"] += 1
        return out

    def process_inbound(self, conn: ALGConnection, data: bytes) -> bytes:
        return self._rewrite(conn, data, conn.public_ip, conn.private_ip)


class ALGHandler:
    """Registry + dispatch (alg.go:1-136). mapper pre-installs data-path
    mappings through the NAT manager (the single writer)."""

    def __init__(self, mapper: Mapper):
        self._algs: dict[int, object] = {
            FTP_PORT: FTPALG(mapper),
            SIP_PORT: SIPALG(mapper),
        }

    def register(self, port: int, alg) -> None:
        self._algs[port] = alg

    def ports(self) -> list[int]:
        return sorted(self._algs)

    def get(self, port: int):
        return self._algs.get(port)

    def process(self, conn: ALGConnection, dst_port: int, data: bytes,
                outbound: bool) -> bytes:
        alg = self._algs.get(dst_port if outbound else conn.private_port) \
            or self._algs.get(dst_port)
        if alg is None:
            return data
        if outbound:
            return alg.process_outbound(conn, data)
        return alg.process_inbound(conn, data)
