"""QinQ (802.1ad) S-tag/C-tag helpers and subscriber<->VLAN registry.

Parity: pkg/qinq — VLANPair model (qinq.go:18-44), VLANRange (:68-86),
Mapper registry with bidirectional index (:100-210). Kernel-side QinQ
parsing lives in the device packet parser (bng_tpu.ops.parse), mirroring
how the reference parses 802.1ad in bpf/dhcp_fastpath.c:352-428.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass(frozen=True)
class VLANPair:
    """An S-tag (outer, 802.1ad) + C-tag (inner, 802.1Q) pair.

    0 means "no tag" on that level, like the reference (qinq.go:18-44).
    """

    s_tag: int = 0
    c_tag: int = 0

    def __post_init__(self):
        for name, v in (("s_tag", self.s_tag), ("c_tag", self.c_tag)):
            if not 0 <= v <= 4095:
                raise ValueError(f"{name} out of range: {v}")

    def __str__(self) -> str:
        if self.is_double_tagged:
            return f"{self.s_tag}.{self.c_tag}"
        if self.is_single_tagged:
            return str(self.c_tag)
        return "untagged"

    @property
    def is_double_tagged(self) -> bool:
        return self.s_tag != 0 and self.c_tag != 0

    @property
    def is_single_tagged(self) -> bool:
        return self.s_tag == 0 and self.c_tag != 0

    @property
    def is_untagged(self) -> bool:
        return self.s_tag == 0 and self.c_tag == 0

    def key(self) -> int:
        """Pack to the u32 {s_tag,c_tag} device-table key (ops.parse layout)."""
        return (self.s_tag << 16) | self.c_tag


@dataclass(frozen=True)
class VLANRange:
    """Inclusive VID range (qinq.go:68-86)."""

    start: int
    end: int

    def contains(self, vid: int) -> bool:
        return self.start <= vid <= self.end

    def size(self) -> int:
        return max(0, self.end - self.start + 1)


@dataclass
class QinQConfig:
    """Valid tag ranges for registration (qinq.go:47-98)."""

    s_tag_range: VLANRange = field(default_factory=lambda: VLANRange(1, 4094))
    c_tag_range: VLANRange = field(default_factory=lambda: VLANRange(1, 4094))
    allow_single_tagged: bool = True
    allow_untagged: bool = False


class QinQMapper:
    """Bidirectional VLANPair <-> subscriber-ID registry (qinq.go:100-210).

    The registry is the control-plane source of truth; activation writes the
    pair into the device vlan_subscriber table (runtime.tables) so the
    fast path can do the 3-tier lookup the reference does in
    bpf/dhcp_fastpath.c:653-681.
    """

    def __init__(self, config: QinQConfig | None = None):
        self.config = config or QinQConfig()
        self._lock = threading.Lock()
        self._by_vlan: dict[VLANPair, str] = {}
        self._by_subscriber: dict[str, VLANPair] = {}

    def register(self, vlan: VLANPair, subscriber_id: str) -> None:
        cfg = self.config
        if vlan.is_untagged and not cfg.allow_untagged:
            raise ValueError("untagged registration not allowed")
        if vlan.s_tag != 0 and vlan.c_tag == 0:
            raise ValueError("s-tag-only pair is invalid (outer without inner tag)")
        if vlan.is_single_tagged:
            if not cfg.allow_single_tagged:
                raise ValueError("single-tagged registration not allowed")
            if not cfg.c_tag_range.contains(vlan.c_tag):
                raise ValueError(f"c_tag {vlan.c_tag} outside allowed range")
        if vlan.is_double_tagged:
            if not cfg.s_tag_range.contains(vlan.s_tag):
                raise ValueError(f"s_tag {vlan.s_tag} outside allowed range")
            if not cfg.c_tag_range.contains(vlan.c_tag):
                raise ValueError(f"c_tag {vlan.c_tag} outside allowed range")
        with self._lock:
            existing = self._by_vlan.get(vlan)
            if existing is not None and existing != subscriber_id:
                raise ValueError(f"VLAN {vlan} already registered to {existing}")
            old = self._by_subscriber.get(subscriber_id)
            if old is not None and old != vlan:
                del self._by_vlan[old]
            self._by_vlan[vlan] = subscriber_id
            self._by_subscriber[subscriber_id] = vlan

    def unregister(self, vlan: VLANPair) -> None:
        with self._lock:
            sub = self._by_vlan.pop(vlan, None)
            if sub is not None and self._by_subscriber.get(sub) == vlan:
                del self._by_subscriber[sub]

    def unregister_subscriber(self, subscriber_id: str) -> None:
        with self._lock:
            vlan = self._by_subscriber.pop(subscriber_id, None)
            if vlan is not None:
                self._by_vlan.pop(vlan, None)

    def get_subscriber(self, vlan: VLANPair) -> str | None:
        with self._lock:
            return self._by_vlan.get(vlan)

    def get_vlan(self, subscriber_id: str) -> VLANPair | None:
        with self._lock:
            return self._by_subscriber.get(subscriber_id)

    def stats(self) -> dict:
        with self._lock:
            double = sum(1 for v in self._by_vlan if v.is_double_tagged)
            return {
                "total_mappings": len(self._by_vlan),
                "double_tagged": double,
                "single_tagged": len(self._by_vlan) - double,
            }
