"""Prometheus-compatible metrics registry with bng_* name parity.

Parity: pkg/metrics — Metrics struct with ~30 bng_* families
(metrics.go:16-380, names at :92-280), Collect polling fast-path stats +
pool stats + DHCP server counters every interval (metrics.go:555-623),
StartCollector (:625), /metrics HTTP endpoint (cmd/bng/main.go:1219-1241).

Implemented without the prometheus client library: a small registry
producing the text exposition format (v0.0.4), which Prometheus scrapes
identically. Counter/Gauge/Histogram support labels.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict

from bng_tpu.utils.structlog import ErrorLog


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in labels.items())
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_text: str, label_names: tuple = ()):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._children: OrderedDict[tuple, float] = OrderedDict()

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(f"{self.name}: expected labels {self.label_names}, "
                             f"got {tuple(labels)}")
        return tuple(labels[n] for n in self.label_names)

    def labeled(self) -> list[dict]:
        """Label dicts currently carrying a child value — lets callers
        reconcile a labeled family against fresh state and `remove`
        labels that no longer exist."""
        with self._lock:
            return [dict(zip(self.label_names, k)) for k in self._children]

    def remove(self, **labels) -> bool:
        """Drop one labeled child (True if it existed). A label whose
        subject disappeared must leave the scrape — a frozen last value
        reads as live state."""
        key = self._key(labels)
        with self._lock:
            return self._children.pop(key, None) is not None

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            if not self._children and not self.label_names:
                out.append(f"{self.name} 0")
            for key, val in self._children.items():
                labels = dict(zip(self.label_names, key))
                out.append(f"{self.name}{_fmt_labels(labels)} {_fmt_value(val)}")
        return out


class Counter(_Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + value

    def set_total(self, value: float, **labels) -> None:
        """Absolute set for counters mirrored from device stats arrays
        (the reference overwrites from the eBPF stats map the same way)."""
        key = self._key(labels)
        with self._lock:
            self._children[key] = max(self._children.get(key, 0.0), float(value))

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._children[self._key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels) -> None:
        self.inc(-value, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)


class Histogram:
    kind = "histogram"
    DEFAULT_BUCKETS = (1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2,
                       1e-1, 5e-1, 1.0, float("inf"))

    def __init__(self, name: str, help_text: str, label_names: tuple = (),
                 buckets: tuple = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_text
        self.label_names = tuple(label_names)
        self.buckets = tuple(sorted(buckets))
        if self.buckets[-1] != float("inf"):
            self.buckets = self.buckets + (float("inf"),)
        self._lock = threading.Lock()
        self._counts: dict[tuple, list[int]] = {}
        self._sums: dict[tuple, float] = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.label_names):
            raise ValueError(f"{self.name}: bad labels {tuple(labels)}")
        return tuple(labels[n] for n in self.label_names)

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            for key, counts in self._counts.items():
                labels = dict(zip(self.label_names, key))
                for ub, c in zip(self.buckets, counts):
                    ls = dict(labels, le=_fmt_value(ub))
                    out.append(f"{self.name}_bucket{_fmt_labels(ls)} {c}")
                out.append(f"{self.name}_sum{_fmt_labels(labels)} "
                           f"{self._sums[key]}")
                out.append(f"{self.name}_count{_fmt_labels(labels)} "
                           f"{counts[-1]}")
        return out


class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: OrderedDict[str, object] = OrderedDict()

    def register(self, metric) -> object:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"duplicate metric {metric.name}")
            self._metrics[metric.name] = metric
        return metric

    def counter(self, name, help_text, labels=()):
        return self.register(Counter(name, help_text, labels))

    def gauge(self, name, help_text, labels=()):
        return self.register(Gauge(name, help_text, labels))

    def histogram(self, name, help_text, labels=(), buckets=Histogram.DEFAULT_BUCKETS):
        return self.register(Histogram(name, help_text, labels, buckets))

    def expose(self) -> str:
        """Text exposition format, scrape-ready."""
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.collect())
        return "\n".join(lines) + "\n"


# Device stats array indexes (mirrors runtime.engine stat layouts; the
# reference reads the same counters from the dhcp_stats map,
# bpf/maps.h:171-191).
DHCP_STAT_NAMES = ("packets_seen", "fastpath_hits", "fastpath_misses",
                   "offers_sent", "acks_sent", "errors", "expired",
                   "non_dhcp", "malformed", "punted")


class BNGMetrics:
    """All bng_* families (metrics.go:16-380) + the 5s collector loop."""

    def __init__(self, registry: Registry | None = None):
        r = self.registry = registry or Registry()
        lbl_type = ("type",)
        self.dhcp_requests_total = r.counter(
            "bng_dhcp_requests_total", "DHCP requests processed", lbl_type)
        self.dhcp_request_duration = r.histogram(
            "bng_dhcp_request_duration_seconds", "DHCP handling latency", ("path",))
        self.dhcp_cache_hit_rate = r.gauge(
            "bng_dhcp_cache_hit_rate", "Fast-path cache hit rate")
        self.dhcp_active_leases = r.gauge(
            "bng_dhcp_active_leases", "Active DHCP leases")
        self.ebpf_fastpath_hits = r.counter(
            "bng_ebpf_fastpath_hits_total", "Device fast-path hits")
        self.ebpf_fastpath_misses = r.counter(
            "bng_ebpf_fastpath_misses_total", "Device fast-path misses")
        self.ebpf_errors = r.counter(
            "bng_ebpf_errors_total", "Device pipeline errors")
        self.ebpf_cache_expired = r.counter(
            "bng_ebpf_cache_expired_total", "Expired fast-path entries")
        self.ebpf_map_entries = r.gauge(
            "bng_ebpf_map_entries", "Entries per device table", ("map",))
        self.pool_utilization = r.gauge(
            "bng_pool_utilization_ratio", "Pool utilization 0-1", ("pool",))
        self.pool_available = r.gauge(
            "bng_pool_available_ips", "Available IPs", ("pool",))
        self.pool_allocated = r.gauge(
            "bng_pool_allocated_ips", "Allocated IPs", ("pool",))
        # counted degradations (storm-suite hygiene): every allocator
        # that can refuse work for capacity reasons reports here, by
        # resource — dhcp_pool / fleet_slice (worker-side dhcp_pool) /
        # dhcp6_addr / dhcp6_pd / nat_block / nat_port
        self.pool_exhausted = r.counter(
            "bng_pool_exhausted_total",
            "Allocations refused on an exhausted resource (degraded "
            "verdicts are counted + rate-limit logged, never silent)",
            ("resource",))
        self.circuit_id_collisions = r.counter(
            "bng_circuit_id_hash_collisions_total", "Circuit-ID hash collisions")
        self.circuit_id_collision_rate = r.gauge(
            "bng_circuit_id_collision_rate", "Circuit-ID collision rate")
        self.session_active = r.gauge(
            "bng_session_active", "Active sessions", lbl_type)
        self.session_total = r.counter(
            "bng_session_total", "Sessions created", lbl_type)
        self.session_bytes_in = r.counter(
            "bng_session_bytes_in_total", "Subscriber bytes in")
        self.session_bytes_out = r.counter(
            "bng_session_bytes_out_total", "Subscriber bytes out")
        self.nat_bindings_active = r.gauge(
            "bng_nat_bindings_active", "Active NAT bindings")
        self.nat_translations_total = r.counter(
            "bng_nat_translations_total", "NAT translations", ("direction",))
        self.nat_ports_used = r.gauge(
            "bng_nat_ports_used", "NAT ports in use", ("public_ip",))
        self.radius_requests_total = r.counter(
            "bng_radius_requests_total", "RADIUS requests", ("type", "status"))
        self.radius_timeouts_total = r.counter(
            "bng_radius_timeouts_total", "RADIUS timeouts")
        self.qos_policies_active = r.gauge(
            "bng_qos_policies_active", "Active QoS policies")
        self.qos_packets_dropped = r.counter(
            "bng_qos_packets_dropped_total", "QoS-dropped packets")
        self.qos_bytes_dropped = r.counter(
            "bng_qos_bytes_dropped_total", "QoS-dropped bytes")
        self.pppoe_sessions_active = r.gauge(
            "bng_pppoe_sessions_active", "Active PPPoE sessions")
        self.pppoe_negotiations_total = r.counter(
            "bng_pppoe_negotiations_total", "PPPoE negotiations", ("result",))
        self.routes_active = r.gauge(
            "bng_routes_active", "Installed routes", ("isp",))
        self.bgp_peers_up = r.gauge(
            "bng_bgp_peers_up", "Established BGP peers")
        self.bgp_prefixes_received = r.gauge(
            "bng_bgp_prefixes_received", "Prefixes from peers", ("peer",))
        self.subscriber_total = r.gauge(
            "bng_subscriber_total", "Known subscribers")
        self.subscriber_by_class = r.gauge(
            "bng_subscriber_by_class", "Subscribers per class", ("class",))
        self.subscriber_by_isp = r.gauge(
            "bng_subscriber_by_isp", "Subscribers per ISP", ("isp",))
        # round-4 subsystems (no reference analog for the device gate —
        # its garden never gated the packet path; observability is how a
        # new enforcement point earns trust)
        self.garden_gated_drops = r.counter(
            "bng_walled_garden_device_drops_total",
            "Packets dropped on device by the walled-garden gate")
        self.garden_allowed_hits = r.counter(
            "bng_walled_garden_device_allowed_total",
            "Gardened packets passed to an allowed destination")
        self.dns_queries = r.counter(
            "bng_dns_queries_total", "DNS queries served", ("outcome",))
        self.dns_cache_hit_rate = r.gauge(
            "bng_dns_cache_hit_rate", "DNS cache hit rate")
        self.dns_overloaded = r.counter(
            "bng_dns_overloaded_total", "DNS queries dropped under overload")
        # latency-tiered scheduler (runtime/scheduler.py). No reference
        # analog: per-packet XDP has no batches to schedule; these are the
        # observability surface the two-lane design earns trust with.
        lbl_lane = ("lane",)
        self.sched_queue_depth = r.gauge(
            "bng_sched_queue_depth", "Frames staged per scheduler lane",
            lbl_lane)
        self.sched_inflight = r.gauge(
            "bng_sched_inflight_batches",
            "Dispatched-but-unretired device batches per lane", lbl_lane)
        self.sched_dispatches = r.counter(
            "bng_sched_dispatches_total",
            "Device dispatches per lane and batch-close reason",
            ("lane", "close"))
        self.sched_frames = r.counter(
            "bng_sched_frames_total", "Frames retired per lane", lbl_lane)
        self.sched_dropped = r.counter(
            "bng_sched_dropped_total",
            "Frames dropped at lane backpressure bound", lbl_lane)
        self.sched_oversize_dropped = r.counter(
            "bng_sched_oversize_dropped_total",
            "Frames dropped at submit for exceeding the engine pkt slot")
        self.sched_completions_evicted = r.counter(
            "bng_sched_completions_evicted_total",
            "Completions evicted from the bounded delivery deque")
        self.sched_batch_occupancy = r.histogram(
            "bng_sched_batch_occupancy_ratio",
            "Dispatched batch fill ratio (1.0 = full close)", lbl_lane,
            buckets=(0.0625, 0.125, 0.25, 0.5, 0.75, 0.875, 1.0))
        self.sched_dispatch_latency = r.histogram(
            "bng_sched_dispatch_latency_seconds",
            "Oldest-frame submit->retire latency per dispatched batch",
            lbl_lane)
        # AOT express OFFER path (ISSUE 13): which program served the
        # express lane, and how often the AOT geometry missed — a miss
        # falls back to the jit full-program path, so a rising miss
        # counter under steady traffic IS a fallback storm
        self.express_program_dispatches = r.counter(
            "bng_express_program_dispatches_total",
            "Express-lane device dispatches by serving program",
            ("program",))
        self.express_aot_miss = r.counter(
            "bng_express_aot_miss_total",
            "Express dispatches that missed the AOT program cache and "
            "fell back to the jit full-program path")
        # express rung-fallback family (ISSUE 18 gray-failure
        # hardening): every event where the express lane served below
        # its configured rung, by reason — compile_failed (AOT refused
        # to lower at setup), geometry_miss (per-dispatch cache miss),
        # devloop_compile_failed / devloop_unavailable / devloop_miss
        # (the ring megakernel degrading to per-batch). Any nonzero
        # rate here under a supposedly-healthy config is a gray failure.
        self.express_fallback = r.counter(
            "bng_express_fallback_total",
            "Express serving-rung fallback events by reason",
            ("reason",))
        # AF_XDP wire path (ISSUE 15): which attach rung actually serves
        # (a requested NIC landing on `memory` is a silent fallback that
        # must never masquerade as wire serving) + the wire pump's frame
        # accounting — pump_stats exported so fill-pool leaks, submit
        # failures and TX-stall overflow drops are dashboard facts.
        self.wire_rung = r.gauge(
            "bng_wire_rung",
            "1 for the attach-ladder rung serving the wire (zerocopy | "
            "copy | memory), 0 for the others", ("mode",))
        self.wire_pump_path = r.gauge(
            "bng_wire_pump_path",
            "1 for the wire-pump implementation in use (scalar | "
            "vector, BNG_WIRE_PUMP)", ("path",))
        self.wire_frames = r.counter(
            "bng_wire_frames_total",
            "Frames moved by the wire pump per direction", ("dir",))
        self.wire_filled = r.counter(
            "bng_wire_filled_total",
            "Free frames fed to the kernel fill ring")
        self.wire_completed = r.counter(
            "bng_wire_completed_total",
            "TX completions reaped back to the frame pool")
        self.wire_rx_submit_fail = r.counter(
            "bng_wire_rx_submit_fail_total",
            "Kernel RX frames the ring refused (rx-full or a length "
            "that cannot fit the chunk room); every one is recycled")
        self.wire_tx_overflow = r.counter(
            "bng_wire_tx_overflow_total",
            "Pending-TX frames dropped at the explicit bound while the "
            "kernel TX ring stalled")
        self.wire_tx_pending = r.gauge(
            "bng_wire_tx_pending",
            "Verdict descriptors awaiting kernel TX slots")
        # slow-path fleet (control/fleet.py + control/admission.py). The
        # reference's concurrency is invisible goroutines; here worker
        # sharding, admission shedding and lease-slice refill are
        # explicit mechanisms that earn trust through these families.
        lbl_worker = ("worker",)
        self.slowpath_workers = r.gauge(
            "bng_slowpath_workers", "Slow-path fleet worker count")
        self.slowpath_worker_frames = r.counter(
            "bng_slowpath_worker_frames_total",
            "Frames handled per fleet worker", lbl_worker)
        self.slowpath_worker_errors = r.counter(
            "bng_slowpath_worker_errors_total",
            "Per-frame handler errors isolated per fleet worker",
            lbl_worker)
        self.slowpath_worker_busy = r.counter(
            "bng_slowpath_worker_busy_seconds_total",
            "Wall seconds each worker spent handling batches", lbl_worker)
        self.slowpath_worker_leases = r.gauge(
            "bng_slowpath_worker_leases",
            "Active leases owned per fleet worker", lbl_worker)
        self.slowpath_slice_free = r.gauge(
            "bng_slowpath_lease_slice_free",
            "Unallocated addresses in a worker's lease slices",
            lbl_worker)
        self.slowpath_admitted = r.counter(
            "bng_slowpath_admitted_total",
            "Frames admitted to fleet worker inboxes")
        self.slowpath_shed = r.counter(
            "bng_slowpath_shed_total",
            "Frames shed by the admission controller", ("reason",))
        self.slowpath_refills = r.counter(
            "bng_slowpath_lease_refills_total",
            "Lease-slice refill grants served to workers")
        self.slowpath_fallback = r.counter(
            "bng_slowpath_fallback_frames_total",
            "Non-DHCPv4 slow frames routed to the parent demux")
        # a configured fleet that silently degraded to one worker is an
        # invisible capacity cliff: the gauge names WHY (per blocker), so
        # the dashboard shows it before the first overload does
        self.slowpath_fleet_blocked = r.gauge(
            "bng_slowpath_fleet_blocked",
            "1 per integration blocking the configured slow-path fleet "
            "(process runs single-worker until these are fleet-aware)",
            ("blocker",))
        # cluster-of-BNGs (bng_tpu/cluster): the front-door
        # coordinator's view — membership, carve-plan ownership and the
        # failover counters. Per-instance gauges reconcile against the
        # live membership (a departed member's labels drop).
        self.cluster_instances = r.gauge(
            "bng_cluster_instances",
            "Cluster members by state (up / dead / pending)", ("state",))
        self.cluster_plan_epoch = r.gauge(
            "bng_cluster_plan_epoch",
            "Carve-plan epoch the coordinator is serving")
        self.cluster_free_blocks = r.gauge(
            "bng_cluster_free_blocks",
            "Unassigned carve blocks (headroom for joiners)")
        self.cluster_addresses = r.gauge(
            "bng_cluster_addresses",
            "Addresses carved to an instance", ("instance",))
        self.cluster_leases = r.gauge(
            "bng_cluster_leases",
            "Live leases held by an instance", ("instance",))
        self.cluster_steered = r.counter(
            "bng_cluster_steered_frames_total",
            "Front-door frames steered to an instance", ("instance",))
        self.cluster_recarves = r.counter(
            "bng_cluster_recarves_total",
            "Carve-plan changes applied (joins, leaves)")
        self.cluster_failovers = r.counter(
            "bng_cluster_failovers_total",
            "Standby promotions after a member death")
        self.cluster_shed = r.counter(
            "bng_cluster_shed_frames_total",
            "Front-door frames shed (steered at a dead member before "
            "its standby promoted)")
        self.cluster_refused_removes = r.counter(
            "bng_cluster_refused_removes_total",
            "Member removals refused for holding live leases "
            "(never-half-allocate)")
        # cluster control fabric (cluster/fabric): the membership lane's
        # own health — beat traffic, per-member suspicion state, verdict
        # and partition counts, and the transport's rejection reasons.
        # The RADIUS fan-out counters ride here too (the fabric owns
        # cross-member steering, and CoA relay is exactly that).
        self.fabric_beats_tx = r.counter(
            "bng_fabric_beats_tx_total",
            "Heartbeats this node sent over the fabric")
        self.fabric_beats_rx = r.counter(
            "bng_fabric_beats_rx_total",
            "Heartbeats this node absorbed from watched peers")
        self.fabric_member_state = r.gauge(
            "bng_fabric_member_state",
            "Detector state per watched member (1 at the current "
            "state's label, 0 elsewhere)", ("member", "state"))
        self.fabric_member_suspicion = r.gauge(
            "bng_fabric_member_suspicion",
            "Accusers currently voting against a member (quorum "
            "pressure; 0 = trusted by everyone)", ("member",))
        self.fabric_verdicts = r.counter(
            "bng_fabric_verdicts_total",
            "Detector verdicts issued by kind", ("verdict",))
        self.fabric_partitions = r.counter(
            "bng_fabric_partitions_observed_total",
            "Suspicion episodes that healed (beats resumed before any "
            "demotion): transient partitions survived")
        self.fabric_rx_rejected = r.counter(
            "bng_fabric_rx_rejected_total",
            "Fabric datagrams rejected on receive", ("reason",))
        # multi-box deployment (ISSUE 20): join bootstrap, the handoff
        # state-transfer lane, and host-loss group promotions
        self.fabric_join_retries = r.counter(
            "bng_fabric_join_retries_total",
            "Join announces re-sent by the capped-backoff bootstrap "
            "(first attempt not counted)")
        self.handoff_chunks = r.counter(
            "bng_handoff_chunks_total",
            "State-transfer chunks by disposition (rx / corrupt / dup "
            "/ orphan / tx / retx)", ("disposition",))
        self.handoff_transfers = r.counter(
            "bng_handoff_transfers_total",
            "State transfers by outcome (completed / rejected / "
            "resumed)", ("outcome",))
        self.cluster_host_losses = r.counter(
            "bng_cluster_host_losses_total",
            "Whole hosts declared lost (every member DOWN by quorum; "
            "surviving-host HA halves promoted as a group)")
        self.fabric_coa_relayed = r.counter(
            "bng_fabric_coa_relayed_total",
            "CoA/Disconnect requests relayed off the steered shard "
            "(the dynamic-authorization missteer corrector)")
        self.fabric_auth_shard = r.counter(
            "bng_fabric_auth_shard_total",
            "RADIUS authentications served per MAC-affine worker "
            "shard", ("worker",))
        # checkpoint/warm-restart subsystem (runtime/checkpoint.py +
        # control/statestore.py). The reference needs none of this — its
        # state survives in kernel-pinned maps; here snapshot health IS
        # restart safety, so it gets first-class observability.
        self.ckpt_saves = r.counter(
            "bng_ckpt_saves_total", "Checkpoints written successfully")
        self.ckpt_failures = r.counter(
            "bng_ckpt_failures_total", "Checkpoint save attempts that failed")
        self.ckpt_last_success_age = r.gauge(
            "bng_ckpt_last_success_age_seconds",
            "Seconds since the last successful checkpoint")
        self.ckpt_bytes = r.gauge(
            "bng_ckpt_bytes", "Size of the last written checkpoint")
        self.ckpt_seq = r.gauge(
            "bng_ckpt_seq", "Sequence number of the last written checkpoint")
        self.ckpt_duration = r.histogram(
            "bng_ckpt_duration_seconds",
            "Quiesce+snapshot+write duration per checkpoint", ("reason",))
        self.ckpt_restore_rows = r.gauge(
            "bng_ckpt_restore_rows",
            "Rows recovered per table by the startup restore", ("table",))
        self.ckpt_restores = r.counter(
            "bng_ckpt_restores_total",
            "Startup restore outcomes", ("outcome",))
        # chaos harness + invariant auditor (bng_tpu/chaos). The
        # reference has no analog — its correctness-under-failure story
        # is kernel-pinned maps; here recovery is code, and code that is
        # only trusted because these families prove it keeps passing.
        self.chaos_faults = r.counter(
            "bng_chaos_faults_injected_total",
            "Faults injected by the chaos harness", ("point", "kind"))
        self.chaos_scenarios = r.counter(
            "bng_chaos_scenarios_total",
            "Chaos scenarios run", ("result",))
        self.invariant_audits = r.counter(
            "bng_invariant_audits_total",
            "Cross-authority invariant audits run")
        self.invariant_violations = r.counter(
            "bng_invariant_violations_total",
            "Invariant violations found, by kind", ("kind",))
        self.invariant_last_epoch = r.gauge(
            "bng_invariant_last_audit_epoch",
            "Epoch (soak epoch or audit counter) of the last audit")
        self.invariant_last_violations = r.gauge(
            "bng_invariant_last_audit_violations",
            "Violations found by the most recent audit")
        # zero-downtime operations (control/fleet.py resize/rolling
        # restart, runtime/ops.py blue/green swap, control/opsctl.py).
        # The reference restarts for every capacity/config change; here
        # each transition is code with a rollback path, and these
        # families are how an operator proves a transition cost what the
        # runbook promised (PERF_NOTES §9).
        lbl_op = ("op",)
        self.ops_transitions = r.counter(
            "bng_ops_transitions_total",
            "Zero-downtime transitions by op and outcome",
            ("op", "outcome"))
        self.ops_transition_duration = r.histogram(
            "bng_ops_transition_duration_seconds",
            "End-to-end duration per transition", lbl_op)
        self.ops_quiesce_duration = r.histogram(
            "bng_ops_quiesce_duration_seconds",
            "Quiesce-barrier cost paid by a transition", lbl_op)
        self.ops_frames_deferred = r.counter(
            "bng_ops_frames_deferred_total",
            "In-flight frames retired early by a transition's quiesce",
            lbl_op)
        self.ops_leases_moved = r.counter(
            "bng_ops_leases_transferred_total",
            "Leases transferred between workers by a transition", lbl_op)
        self.ops_offers_moved = r.counter(
            "bng_ops_offers_transferred_total",
            "In-flight (un-ACKed) OFFERs carried across a transition",
            lbl_op)
        self.ops_delta_rows = r.counter(
            "bng_ops_delta_rows_replayed_total",
            "Host-mirror rows delta-replayed into the standby engine")
        self.ops_autoscaler_target = r.gauge(
            "bng_ops_autoscaler_target_workers",
            "Most recent worker count the autoscaler steered to")
        # telemetry subsystem (bng_tpu/telemetry): flight-recorder and
        # tracer health. The per-stage latency distributions themselves
        # export as bng_stage_latency_us via attach_telemetry (a live
        # view over the tracer's mergeable log-bucketed histograms — a
        # 5s scrape cannot reconstruct a p999).
        self.flight_dumps = r.counter(
            "bng_flight_dumps_total",
            "Flight-recorder dumps written, by anomaly trigger",
            ("reason",))
        self.telemetry_records = r.counter(
            "bng_telemetry_batch_records_total",
            "Per-batch flight records finalized by the tracer")
        self.telemetry_dropped = r.counter(
            "bng_telemetry_records_dropped_total",
            "Batch records dropped because the open-slot pool was full")
        self._stage_latency_export = None  # attach_telemetry wires it
        # SLO engine (telemetry/slo.py SLOMonitor): live burn-rate
        # verdicts over the per-stage budgets. The budget gauge exports
        # the configured line so dashboards draw target vs observed
        # from one scrape.
        lbl_stage = ("stage",)
        self.slo_breaches = r.counter(
            "bng_slo_breaches_total",
            "Burn-rate SLO breaches by stage (slo_breach flight dumps "
            "fire alongside)", lbl_stage)
        self.slo_burning = r.gauge(
            "bng_slo_burning_windows",
            "Consecutive over-budget windows per stage (resets on a "
            "healthy window)", lbl_stage)
        self.slo_window_p99 = r.gauge(
            "bng_slo_window_p99_us",
            "Windowed p99 per stage from the live SLO monitor",
            lbl_stage)
        self.slo_budget = r.gauge(
            "bng_slo_budget_us",
            "Configured per-stage p99 budget (amortized by the spec's "
            "per divisor)", lbl_stage)
        self.slo_ok = r.gauge(
            "bng_slo_ok", "1 while no stage is burning its SLO budget")
        # sharded-path telemetry (parallel/sharded.py ShardTelemetry):
        # per-shard verdict/punt counters + per-shard stage p99s — the
        # observability the 8-chip serving-path promotion gates on
        self.shard_frames = r.counter(
            "bng_shard_frames_total",
            "Real frames processed per shard by verdict",
            ("shard", "verdict"))
        self.shard_nat_punts = r.counter(
            "bng_shard_nat_punts_total",
            "NAT egress-miss punts per shard", ("shard",))
        self.shard_missteers = r.counter(
            "bng_shard_missteer_total",
            "Wrong-shard punts counted exactly at retire (a PASS lane "
            "whose affinity owner is a different shard): nonzero means "
            "steering drift, not slow-path load", ("shard",))
        self.shard_psum_hits = r.counter(
            "bng_shard_psum_dhcp_hits_total",
            "DHCP fast-path hits psum-reduced over the mesh")
        self.shard_stage_p99 = r.gauge(
            "bng_shard_stage_p99_us",
            "Per-shard stage p99 from the sharded-path histograms",
            ("shard", "stage"))
        # antispoof stage (ops/antispoof.py AST_* words). The reference
        # streams violations over a perf-event buffer; here the device
        # counts and the host logs rate-limited, so the counters are the
        # durable record a DDoS post-mortem reads.
        self.antispoof_allowed = r.counter(
            "bng_antispoof_allowed_total",
            "Access-side frames the source-validation stage passed")
        self.antispoof_dropped = r.counter(
            "bng_antispoof_dropped_total",
            "Frames dropped for a spoofed source address")
        self.antispoof_logged = r.counter(
            "bng_antispoof_logged_total",
            "Violations recorded by log-only mode (frame still passed)")
        self.antispoof_violations = r.counter(
            "bng_antispoof_violations_total",
            "Source-validation violations by address family",
            ("family",))
        # edge protection (bng_tpu/edge): device tap-match + next-hop
        # rewrite. Armed-tap and route-row gauges reconcile against the
        # control plane (the _audit_edge clauses); the counters are the
        # fast-path truth a lawful-intercept export is reconciled to.
        self.edge_taps_armed = r.gauge(
            "bng_edge_taps_armed", "Tap rows armed on the device")
        self.edge_routes_active = r.gauge(
            "bng_edge_routes_active", "Next-hop route rows on the device")
        self.edge_dirty_slots = r.gauge(
            "bng_edge_dirty_slots",
            "Edge table rows changed host-side awaiting the next drain")
        self.edge_mirrored = r.counter(
            "bng_edge_mirrored_total",
            "Frames flagged MIRROR by the device tap-match stage")
        self.edge_tap_filtered = r.counter(
            "bng_edge_tap_filtered_total",
            "Tapped-subscriber frames the DEVICE filter predicate "
            "excluded (never reached the host mirror path)")
        self.edge_route_rewrites = r.counter(
            "bng_edge_route_rewrites_total",
            "Upstream frames steered by the device next-hop rewrite")
        self.edge_route_misses = r.counter(
            "bng_edge_route_misses_total",
            "Upstream data frames with no route row (default path)")
        # lawful intercept (control/intercept.py): warrant book + export
        # stream health. export_errors nonzero is an evidentiary gap.
        self.intercept_warrants = r.gauge(
            "bng_intercept_warrants", "Warrants in the book")
        self.intercept_sessions = r.gauge(
            "bng_intercept_sessions_active",
            "Sessions currently matched to a warrant")
        self.intercept_iri = r.counter(
            "bng_intercept_iri_records_total",
            "IRI (intercept-related information) records exported")
        self.intercept_cc = r.counter(
            "bng_intercept_cc_records_total",
            "CC (content) records exported from mirrored frames")
        self.intercept_filtered = r.counter(
            "bng_intercept_filtered_total",
            "Mirrored frames excluded by host-side warrant filters")
        self.intercept_export_errors = r.counter(
            "bng_intercept_export_errors_total",
            "Delivery failures while exporting intercept records")

    # -- telemetry (bng_tpu/telemetry) ----------------------------------

    def attach_telemetry(self, tracer) -> None:
        """Register the bng_stage_latency_us family as a live view over
        the tracer's per-stage histograms and remember the tracer for
        collect_telemetry. Idempotent (re-attach swaps the tracer)."""
        if self._stage_latency_export is None:
            self._stage_latency_export = _StageLatencyExport(tracer)
            self.registry.register(self._stage_latency_export)
        else:
            self._stage_latency_export.tracer = tracer

    def collect_telemetry(self, tracer) -> None:
        """Tracer/recorder health -> counters (a 5s-scrape source)."""
        self.telemetry_records.set_total(tracer.seq)
        self.telemetry_dropped.set_total(tracer.records_dropped)
        rec = tracer.recorder
        if rec is not None:
            for reason, n in rec.triggers.items():
                self.flight_dumps.set_total(n, reason=reason)

    def collect_slo(self, monitor) -> None:
        """Live SLO monitor (telemetry/slo.py) -> bng_slo_* families.
        Reads one locked snapshot — never monitor internals — so the
        scrape thread can never observe a half-evaluated window."""
        snap = monitor.snapshot()
        self.slo_ok.set(1.0 if snap["ok"] else 0.0)
        for stage, limit in snap["budgets_us"].items():
            self.slo_budget.set(limit, stage=stage)
        for stage, n in snap["breaches"].items():
            self.slo_breaches.set_total(n, stage=stage)
        for stage, n in snap["burning"].items():
            self.slo_burning.set(n, stage=stage)
        for stage, p99 in snap["window_p99_us"].items():
            self.slo_window_p99.set(p99, stage=stage)

    def collect_wire(self, attachment, pump=None) -> None:
        """AF_XDP wire identity + pump accounting (runtime/xsk.py) ->
        bng_wire_* families. `attachment` is the WireAttachment the
        attach ladder returned (None = wire never requested); `pump`
        defaults to the attached socket's WirePump and may be passed
        explicitly for memory-rung loops (SimKernelRings)."""
        if attachment is None and pump is None:
            return
        if attachment is not None:
            from bng_tpu.runtime.xsk import (MODE_COPY, MODE_MEMORY,
                                             MODE_ZEROCOPY)

            for mode in (MODE_ZEROCOPY, MODE_COPY, MODE_MEMORY):
                self.wire_rung.set(1.0 if attachment.mode == mode else 0.0,
                                   mode=mode)
            if pump is None and attachment.xsk is not None:
                pump = attachment.xsk.wire_pump
        if pump is None:
            return
        from bng_tpu.runtime.xsk import WIRE_PUMPS

        for p in WIRE_PUMPS:
            self.wire_pump_path.set(1.0 if pump.path == p else 0.0, path=p)
        st = pump.pump_stats
        self.wire_frames.set_total(st["rx"], dir="rx")
        self.wire_frames.set_total(st["tx"], dir="tx")
        self.wire_filled.set_total(st["filled"])
        self.wire_completed.set_total(st["completed"])
        self.wire_rx_submit_fail.set_total(st["rx_submit_fail"])
        self.wire_tx_overflow.set_total(st["tx_overflow"])
        self.wire_tx_pending.set(pump.tx_pending())

    def collect_sharded(self, cluster) -> None:
        """Sharded-path telemetry (parallel/sharded.py ShardTelemetry)
        -> bng_shard_* families: per-shard verdict/punt counters + the
        per-shard stage p99s, from one snapshot."""
        snap = cluster.telemetry.snapshot()
        self.shard_psum_hits.set_total(snap["psum_dhcp_hits"])
        for i, sh in enumerate(snap["per_shard"]):
            shard = str(i)
            for verdict, n in sh["verdicts"].items():
                self.shard_frames.set_total(n, shard=shard,
                                            verdict=verdict)
            self.shard_nat_punts.set_total(sh["nat_punts"], shard=shard)
            self.shard_missteers.set_total(sh["missteers"], shard=shard)
            for stage, s in sh["stages"].items():
                self.shard_stage_p99.set(s["p99_us"], shard=shard,
                                         stage=stage)

    # -- collection (metrics.go:555-623) -------------------------------

    def collect_engine(self, engine_stats) -> None:
        """Pull device-side counters from runtime.engine.EngineStats."""
        d = engine_stats.dhcp
        names = DHCP_STAT_NAMES[: len(d)]
        vals = {n: int(v) for n, v in zip(names, d)}
        hits = vals.get("fastpath_hits", 0)
        misses = vals.get("fastpath_misses", 0)
        self.ebpf_fastpath_hits.set_total(hits)
        self.ebpf_fastpath_misses.set_total(misses)
        self.ebpf_errors.set_total(vals.get("errors", 0) + vals.get("malformed", 0))
        self.ebpf_cache_expired.set_total(vals.get("expired", 0))
        total = hits + misses
        if total:
            self.dhcp_cache_hit_rate.set(hits / total)

    def collect_pools(self, pool_stats: dict) -> None:
        """pool_stats: {pool_name: {"size": N, "allocated"|"used": M}}."""
        for name, st in pool_stats.items():
            size = st.get("size", 0)
            alloc = st.get("allocated", st.get("used", 0))
            self.pool_allocated.set(alloc, pool=name)
            self.pool_available.set(size - alloc, pool=name)
            if size:
                self.pool_utilization.set(alloc / size, pool=name)

    def collect_dhcp_server(self, server_stats) -> None:
        for msg in ("discover", "offer", "request", "ack", "nak", "release"):
            v = getattr(server_stats, msg, None)
            if v is not None:
                self.dhcp_requests_total.set_total(v, type=msg)
        v = getattr(server_stats, "pool_exhausted", None)
        if v:
            self.pool_exhausted.set_total(v, resource="dhcp_pool")

    def collect_exhaustion(self, dhcpv6=None, nat=None, fleet=None) -> None:
        """Mirror the per-subsystem exhaustion counters into
        bng_pool_exhausted_total (the v4 server's ride along in
        collect_dhcp_server). Nil-safe per component so one source call
        covers whatever the composition root actually built."""
        if dhcpv6 is not None:
            if dhcpv6.stats.addr_exhausted:
                self.pool_exhausted.set_total(dhcpv6.stats.addr_exhausted,
                                              resource="dhcp6_addr")
            if dhcpv6.stats.pd_exhausted:
                self.pool_exhausted.set_total(dhcpv6.stats.pd_exhausted,
                                              resource="dhcp6_pd")
        if nat is not None:
            if nat.exhausted["block"]:
                self.pool_exhausted.set_total(nat.exhausted["block"],
                                              resource="nat_block")
            if nat.exhausted["port"]:
                self.pool_exhausted.set_total(nat.exhausted["port"],
                                              resource="nat_port")
        if fleet is not None:
            # monotonic across resize/rolling-restart (per-worker stats
            # restart at 0; the fleet folds dead sets' counts)
            total = fleet.pool_exhausted_total()
            if total:
                self.pool_exhausted.set_total(total, resource="fleet_slice")

    def collect_garden(self, engine_stats) -> None:
        """Device walled-garden gate counters (EngineStats.garden)."""
        g = getattr(engine_stats, "garden", None)
        if g is None or len(g) < 2:
            return
        self.garden_gated_drops.set_total(int(g[0]))
        self.garden_allowed_hits.set_total(int(g[1]))

    def collect_antispoof(self, engine_stats) -> None:
        """Antispoof stage counters (EngineStats.spoof, AST_* order)."""
        s = getattr(engine_stats, "spoof", None)
        if s is None or len(s) < 6:
            return
        self.antispoof_allowed.set_total(int(s[0]))
        self.antispoof_dropped.set_total(int(s[1]))
        self.antispoof_logged.set_total(int(s[2]))
        self.antispoof_violations.set_total(int(s[3]), family="v4")
        self.antispoof_violations.set_total(int(s[4]), family="v6")

    def collect_edge(self, engine_stats, tables=None) -> None:
        """Edge-protection counters (EngineStats.edge, EST_* order) +
        table-occupancy gauges from the host surface (Engine.edge or a
        ShardedCluster, both expose tap_rows/route_rows)."""
        e = getattr(engine_stats, "edge", None)
        if e is None and isinstance(engine_stats, dict):
            e = engine_stats.get("edge")
        if e is not None and len(e) >= 4:
            self.edge_mirrored.set_total(int(e[0]))
            self.edge_tap_filtered.set_total(int(e[1]))
            self.edge_route_rewrites.set_total(int(e[2]))
            self.edge_route_misses.set_total(int(e[3]))
        if tables is not None:
            self.edge_taps_armed.set(len(tables.tap_rows()))
            self.edge_routes_active.set(len(tables.route_rows()))
            dirty = getattr(tables, "dirty_count", None)
            if dirty is not None:
                self.edge_dirty_slots.set(dirty())

    def collect_intercept(self, manager) -> None:
        """Warrant-book + export-stream health (InterceptManager.stats()
        or an equivalent dict)."""
        st = manager.stats() if callable(getattr(manager, "stats", None)) \
            else dict(manager)
        self.intercept_warrants.set(st.get("warrants", 0))
        self.intercept_sessions.set(st.get("active_sessions", 0))
        self.intercept_iri.set_total(st.get("iri_records", 0))
        self.intercept_cc.set_total(st.get("cc_records", 0))
        self.intercept_filtered.set_total(st.get("filtered", 0))
        self.intercept_export_errors.set_total(st.get("export_errors", 0))

    def collect_scheduler(self, scheduler) -> None:
        """TieredScheduler.stats_snapshot() -> bng_sched_* gauges/counters
        (the histograms are fed live at dispatch/retire by the scheduler
        itself — a 5s scrape cannot reconstruct a latency distribution)."""
        snap = scheduler.stats_snapshot()
        for lane in ("express", "bulk"):
            s = snap.get(lane)
            if not s:
                continue
            self.sched_queue_depth.set(s["queue_depth"], lane=lane)
            self.sched_inflight.set(s["inflight"], lane=lane)
            self.sched_dropped.set_total(s["dropped_overflow"], lane=lane)
        self.sched_oversize_dropped.set_total(snap.get("oversize_dropped", 0))
        self.sched_completions_evicted.set_total(
            snap.get("completions_dropped", 0))
        ex = snap.get("express") or {}
        self.express_program_dispatches.set_total(
            ex.get("aot_dispatches", 0), program="aot-express")
        self.express_program_dispatches.set_total(
            ex.get("jit_dispatches", 0), program="jit-full")
        self.express_aot_miss.set_total(ex.get("aot_misses", 0))
        for reason, n in (ex.get("fallbacks") or {}).items():
            self.express_fallback.set_total(n, reason=reason)
        dl = ex.get("devloop")
        if dl:
            self.express_program_dispatches.set_total(
                dl.get("dispatches", 0), program="devloop")

    def collect_fleet(self, fleet) -> None:
        """SlowPathFleet.stats_snapshot() -> bng_slowpath_* families."""
        snap = fleet.stats_snapshot()
        self.slowpath_workers.set(snap["workers"])
        self.slowpath_refills.set_total(snap["refills"])
        self.slowpath_fallback.set_total(snap["fallback_frames"])
        for i, w in enumerate(snap["per_worker"]):
            if not w:
                continue  # no batch has reached this worker yet
            wl = str(i)
            self.slowpath_worker_frames.set_total(w["frames"], worker=wl)
            self.slowpath_worker_errors.set_total(w["errors"], worker=wl)
            self.slowpath_worker_busy.set_total(w["busy_s"], worker=wl)
            self.slowpath_worker_leases.set(w["leases"], worker=wl)
            self.slowpath_slice_free.set(
                sum(w["slice_free"].values()), worker=wl)
        adm = snap["admission"]
        self.slowpath_admitted.set_total(adm["admitted"])
        for reason, n in adm["shed"].items():
            self.slowpath_shed.set_total(n, reason=reason)
        # RADIUS fan-out (ISSUE 19): per-shard auth affinity + the CoA
        # relay counter (requests that arrived missteered and were
        # routed to the owning shard)
        if "coa_relayed" in snap:
            self.fabric_coa_relayed.set_total(snap["coa_relayed"])
        for i, w in enumerate(snap["per_worker"]):
            if w and "auth_requests" in w:
                self.fabric_auth_shard.set_total(w["auth_requests"],
                                                 worker=str(i))

    def collect_fabric(self, fabric: dict) -> None:
        """ClusterCoordinator.status()['fabric'] -> bng_fabric_*.
        Member-labeled gauges reconcile against the current watch set
        (a forgotten peer drops its labels, same staleness rule as
        record_cluster)."""
        self.fabric_beats_tx.set_total(fabric.get("beats_tx", 0))
        self.fabric_beats_rx.set_total(fabric.get("beats_rx", 0))
        for verdict, n in (fabric.get("verdicts") or {}).items():
            self.fabric_verdicts.set_total(n, verdict=str(verdict))
        self.fabric_partitions.set_total(
            fabric.get("partitions_observed", 0))
        peers = fabric.get("peers") or {}
        for labels in self.fabric_member_suspicion.labeled():
            if labels["member"] not in peers:
                self.fabric_member_suspicion.remove(**labels)
        for labels in self.fabric_member_state.labeled():
            if labels["member"] not in peers:
                self.fabric_member_state.remove(**labels)
        for member, view in sorted(peers.items()):
            self.fabric_member_suspicion.set(
                len(view.get("accused_by", ())), member=str(member))
            for state in ("up", "suspect", "gray", "down"):
                self.fabric_member_state.set(
                    1 if view.get("state") == state else 0,
                    member=str(member), state=state)
        for reason in ("bad_sig", "replay", "skew", "malformed"):
            n = (fabric.get("transport") or {}).get(f"rx_{reason}")
            if n is not None:
                self.fabric_rx_rejected.set_total(n, reason=reason)
        if "handoff" in fabric:
            self.collect_handoff(fabric["handoff"])

    def collect_handoff(self, h: dict) -> None:
        """HandoffManager.stats() -> bng_handoff_* (one node's view:
        the coordinator counts tx/retx, a member counts rx/rejects —
        both expose the same families)."""
        for disp in ("rx", "corrupt", "dup", "orphan"):
            self.handoff_chunks.set_total(
                h.get(f"rx_{disp}" if disp != "rx" else "rx_chunks", 0),
                disposition=disp)
        self.handoff_chunks.set_total(h.get("tx_chunks", 0),
                                      disposition="tx")
        self.handoff_chunks.set_total(h.get("retx_chunks", 0),
                                      disposition="retx")
        self.handoff_transfers.set_total(h.get("completed", 0),
                                         outcome="completed")
        self.handoff_transfers.set_total(h.get("rejects", 0),
                                         outcome="rejected")
        self.handoff_transfers.set_total(h.get("resumes", 0),
                                         outcome="resumed")

    def record_member(self, status: dict) -> None:
        """MemberRuntime.status() -> the joiner-side families: the
        bootstrap retry counter and its handoff receive lane."""
        self.fabric_join_retries.set_total(status.get("join_retries", 0))
        if "handoff" in status:
            self.collect_handoff(status["handoff"])

    def collect_checkpoint(self, checkpointer, now: float | None = None) -> None:
        """PeriodicCheckpointer.stats -> bng_ckpt_* gauges/counters (the
        duration histogram is fed live at save time)."""
        s = checkpointer.stats
        self.ckpt_saves.set_total(s["saves"])
        self.ckpt_failures.set_total(s["failures"])
        # before the first success, age counts from checkpointer start:
        # a dir that has NEVER taken a save must trip staleness alerts,
        # not read as perpetually fresh
        origin = s["last_success_t"] or getattr(checkpointer,
                                                "started_at", 0.0)
        if origin:
            now = now if now is not None else time.time()
            self.ckpt_last_success_age.set(max(0.0, now - origin))
        if s["last_success_t"]:
            self.ckpt_bytes.set(s["last_bytes"])
            self.ckpt_seq.set(s["last_seq"])

    def record_audit(self, report, epoch=None) -> None:
        """Invariant AuditReport -> bng_invariant_* families. `epoch`
        defaults to the running audit count (a monotonic stamp either
        way, so alerting can detect a stalled auditor)."""
        self.invariant_audits.inc()
        by_kind = report.violations_by_kind()
        for kind, n in by_kind.items():
            self.invariant_violations.inc(n, kind=kind)
        self.invariant_last_violations.set(sum(by_kind.values()))
        self.invariant_last_epoch.set(
            epoch if epoch is not None else self.invariant_audits.value())

    def record_transition(self, report: dict) -> None:
        """One zero-downtime transition report (fleet resize / rolling
        restart / engine swap) -> bng_ops_* families. Fed at transition
        time, not by the 5s scrape — transitions are rare events whose
        distribution a poll could miss entirely."""
        op = str(report.get("op", "unknown"))
        self.ops_transitions.inc(op=op,
                                outcome=str(report.get("outcome", "unknown")))
        if "duration_s" in report:
            self.ops_transition_duration.observe(float(report["duration_s"]),
                                                 op=op)
        if "quiesce_s" in report:
            self.ops_quiesce_duration.observe(float(report["quiesce_s"]),
                                              op=op)
        if report.get("frames_deferred"):
            self.ops_frames_deferred.inc(report["frames_deferred"], op=op)
        if report.get("leases_moved"):
            self.ops_leases_moved.inc(report["leases_moved"], op=op)
        if report.get("offers_moved"):
            self.ops_offers_moved.inc(report["offers_moved"], op=op)
        if report.get("delta_rows"):
            self.ops_delta_rows.inc(report["delta_rows"])

    def record_fleet_blocked(self, blockers: list[str]) -> None:
        """The configured-but-degraded fleet gauge: one labeled 1 per
        blocking integration (empty list = nothing blocked). A blocker
        that disappears across a config reload must DROP its label —
        a stale 1 reads as still-degraded forever on the dashboard."""
        want = {str(b) for b in blockers}
        for labels in self.slowpath_fleet_blocked.labeled():
            if labels["blocker"] not in want:
                self.slowpath_fleet_blocked.remove(**labels)
        for b in want:
            self.slowpath_fleet_blocked.set(1, blocker=b)

    def record_cluster(self, status: dict) -> None:
        """ClusterCoordinator.status() -> bng_cluster_* families.
        Instance-labeled gauges reconcile against the live membership:
        a member that left drops its labels (same staleness rule as
        record_fleet_blocked)."""
        states = {"up": 0, "dead": 0, "pending": 0}
        leases: dict[str, float] = {}
        steered: dict[str, float] = {}
        addrs: dict[str, float] = {}
        for iid, m in status.get("members", {}).items():
            if m.get("pending"):
                states["pending"] += 1
            elif not m.get("alive", True):
                states["dead"] += 1
            else:
                states["up"] += 1
            if "leases" in m:
                leases[str(iid)] = float(m["leases"])
            steered[str(iid)] = float(m.get("steered", 0))
        plan = status.get("plan") or {}
        if plan:
            self.cluster_plan_epoch.set(plan.get("epoch", 0))
            self.cluster_free_blocks.set(plan.get("free_blocks", 0))
            addrs = {str(i): float(a)
                     for i, a in plan.get("members", {}).items()}
        for state, n in states.items():
            self.cluster_instances.set(n, state=state)
        for gauge, want in ((self.cluster_addresses, addrs),
                            (self.cluster_leases, leases)):
            for labels in gauge.labeled():
                if labels["instance"] not in want:
                    gauge.remove(**labels)
            for iid, v in want.items():
                gauge.set(v, instance=iid)
        for iid, v in steered.items():
            self.cluster_steered.set_total(v, instance=iid)
        self.cluster_recarves.set_total(status.get("recarves", 0))
        self.cluster_failovers.set_total(status.get("failovers", 0))
        self.cluster_shed.set_total(status.get("shed_frames", 0))
        self.cluster_refused_removes.set_total(
            status.get("refused_removes", 0))
        self.cluster_host_losses.set_total(status.get("host_losses", 0))
        if "fabric" in status:
            self.collect_fabric(status["fabric"])

    def record_restore(self, rows: dict, outcome: str = "ok") -> None:
        """Startup-restore result -> bng_ckpt_restore_rows / restores."""
        self.ckpt_restores.inc(outcome=outcome)
        for table, n in rows.items():
            self.ckpt_restore_rows.set(n, table=table)

    def collect_dns(self, server_stats: dict, resolver_stats: dict) -> None:
        """DNSServer.stats + Resolver.stats() -> bng_dns_* families."""
        self.dns_queries.set_total(server_stats.get("served", 0),
                                   outcome="served")
        self.dns_queries.set_total(server_stats.get("bad_packets", 0),
                                   outcome="bad_packet")
        self.dns_queries.set_total(server_stats.get("server_errors", 0),
                                   outcome="error")
        self.dns_overloaded.set_total(server_stats.get("overloaded", 0))
        hits = resolver_stats.get("cache_hits", 0)
        total = resolver_stats.get("queries", 0)
        if total:
            self.dns_cache_hit_rate.set(hits / total)

    def expose(self) -> str:
        return self.registry.expose()


class _StageLatencyExport:
    """bng_stage_latency_us: Prometheus-histogram rendering of the
    telemetry tracer's per-stage log-bucketed histograms (telemetry/
    hist.py), materialized at expose time. The native buckets (8 per
    octave, <=12.5% relative error) are re-binned onto a fixed 1-2-5
    microsecond ladder so the exposition stays a bounded ~20 lines per
    stage while percentile math still happens on the full-resolution
    histograms (bench stage_breakdown, trace CLI)."""

    name = "bng_stage_latency_us"
    BOUNDS = (1, 2, 5, 10, 20, 50, 100, 200, 500,
              1_000, 2_000, 5_000, 10_000, 50_000, 100_000, 1_000_000)

    def __init__(self, tracer):
        self.tracer = tracer

    def collect(self) -> list[str]:
        out = [f"# HELP {self.name} Per-stage packet-lifecycle latency "
               f"(telemetry tracer)",
               f"# TYPE {self.name} histogram"]
        from bng_tpu.telemetry.spans import STAGE_NAMES

        for i, h in enumerate(self.tracer.hists):
            if not h.n:
                continue
            stage = STAGE_NAMES[i]
            for ub in self.BOUNDS:
                out.append(f'{self.name}_bucket{{stage="{stage}",'
                           f'le="{ub}"}} {h.cumulative_le(float(ub))}')
            out.append(f'{self.name}_bucket{{stage="{stage}",'
                       f'le="+Inf"}} {h.n}')
            out.append(f'{self.name}_sum{{stage="{stage}"}} '
                       f'{round(h.sum_us, 3)}')
            out.append(f'{self.name}_count{{stage="{stage}"}} {h.n}')
        return out


class MetricsCollector:
    """Background collector loop (metrics.go:625) + HTTP /metrics server."""

    def __init__(self, metrics: BNGMetrics, interval: float = 5.0):
        self.metrics = metrics
        self.interval = interval
        self._sources: list = []  # callables () -> None that update metrics
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._httpd = None
        self.source_errors = 0
        self._source_err_log = ErrorLog(
            "metrics", "metrics source failed; its families go stale")

    def add_source(self, fn) -> None:
        self._sources.append(fn)

    def collect_once(self) -> None:
        for fn in self._sources:
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — one bad source must
                # not stop the scrape, but a source that fails every 5s
                # forever is exactly how dashboards go quietly stale
                self.source_errors += 1
                self._source_err_log.report(
                    e, source=getattr(fn, "__qualname__", repr(fn)))

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        from bng_tpu.analysis.sanitize import ctx_enter

        ctx_enter("scrape")
        while not self._stop.wait(self.interval):
            self.collect_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._httpd:
            self._httpd.shutdown()

    def serve_http(self, port: int = 9090, host: str = "127.0.0.1") -> int:
        """Expose /metrics; returns the bound port (0 picks a free one)."""
        import http.server

        metrics = self.metrics

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                from bng_tpu.analysis.sanitize import ctx_enter

                ctx_enter("scrape")
                if self.path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = metrics.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port), Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self._httpd.server_address[1]
