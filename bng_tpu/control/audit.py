"""Security/compliance audit pipeline.

Parity: pkg/audit — Event/EventType/Severity (types.go:8-180), async
Logger with severity filter + buffered worker (logger.go:15-628), Storage
interface + query (logger.go:89-133), exporters: syslog RFC 5424
(export.go:17-141), IPFIX-ish binary NAT records (export.go:143-315),
JSON lines (export.go:317-404), rotating file with gzip + retention
(rotation.go:19-413), RetentionManager with legal holds + standard ISP
retention presets (retention.go:9-370).
"""

from __future__ import annotations

import gzip
import json
import os
import queue
import struct
import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum, IntEnum


class Severity(IntEnum):
    DEBUG = 0
    INFO = 1
    NOTICE = 2
    WARNING = 3
    ERROR = 4
    CRITICAL = 5


class EventType(str, Enum):
    # Session (types.go:13-16)
    SESSION_START = "SESSION_START"
    SESSION_STOP = "SESSION_STOP"
    SESSION_UPDATE = "SESSION_UPDATE"
    SESSION_TIMEOUT = "SESSION_TIMEOUT"
    # Auth (types.go:19-21)
    AUTH_SUCCESS = "AUTH_SUCCESS"
    AUTH_FAILURE = "AUTH_FAILURE"
    AUTH_REJECT = "AUTH_REJECT"
    # DHCP (types.go:24-30)
    DHCP_DISCOVER = "DHCP_DISCOVER"
    DHCP_OFFER = "DHCP_OFFER"
    DHCP_REQUEST = "DHCP_REQUEST"
    DHCP_ACK = "DHCP_ACK"
    DHCP_NAK = "DHCP_NAK"
    DHCP_RELEASE = "DHCP_RELEASE"
    DHCP_DECLINE = "DHCP_DECLINE"
    # NAT (types.go:33-34)
    NAT_MAPPING = "NAT_MAPPING"
    NAT_EXPIRY = "NAT_EXPIRY"
    # Policy (types.go:37-38)
    POLICY_APPLY = "POLICY_APPLY"
    POLICY_VIOLATION = "POLICY_VIOLATION"
    # Walled garden (types.go:41-43)
    WALLED_GARDEN_ADD = "WALLED_GARDEN_ADD"
    WALLED_GARDEN_RELEASE = "WALLED_GARDEN_RELEASE"
    WALLED_GARDEN_BLOCK = "WALLED_GARDEN_BLOCK"
    # Admin / system (types.go:46-53)
    CONFIG_CHANGE = "CONFIG_CHANGE"
    ADMIN_ACTION = "ADMIN_ACTION"
    SYSTEM_START = "SYSTEM_START"
    SYSTEM_STOP = "SYSTEM_STOP"
    SYSTEM_ERROR = "SYSTEM_ERROR"
    # Device registration (types.go:56-59)
    DEVICE_REGISTRATION_ATTEMPT = "DEVICE_REGISTRATION_ATTEMPT"
    DEVICE_REGISTRATION_SUCCESS = "DEVICE_REGISTRATION_SUCCESS"
    DEVICE_REGISTRATION_FAILURE = "DEVICE_REGISTRATION_FAILURE"
    DEVICE_DEREGISTRATION = "DEVICE_DEREGISTRATION"
    # API security (types.go:62-66)
    API_AUTH_ATTEMPT = "API_AUTH_ATTEMPT"
    API_AUTH_SUCCESS = "API_AUTH_SUCCESS"
    API_AUTH_FAILURE = "API_AUTH_FAILURE"
    API_ACCESS_DENIED = "API_ACCESS_DENIED"
    API_RATE_LIMITED = "API_RATE_LIMITED"
    # Suspicious activity (types.go:69-74)
    SUSPICIOUS_ACTIVITY = "SUSPICIOUS_ACTIVITY"
    BRUTE_FORCE_DETECTED = "BRUTE_FORCE_DETECTED"
    UNAUTHORIZED_ACCESS = "UNAUTHORIZED_ACCESS"
    MAC_SPOOF_DETECTED = "MAC_SPOOF_DETECTED"
    IP_SPOOF_DETECTED = "IP_SPOOF_DETECTED"
    DHCP_STARVATION_ATTEMPT = "DHCP_STARVATION_ATTEMPT"
    # Resources (types.go:77-79)
    RESOURCE_ALLOCATED = "RESOURCE_ALLOCATED"
    RESOURCE_DEALLOCATED = "RESOURCE_DEALLOCATED"
    RESOURCE_EXHAUSTED = "RESOURCE_EXHAUSTED"


_CATEGORY_PREFIXES = [
    ("SESSION", "session"), ("AUTH", "auth"), ("DHCP", "dhcp"),
    ("NAT", "nat"), ("POLICY", "policy"), ("WALLED_GARDEN", "walledgarden"),
    ("CONFIG", "admin"), ("ADMIN", "admin"), ("SYSTEM", "system"),
    ("DEVICE", "device"), ("API", "api"), ("RESOURCE", "resource"),
]


def event_category(event_type: EventType) -> str:
    """Map event type -> retention category (retention.go:80-97 spirit)."""
    name = event_type.value
    for prefix, cat in _CATEGORY_PREFIXES:
        if name.startswith(prefix):
            return cat
    return "security"


@dataclass
class Event:
    event_type: EventType
    severity: Severity = Severity.INFO
    id: str = ""
    timestamp: float = 0.0
    subscriber_id: str = ""
    session_id: str = ""
    username: str = ""
    mac: str = ""
    ip: str = ""
    nat_public_ip: str = ""
    nat_public_port: int = 0
    nat_private_port: int = 0
    protocol: int = 0
    source: str = ""  # emitting component
    message: str = ""
    details: dict = field(default_factory=dict)

    @property
    def category(self) -> str:
        return event_category(self.event_type)


@dataclass
class AuditQuery:
    """logger.go:110-133."""

    start_time: float = 0.0
    end_time: float = 0.0
    event_types: list[EventType] = field(default_factory=list)
    subscriber_id: str = ""
    session_id: str = ""
    mac: str = ""
    ip: str = ""
    min_severity: Severity = Severity.DEBUG
    limit: int = 0


class MemoryStorage:
    """In-memory Storage impl (the reference's test double; Storage iface
    logger.go:89-108)."""

    def __init__(self, max_events: int = 100_000):
        self.max_events = max_events
        self._lock = threading.Lock()
        self._events: list[Event] = []

    def store(self, event: Event) -> None:
        with self._lock:
            self._events.append(event)
            if len(self._events) > self.max_events:
                del self._events[: len(self._events) - self.max_events]

    def query(self, q: AuditQuery) -> list[Event]:
        with self._lock:
            out = []
            for e in self._events:
                if q.start_time and e.timestamp < q.start_time:
                    continue
                if q.end_time and e.timestamp >= q.end_time:
                    continue
                if q.event_types and e.event_type not in q.event_types:
                    continue
                if q.subscriber_id and e.subscriber_id != q.subscriber_id:
                    continue
                if q.session_id and e.session_id != q.session_id:
                    continue
                if q.mac and e.mac.lower() != q.mac.lower():
                    continue
                if q.ip and e.ip != q.ip:
                    continue
                if e.severity < q.min_severity:
                    continue
                out.append(e)
                if q.limit and len(out) >= q.limit:
                    break
            return out

    def delete_before(self, cutoff: float, category: str = "",
                      keep=None) -> int:
        """Retention enforcement; keep(event) -> True preserves (legal hold)."""
        with self._lock:
            kept, dropped = [], 0
            for e in self._events:
                expired = e.timestamp < cutoff and \
                    (not category or e.category == category)
                if expired and not (keep and keep(e)):
                    dropped += 1
                else:
                    kept.append(e)
            self._events = kept
            return dropped

    def count(self) -> int:
        with self._lock:
            return len(self._events)


class AuditLogger:
    """Async audit logger (logger.go:15-628): buffered queue, worker
    thread, severity filter, storage + fan-out to exporters."""

    def __init__(self, storage=None, min_severity: Severity = Severity.INFO,
                 buffer_size: int = 10_000, clock=time.time,
                 async_mode: bool = True):
        self.storage = storage if storage is not None else MemoryStorage()
        self.min_severity = min_severity
        self._clock = clock
        self._async = async_mode
        self._queue: queue.Queue = queue.Queue(maxsize=buffer_size)
        self._exporters: list = []
        self._worker: threading.Thread | None = None
        self._running = False
        self._lock = threading.Lock()
        self.stats = {"logged": 0, "dropped": 0, "filtered": 0,
                      "export_errors": 0}

    def add_exporter(self, exporter) -> None:
        self._exporters.append(exporter)

    def start(self) -> None:
        if not self._async or self._running:
            return
        self._running = True
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def stop(self) -> None:
        if self._running:
            self._running = False
            self._queue.put(None)
            self._worker.join(timeout=5)
        self.flush()

    def flush(self) -> None:
        while True:
            try:
                ev = self._queue.get_nowait()
            except queue.Empty:
                return
            if ev is not None:
                self._store_and_export(ev)

    # -- logging entry points (logger.go:265-392) ----------------------

    def log_event(self, event: Event) -> None:
        if event.severity < self.min_severity:
            with self._lock:
                self.stats["filtered"] += 1
            return
        event.id = event.id or uuid.uuid4().hex
        event.timestamp = event.timestamp or self._clock()
        if self._async and self._running:
            try:
                self._queue.put_nowait(event)
            except queue.Full:
                with self._lock:
                    self.stats["dropped"] += 1
        else:
            self._store_and_export(event)

    def log(self, event_type: EventType, severity: Severity = Severity.INFO,
            **fields) -> None:
        self.log_event(Event(event_type=event_type, severity=severity, **fields))

    def log_session_start(self, **fields) -> None:
        self.log(EventType.SESSION_START, **fields)

    def log_session_stop(self, **fields) -> None:
        self.log(EventType.SESSION_STOP, **fields)

    def log_nat_mapping(self, **fields) -> None:
        self.log(EventType.NAT_MAPPING, **fields)

    def log_auth(self, success: bool, **fields) -> None:
        self.log(EventType.AUTH_SUCCESS if success else EventType.AUTH_FAILURE,
                 Severity.INFO if success else Severity.WARNING, **fields)

    def log_suspicious(self, threat_type: str, score: int, **fields) -> None:
        details = fields.pop("details", {})
        details.update({"threat_type": threat_type, "score": score})
        self.log(EventType.SUSPICIOUS_ACTIVITY, Severity.WARNING,
                 details=details, **fields)

    def log_config_change(self, **fields) -> None:
        self.log(EventType.CONFIG_CHANGE, Severity.NOTICE, **fields)

    # -- internals ------------------------------------------------------

    def _drain(self) -> None:
        while self._running:
            ev = self._queue.get()
            if ev is None:
                break
            self._store_and_export(ev)

    def _store_and_export(self, event: Event) -> None:
        self.storage.store(event)
        with self._lock:
            self.stats["logged"] += 1
        for exp in self._exporters:
            try:
                exp.export(event)
            except Exception:
                with self._lock:
                    self.stats["export_errors"] += 1


# -- exporters ----------------------------------------------------------

def event_to_dict(event: Event) -> dict:
    d = {k: v for k, v in event.__dict__.items() if v not in ("", 0, {}, None)}
    d["event_type"] = event.event_type.value
    d["severity"] = event.severity.name
    d["timestamp"] = event.timestamp
    return d


class SyslogAuditExporter:
    """RFC 5424 structured-data lines to a sink (export.go:17-141)."""

    _SEV_MAP = {Severity.DEBUG: 7, Severity.INFO: 6, Severity.NOTICE: 5,
                Severity.WARNING: 4, Severity.ERROR: 3, Severity.CRITICAL: 2}

    def __init__(self, sink, facility: int = 13, hostname: str = "bng",
                 app: str = "bng-audit"):
        self._sink = sink
        self.facility = facility
        self.hostname = hostname
        self.app = app

    def name(self) -> str:
        return "syslog"

    def export(self, event: Event) -> None:
        pri = self.facility * 8 + self._SEV_MAP[event.severity]
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(event.timestamp))
        sd = (f'[bng@32473 type="{event.event_type.value}" '
              f'subscriber="{event.subscriber_id}" session="{event.session_id}" '
              f'mac="{event.mac}" ip="{event.ip}"]')
        line = (f"<{pri}>1 {ts} {self.hostname} {self.app} - {event.id} "
                f"{sd} {event.message}")
        self._sink(line.encode())


class IPFIXAuditExporter:
    """Binary NAT-record export (export.go:143-315): fixed 32-byte record
    per NAT event, big-endian — timestamp_ms u64, private ip u32,
    private port u16, public ip u32, public port u16, protocol u8,
    event u8 (1=create 2=delete), subscriber-id FNV-1a u32, pad u64."""

    RECORD = struct.Struct(">QIHIHBBIQ")

    def __init__(self, sink):
        self._sink = sink

    def name(self) -> str:
        return "ipfix"

    def export(self, event: Event) -> None:
        if event.event_type not in (EventType.NAT_MAPPING, EventType.NAT_EXPIRY):
            return
        from bng_tpu.utils.net import fnv1a32, ip_to_u32
        self._sink(self.RECORD.pack(
            int(event.timestamp * 1000),
            ip_to_u32(event.ip) if event.ip else 0,
            event.nat_private_port & 0xFFFF,
            ip_to_u32(event.nat_public_ip) if event.nat_public_ip else 0,
            event.nat_public_port & 0xFFFF,
            event.protocol & 0xFF,
            1 if event.event_type == EventType.NAT_MAPPING else 2,
            fnv1a32(event.subscriber_id.encode()) if event.subscriber_id else 0,
            0))


class JSONAuditExporter:
    """JSON-lines to a sink (export.go:317-404)."""

    def __init__(self, sink):
        self._sink = sink

    def name(self) -> str:
        return "json"

    def export(self, event: Event) -> None:
        self._sink((json.dumps(event_to_dict(event), separators=(",", ":"),
                               default=str) + "\n").encode())


class RotatingFileExporter:
    """Size-based rotation with optional gzip + retention sweep
    (rotation.go:19-413)."""

    def __init__(self, path: str, max_bytes: int = 10 * 1024 * 1024,
                 max_files: int = 10, compress: bool = True, clock=time.time):
        self.path = path
        self.max_bytes = max_bytes
        self.max_files = max_files
        self.compress = compress
        self._clock = clock
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "ab")

    def name(self) -> str:
        return "rotating-file"

    def export(self, event: Event) -> None:
        line = (json.dumps(event_to_dict(event), separators=(",", ":"),
                           default=str) + "\n").encode()
        with self._lock:
            self._fh.write(line)
            self._fh.flush()
            if self._fh.tell() >= self.max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        self._fh.close()
        stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime(self._clock()))
        rotated = f"{self.path}.{stamp}.{uuid.uuid4().hex[:6]}"
        os.rename(self.path, rotated)
        if self.compress:
            with open(rotated, "rb") as src, gzip.open(rotated + ".gz", "wb") as dst:
                dst.write(src.read())
            os.remove(rotated)
        self._fh = open(self.path, "ab")
        self._enforce_retention()

    def _enforce_retention(self) -> None:
        base = os.path.basename(self.path)
        d = os.path.dirname(self.path) or "."
        rotated = sorted(f for f in os.listdir(d)
                         if f.startswith(base + ".") and f != base)
        while len(rotated) > self.max_files:
            os.remove(os.path.join(d, rotated.pop(0)))

    def close(self) -> None:
        with self._lock:
            self._fh.close()


# -- retention ----------------------------------------------------------

@dataclass
class LegalHold:
    """retention.go:26-41: preserve matching events regardless of policy."""

    id: str
    reason: str = ""
    created_at: float = 0.0
    expires_at: float = 0.0  # 0 = indefinite
    subscriber_id: str = ""
    session_id: str = ""
    mac: str = ""
    ip: str = ""
    event_types: list[EventType] = field(default_factory=list)


def standard_retention_policies() -> dict[str, int]:
    """Standard ISP retention presets in days (retention.go:304-345)."""
    return {
        "session": 365, "nat": 365, "auth": 365, "dhcp": 90, "admin": 730,
        "policy": 365, "walledgarden": 90, "system": 30, "device": 365,
        "api": 365, "security": 730, "resource": 365,
    }


class RetentionManager:
    """Per-category retention + legal holds (retention.go:9-302)."""

    def __init__(self, default_days: int = 365,
                 category_days: dict[str, int] | None = None, clock=time.time):
        self.default_days = default_days
        self.category_days = dict(category_days or standard_retention_policies())
        self._clock = clock
        self._lock = threading.Lock()
        self._holds: dict[str, LegalHold] = {}

    def get_retention(self, category: str) -> int:
        return self.category_days.get(category, self.default_days)

    def set_category_retention(self, category: str, days: int) -> None:
        self.category_days[category] = days

    def add_legal_hold(self, hold: LegalHold) -> None:
        with self._lock:
            hold.created_at = hold.created_at or self._clock()
            self._holds[hold.id] = hold

    def remove_legal_hold(self, hold_id: str) -> bool:
        with self._lock:
            return self._holds.pop(hold_id, None) is not None

    def legal_holds(self) -> list[LegalHold]:
        with self._lock:
            return list(self._holds.values())

    def is_under_legal_hold(self, event: Event) -> bool:
        """retention.go:155-263."""
        now = self._clock()
        with self._lock:
            holds = list(self._holds.values())
        for h in holds:
            if h.expires_at and now >= h.expires_at:
                continue
            if self._matches(event, h):
                return True
        return False

    @staticmethod
    def _matches(e: Event, h: LegalHold) -> bool:
        if h.subscriber_id and e.subscriber_id != h.subscriber_id:
            return False
        if h.session_id and e.session_id != h.session_id:
            return False
        if h.mac and e.mac.lower() != h.mac.lower():
            return False
        if h.ip and e.ip != h.ip:
            return False
        if h.event_types and e.event_type not in h.event_types:
            return False
        # A hold with no selectors holds everything.
        return True

    def cleanup_expired_holds(self) -> int:
        now = self._clock()
        with self._lock:
            dead = [k for k, h in self._holds.items()
                    if h.expires_at and now >= h.expires_at]
            for k in dead:
                del self._holds[k]
            return len(dead)

    def enforce(self, storage: MemoryStorage) -> int:
        """Sweep expired events out of storage, honoring legal holds."""
        now = self._clock()
        dropped = 0
        for category in set(list(self.category_days) + ["security"]):
            cutoff = now - self.get_retention(category) * 86400
            dropped += storage.delete_before(cutoff, category,
                                             keep=self.is_under_legal_hold)
        return dropped

    def policy_summary(self) -> dict[str, int]:
        return dict(self.category_days)
