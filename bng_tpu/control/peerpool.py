"""Peer-to-peer distributed IP pool — no central server (Demo G).

Parity: pkg/pool/peer.go — PeerPool (:23), Allocate owner-or-forward
(:230-368), rendezvous/HRW owner selection + ranked failover
(:723-776), health-check loop (:541-631), HTTP API /allocate /release
/status /get (:633-721; here the transport is injectable — production
rides DCN/HTTP, tests wire peers directly).

The same rendezvous placement decides which chip's HBM shard owns a
subscriber entry (bng_tpu.parallel.hashring is the shared module).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

from bng_tpu.parallel.hashring import rendezvous_ranked


class PeerPoolError(Exception):
    pass


@dataclass
class PeerStatus:
    node_id: str
    healthy: bool = True
    last_seen: float = 0.0
    consecutive_failures: int = 0


@dataclass
class PoolRange:
    """The shared range every peer agrees on (peer.go config)."""

    network: int  # host-order base
    size: int  # usable addresses


class PeerPool:
    """One node's view of the shared pool.

    Allocation protocol (peer.go:230-368): the subscriber's owner node is
    the top rendezvous rank among HEALTHY peers; if we are the owner we
    allocate locally, otherwise we forward to the owner. On owner failure
    we fall through the ranked list.
    """

    def __init__(self, node_id: str, peers: list[str], pool: PoolRange,
                 transport: Callable[[str], "PeerPool"] | None = None,
                 health_failure_threshold: int = 3):
        self.node_id = node_id
        self.pool = pool
        self.transport = transport
        self.peers: dict[str, PeerStatus] = {
            p: PeerStatus(p) for p in peers if p != node_id}
        self.health_failure_threshold = health_failure_threshold
        # local slice of the shared pool: ip -> subscriber
        self.allocations: dict[int, str] = {}
        self.by_subscriber: dict[str, int] = {}
        self.stats = {"local_allocs": 0, "forwarded": 0, "failovers": 0,
                      "releases": 0, "conflicts": 0}

    # ---- membership ----
    def _healthy_nodes(self) -> list[str]:
        nodes = [self.node_id]
        nodes += [p.node_id for p in self.peers.values() if p.healthy]
        return sorted(nodes)

    def owner_ranked(self, subscriber_id: str) -> list[str]:
        """Ranked owner list over healthy nodes (peer.go:745-776)."""
        return rendezvous_ranked(self._healthy_nodes(), subscriber_id)

    # ---- the API surface (/allocate /release /get /status) ----
    def allocate(self, subscriber_id: str) -> int:
        """Owner-or-forward with ranked failover (peer.go:230-368)."""
        ranked = self.owner_ranked(subscriber_id)
        last_err: Exception | None = None
        for rank, node in enumerate(ranked):
            if rank > 0:
                self.stats["failovers"] += 1
            if node == self.node_id:
                return self._allocate_local(subscriber_id)
            try:
                peer = self._dial(node)
                self.stats["forwarded"] += 1
                return peer._allocate_local(subscriber_id)
            except (ConnectionError, PeerPoolError) as e:
                self._mark_failure(node)
                last_err = e
        raise PeerPoolError(f"no healthy owner for {subscriber_id}: {last_err}")

    def _allocate_local(self, subscriber_id: str) -> int:
        existing = self.by_subscriber.get(subscriber_id)
        if existing is not None:
            return existing
        # deterministic candidate scan from hash(subscriber), bounded
        # linear probe — the hashring allocation discipline
        # (pkg/nexus/client.go:544-577) applied to the peer's local slice
        from bng_tpu.parallel.hashring import hashring_allocate

        idx = hashring_allocate(subscriber_id, self.pool.size,
                                lambda i: (self.pool.network + 1 + i)
                                not in self.allocations)
        if idx is None and len(self.allocations) < self.pool.size:
            # hash candidates exhausted but the pool isn't: linear sweep
            # (small pools can alias all 1024 hash candidates)
            idx = next((i for i in range(self.pool.size)
                        if (self.pool.network + 1 + i) not in self.allocations),
                       None)
        if idx is None:
            raise PeerPoolError("pool exhausted")
        ip = self.pool.network + 1 + idx
        self.allocations[ip] = subscriber_id
        self.by_subscriber[subscriber_id] = ip
        self.stats["local_allocs"] += 1
        return ip

    def release(self, subscriber_id: str) -> bool:
        ranked = self.owner_ranked(subscriber_id)
        for node in ranked:
            if node == self.node_id:
                return self._release_local(subscriber_id)
            try:
                return self._dial(node)._release_local(subscriber_id)
            except (ConnectionError, PeerPoolError):
                self._mark_failure(node)
        return False

    def _release_local(self, subscriber_id: str) -> bool:
        ip = self.by_subscriber.pop(subscriber_id, None)
        if ip is None:
            return False
        self.allocations.pop(ip, None)
        self.stats["releases"] += 1
        return True

    def get(self, subscriber_id: str) -> int | None:
        """Read from any node: check local, then the owner."""
        ip = self.by_subscriber.get(subscriber_id)
        if ip is not None:
            return ip
        for node in self.owner_ranked(subscriber_id):
            if node == self.node_id:
                continue
            try:
                got = self._dial(node).by_subscriber.get(subscriber_id)
                if got is not None:
                    return got
            except (ConnectionError, PeerPoolError):
                self._mark_failure(node)
        return None

    def status(self) -> dict:
        return {
            "node_id": self.node_id,
            "allocated": len(self.allocations),
            "pool_size": self.pool.size,
            "healthy_peers": len([p for p in self.peers.values() if p.healthy]),
            "stats": dict(self.stats),
        }

    # ---- health (peer.go:541-631) ----
    def _dial(self, node: str) -> "PeerPool":
        if self.transport is None:
            raise ConnectionError("no transport")
        return self.transport(node)

    def _mark_failure(self, node: str) -> None:
        st = self.peers.get(node)
        if st is None:
            return
        st.consecutive_failures += 1
        if st.consecutive_failures >= self.health_failure_threshold:
            st.healthy = False

    def health_check(self, now: float = 0.0) -> None:
        """Probe every peer; recover marks on success."""
        for st in self.peers.values():
            try:
                self._dial(st.node_id).status()
                st.healthy = True
                st.consecutive_failures = 0
                st.last_seen = now
            except (ConnectionError, PeerPoolError):
                st.consecutive_failures += 1
                if st.consecutive_failures >= self.health_failure_threshold:
                    st.healthy = False

    def reconcile(self) -> int:
        """After a heal, pull peers' allocations for our owned keys and
        drop double-allocations (newest loses; the CRDT-merge role)."""
        conflicts = 0
        for st in self.peers.values():
            if not st.healthy:
                continue
            try:
                peer = self._dial(st.node_id)
            except (ConnectionError, PeerPoolError):
                continue
            for ip, sub in list(peer.allocations.items()):
                mine = self.allocations.get(ip)
                if mine is not None and mine != sub:
                    # both handed out the same ip during a partition
                    owner = self.owner_ranked(sub)[0]
                    if owner == self.node_id:
                        peer._release_local(sub)
                    else:
                        self._release_local(mine)
                    conflicts += 1
                    self.stats["conflicts"] += 1
        return conflicts
