"""Lawful intercept: warrants, session matching, IRI/CC records, exporters.

Parity: pkg/intercept — Warrant model + delivery methods (types.go:40-120),
Manager with AddWarrant/validate (manager.go:142-233, :467-496), target
indexes + MatchSession (manager.go:260-301, :498-534), RecordIRI/RecordCC
with port/protocol/dest-IP filters (manager.go:303-379), intercept session
lifecycle (manager.go:381-458), ETSI TS 102 232 HI2/HI3 PDU export
(exporter.go:191-317), JSON and syslog exporters (exporter.go:319-513),
warrant expiry.

Exporters here write to pluggable sinks (callables); TLSDeliverySink is
the production sink — a persistent pinned-TLS channel to the LEA
collector (the exporter.go:191-317 TLS delivery role) with bounded
buffering and reconnect-on-failure, built on control.ztp_tls.
"""

from __future__ import annotations

import json
import struct
import threading
import time
import uuid
from dataclasses import dataclass, field
from enum import Enum


class WarrantType(str, Enum):
    SUBSCRIBER = "subscriber"
    IP = "ip"
    MAC = "mac"
    USERNAME = "username"


class WarrantStatus(str, Enum):
    PENDING = "pending"
    ACTIVE = "active"
    SUSPENDED = "suspended"
    EXPIRED = "expired"
    REVOKED = "revoked"


class DeliveryMethod(str, Enum):
    ETSI = "ETSI"
    PCAP = "PCAP"
    SYSLOG = "SYSLOG"
    JSON_HTTPS = "JSON_HTTPS"


class IRIEventType(str, Enum):
    SESSION_START = "session_start"
    SESSION_STOP = "session_stop"
    SESSION_UPDATE = "session_update"
    ADDRESS_ASSIGNED = "address_assigned"
    ADDRESS_RELEASED = "address_released"
    AUTH_SUCCESS = "auth_success"
    AUTH_FAILURE = "auth_failure"


class Direction(str, Enum):
    UPSTREAM = "upstream"
    DOWNSTREAM = "downstream"


@dataclass
class Warrant:
    """types.go:40-83."""

    id: str
    liid: str  # Lawful Interception ID assigned by the LEA
    type: WarrantType = WarrantType.SUBSCRIBER
    status: WarrantStatus = WarrantStatus.PENDING
    authority_ref: str = ""
    issuing_body: str = ""
    target_subscriber_id: str = ""
    target_mac: str = ""
    target_ipv4: str = ""
    target_ipv6: str = ""
    target_username: str = ""
    valid_from: float = 0.0
    valid_until: float = 0.0
    delivery_method: DeliveryMethod = DeliveryMethod.ETSI
    mediation_address: str = ""
    mediation_port: int = 0
    filter_source_ports: list[int] = field(default_factory=list)
    filter_dest_ports: list[int] = field(default_factory=list)
    filter_protocols: list[int] = field(default_factory=list)
    filter_dest_ips: list[str] = field(default_factory=list)
    sessions_matched: int = 0
    bytes_intercepted: int = 0
    last_activity: float = 0.0
    created_at: float = 0.0


@dataclass
class InterceptSession:
    """An active tap on one subscriber session (manager.go:381-416)."""

    id: str
    warrant_id: str
    liid: str
    subscriber_id: str = ""
    mac: str = ""
    ipv4: str = ""
    ipv6: str = ""
    started_at: float = 0.0
    iri_count: int = 0
    cc_count: int = 0
    cc_bytes: int = 0


@dataclass
class InterceptRecord:
    """types.go:96-140: one IRI (metadata) or CC (content) record."""

    id: str
    liid: str
    warrant_id: str
    timestamp: float
    record_type: str  # "IRI" | "CC"
    subscriber_id: str = ""
    mac: str = ""
    source_ip: str = ""
    dest_ip: str = ""
    source_port: int = 0
    dest_port: int = 0
    protocol: int = 0
    session_id: str = ""
    event_type: str = ""
    direction: str = ""
    payload: bytes = b""
    party_info: dict | None = None


class InterceptManager:
    """Warrant store + matcher + record pipeline (manager.go:15-534)."""

    def __init__(self, clock=time.time):
        self._clock = clock
        self._lock = threading.Lock()
        self._warrants: dict[str, Warrant] = {}
        self._by_subscriber: dict[str, list[str]] = {}
        self._by_mac: dict[str, list[str]] = {}
        self._by_ip: dict[str, list[str]] = {}
        self._by_username: dict[str, list[str]] = {}
        self._sessions: dict[str, InterceptSession] = {}
        self._exporters: dict[DeliveryMethod, object] = {}
        self._stats = {"iri_records": 0, "cc_records": 0, "filtered": 0,
                       "export_errors": 0}

    # -- warrant CRUD ---------------------------------------------------

    def add_exporter(self, method: DeliveryMethod, exporter) -> None:
        self._exporters[method] = exporter

    def add_warrant(self, warrant: Warrant) -> None:
        self._validate(warrant)
        now = self._clock()
        with self._lock:
            if warrant.id in self._warrants:
                raise ValueError(f"warrant {warrant.id} already exists")
            warrant.created_at = warrant.created_at or now
            if warrant.status == WarrantStatus.PENDING and \
                    warrant.valid_from <= now < warrant.valid_until:
                warrant.status = WarrantStatus.ACTIVE
            self._warrants[warrant.id] = warrant
            self._index(warrant)

    def remove_warrant(self, warrant_id: str) -> None:
        with self._lock:
            w = self._warrants.pop(warrant_id, None)
            if w is None:
                raise KeyError(warrant_id)
            self._unindex(w)
            for sid in [s.id for s in self._sessions.values()
                        if s.warrant_id == warrant_id]:
                del self._sessions[sid]

    def update_warrant_status(self, warrant_id: str, status: WarrantStatus) -> None:
        with self._lock:
            w = self._warrants.get(warrant_id)
            if w is None:
                raise KeyError(warrant_id)
            w.status = status

    def get_warrant(self, warrant_id: str) -> Warrant:
        with self._lock:
            w = self._warrants.get(warrant_id)
            if w is None:
                raise KeyError(warrant_id)
            return w

    def list_warrants(self) -> list[Warrant]:
        with self._lock:
            return list(self._warrants.values())

    def _validate(self, w: Warrant) -> None:
        """manager.go:467-496."""
        if not w.id or not w.liid:
            raise ValueError("warrant needs id and liid")
        if not (w.target_subscriber_id or w.target_mac or w.target_ipv4
                or w.target_ipv6 or w.target_username):
            raise ValueError("warrant needs at least one target identifier")
        if w.valid_until <= w.valid_from:
            raise ValueError("warrant validity window is empty")

    def _index(self, w: Warrant) -> None:
        if w.target_subscriber_id:
            self._by_subscriber.setdefault(w.target_subscriber_id, []).append(w.id)
        if w.target_mac:
            self._by_mac.setdefault(w.target_mac.lower(), []).append(w.id)
        for ip in (w.target_ipv4, w.target_ipv6):
            if ip:
                self._by_ip.setdefault(ip, []).append(w.id)
        if w.target_username:
            self._by_username.setdefault(w.target_username, []).append(w.id)

    def _unindex(self, w: Warrant) -> None:
        for index, key in ((self._by_subscriber, w.target_subscriber_id),
                           (self._by_mac, w.target_mac.lower()),
                           (self._by_ip, w.target_ipv4),
                           (self._by_ip, w.target_ipv6),
                           (self._by_username, w.target_username)):
            if key and key in index:
                index[key] = [i for i in index[key] if i != w.id]
                if not index[key]:
                    del index[key]

    # -- matching (manager.go:260-301) ---------------------------------

    def match_session(self, subscriber_id: str = "", mac: str = "",
                      ipv4: str = "", ipv6: str = "",
                      username: str = "") -> list[Warrant]:
        now = self._clock()
        with self._lock:
            ids: list[str] = []
            if subscriber_id:
                ids += self._by_subscriber.get(subscriber_id, [])
            if mac:
                ids += self._by_mac.get(mac.lower(), [])
            for ip in (ipv4, ipv6):
                if ip:
                    ids += self._by_ip.get(ip, [])
            if username:
                ids += self._by_username.get(username, [])
            out = []
            for wid in dict.fromkeys(ids):  # dedupe, preserve order
                w = self._warrants.get(wid)
                if w is None or w.status != WarrantStatus.ACTIVE:
                    continue
                if not (w.valid_from <= now < w.valid_until):
                    continue
                w.sessions_matched += 1
                out.append(w)
            return out

    # -- intercept sessions --------------------------------------------

    def start_intercept_session(self, warrant: Warrant, session_id: str,
                                subscriber_id: str = "", mac: str = "",
                                ipv4: str = "", ipv6: str = "") -> InterceptSession:
        s = InterceptSession(id=session_id, warrant_id=warrant.id,
                            liid=warrant.liid, subscriber_id=subscriber_id,
                            mac=mac, ipv4=ipv4, ipv6=ipv6,
                            started_at=self._clock())
        with self._lock:
            self._sessions[session_id] = s
        self.record_iri(warrant, IRIEventType.SESSION_START, s)
        return s

    def stop_intercept_session(self, session_id: str) -> None:
        with self._lock:
            s = self._sessions.pop(session_id, None)
            w = self._warrants.get(s.warrant_id) if s else None
        if s is not None and w is not None:
            self.record_iri(w, IRIEventType.SESSION_STOP, s)

    def get_session(self, session_id: str) -> InterceptSession | None:
        with self._lock:
            return self._sessions.get(session_id)

    # -- record generation (manager.go:303-379) ------------------------

    def record_iri(self, warrant: Warrant, event_type: IRIEventType,
                   session: InterceptSession, party_info: dict | None = None) -> None:
        rec = InterceptRecord(
            id=uuid.uuid4().hex, liid=warrant.liid, warrant_id=warrant.id,
            timestamp=self._clock(), record_type="IRI",
            subscriber_id=session.subscriber_id, mac=session.mac,
            session_id=session.id, event_type=event_type.value,
            party_info=party_info)
        with self._lock:
            session.iri_count += 1
            warrant.last_activity = rec.timestamp
            self._stats["iri_records"] += 1
        self._deliver(warrant, rec, iri=True)

    def record_cc(self, warrant: Warrant, session: InterceptSession,
                  direction: Direction, src_ip: str, dst_ip: str,
                  src_port: int, dst_port: int, protocol: int,
                  payload: bytes) -> bool:
        """Returns False if the warrant's filters exclude this packet."""
        if not self._passes_filters(warrant, src_port, dst_port, protocol, dst_ip):
            with self._lock:
                self._stats["filtered"] += 1
            return False
        rec = InterceptRecord(
            id=uuid.uuid4().hex, liid=warrant.liid, warrant_id=warrant.id,
            timestamp=self._clock(), record_type="CC",
            subscriber_id=session.subscriber_id, mac=session.mac,
            source_ip=src_ip, dest_ip=dst_ip, source_port=src_port,
            dest_port=dst_port, protocol=protocol, session_id=session.id,
            direction=direction.value, payload=payload)
        with self._lock:
            session.cc_count += 1
            session.cc_bytes += len(payload)
            warrant.bytes_intercepted += len(payload)
            warrant.last_activity = rec.timestamp
            self._stats["cc_records"] += 1
        self._deliver(warrant, rec, iri=False)
        return True

    @staticmethod
    def _passes_filters(w: Warrant, src_port: int, dst_port: int,
                        protocol: int, dst_ip: str) -> bool:
        if w.filter_source_ports and src_port not in w.filter_source_ports:
            return False
        if w.filter_dest_ports and dst_port not in w.filter_dest_ports:
            return False
        if w.filter_protocols and protocol not in w.filter_protocols:
            return False
        if w.filter_dest_ips and dst_ip not in w.filter_dest_ips:
            return False
        return True

    def _deliver(self, warrant: Warrant, rec: InterceptRecord, iri: bool) -> None:
        exp = self._exporters.get(warrant.delivery_method)
        if exp is None:
            return
        try:
            if iri:
                exp.deliver_iri(rec)
            else:
                exp.deliver_cc(rec)
        except Exception:
            with self._lock:
                self._stats["export_errors"] += 1

    # -- maintenance ----------------------------------------------------

    def expire_warrants(self, max_reaps: int | None = None) -> int:
        """Sweep ACTIVE warrants past their validity window to EXPIRED.

        `max_reaps` bounds one sweep (the `cleanup_expired` mold): a
        maintenance tick over a large warrant store expires at most
        that many per call, the remainder reaped by later ticks —
        iteration order is insertion order, so repeated bounded sweeps
        converge without starvation.
        """
        now = self._clock()
        n = 0
        with self._lock:
            for w in self._warrants.values():
                if max_reaps is not None and n >= max_reaps:
                    break
                if w.status == WarrantStatus.ACTIVE and now >= w.valid_until:
                    w.status = WarrantStatus.EXPIRED
                    n += 1
        return n

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats,
                        warrants=len(self._warrants),
                        active_sessions=len(self._sessions))


# -- exporters ----------------------------------------------------------

class ETSIExporter:
    """ETSI TS 102 232 HI2 (IRI) / HI3 (CC) handover PDUs
    (exporter.go:17-317). Simplified TLV framing, per-LIID sequencing."""

    VERSION = 0x02
    HI2 = 0x02
    HI3 = 0x03

    def __init__(self, sink, country_code: str = "XX"):
        """sink: Callable[[bytes], None] — the HI delivery channel."""
        self._sink = sink
        self.country_code = country_code
        self._seq: dict[str, int] = {}
        self._lock = threading.Lock()

    def name(self) -> str:
        return "etsi"

    def _next_seq(self, liid: str) -> int:
        with self._lock:
            seq = self._seq.get(liid, 0)
            self._seq[liid] = seq + 1
            return seq

    def _header(self, handover: int, rec: InterceptRecord, seq: int) -> bytearray:
        buf = bytearray()
        buf.append(self.VERSION)
        buf.append(handover)
        buf += rec.liid.encode() + b"\x00"
        buf += struct.pack(">Q", seq)
        buf += struct.pack(">Q", int(rec.timestamp * 1000))
        return buf

    def deliver_iri(self, rec: InterceptRecord) -> None:
        buf = self._header(self.HI2, rec, self._next_seq(rec.liid))
        payload = json.dumps({
            "event_type": rec.event_type,
            "timestamp": rec.timestamp,
            "session_id": rec.session_id,
            "subscriber_id": rec.subscriber_id,
            "source_ip": rec.source_ip,
            "dest_ip": rec.dest_ip,
            "source_port": rec.source_port,
            "dest_port": rec.dest_port,
            "protocol": rec.protocol,
            "party_info": rec.party_info,
            "country_code": self.country_code,
        }, separators=(",", ":")).encode()
        buf += struct.pack(">I", len(payload))
        buf += payload
        self._sink(bytes(buf))

    def deliver_cc(self, rec: InterceptRecord) -> None:
        buf = self._header(self.HI3, rec, self._next_seq(rec.liid))
        buf.append(len(rec.direction))
        buf += rec.direction.encode()
        for ip in (rec.source_ip, rec.dest_ip):
            raw = _pack_ip(ip)
            buf.append(len(raw))
            buf += raw
            # port follows each address, src then dst
            buf += struct.pack(">H", rec.source_port if ip == rec.source_ip
                               else rec.dest_port)
        buf.append(rec.protocol)
        buf += struct.pack(">I", len(rec.payload))
        buf += rec.payload
        self._sink(bytes(buf))


class JSONExporter:
    """JSON-lines delivery (exporter.go:319-424)."""

    def __init__(self, sink):
        self._sink = sink

    def name(self) -> str:
        return "json"

    def _deliver(self, rec: InterceptRecord) -> None:
        d = {k: v for k, v in rec.__dict__.items() if k != "payload"}
        if rec.payload:
            d["payload_len"] = len(rec.payload)
            d["payload_hex"] = rec.payload.hex()
        self._sink((json.dumps(d, separators=(",", ":")) + "\n").encode())

    deliver_iri = _deliver
    deliver_cc = _deliver


class SyslogExporter:
    """IRI-only syslog delivery (exporter.go:426-513); CC is refused the
    way the reference's syslog path only carries metadata."""

    def __init__(self, sink, facility: int = 13):
        self._sink = sink
        self.facility = facility

    def name(self) -> str:
        return "syslog"

    def deliver_iri(self, rec: InterceptRecord) -> None:
        pri = self.facility * 8 + 6  # informational
        msg = (f"<{pri}>1 - bng intercept - - - "
               f'liid={rec.liid} event={rec.event_type} session={rec.session_id} '
               f'subscriber={rec.subscriber_id}')
        self._sink(msg.encode())

    def deliver_cc(self, rec: InterceptRecord) -> None:
        raise ValueError("syslog delivery carries IRI only")


def _pack_ip(ip: str) -> bytes:
    if not ip:
        return b""
    if ":" in ip:
        import ipaddress
        return ipaddress.IPv6Address(ip).packed
    return bytes(int(x) for x in ip.split("."))


class TLSDeliverySink:
    """Persistent TLS delivery channel to an LEA collector — the sink an
    ETSIExporter (or JSONExporter) writes through in production.

    Parity: exporter.go:191-317 — the reference dials the collector over
    TLS, frames each PDU with a 4-byte big-endian length prefix, and
    reconnects with backoff on failure. Verification (CA and/or pinning,
    optional mTLS client identity) rides control.ztp_tls.TLSConfig: the
    pin check runs post-handshake, BEFORE any intercept product leaves
    the box — a mis-dialed collector sees zero bytes of HI2/HI3.

    Delivery is synchronous while the channel is HEALTHY (connected, or
    never yet failed): each record writes through inline. The moment a
    dial fails, send() stops dialing — records only buffer (bounded at
    `buffer_max`, oldest dropped + counted) and reconnection happens in
    flush(), which the owner drives from its tick loop. This keeps the
    capture path free of connect() stalls for the whole outage: the
    blocking dial cost lands on the 1 Hz maintenance heartbeat, not on
    per-packet interception.
    """

    FRAME_HDR = 4  # uint32 length prefix per PDU

    def __init__(self, host: str, port: int, tls_cfg, timeout: float = 5.0,
                 reconnect_backoff_s: float = 2.0, buffer_max: int = 4096,
                 clock=time.time, auto_flush: bool = True):
        from bng_tpu.control.ztp_tls import build_ssl_context

        self.host = host
        self.port = port
        self.tls_cfg = tls_cfg
        # built ONCE: validates the config at construction and keeps the
        # cert/CA file I/O off the per-dial path (backoff retries included)
        self._ctx = build_ssl_context(tls_cfg)
        self.timeout = timeout
        self.backoff_s = reconnect_backoff_s
        self.buffer_max = buffer_max
        self.clock = clock
        self._sock = None
        self._buffer: list[bytes] = []
        self._next_dial = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.stats = {"delivered": 0, "buffered": 0, "dropped": 0,
                      "connects": 0, "connect_failures": 0}
        # self-healing: after a dial failure send() stops dialing (no
        # connect stalls on the capture path), so SOMETHING must redial —
        # this daemon retries every backoff while records are buffered.
        # auto_flush=False hands that duty to the owner's explicit
        # flush() (tests with fake clocks; apps with their own tick).
        if auto_flush:
            threading.Thread(target=self._flush_loop, daemon=True,
                             name=f"etsi-tls-{host}:{port}").start()

    def _flush_loop(self) -> None:
        while not self._stop.wait(self.backoff_s):
            with self._lock:
                need = bool(self._buffer) and self._sock is None
            if not need:
                continue
            # dial OUTSIDE the buffer lock: a blocked connect (up to
            # `timeout`) must never stall a capture-path send() waiting
            # on the lock — that would defeat the class's entire design
            tls = self._dial()
            with self._lock:
                if tls is None:
                    self.stats["connect_failures"] += 1
                    self._next_dial = self.clock() + self.backoff_s
                elif self._sock is None:
                    self.stats["connects"] += 1
                    self._sock = tls
                    self._flush_locked()
                else:  # a send() beat us to it
                    try:
                        tls.close()
                    except OSError:
                        pass  # race loser; the winning socket is kept

    # -- the sink callable the exporters take --
    def __call__(self, pdu: bytes) -> None:
        self.send(pdu)

    def send(self, pdu: bytes) -> None:
        with self._lock:
            self._buffer.append(pdu)
            if len(self._buffer) > self.buffer_max:
                self._buffer.pop(0)
                self.stats["dropped"] += 1
            else:
                self.stats["buffered"] += 1
            # inline delivery only while healthy: _next_dial > 0 means a
            # dial failed and hasn't been cleared by a successful flush —
            # buffer without blocking; flush() (tick-driven) redials
            if self._sock is not None or self._next_dial == 0.0:
                self._flush_locked()

    def _dial(self):
        """Dial + verify; returns the TLS socket or None. Takes NO locks
        — callers decide how the result is installed."""
        import socket as _socket

        from bng_tpu.control.ztp_tls import verify_wrapped_socket

        try:
            raw = _socket.create_connection((self.host, self.port),
                                            timeout=self.timeout)
            sn = self.tls_cfg.server_name or self.host
            tls = self._ctx.wrap_socket(raw, server_hostname=sn)
            verify_wrapped_socket(tls, self.tls_cfg)  # raises pre-delivery
            return tls
        except Exception:
            return None

    def _connect_locked(self):
        now = self.clock()
        if now < self._next_dial:
            return None
        tls = self._dial()
        if tls is None:
            self.stats["connect_failures"] += 1
            self._next_dial = now + self.backoff_s
            return None
        self.stats["connects"] += 1
        self._sock = tls
        return tls

    def _flush_locked(self) -> None:
        sock = self._sock or self._connect_locked()
        if sock is None:
            return
        while self._buffer:
            pdu = self._buffer[0]
            try:
                sock.sendall(struct.pack(">I", len(pdu)) + pdu)
            except Exception:
                # connection died mid-delivery: keep the PDU buffered,
                # drop the socket, back off before redialing
                try:
                    sock.close()
                except OSError:
                    pass  # socket already dead; nothing to release
                self._sock = None
                self._next_dial = self.clock() + self.backoff_s
                return
            self._buffer.pop(0)
            self.stats["delivered"] += 1

    def flush(self) -> bool:
        """Retry buffered PDUs now (tick hook). True = buffer empty."""
        with self._lock:
            self._next_dial = 0.0  # an explicit flush overrides backoff
            self._flush_locked()
            return not self._buffer

    def close(self) -> None:
        self._stop.set()
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass  # shutdown path; the socket is gone either way
                self._sock = None


def parse_etsi_pdu(data: bytes) -> dict:
    """Decode the framing produced by ETSIExporter (for tests/mediation)."""
    version, handover = data[0], data[1]
    end = data.index(0, 2)
    liid = data[2:end].decode()
    off = end + 1
    seq, ts_ms = struct.unpack_from(">QQ", data, off)
    off += 16
    out = {"version": version, "handover": handover, "liid": liid,
           "seq": seq, "timestamp_ms": ts_ms}
    if handover == ETSIExporter.HI2:
        (plen,) = struct.unpack_from(">I", data, off)
        out["iri"] = json.loads(data[off + 4:off + 4 + plen])
    else:
        dlen = data[off]; off += 1
        out["direction"] = data[off:off + dlen].decode(); off += dlen
        for which in ("source", "dest"):
            alen = data[off]; off += 1
            raw = data[off:off + alen]; off += alen
            out[f"{which}_ip"] = (".".join(str(b) for b in raw)
                                  if alen == 4 else raw.hex())
            (out[f"{which}_port"],) = struct.unpack_from(">H", data, off)
            off += 2
        out["protocol"] = data[off]; off += 1
        (plen,) = struct.unpack_from(">I", data, off)
        out["payload"] = data[off + 4:off + 4 + plen]
    return out
