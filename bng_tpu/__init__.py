"""bng_tpu — a TPU-native Broadband Network Gateway framework.

A from-scratch reimplementation of the capabilities of codelaboratoryltd/bng
(Go + eBPF/XDP) designed for TPU hardware:

- The eBPF/XDP fast path (bpf/dhcp_fastpath.c, bpf/nat44.c,
  bpf/qos_ratelimit.c, bpf/antispoof.c) becomes a single fused JAX/Pallas
  batched-packet pipeline (`bng_tpu.ops.pipeline`) operating on [B, 512]
  uint8 packet batches in HBM/VMEM.
- The eBPF maps (bpf/maps.h) become HBM-resident cuckoo hash tables
  (`bng_tpu.ops.table`) with the host as single writer — mirroring the
  reference's slow-path-populates-cache design (pkg/dhcp/server.go:1057).
- The Go control plane (pkg/dhcp, pkg/allocator, pkg/radius, pkg/nexus,
  pkg/ha, pkg/resilience, ...) becomes the `bng_tpu.control` package.
- Scale-out is jax.sharding over a device Mesh with ICI collectives
  (`bng_tpu.parallel`) instead of the reference's HTTP/SSE + libp2p mesh.
"""

__version__ = "0.1.0"
