"""Structured JSON logging — the zap parity layer (SURVEY §5).

The reference logs structured JSON everywhere (zap production config,
cmd/bng/main.go:1398-1418): machine-parseable lines with bound fields
(component, subscriber, mac, ...). Python stdlib logging gets the same
shape here:

    log = get_logger("dhcp", component="dhcp-server")
    log.info("lease allocated", mac="02:..:42", ip="10.0.0.9", pool=1)

  -> {"ts": "2026-07-30T00:00:00.123Z", "level": "info",
      "logger": "dhcp", "msg": "lease allocated",
      "component": "dhcp-server", "mac": "02:..:42", ...}

`setup(level=..., fmt="json"|"console")` configures the root once (CLI
flags --log-level/--log-format); libraries call get_logger() and never
configure handlers themselves (the zap discipline).
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import IO

_CONFIGURED = False


class JSONFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        line = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime(record.created))
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        line.update(getattr(record, "bng_fields", {}))
        if record.exc_info:
            line["exc"] = self.formatException(record.exc_info)
        return json.dumps(line)


class ConsoleFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        fields = getattr(record, "bng_fields", {})
        tail = "".join(f" {k}={v}" for k, v in fields.items())
        return (f"{time.strftime('%H:%M:%S', time.gmtime(record.created))} "
                f"{record.levelname:<5} {record.name}: "
                f"{record.getMessage()}{tail}")


class BoundLogger:
    """A logger with bound fields; per-call kwargs become JSON fields."""

    def __init__(self, logger: logging.Logger, fields: dict):
        self._logger = logger
        self._fields = fields

    def bind(self, **fields) -> "BoundLogger":
        return BoundLogger(self._logger, {**self._fields, **fields})

    def _log(self, level: int, msg: str, kw: dict) -> None:
        if self._logger.isEnabledFor(level):
            exc_info = kw.pop("exc_info", None)
            self._logger.log(level, msg, exc_info=exc_info,
                             extra={"bng_fields": {**self._fields, **kw}})

    def debug(self, msg: str, **kw) -> None:
        self._log(logging.DEBUG, msg, kw)

    def info(self, msg: str, **kw) -> None:
        self._log(logging.INFO, msg, kw)

    def warning(self, msg: str, **kw) -> None:
        self._log(logging.WARNING, msg, kw)

    def error(self, msg: str, **kw) -> None:
        self._log(logging.ERROR, msg, kw)


def setup(level: str = "info", fmt: str = "json",
          stream: IO | None = None, force: bool = False) -> None:
    """Configure the root 'bng' logger.

    First explicit configuration wins (the zap discipline: the operator's
    sink is not clobbered by a library's later convenience call) — a
    repeat call without `stream`/`force` only adjusts the level.
    """
    global _CONFIGURED
    root = logging.getLogger("bng")
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    if _CONFIGURED and not force and stream is None:
        return
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(JSONFormatter() if fmt == "json" else ConsoleFormatter())
    root.handlers[:] = [handler]
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str, **fields) -> BoundLogger:
    """Namespaced logger under 'bng.'; safe before setup() (lazy default)."""
    if not _CONFIGURED:
        setup()
    return BoundLogger(logging.getLogger(f"bng.{name}"), fields)


class RateLimiter:
    """Token-bucket guard for hot-path log sites (zap's sampler role).

    A per-frame slow-path failure under a malformed-packet flood must not
    turn the dataplane into a log firehose, but it must not be silent
    either (the reference logs every DHCP handler error,
    pkg/dhcp/server.go:330 — it can afford to; a batch engine cannot).
    allow() grants up to `burst` events immediately and refills at `rate`
    per second; each grant reports how many events were suppressed since
    the previous grant, so the emitted line carries the loss count.
    """

    def __init__(self, rate: float = 1.0, burst: int = 5,
                 clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = self.burst
        self._last = clock()
        self._suppressed = 0

    def allow(self) -> tuple[bool, int]:
        """-> (granted, events suppressed since the last grant)."""
        now = self.clock()
        self._tokens = min(self.burst,
                           self._tokens + (now - self._last) * self.rate)
        self._last = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            suppressed, self._suppressed = self._suppressed, 0
            return True, suppressed
        self._suppressed += 1
        return False, self._suppressed


class ErrorLog:
    """Rate-limited exception reporter for background/maintenance paths.

    The generic sibling of SlowPathErrorLog, for the Yuan-style handler
    fixes (bngcheck BNG020/BNG021): a broad `except` that used to be
    `pass` reports here instead — one line per `rate`/s with a
    suppressed-count, traceback included, never raising into the path it
    guards.
    """

    def __init__(self, name: str, message: str, rate: float = 1.0,
                 burst: int = 5, clock=time.monotonic, level: str = "warning",
                 **bound):
        self._log = get_logger(name, **bound)
        self._message = message
        self._level = level
        self._limit = RateLimiter(rate=rate, burst=burst, clock=clock)

    def report(self, exc: BaseException, **fields) -> bool:
        """Log `exc` (with traceback) unless rate-limited; returns
        whether the line was emitted. Never raises — a logging failure
        must not take down the path it guards."""
        try:
            ok, suppressed = self._limit.allow()
            if not ok:
                return False
            getattr(self._log, self._level)(
                self._message,
                error=f"{type(exc).__name__}: {exc}",
                suppressed=suppressed,
                exc_info=(type(exc), exc, exc.__traceback__),
                **fields)
            return True
        except Exception:  # pragma: no cover - defensive
            return False


class SlowPathErrorLog(ErrorLog):
    """Rate-limited exception reporter for the engine slow-path drains.

    The engines count `slow_errors` for metrics; this adds the traceback
    the counter was dropping (VERDICT weakness: engine.py/sharded.py
    swallowed the exception entirely). One instance per engine — the
    limiter state is shared across that engine's drain sites, so a single
    poisoned flood cannot log more than `rate`/s no matter which path
    (sync, pipelined, DHCP-only) it enters through.
    """

    def __init__(self, component: str, rate: float = 1.0, burst: int = 5,
                 clock=time.monotonic):
        super().__init__("slowpath", "slow-path handler failed",
                         rate=rate, burst=burst, clock=clock,
                         level="error", component=component)
