"""Network address helpers shared by host control plane and device kernels.

Behavioral parity with the reference's conversion utilities:
- MAC-as-u64 keys: pkg/ebpf/loader.go:666-701 and bpf/dhcp_fastpath.c:175-182
  (big-endian byte order: mac[0] is the most significant byte).
- FNV-1a hashing: pkg/ebpf/loader.go:546-553 (circuit-ID hashing) and
  pkg/pool/peer.go:777-790 (rendezvous hash combine).
- prefix_to_mask: bpf/dhcp_fastpath.c:510-516.

All integer math here is plain Python int / numpy; device-side equivalents
live in bng_tpu.ops.
"""

from __future__ import annotations

FNV1A32_OFFSET = 0x811C9DC5
FNV1A32_PRIME = 0x01000193
FNV1A64_OFFSET = 0xCBF29CE484222325
FNV1A64_PRIME = 0x100000001B3

_U32 = 0xFFFFFFFF
_U64 = 0xFFFFFFFFFFFFFFFF


def parse_mac(mac: str) -> bytes:
    """Parse "aa:bb:cc:dd:ee:ff" (or '-' separated) into 6 bytes."""
    parts = mac.replace("-", ":").split(":")
    if len(parts) != 6:
        raise ValueError(f"malformed MAC {mac!r}: want 6 colon-separated octets")
    try:
        out = bytes(int(p, 16) for p in parts)
    except ValueError as e:
        raise ValueError(f"malformed MAC {mac!r}: {e}") from None
    return out


def mac_to_u64(mac: bytes | str) -> int:
    """Convert a 6-byte MAC to a u64 key (big-endian, like the reference)."""
    if isinstance(mac, str):
        mac = parse_mac(mac)
    if len(mac) != 6:
        raise ValueError(f"MAC must be 6 bytes, got {len(mac)}")
    out = int.from_bytes(mac, "big")
    return out


def u64_to_mac(key: int) -> bytes:
    return bytes((key >> (8 * (5 - i))) & 0xFF for i in range(6))


def ip_to_u32(ip: str | bytes) -> int:
    """Dotted-quad (or 4 raw bytes) to host-order u32 (10.0.0.1 -> 0x0A000001)."""
    if isinstance(ip, bytes):
        if len(ip) != 4:
            raise ValueError("need 4 bytes")
        parts = list(ip)
    else:
        parts = [int(p) for p in ip.split(".")]
    if len(parts) != 4 or any(p < 0 or p > 255 for p in parts):
        raise ValueError(f"bad IPv4 address: {ip!r}")
    return (parts[0] << 24) | (parts[1] << 16) | (parts[2] << 8) | parts[3]


def u32_to_ip(v: int) -> str:
    return f"{(v >> 24) & 0xFF}.{(v >> 16) & 0xFF}.{(v >> 8) & 0xFF}.{v & 0xFF}"


def prefix_to_mask(prefix_len: int) -> int:
    """CIDR prefix length to host-order netmask u32."""
    if prefix_len <= 0:
        return 0
    if prefix_len >= 32:
        return _U32
    return (_U32 << (32 - prefix_len)) & _U32


def fnv1a32(data: bytes, seed: int = FNV1A32_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * FNV1A32_PRIME) & _U32
    return h


def fnv1a64(data: bytes, seed: int = FNV1A64_OFFSET) -> int:
    h = seed
    for b in data:
        h ^= b
        h = (h * FNV1A64_PRIME) & _U64
    return h


def split_u64(v: int) -> tuple[int, int]:
    """u64 -> (lo32, hi32) for storage in uint32 table key words."""
    return v & _U32, (v >> 32) & _U32


def join_u64(lo: int, hi: int) -> int:
    return (hi << 32) | lo
