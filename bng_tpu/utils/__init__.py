from bng_tpu.utils.net import (  # noqa: F401
    mac_to_u64,
    u64_to_mac,
    ip_to_u32,
    u32_to_ip,
    prefix_to_mask,
    fnv1a32,
    split_u64,
    join_u64,
)
