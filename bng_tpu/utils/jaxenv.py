"""Environment-proof JAX backend initialization for driver entry points.

The container's sitecustomize registers an `axon` PJRT plugin in every
interpreter.  Initializing it contends for the single real TPU chip: when
another process holds the claim (or the tunnel is down) `jax.devices()`
either raises UNAVAILABLE or *hangs* indefinitely.  Round 1 shipped both
failure modes as driver artifacts (BENCH_r01 rc=1, MULTICHIP_r01 rc=124).

Two guards, mirroring tests/conftest.py:

- ``force_cpu(n_devices)``: hermetically pin this process to the CPU
  backend with an ``n_devices``-device virtual mesh and drop every non-cpu
  PJRT factory so nothing can touch the chip.  Used by
  ``__graft_entry__.dryrun_multichip`` and test runs.

- ``guarded_backend(...)``: probe accelerator availability in a *subprocess*
  with a hard timeout (a hung in-process PJRT init cannot be interrupted),
  retry a bounded number of times, and on final failure force CPU and
  return the diagnostic.  Used by ``bench.py`` so the driver always gets a
  JSON line — a measured TPU number when the chip is reachable, a
  CPU-fallback number plus ``"error"`` diagnostics when it is not.

Reference analog: the XDP attach ladder driver->generic->error in
/root/reference/pkg/ebpf/loader.go:294-315 — always degrade, never crash.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

_PROBE_SRC = (
    "import jax; d = jax.devices(); "
    "import jax.numpy as jnp; jnp.zeros((8,)).block_until_ready(); "
    "print(d[0].platform, len(d))"
)


def _ensure_host_device_count(n_devices: int) -> None:
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    opt = "--xla_force_host_platform_device_count"
    m = re.search(rf"{opt}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {opt}={n_devices}".strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = flags.replace(m.group(0), f"{opt}={n_devices}")


def force_cpu(n_devices: int = 8) -> None:
    """Pin this process to a hermetic CPU backend with a virtual mesh.

    Safe to call multiple times.  Must run before the first real backend
    initialization; sitecustomize importing jax is fine (config is updated
    live and the non-cpu PJRT factories are dropped, so a stray request
    fails loudly instead of hanging on the chip claim).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    _ensure_host_device_count(n_devices)

    import jax

    jax.config.update("jax_platforms", "cpu")
    # Preload pallas while the platform registry is intact: its import
    # registers "tpu" lowering rules, which fails once factories are gone.
    try:
        import jax.experimental.pallas  # noqa: F401
        import jax.experimental.pallas.tpu  # noqa: F401
    except Exception:  # pragma: no cover - pallas optional on exotic jaxlibs
        pass
    try:
        import jax._src.xla_bridge as _xb

        for _name in list(getattr(_xb, "_backend_factories", {})):
            if _name != "cpu":
                _xb._backend_factories.pop(_name, None)
    except Exception:  # pragma: no cover - best effort
        pass


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Turn on JAX's persistent compilation cache (works on CPU too).

    The test suite and bench are compile-dominated (VERDICT weakness 5:
    92 core tests spent ~265s, nearly all XLA compiles); a warm disk
    cache collapses repeat runs. Thresholds drop to zero so the many
    small per-geometry pipeline compiles are cached, not just the big
    ones. Resolution order: explicit arg > $BNG_JAX_CACHE_DIR > a stable
    per-user default. Set BNG_JAX_CACHE_DIR=0 to disable. Returns the
    cache dir, or None when disabled/unsupported (old jaxlibs) — callers
    never fail because caching was unavailable.

    CPU GUARD (measured, round 6): on jaxlib 0.4.37 XLA:CPU, executables
    DESERIALIZED from the cache compute wrong results for the donated
    fused-pipeline programs (cold-write runs pass, warm-read runs fail
    NAT/fast-lane e2e and SIGABRT the sharded step; see PERF_NOTES §4).
    Accelerator backends use the mature serialization path. So: enabled
    by default only off-CPU; BNG_JAX_CACHE_CPU=1 opts CPU in for jaxlibs
    where the bug is fixed.
    """
    cache_dir = cache_dir or os.environ.get("BNG_JAX_CACHE_DIR")
    if cache_dir in ("0", "off", "none"):
        return None
    try:
        import jax

        backend = jax.default_backend()
    except Exception:  # pragma: no cover - no usable backend at all
        return None
    if backend == "cpu" and os.environ.get("BNG_JAX_CACHE_CPU") != "1":
        return None
    if not cache_dir:
        cache_dir = os.path.join(
            os.path.expanduser("~"), ".cache", "bng-tpu", "jax-cache")
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:  # newer jaxlibs only; the size threshold is best-effort
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except Exception:
            pass
        return cache_dir
    except Exception:  # pragma: no cover - cache is an optimization only
        return None


def probe_accelerator(timeout_s: float = 120.0) -> tuple[str, str]:
    """Probe backend availability in a subprocess with a hard timeout.

    Returns ``(platform, "")`` on success (e.g. ``("tpu", "")``) or
    ``("", diagnostic)`` on failure.  The subprocess inherits the default
    environment (axon plugin active) so it exercises exactly the init path
    the current process would take.
    """
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the plugin pick the accelerator
    try:
        res = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return "", f"probe timed out after {timeout_s:.0f}s (chip held or tunnel down)"
    except Exception as e:  # pragma: no cover - spawn failure
        return "", f"probe spawn failed: {e!r}"
    if res.returncode != 0:
        tail = (res.stderr or res.stdout or "").strip().splitlines()[-3:]
        return "", f"probe rc={res.returncode}: " + " | ".join(tail)
    out = (res.stdout or "").strip().split()
    return (out[0] if out else "unknown"), ""


def tunnel_precheck(timeout_s: float = 20.0) -> tuple[bool, str]:
    """Cheap relay/tunnel health check BEFORE committing to a long probe
    window (VERDICT "What's weak" §1: three rounds burned their window
    against a tunnel that was down from the first second). One short
    subprocess probe: (True, platform) when an accelerator answers fast,
    (False, diagnostic) when it doesn't — the caller then decides
    whether the full backoff window is worth spending."""
    platform, err = probe_accelerator(timeout_s)
    if platform and platform != "cpu":
        return True, platform
    return False, err or f"probe returned platform={platform!r}"


def guarded_backend(
    prefer_accelerator: bool = True,
    tries: int = 2,
    probe_timeout_s: float = 120.0,
    retry_sleep_s: float = 10.0,
    cpu_devices: int = 8,
    window_s: float = 0.0,
    backoff: float = 1.0,
    max_sleep_s: float = 120.0,
) -> tuple[str, str]:
    """Initialize a usable JAX backend without ever hanging or crashing.

    Returns ``(platform, error)``.  ``error`` is non-empty when the
    accelerator was wanted but unreachable and CPU fallback was taken.

    ``window_s > 0`` turns the bounded ``tries`` loop into a
    capture-on-return loop: keep probing (each probe bounded by
    ``probe_timeout_s``) until a probe succeeds or the wall-clock window
    expires.  This is the unattended round-end mode (VERDICT r3 weak #4):
    the axon tunnel drops for stretches, and a single 150 s probe turned a
    whole round's deliverable into a CPU artifact.  Probes are subprocesses,
    so a dead tunnel costs one child per attempt, never a wedged parent.

    ``backoff > 1`` grows the inter-probe sleep geometrically (capped at
    ``max_sleep_s``): a down tunnel gets polled often early (it usually
    flaps back within a minute) without burning the whole window on
    fixed-cadence probes when it stays down.
    """
    if not prefer_accelerator or os.environ.get("JAX_PLATFORMS") == "cpu":
        force_cpu(cpu_devices)
        return "cpu", ""
    err = ""
    deadline = time.monotonic() + window_s if window_s > 0 else None
    attempt = 0
    sleep_s = retry_sleep_s
    while True:
        if attempt >= tries:
            break
        if deadline is not None and attempt:
            # a retry costs up to sleep+probe: only start one that can
            # finish inside the window, so probing never eats run budget
            if time.monotonic() + sleep_s + probe_timeout_s >= deadline:
                break
        if attempt:
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * max(backoff, 1.0), max_sleep_s)
        attempt += 1
        platform, err = probe_accelerator(probe_timeout_s)
        if platform:
            # Probe succeeded; in-process init should follow the same path.
            # A SIGALRM watchdog closes (best-effort) the race window where
            # the chip is claimed between probe exit and our init — the
            # exact hang this module exists to prevent.
            import signal

            import jax

            def _timeout(_sig, _frm):
                raise TimeoutError("in-process backend init watchdog fired")

            old = signal.signal(signal.SIGALRM, _timeout)
            signal.alarm(int(probe_timeout_s) + 30)
            try:
                return jax.devices()[0].platform, ""
            except Exception as e:  # raced: chip claimed between probe and init
                err = f"in-process init failed after OK probe: {e!r}"
            finally:
                signal.alarm(0)
                signal.signal(signal.SIGALRM, old)
    force_cpu(cpu_devices)
    return "cpu", err
