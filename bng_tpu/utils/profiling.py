"""Op-level device profiling — the tracing subsystem (SURVEY.md §5).

The reference leans on perf/bpftool-style tracing to find its hot spots;
the TPU analog is the XLA profiler. This module institutionalizes the
workflow that diagnosed the round-2 QoS bottleneck (narrow-gather fusions
at ~7ns/element): capture a `jax.profiler` trace around a callable, parse
the Chrome-trace export, and aggregate per-op device time.

    from bng_tpu.utils.profiling import profile_op_times
    report = profile_op_times(lambda: step(tables, pkt, ln), iters=10)
    print(format_report(report))

Used by `python -m bng_tpu.utils.profiling` (smoke) and available to
bench.py via BNG_BENCH_PROFILE=1.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
from collections import defaultdict
from dataclasses import dataclass
from typing import Callable


@dataclass
class OpTime:
    name: str
    us_per_iter: float
    calls_per_iter: float


@dataclass
class ProfileReport:
    device_total_us: float  # sum of top-level device program time per iter
    host_total_us: float
    ops: list[OpTime]  # device ops, descending by time
    trace_dir: str


def profile_op_times(fn: Callable[[], object], iters: int = 10,
                     trace_dir: str | None = None) -> ProfileReport:
    """Run fn() `iters` times under the profiler; aggregate device ops.

    fn should be pre-compiled (call it once before) so the trace holds
    steady-state executions, not compilation. With no explicit trace_dir
    the raw trace (tens of MB for a big pipeline) is parsed and DELETED —
    pass trace_dir to keep it for tensorboard.
    """
    import shutil

    import jax

    keep = trace_dir is not None
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="bng-prof-")
    try:
        with jax.profiler.trace(trace_dir):
            out = None
            for _ in range(iters):
                out = fn()
            jax.block_until_ready(out)

        traces = sorted(glob.glob(
            os.path.join(trace_dir, "plugins", "profile", "*",
                         "*.trace.json.gz")))
        if not traces:
            return ProfileReport(0.0, 0.0, [],
                                 trace_dir if keep else "(discarded)")
        with gzip.open(traces[-1]) as f:
            tr = json.load(f)
    finally:
        if not keep:
            shutil.rmtree(trace_dir, ignore_errors=True)
            trace_dir = "(discarded)"
    ev = tr.get("traceEvents", [])
    pids = {e["pid"]: e["args"].get("name", "") for e in ev
            if e.get("ph") == "M" and e.get("name") == "process_name"}

    dev_agg: dict[str, float] = defaultdict(float)
    dev_cnt: dict[str, int] = defaultdict(int)
    dev_top = 0.0
    host_top = 0.0
    for e in ev:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        where = pids.get(e["pid"], "")
        name = e["name"]
        if "TPU" in where or "GPU" in where or "device" in where.lower():
            if name.startswith("jit_") or name.startswith("pjit"):
                dev_top += e["dur"]
            else:
                dev_agg[name] += e["dur"]
                dev_cnt[name] += 1
        elif "CPU" in where and name.startswith("PjitFunction"):
            host_top += e["dur"]

    ops = [OpTime(n, d / iters, dev_cnt[n] / iters)
           for n, d in sorted(dev_agg.items(), key=lambda kv: -kv[1])]
    # NOTE: XLA:CPU emits no separate device track (only /host:CPU), so on
    # CPU this degrades to host dispatch totals — op attribution needs an
    # accelerator backend (the tool's purpose is the real chip anyway).
    return ProfileReport(device_total_us=dev_top / iters,
                         host_total_us=host_top / iters,
                         ops=ops, trace_dir=trace_dir)


@dataclass
class StepDurations:
    """Per-execution program durations from one profiled run.

    source: which trace signal supplied them —
      "device"   top-level jit_/pjit events on the accelerator track
                 (true device time, the <50us OFFER target's quantity)
      "cpu-exec" TfrtCpuExecutable::ExecuteHelper on the host track
                 (XLA:CPU per-execution runtime — no separate device
                 track exists there, this is the closest isolate)
    """

    us: list[float]
    source: str

    def __post_init__(self):
        self._sorted = None  # lazy sort cache, built once per instance

    def percentile(self, q: float) -> float:
        """Linearly interpolated percentile over a ONCE-sorted copy.

        Callers ask for several quantiles per run (p50/p99 per bench
        section); re-sorting per call was O(n log n) each time. Linear
        interpolation matches numpy.percentile's default method
        (pinned by tests/test_telemetry.py against numpy directly)."""
        if not self.us:
            return 0.0
        if self._sorted is None:
            import numpy as _np

            self._sorted = _np.sort(_np.asarray(self.us, dtype=_np.float64))
        s = self._sorted
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q={q} outside [0, 100]")
        pos = (len(s) - 1) * (q / 100.0)
        lo = int(pos)
        frac = pos - lo
        if frac == 0.0 or lo + 1 >= len(s):
            return float(s[lo])
        return float(s[lo] + (s[lo + 1] - s[lo]) * frac)


def profile_step_durations(fn: Callable[[], object], iters: int = 50,
                           trace_dir: str | None = None) -> StepDurations:
    """Per-iteration execution durations of fn's jitted program.

    Where profile_op_times aggregates (mean us/iter), this keeps the
    DISTRIBUTION — the p99 the latency targets constrain is a tail
    statistic that an aggregate cannot recover. Blocked wall-clock
    timing includes host dispatch + sync artifacts (the axon tunnel's
    ~63ms completion-poll bucket, PERF_NOTES §1); the profiler events
    isolate the execution itself. fn must be pre-compiled and should run
    exactly ONE jitted program per call (extra programs would interleave
    into the sample list).
    """
    import shutil

    import jax

    keep = trace_dir is not None
    trace_dir = trace_dir or tempfile.mkdtemp(prefix="bng-prof-")
    try:
        with jax.profiler.trace(trace_dir):
            out = None
            for _ in range(iters):
                out = fn()
            jax.block_until_ready(out)
        traces = sorted(glob.glob(
            os.path.join(trace_dir, "plugins", "profile", "*",
                         "*.trace.json.gz")))
        if not traces:
            return StepDurations([], "none")
        with gzip.open(traces[-1]) as f:
            tr = json.load(f)
    finally:
        if not keep:
            shutil.rmtree(trace_dir, ignore_errors=True)
    ev = tr.get("traceEvents", [])
    pids = {e["pid"]: e["args"].get("name", "") for e in ev
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    device, cpu_exec = [], []
    for e in ev:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        where = pids.get(e["pid"], "")
        name = e["name"]
        if ("TPU" in where or "GPU" in where or "device" in where.lower()):
            if name.startswith("jit_") or name.startswith("pjit"):
                device.append((e.get("ts", 0), float(e["dur"])))
        elif name == "TfrtCpuExecutable::ExecuteHelper":
            cpu_exec.append((e.get("ts", 0), float(e["dur"])))
    for samples, source in ((device, "device"), (cpu_exec, "cpu-exec")):
        if samples:
            samples.sort()  # execution order, so warmup skew trims cleanly
            return StepDurations([d for _, d in samples], source)
    return StepDurations([], "none")


def format_report(r: ProfileReport, top: int = 15) -> str:
    lines = [f"device program: {r.device_total_us:9.1f} us/iter   "
             f"(host dispatch {r.host_total_us:.1f} us)   trace: {r.trace_dir}"]
    for op in r.ops[:top]:
        lines.append(f"  {op.us_per_iter:9.1f} us  x{op.calls_per_iter:4.1f}  {op.name}")
    return "\n".join(lines)


def _smoke() -> None:  # pragma: no cover - manual tool
    import jax
    import jax.numpy as jnp

    x = jnp.ones((4096, 4096), jnp.float32)
    f = jax.jit(lambda a: (a @ a).sum())
    jax.block_until_ready(f(x))
    print(format_report(profile_op_times(lambda: f(x), iters=5)))


if __name__ == "__main__":  # pragma: no cover
    _smoke()
