"""Pallas TPU kernel: the fused bucketized-cuckoo table probe.

`ops/table.py:device_lookup` is the repo's hottest code — every stage of
the fused pipeline (DHCP 3-tier chain, NAT44 forward/reverse, antispoof,
garden, PPPoE) funnels through it, and PERF_NOTES §2 measured the XLA
lowering of the composed cascade as the throughput ceiling: narrow
(<8-word-row) gathers serialize to ~7 ns/element loops, and even the
wide-row relayout leaves each probe as 3+ separate HBM gather fusions
that XLA stages through VMEM copies of its own choosing.

This kernel fuses the whole probe into ONE program over the batch:

    hash -> two wide bucket-row gathers from HBM (per-lane async DMA,
    driven by scalar-prefetched bucket indices) -> per-way lane compare
    -> stash broadcast compare -> value fetch (the candidate value
    blocks ride the same DMA wave; stash values select by mask)

Layout notes (Mosaic tiling wants (8k, 128m) trailing dims):

- Per-lane probe rows are DMA'd from HBM (`pl.ANY`) into VMEM scratch
  whose lane dim is padded to 128; the DMAs are contiguous row copies
  (the measured-fast shape), issued for a whole lane tile and then
  awaited — start-all/wait-all on one DMA semaphore.
- Query words arrive as [K, nt, 8, T] blocks (the ops/pallas_qos
  sublane-replication trick) and bucket indices are recomputed
  in-kernel from them (vectorized lowbias32) so slot arithmetic is
  vector math; the scalar-prefetch copy of the same indices drives the
  DMA descriptors.
- Stash rows/values are transposed to [word, stash] lane-major arrays
  so the stash compare is a (T, stash) broadcast and the value select
  a masked integer sum — never a float matmul (value words are uint32
  and must survive bit-exactly; f32 accumulation would corrupt words
  >= 2^24).
- All selects are first-match-wins in device_lookup's candidate order
  (b1 ways, b2 ways, stash) so the kernel is BIT-IDENTICAL to the XLA
  path and the host mirror — pinned by tests/test_pallas_table.py
  across every table geometry in the repo.

Interpret-mode caveats (PERF_NOTES §13): on every non-TPU backend the
kernel runs under `interpret=True` — same semantics, executed by the
Pallas interpreter — so the whole tier-1 suite exercises the kernel
without hardware. Mosaic lowering is only proven by the TPU gate
(runtime/verify.py `table_lookup[pallas]`, tpu_run.sh A/B step).

Impl selection lives in ops/table.py (`BNG_TABLE_IMPL=xla|pallas|auto`,
the qos_kernel[sort|pallas] mold); this module is only the kernel.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _ANY = pltpu.ANY
except (ImportError, NotImplementedError):  # pragma: no cover - env specific
    # Even interpret mode needs pltpu (PrefetchScalarGridSpec, VMEM
    # scratch, DMA descriptors) — without it the kernel cannot run in
    # ANY mode. pallas_probe raises a clear error; the selector default
    # ("xla") means such jaxlibs simply never take this path.
    pltpu = None
    _ANY = None

from bng_tpu.ops.hashing import SEED1, SEED2, hash_words

LANE_TILE = 128  # lanes per grid step (the DMA wave size)
SUBLANES = 8  # Mosaic tiling: rank>=2 blocks need (8k, 128m) trailing dims


def _pad_to(n: int, m: int) -> int:
    return -(-n // m) * m


def _probe_kernel(idx_ref, krows_ref, vals_ref, qw_ref, stash_ref, svals_ref,
                  found_ref, slot_ref, vals_out_ref,
                  krows_scr, vrows_scr, sem,
                  *, K, KW, V, T, WS, WSP, VP, nbuckets, stash, SP, WAYS):
    i = pl.program_id(0)

    def _copies(lane):
        """The 4 DMA descriptors of one lane: 2 packed bucket probe rows
        + the 2 matching 4-way value blocks (contiguous in vals — slot
        layout is bucket-major). Built identically in the start and
        wait loops so each wait consumes its own copy's bytes from the
        shared semaphore."""
        b1 = idx_ref[0, i * T + lane]
        b2 = idx_ref[1, i * T + lane]
        out = []
        for side, b in ((0, b1), (1, b2)):
            out.append(pltpu.make_async_copy(
                krows_ref.at[b],
                krows_scr.at[lane, pl.ds(side * WSP, WS)], sem))
            out.append(pltpu.make_async_copy(
                vals_ref.at[pl.ds(b * WAYS, WAYS), :],
                vrows_scr.at[lane, pl.ds(side * WAYS, WAYS), pl.ds(0, V)],
                sem))
        return out

    def _start(lane, _):
        for c in _copies(lane):
            c.start()
        return 0

    jax.lax.fori_loop(0, T, _start, 0, unroll=False)

    def _wait(lane, _):
        for c in _copies(lane):
            c.wait()
        return 0

    jax.lax.fori_loop(0, T, _wait, 0, unroll=False)

    # query words as (T,) vectors; bucket ids recomputed in-kernel
    # (vectorized — the scalar-prefetch copy only drives the DMAs)
    qws = [qw_ref[k, 0, 0, :] for k in range(K)]
    mask = np.uint32(nbuckets - 1)
    b1v = (hash_words(qws, SEED1) & mask).astype(jnp.int32)
    b2v = (hash_words(qws, SEED2) & mask).astype(jnp.int32)

    rows = krows_scr[:]  # (T, 2*WSP) — gathered probe rows
    vrows = vrows_scr[:]  # (T, 2*WAYS, VP) — candidate value blocks

    # per-way match in device_lookup's candidate order: b1 ways, b2 ways
    m = []
    slots = []
    for side in range(2):
        base = side * WSP
        bv = b1v if side == 0 else b2v
        for w in range(WAYS):
            col = base + w * KW
            mk = rows[:, col + K] != 0  # used flag
            for k in range(K):
                mk = mk & (rows[:, col + k] == qws[k])
            m.append(mk)
            slots.append(bv * WAYS + w)
    any_before = jnp.zeros((T,), dtype=bool)
    first = []
    for w in range(2 * WAYS):
        first.append(m[w] & ~any_before)
        any_before = any_before | m[w]
    found_b = any_before

    slot = jnp.zeros((T,), dtype=jnp.int32)
    for w in range(2 * WAYS):
        slot = slot + jnp.where(first[w], slots[w], 0)

    # value select: masked integer sums (at most one `first` lane set) —
    # exact for all uint32 words, unlike an MXU f32 contraction
    vcols = []
    for v in range(V):
        col = jnp.zeros((T,), dtype=jnp.uint32)
        for w in range(2 * WAYS):
            col = col + jnp.where(first[w], vrows[:, w, v], np.uint32(0))
        vcols.append(col)

    if stash > 0:
        sm = stash_ref[K, :][None, :] != 0  # (1, SP) used row
        for k in range(K):
            sm = sm & (qws[k][:, None] == stash_ref[k, :][None, :])
        cum = jnp.cumsum(sm.astype(jnp.int32), axis=1)
        sfirst = sm & (cum == 1)  # first stash match per lane
        found_s = jnp.any(sm, axis=1)
        sidx = jnp.sum(jnp.where(
            sfirst, jax.lax.broadcasted_iota(jnp.int32, (T, SP), 1), 0),
            axis=1)
        sbase = np.int32(nbuckets * WAYS)
        slot = jnp.where(found_b, slot,
                         jnp.where(found_s, sbase + sidx, 0))
        for v in range(V):
            sval = jnp.sum(jnp.where(sfirst, svals_ref[v, :][None, :],
                                     np.uint32(0)), axis=1, dtype=jnp.uint32)
            vcols[v] = jnp.where(found_b, vcols[v],
                                 jnp.where(found_s, sval, 0))
        found = found_b | found_s
    else:
        found = found_b

    # not-found slot parity: xla_lookup's argmax over all-False picks
    # candidate 0 = b1*WAYS (slot is documented valid-only-where-found,
    # but bit-exactness is the contract the property tests pin)
    slot = jnp.where(found, slot, b1v * WAYS)
    found_ref[0, 0, :] = found.astype(jnp.uint32)
    slot_ref[0, 0, :] = slot
    for v in range(V):
        vals_out_ref[v, 0, 0, :] = jnp.where(found, vcols[v], np.uint32(0))


@functools.partial(jax.jit, static_argnames=("nbuckets", "stash",
                                             "interpret"))
def _probe_jit(krows, stash_rows, vals, query, nbuckets, stash, interpret):
    """Jitted entry (the ops/pallas_qos mold) so EAGER callers — tests,
    the bench impl race — pay one compile per geometry instead of a
    fresh kernel trace per call; traced callers (the engine programs)
    inline it."""
    from bng_tpu.ops.table import WAYS  # late: table.py imports us lazily
    B, K = query.shape
    KW = stash_rows.shape[1]
    V = vals.shape[1]
    WS = WAYS * KW
    WSP = _pad_to(WS, 128)
    VP = _pad_to(V, 128)
    T = LANE_TILE
    Bp = _pad_to(max(B, T), T)
    nt = Bp // T

    q = query
    if Bp != B:
        # pad lanes carry zero keys: their DMAs land on valid buckets
        # (hash & mask is always in range) and their lanes are sliced off
        q = jnp.concatenate([q, jnp.zeros((Bp - B, K), dtype=jnp.uint32)])
    words = [q[:, k] for k in range(K)]
    mask = np.uint32(nbuckets - 1)
    b1 = (hash_words(words, SEED1) & mask).astype(jnp.int32)
    b2 = (hash_words(words, SEED2) & mask).astype(jnp.int32)
    idx = jnp.stack([b1, b2])  # [2, Bp] scalar prefetch (SMEM)

    # query words replicated across sublanes: [K, nt, SUB, T] blocks
    qws = jnp.broadcast_to(q.T.reshape(K, nt, 1, T), (K, nt, SUBLANES, T))

    # stash probe rows + value rows transposed to [word, stash-lane]
    SP = max(128, _pad_to(max(stash, 1), 128))
    KP = _pad_to(K + 1, 8)
    VR = _pad_to(max(V, 1), 8)
    stash_t = jnp.zeros((KP, SP), dtype=jnp.uint32)
    svals_t = jnp.zeros((VR, SP), dtype=jnp.uint32)
    if stash > 0:
        stash_t = stash_t.at[: K + 1, :stash].set(stash_rows[:, : K + 1].T)
        svals_t = svals_t.at[:V, :stash].set(vals[nbuckets * WAYS:, :].T)

    kernel = functools.partial(
        _probe_kernel, K=K, KW=KW, V=V, T=T, WS=WS, WSP=WSP, VP=VP,
        nbuckets=nbuckets, stash=stash, SP=SP, WAYS=WAYS)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nt,),
        in_specs=[
            pl.BlockSpec(memory_space=_ANY),  # krows stay in HBM
            pl.BlockSpec(memory_space=_ANY),  # vals stay in HBM
            pl.BlockSpec((K, 1, SUBLANES, T), lambda i, idx_ref: (0, i, 0, 0)),
            pl.BlockSpec((KP, SP), lambda i, idx_ref: (0, 0)),
            pl.BlockSpec((VR, SP), lambda i, idx_ref: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, SUBLANES, T), lambda i, idx_ref: (i, 0, 0)),
            pl.BlockSpec((1, SUBLANES, T), lambda i, idx_ref: (i, 0, 0)),
            pl.BlockSpec((V, 1, SUBLANES, T), lambda i, idx_ref: (0, i, 0, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((T, 2 * WSP), jnp.uint32),
            pltpu.VMEM((T, 2 * WAYS, VP), jnp.uint32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    found, slot, out_vals = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((nt, SUBLANES, T), jnp.uint32),
            jax.ShapeDtypeStruct((nt, SUBLANES, T), jnp.int32),
            jax.ShapeDtypeStruct((V, nt, SUBLANES, T), jnp.uint32),
        ],
        interpret=interpret,
    )(idx, krows, vals, qws, stash_t, svals_t)
    return (found[:, 0, :].reshape(Bp)[:B] != 0,
            slot[:, 0, :].reshape(Bp)[:B],
            out_vals[:, :, 0, :].reshape(V, Bp)[:, :B].T)


def pallas_probe(krows: jax.Array, stash_rows: jax.Array, vals: jax.Array,
                 query: jax.Array, nbuckets: int, stash: int,
                 interpret: bool | None = None):
    """The raw fused probe: returns (found [B] bool, slot [B] i32,
    vals [B, V] u32) bit-identical to ops.table.xla_lookup.

    interpret=None resolves per backend: Mosaic lowering is TPU-only,
    every other backend runs the Pallas interpreter (ADVICE r1: a GPU
    backend must not try to compile the Mosaic kernel).
    """
    if pltpu is None:  # pragma: no cover - env specific
        raise RuntimeError(
            "pallas TPU support unavailable in this jaxlib "
            "(jax.experimental.pallas.tpu failed to import) — the fused "
            "table probe cannot run even in interpret mode; use "
            "BNG_TABLE_IMPL=xla")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _probe_jit(krows, stash_rows, vals, query, nbuckets, stash,
                      interpret)


def pallas_lookup(state, query: jax.Array, nbuckets: int, stash: int,
                  interpret: bool | None = None):
    """device_lookup-shaped wrapper: TableState in, LookupResult out."""
    from bng_tpu.ops.table import LookupResult

    found, slot, vals = pallas_probe(state.krows, state.stash_rows,
                                     state.vals, query, nbuckets, stash,
                                     interpret=interpret)
    return LookupResult(found=found, slot=slot, vals=vals)
