"""The fused BNG packet pipeline — one jitted program per batch.

The reference runs four separate eBPF programs on different hooks (XDP
DHCP, TC antispoof/qos/NAT, SURVEY.md §1). On TPU, dispatch overhead
dominates small kernels, so the whole chain is ONE fused XLA program over a
[B, L] batch:

    parse -> antispoof -> DHCP responder -> NAT44 (SNAT/DNAT) -> QoS

Hook-order parity: XDP runs before TC in the kernel, so a DHCP fast-path
reply (XDP_TX) never traverses antispoof/QoS — here TX lanes are exempt
from the drop masks the same way. Slow-path DHCP requests (is_dhcp &
~is_reply) are likewise exempt from antispoof (DISCOVER's 0.0.0.0 source
must reach the DHCP server; the reference achieves this by attaching
antispoof only to data VLANs).

Direction is per-lane via `from_access` (True = subscriber-side ingress,
the uplink; False = core-side, the downlink) — the role of the two
interfaces in pkg/nat/tc_linux.go.

Verdicts (the XDP_TX/XDP_PASS/TC_ACT_SHOT model, per lane):
    PASS=0 (slow path / untouched), DROP=1, TX=2 (device-generated reply),
    FWD=3 (rewritten, forward).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops.antispoof import (
    ANTISPOOF_NSTATS,
    AntispoofGeom,
    antispoof_kernel,
)
from bng_tpu.ops import bytes as B_
from bng_tpu.ops.dhcp import DHCPGeom, DHCPTables, NSTATS as DHCP_NSTATS, dhcp_fastpath
from bng_tpu.ops.nat44 import (
    NATGeom,
    NATTables,
    NAT_NSTATS,
    nat44_kernel,
    nat44_update_sessions,
)
from bng_tpu.ops.parse import parse_batch
from bng_tpu.ops.qos import QOS_NSTATS, QoSGeom, qos_kernel
from bng_tpu.ops.qtable import QTableState
from bng_tpu.ops.table import TableGeom, TableState

VERDICT_PASS, VERDICT_DROP, VERDICT_TX, VERDICT_FWD = 0, 1, 2, 3


class PipelineTables(NamedTuple):
    """All device-resident state for the fused pipeline (a pytree)."""

    dhcp: DHCPTables
    nat: NATTables
    qos_up: QTableState  # keyed by src ip (upload; qos_ingress map role)
    qos_down: QTableState  # keyed by dst ip (download; qos_egress map role)
    spoof: TableState
    spoof_ranges: jax.Array  # [R, 2]
    spoof_config: jax.Array  # [2]
    # device-side walled garden (beyond the reference, ops/garden.py);
    # None = gate disabled (nil-safe, the reference's optional-maps
    # discipline, walledgarden/manager.go:113-116)
    garden: TableState | None = None
    garden_allowed: jax.Array | None = None  # [D, 3]
    # PPPoE session tables (ops/pppoe.py; control plane =
    # control/pppoe/server.py). None = no PPPoE stage compiled in — an
    # IPoE-only deployment pays nothing per batch. by_sid keys upstream
    # decap (session id -> MAC/IP row), by_ip keys downstream encap
    # (post-DNAT subscriber IP -> session row).
    pppoe_by_sid: TableState | None = None
    pppoe_by_ip: TableState | None = None
    pppoe_server_mac: jax.Array | None = None  # [2] uint32 (hi16, lo32)
    # edge protection (bng_tpu/edge): intercept tap-match rows + dense
    # filter/armed arrays, and the next-hop route table. None = no edge
    # stage compiled in; an armed-but-warrantless tap table costs one
    # predicate (the lax.cond in edge.ops.tap_match).
    tap: TableState | None = None
    tap_filters: jax.Array | None = None  # [F, 4] uint32
    tap_config: jax.Array | None = None  # [2] uint32
    route: TableState | None = None


class PipelineGeom(NamedTuple):
    dhcp: DHCPGeom
    nat: NATGeom
    qos: QoSGeom
    spoof: AntispoofGeom
    garden: TableGeom | None = None
    pppoe: TableGeom | None = None
    tap: TableGeom | None = None
    route: TableGeom | None = None


class PipelineResult(NamedTuple):
    verdict: jax.Array  # [B] int32
    out_pkt: jax.Array  # [B, L] uint8
    out_len: jax.Array  # [B] uint32
    tables: PipelineTables  # updated device state (counters/tokens)
    dhcp_stats: jax.Array  # [DHCP_NSTATS]
    nat_stats: jax.Array  # [NAT_NSTATS]
    qos_stats: jax.Array  # [QOS_NSTATS] (up + down combined)
    spoof_stats: jax.Array  # [ANTISPOOF_NSTATS]
    priority: jax.Array  # [B] uint32 (QoS class)
    nat_punt: jax.Array  # [B] bool — new flow, host must create session
    spoof_violation: jax.Array  # [B] bool — host audit log
    garden_stats: jax.Array | None = None  # [GARDEN_NSTATS] when gated
    pppoe_stats: jax.Array | None = None  # [PPPOE_NSTATS] when PPPoE on
    # [B] uint32: warrant id the lane mirrors for (0 = not mirrored).
    # Deliberately a side array, NOT a verdict bit: verdict histograms
    # and == VERDICT_* comparisons stay exact. The host retire path
    # (engine mirror_sink) extracts wid != 0 lanes for RecordCC/HI3.
    mirror: jax.Array | None = None
    edge_stats: jax.Array | None = None  # [EDGE_NSTATS] when edge on


def pipeline_step(
    tables: PipelineTables,
    pkt: jax.Array,
    length: jax.Array,
    from_access: jax.Array,
    geom: PipelineGeom,
    now_s: jax.Array,
    now_us: jax.Array,
) -> PipelineResult:
    # --- PPPoE decap pre-stage (session-stage upstream data; the
    # AC-termination role of pkg/pppoe/server.go:466-529, moved on-device
    # for DATA frames — control negotiation stays host-side and reaches it
    # via PASS lanes). Runs BEFORE the main parse so NAT/QoS/antispoof see
    # the inner IPv4 packet; PPPoE control/discovery and unknown-session
    # frames keep their original bytes, parse as non-IP, and fall through
    # every later stage to VERDICT_PASS (the slow-path punt).
    pppoe_dec = None
    if tables.pppoe_by_sid is not None:
        from bng_tpu.ops.parse import eth_vlan
        from bng_tpu.ops.pppoe import pppoe_decap

        vo, et = eth_vlan(pkt)
        # access-side only: a session ethertype arriving from the core is
        # foreign traffic — leave it untouched (PASS, host decides)
        et_gated = jnp.where(from_access, et, 0)
        pppoe_dec = pppoe_decap(pkt, length, vo, et_gated,
                                tables.pppoe_by_sid, geom.pppoe)
        pkt = jnp.where(pppoe_dec.done[:, None], pppoe_dec.out_pkt, pkt)
        length = jnp.where(pppoe_dec.done, pppoe_dec.out_len, length)

    parsed = parse_batch(pkt, length)

    # --- antispoof (TC ingress on access side; antispoof.c:188-293) ---
    spoof = antispoof_kernel(pkt, parsed, tables.spoof, geom.spoof,
                             tables.spoof_ranges, tables.spoof_config)
    spoof_drop = spoof.dropped & from_access

    # --- DHCP fast path (XDP; dhcp_fastpath.c:619-813) ---
    dhcp = dhcp_fastpath(pkt, length, parsed, tables.dhcp, geom.dhcp, now_s)
    dhcp_tx = dhcp.is_reply & from_access
    dhcp_slow = dhcp.is_dhcp & from_access & ~dhcp_tx
    # DHCP traffic bypasses antispoof (XDP-before-TC for TX; DISCOVER src
    # 0.0.0.0 must reach the slow path)
    spoof_drop = spoof_drop & ~dhcp.is_dhcp

    # --- walled-garden gate (device-side; BEYOND the reference, whose
    # garden maps have no consuming bpf program — ops/garden.py) ---
    garden_drop = jnp.zeros_like(from_access)
    garden_stats = None
    if tables.garden is not None:
        from bng_tpu.ops.garden import garden_kernel

        garden = garden_kernel(
            parsed,
            from_access & parsed.is_ipv4 & ~dhcp.is_dhcp,
            tables.garden, geom.garden, tables.garden_allowed)
        garden_drop = garden.gate_drop
        garden_stats = garden.stats

    # --- NAT44 (TC; nat44.c:565-948) — not for DHCP or gated lanes ---
    nat = nat44_kernel(pkt, length, parsed, tables.nat, geom.nat, now_s)
    natable = ~dhcp.is_dhcp & ~spoof_drop & ~garden_drop
    nat_fwd = nat.translated & natable
    nat_punt = nat.punted & natable

    # --- QoS (TC; qos_ratelimit.c:126-222) ---
    # upload: access-side lanes keyed by src ip (qos_ingress_prog :178)
    up = qos_kernel(parsed.src_ip, length, from_access & parsed.is_ipv4 & ~dhcp.is_dhcp,
                    tables.qos_up, geom.qos, now_us)
    # download: core-side lanes keyed by POST-DNAT dst ip (the subscriber
    # address — after DNAT the dst is the private ip, qos_egress_prog :126).
    # Read it from the rewritten bytes: covers translated and untouched lanes.
    dnat_dst = B_.be32_at(nat.out_pkt, parsed.l3_off + 16)
    down = qos_kernel(dnat_dst, length, ~from_access & parsed.is_ipv4,
                      tables.qos_down, geom.qos, now_us)
    qos_drop = (up.dropped & from_access) | (down.dropped & ~from_access)

    # --- edge protection (bng_tpu/edge): intercept tap-match + next-hop
    # route rewrite. The tap keys on the SUBSCRIBER address of the lane
    # (src upstream, post-DNAT dst downstream) so one row taps both
    # directions of a session; the route table steers upstream lanes to
    # their ISP next-hop (per-class ECMP compiled host-side). Mirror is
    # a side array (see PipelineResult); the route rewrite patches the
    # L2 dst MAC in place on nat.out_pkt — upstream-only, disjoint from
    # pppoe_encap's downstream MAC stamp.
    mirror = None
    edge_stats = None
    data_pkt = nat.out_pkt
    route_fwd = jnp.zeros_like(from_access)
    if tables.tap is not None:
        from bng_tpu.edge.ops import route_rewrite, tap_match

        sub_ip = jnp.where(from_access, parsed.src_ip, dnat_dst)
        peer_ip = jnp.where(from_access, parsed.dst_ip, parsed.src_ip)
        data_lane = parsed.is_ipv4 & ~dhcp.is_dhcp
        tap = tap_match(sub_ip, parsed.src_port, parsed.dst_port,
                        parsed.proto, peer_ip, data_lane, tables.tap,
                        tables.tap_filters, tables.tap_config, geom.tap)
        mirror = tap.mirror
        rt = route_rewrite(data_pkt, sub_ip, data_lane & from_access,
                           tables.route, geom.route)
        data_pkt = rt.out_pkt
        route_fwd = rt.hit
        edge_stats = jnp.concatenate([tap.stats, rt.stats])

    # --- PPPoE encap post-stage: downstream data whose post-DNAT dst is
    # an OPEN PPPoE session gets its AC framing here (the reference builds
    # these frames host-side per packet, pkg/pppoe/server.go; batched
    # on-device they ride the same program). Applies to nat.out_pkt —
    # dhcp_tx lanes are access-side and disjoint.
    pppoe_enc = None
    if tables.pppoe_by_ip is not None:
        from bng_tpu.ops.pppoe import pppoe_encap

        enc_et = jnp.where(~from_access, parsed.ethertype, 0)
        pppoe_enc = pppoe_encap(nat.out_pkt, length, parsed.vlan_offset,
                                enc_et, dnat_dst, tables.pppoe_by_ip,
                                geom.pppoe, tables.pppoe_server_mac)

    # --- verdict combination (precedence: TX > DROP > FWD > PASS) ---
    drop = (spoof_drop | qos_drop | garden_drop) & ~dhcp_tx
    # a routed (next-hop-rewritten) lane forwards even when NAT left it
    # untouched — the non-CGNAT routed-subscriber case
    fwd = nat_fwd | (route_fwd & ~drop & ~dhcp_tx)
    out_pkt = jnp.where(dhcp_tx[:, None], dhcp.out_pkt, data_pkt)
    out_len = jnp.where(dhcp_tx, dhcp.out_len, length)
    if pppoe_enc is not None:
        enc_done = pppoe_enc.done & ~drop & ~dhcp_tx
        out_pkt = jnp.where(enc_done[:, None], pppoe_enc.out_pkt, out_pkt)
        out_len = jnp.where(enc_done, pppoe_enc.out_len, out_len)
        # an encapsulated frame forwards even when NAT left it untouched
        # (routed/IPoE-free deployments still need the PPP framing)
        fwd = fwd | enc_done
    verdict = jnp.where(
        dhcp_tx, VERDICT_TX,
        jnp.where(drop, VERDICT_DROP,
                  jnp.where(fwd, VERDICT_FWD, VERDICT_PASS)),
    ).astype(jnp.int32)

    # NAT accounting only for lanes that actually forward: a packet the
    # pipeline drops (QoS/antispoof) must not advance session counters
    new_sessions = nat44_update_sessions(
        tables.nat.sessions, nat, parsed, length,
        keep=nat_fwd & ~drop, now_s=now_s)
    new_tables = tables._replace(
        nat=tables.nat._replace(sessions=new_sessions),
        qos_up=up.table,
        qos_down=down.table,
    )
    return PipelineResult(
        verdict=verdict,
        out_pkt=out_pkt,
        out_len=out_len,
        tables=new_tables,
        dhcp_stats=dhcp.stats,
        nat_stats=nat.stats,
        qos_stats=up.stats + down.stats,
        spoof_stats=spoof.stats,
        priority=jnp.maximum(up.priority, down.priority),
        nat_punt=nat_punt,
        spoof_violation=spoof.violation,
        garden_stats=garden_stats,
        pppoe_stats=(None if pppoe_dec is None else
                     pppoe_dec.stats + (0 if pppoe_enc is None
                                        else pppoe_enc.stats)),
        mirror=mirror,
        edge_stats=edge_stats,
    )
