"""NAT44/CGNAT kernel: batched SNAT (egress) + DNAT (ingress) on device.

TPU re-expression of bpf/nat44.c. The reference's "conntrack hybrid"
architecture (nat44.c:6-9: first packet of a new flow goes slow-path, later
packets fast-path) maps perfectly onto the host-single-writer table design:

- Established flows: device translates at line rate from the `sessions` /
  `reverse` cuckoo tables and updates per-session counters with HBM
  scatter-adds (the per-CPU-atomic role of nat44.c:286-292).
- New flows (session miss) return verdict PASS; the host NAT manager
  (bng_tpu.control.nat) performs RFC 6431 port-block allocation + RFC 4787
  EIM host-side — the get_eim_mapping/allocate_port_from_block logic
  (nat44.c:408-528) — inserts session+reverse rows, and the flow is
  device-resident from packet 2 on. This removes the reference's
  "benign race" port allocation (nat44.c:411-418) entirely: one writer.

Device-visible state:
- sessions: key [src_ip, dst_ip, ports, proto] -> session row (V=16)
- reverse:  key [remote_ip, nat_ip, ports, proto] -> original session key
- sub_nat:  key [private_ip] -> port-block summary (presence gates NAT;
            parity: subscriber_nat map, nat44.c:246-252)
- hairpin_ips: dense [H] public IPs (nat44.c:262-268)
- alg_ports: dense [A] (port<<16|proto) trigger list (nat44.c:300-306)
- config: flags word (nat44.c:270-277)

Counter semantics: device owns session counters/last_seen/TCP state;
the host treats them as read-only telemetry (fetched for accounting and
expiry) and only writes rows at insert/delete time.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops import bytes as B_
from bng_tpu.ops.checksum import csum_update16, csum_update32
from bng_tpu.ops.parse import PROTO_ICMP, PROTO_TCP, PROTO_UDP, Parsed
from bng_tpu.ops.table import TableGeom, TableState, lookup

# session value-word layout (parity: struct nat_session, nat44.c:123-141)
(SV_NAT_IP, SV_NAT_PORT, SV_ORIG_IP, SV_ORIG_PORT, SV_DEST_IP, SV_DEST_PORT,
 SV_CREATED, SV_LAST_SEEN, SV_STATE, SV_PROTO, SV_FLAGS,
 SV_PKTS_OUT, SV_PKTS_IN, SV_BYTES_OUT, SV_BYTES_IN) = range(15)
SESSION_WORDS = 16

# reverse rows carry the 4 original-session key words, padded to the
# 8-word gather-fast row shape (BNG014: <8-word value rows are the
# PERF_NOTES §2 serialization class — the pad is free HBM, the narrow
# gather was not)
REVERSE_WORDS = 8

# subscriber_nat value layout (parity: struct port_block, nat44.c:144-155)
(BV_PUBLIC_IP, BV_PORT_START, BV_PORT_END, BV_NEXT_PORT, BV_IN_USE,
 BV_SUB_ID, BV_FLAGS) = range(7)
SUBNAT_WORDS = 8

# NAT states (nat44.c:64-71)
NAT_STATE_NEW, NAT_STATE_ESTABLISHED, NAT_STATE_FIN_WAIT, NAT_STATE_CLOSING, NAT_STATE_TIME_WAIT = range(5)

# config flags (nat44.c:55-62)
FLAG_EIM, FLAG_EIF, FLAG_HAIRPIN, FLAG_ALG_FTP, FLAG_ALG_SIP, FLAG_PORT_PARITY, FLAG_PORT_CONTIG = (
    0x01, 0x02, 0x04, 0x08, 0x10, 0x20, 0x40)

# stats indices (parity: struct nat_stats, nat44.c:176-190)
(NST_SNAT, NST_DNAT, NST_HAIRPIN, NST_DROPPED, NST_PASSED, NST_CREATED,
 NST_EXPIRED, NST_PORT_EXH, NST_EIM_HIT, NST_EIM_MISS, NST_ALG) = range(11)
NAT_NSTATS = 11


class NATTables(NamedTuple):
    sessions: TableState  # K=4, V=SESSION_WORDS
    reverse: TableState  # K=4, V=8 (original key words + gather pad)
    sub_nat: TableState  # K=1, V=SUBNAT_WORDS
    hairpin_ips: jax.Array  # [H] uint32 (0 = empty)
    alg_ports: jax.Array  # [A] uint32 (port<<16|proto; 0 = empty)
    config: jax.Array  # [4] uint32: [flags, port_start, port_end, ports_per_sub]


class NATGeom(NamedTuple):
    sessions: TableGeom
    reverse: TableGeom
    sub_nat: TableGeom


class NATResult(NamedTuple):
    translated: jax.Array  # [B] bool — SNAT/DNAT applied (fast path hit)
    punted: jax.Array  # [B] bool — new flow / ALG: needs slow path
    dropped: jax.Array  # [B] bool
    out_pkt: jax.Array  # [B, L] uint8 rewritten packets
    stats: jax.Array  # [NAT_NSTATS] uint32
    is_hairpin: jax.Array  # [B] bool
    # per-lane hit info for the deferred accounting pass
    # (update_sessions applies counters only for lanes the pipeline
    # actually forwards, so QoS/antispoof drops are never billed)
    egress_hit: jax.Array  # [B] bool
    ingress_hit: jax.Array  # [B] bool
    e_slot: jax.Array  # [B] int32 session row for egress hits
    i_slot: jax.Array  # [B] int32 session row for ingress hits
    i_state: jax.Array  # [B] uint32 current TCP state (ingress rows)


def is_private_ip(ip):
    """Branch-free RFC1918 + 100.64/10 check. Parity: nat44.c:340-363."""
    o1 = ip >> 24
    o2 = (ip >> 16) & 0xFF
    return (
        (o1 == 10)
        | ((o1 == 172) & (o2 >= 16) & (o2 <= 31))
        | ((o1 == 192) & (o2 == 168))
        | ((o1 == 100) & (o2 >= 64) & (o2 <= 127))
    )


def _in_set(values, dense_set):
    """[B] membership test against a small dense uint32 set (0 = empty)."""
    eq = values[:, None] == dense_set[None, :]
    return jnp.any(eq & (dense_set[None, :] != 0), axis=1)


def _session_key(a_ip, b_ip, a_port, b_port, proto):
    return jnp.stack(
        [a_ip, b_ip, ((a_port & 0xFFFF) << 16) | (b_port & 0xFFFF), proto], axis=1
    ).astype(jnp.uint32)


def _rewrite_l3_l4(pkt, parsed, mask, new_ip, new_port, is_src):
    """Apply SNAT (is_src) or DNAT rewrite + incremental checksums.

    Parity: nat44.c:752-801 (egress) / :897-944 (ingress).
    """
    ip_field_off = parsed.l3_off + jnp.where(is_src, 12, 16)
    old_ip = jnp.where(is_src, parsed.src_ip, parsed.dst_ip)
    old_port = jnp.where(is_src, parsed.src_port, parsed.dst_port)

    # IP header checksum (incremental)
    ip_csum = B_.be16_at(pkt, parsed.l3_off + 10)
    new_ip_csum = csum_update32(ip_csum, old_ip, new_ip)

    pkt = B_.scatter_be32_at_masked(pkt, ip_field_off, new_ip, mask)
    pkt = B_.scatter_be16_at_masked(pkt, parsed.l3_off + 10, new_ip_csum, mask)

    # L4 rewrite
    port_off = parsed.l4_off + jnp.where(is_src, 0, 2)
    tcp_mask = mask & parsed.is_tcp
    udp_mask = mask & parsed.is_udp
    icmp_mask = mask & parsed.is_icmp

    # TCP checksum at l4_off+16 (pseudo-header includes IP)
    tcp_csum = B_.be16_at(pkt, parsed.l4_off + 16)
    tcp_csum = csum_update32(tcp_csum, old_ip, new_ip)
    tcp_csum = csum_update16(tcp_csum, old_port, new_port)
    pkt = B_.scatter_be16_at_masked(pkt, parsed.l4_off + 16, tcp_csum, tcp_mask)
    pkt = B_.scatter_be16_at_masked(pkt, port_off, new_port, tcp_mask)

    # UDP checksum at l4_off+6 (0 = absent; 0 result -> 0xFFFF, nat44.c:784)
    udp_csum = B_.be16_at(pkt, parsed.l4_off + 6)
    has_csum = udp_csum != 0
    new_udp_csum = csum_update16(csum_update32(udp_csum, old_ip, new_ip), old_port, new_port)
    new_udp_csum = jnp.where(new_udp_csum == 0, 0xFFFF, new_udp_csum)
    pkt = B_.scatter_be16_at_masked(pkt, parsed.l4_off + 6, new_udp_csum, udp_mask & has_csum)
    pkt = B_.scatter_be16_at_masked(pkt, port_off, new_port, udp_mask)

    # ICMP: echo id at l4_off+4, checksum at l4_off+2 (no pseudo-header)
    icmp_csum = B_.be16_at(pkt, parsed.l4_off + 2)
    new_icmp_csum = csum_update16(icmp_csum, old_port, new_port)
    pkt = B_.scatter_be16_at_masked(pkt, parsed.l4_off + 2, new_icmp_csum, icmp_mask)
    pkt = B_.scatter_be16_at_masked(pkt, parsed.l4_off + 4, new_port, icmp_mask)

    return pkt


def nat44_kernel(
    pkt: jax.Array,
    length: jax.Array,
    parsed: Parsed,
    tables: NATTables,
    geom: NATGeom,
    now_s: jax.Array,
) -> NATResult:
    """Fused egress-SNAT + ingress-DNAT over one batch.

    Direction is per-lane: private source -> egress path (nat44_egress),
    otherwise -> ingress path (nat44_ingress). Lanes that are not IPv4 or
    not TCP/UDP/ICMP pass through untouched (TC_ACT_OK parity).
    """
    Bsz = pkt.shape[0]
    stats = jnp.zeros((NAT_NSTATS,), dtype=jnp.uint32)
    cfg_flags = tables.config[0]

    def count(m):
        return jnp.sum(m, dtype=jnp.uint32)

    l4ok = parsed.is_tcp | parsed.is_udp | parsed.is_icmp
    eligible = parsed.is_ipv4 & l4ok
    egress = eligible & is_private_ip(parsed.src_ip)
    ingress = eligible & ~is_private_ip(parsed.src_ip)

    # ---- egress: subscriber allocation gate (nat44.c:589-596) ----
    sub_res = lookup(tables.sub_nat, parsed.src_ip[:, None], geom.sub_nat)
    has_alloc = sub_res.found & egress
    no_alloc = egress & ~sub_res.found
    stats = stats.at[NST_PASSED].add(count(no_alloc))

    # ---- ALG triggers (nat44.c:616-641): punt to host ALG ----
    alg_enabled = (cfg_flags & (FLAG_ALG_FTP | FLAG_ALG_SIP)) != 0
    alg_key = ((parsed.dst_port & 0xFFFF) << 16) | (parsed.proto & 0xFF)
    alg_hit = has_alloc & alg_enabled & _in_set(alg_key, tables.alg_ports) & (parsed.is_tcp | parsed.is_udp)
    stats = stats.at[NST_ALG].add(count(alg_hit))

    # ---- hairpin detection (nat44.c:659-665) ----
    hairpin_on = (cfg_flags & FLAG_HAIRPIN) != 0
    is_hairpin = has_alloc & hairpin_on & _in_set(parsed.dst_ip, tables.hairpin_ips)
    stats = stats.at[NST_HAIRPIN].add(count(is_hairpin))

    # ---- egress session lookup (nat44.c:668-681) ----
    # ICMP key halves per the reference: egress tracks (echo_id, 0)
    # (nat44.c:643-649), ingress matches (0, echo_id) (nat44.c:846-851).
    e_dst_port = jnp.where(parsed.is_icmp, 0, parsed.dst_port)
    ekey = _session_key(parsed.src_ip, parsed.dst_ip, parsed.src_port, e_dst_port, parsed.proto)
    esess = lookup(tables.sessions, ekey, geom.sessions)
    egress_active = has_alloc & ~alg_hit
    egress_hit = egress_active & esess.found
    egress_miss = egress_active & ~esess.found  # new flow -> punt to host
    stats = stats.at[NST_SNAT].add(count(egress_hit))

    # ---- ingress reverse lookup (nat44.c:860-876) ----
    i_src_port = jnp.where(parsed.is_icmp, 0, parsed.src_port)
    rkey = _session_key(parsed.src_ip, parsed.dst_ip, i_src_port, parsed.dst_port, parsed.proto)
    rres = lookup(tables.reverse, rkey, geom.reverse)
    ingress_rhit = ingress & rres.found
    stats = stats.at[NST_PASSED].add(count(ingress & ~rres.found))
    isess = lookup(tables.sessions, rres.vals[:, :4], geom.sessions)
    ingress_hit = ingress_rhit & isess.found
    ingress_orphan = ingress_rhit & ~isess.found  # reverse without session
    stats = stats.at[NST_EXPIRED].add(count(ingress_orphan))
    stats = stats.at[NST_DNAT].add(count(ingress_hit))

    hit_any = egress_hit | ingress_hit

    # ---- packet rewrite ----
    nat_ip = esess.vals[:, SV_NAT_IP]
    nat_port = esess.vals[:, SV_NAT_PORT]
    pkt = _rewrite_l3_l4(pkt, parsed, egress_hit, nat_ip, nat_port, is_src=jnp.ones((Bsz,), dtype=bool))
    orig_ip = isess.vals[:, SV_ORIG_IP]
    orig_port = isess.vals[:, SV_ORIG_PORT]
    pkt = _rewrite_l3_l4(pkt, parsed, ingress_hit, orig_ip, orig_port, is_src=jnp.zeros((Bsz,), dtype=bool))

    punted = egress_miss | alg_hit
    return NATResult(
        translated=hit_any,
        punted=punted,
        dropped=jnp.zeros((Bsz,), dtype=bool),
        out_pkt=pkt,
        stats=stats,
        is_hairpin=is_hairpin,
        egress_hit=egress_hit,
        ingress_hit=ingress_hit,
        e_slot=esess.slot.astype(jnp.int32),
        i_slot=isess.slot.astype(jnp.int32),
        i_state=isess.vals[:, SV_STATE],
    )


def nat44_update_sessions(
    sessions: TableState,
    res: NATResult,
    parsed: Parsed,
    length: jax.Array,
    keep: jax.Array,
    now_s: jax.Array,
) -> TableState:
    """Apply session counters/last_seen/TCP-state for forwarded lanes only.

    `keep` is the pipeline's final forward decision: packets dropped by
    QoS/antispoof after translation must not be billed to the subscriber
    (the kernel hooks get this for free from hook ordering; here the
    accounting pass is explicitly gated).
    """
    Bsz = length.shape[0]
    egress_hit = res.egress_hit & keep
    ingress_hit = res.ingress_hit & keep
    hit_any = egress_hit | ingress_hit
    slot = jnp.where(egress_hit, res.e_slot, res.i_slot)
    # out-of-bounds slot for non-hit lanes -> dropped by scatter
    S = sessions.vals.shape[0]
    upd_slot = jnp.where(hit_any, slot, S).astype(jnp.int32)
    plen = length.astype(jnp.uint32)
    vals = sessions.vals
    zeros = jnp.zeros((Bsz,), dtype=jnp.uint32)
    ones = jnp.ones((Bsz,), dtype=jnp.uint32)
    add_block = jnp.stack(
        [
            jnp.where(egress_hit, ones, zeros),  # SV_PKTS_OUT
            jnp.where(ingress_hit, ones, zeros),  # SV_PKTS_IN
            jnp.where(egress_hit, plen, zeros),  # SV_BYTES_OUT
            jnp.where(ingress_hit, plen, zeros),  # SV_BYTES_IN
        ],
        axis=1,
    )
    vals = vals.at[upd_slot, SV_PKTS_OUT : SV_BYTES_IN + 1].add(add_block, mode="drop")
    vals = vals.at[upd_slot, SV_LAST_SEEN].set(
        jnp.broadcast_to(now_s, (Bsz,)).astype(jnp.uint32), mode="drop")

    # TCP state machine on ingress (nat44.c:885-895). Scatter-max keeps
    # duplicate-slot batches deterministic: states are ordered
    # NEW < ESTABLISHED < FIN_WAIT < CLOSING, so a FIN/RST lane always
    # wins over a same-batch ACK lane regardless of scatter order.
    fin_or_rst = (parsed.tcp_flags & 0x05) != 0  # FIN|RST
    ack = (parsed.tcp_flags & 0x10) != 0
    cur_state = res.i_state
    new_state = jnp.where(
        fin_or_rst, NAT_STATE_CLOSING,
        jnp.where((cur_state == NAT_STATE_NEW) & ack, NAT_STATE_ESTABLISHED, cur_state),
    ).astype(jnp.uint32)
    state_slot = jnp.where(ingress_hit & parsed.is_tcp, res.i_slot, S).astype(jnp.int32)
    vals = vals.at[state_slot, SV_STATE].max(new_state, mode="drop")
    return sessions._replace(vals=vals)
