"""Anti-spoofing / uRPF source validation, batched.

TPU re-expression of bpf/antispoof.c (antispoof_ingress, :188-293).
Per-lane mode resolution, strict/loose/log-only semantics, IPv4 + IPv6
exact binding, and LPM "allowed ranges" done as a dense broadcast compare
(<=256 ranges, antispoof.c:113-119 — a [B, R] compare beats a trie on TPU).

Deliberate parity quirk preserved: a subscriber with a valid IPv4 binding
in LOOSE mode is never matched against the range list (antispoof.c:227-235
only checks ranges in the else-branch), so loose-mode-with-binding drops
unless the mode is strict/log-only and the IP matches.

Violation reporting: instead of a perf-event buffer (antispoof.c:100-105)
the kernel returns per-lane violation flags; the engine extracts violating
lanes and hands them to the host audit logger.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops import bytes as B_
from bng_tpu.ops.parse import Parsed
from bng_tpu.ops.table import TableGeom, TableState, lookup

# modes (antispoof.c:30-33)
MODE_DISABLED, MODE_STRICT, MODE_LOOSE, MODE_LOG_ONLY = range(4)

# binding value words (parity: struct subscriber_binding, antispoof.c:36-43)
(AB_IPV4, AB_V6_0, AB_V6_1, AB_V6_2, AB_V6_3, AB_VALIDS, AB_MODE) = range(7)
ANTISPOOF_WORDS = 8
VALID_V4, VALID_V6 = 0x01, 0x02

# stats (parity: struct antispoof_stats, antispoof.c:58-65)
(AST_ALLOWED, AST_DROPPED, AST_LOGGED, AST_V4_VIOL, AST_V6_VIOL, AST_UNKNOWN_MAC) = range(6)
ANTISPOOF_NSTATS = 6


AntispoofGeom = TableGeom


class AntispoofResult(NamedTuple):
    dropped: jax.Array  # [B] bool
    violation: jax.Array  # [B] bool (includes log-only violations)
    stats: jax.Array  # [ANTISPOOF_NSTATS] uint32


def antispoof_kernel(
    pkt: jax.Array,
    parsed: Parsed,
    bindings: TableState,
    geom: AntispoofGeom,
    allowed_ranges: jax.Array,  # [R, 2] uint32: (prefix_len, network); plen 0 = empty row
    config: jax.Array,  # [2] uint32: [default_mode, log_violations]
) -> AntispoofResult:
    Bsz = pkt.shape[0]
    default_mode = config[0]

    mac_key = jnp.stack([parsed.src_mac_hi, parsed.src_mac_lo], axis=1)
    res = lookup(bindings, mac_key, geom)
    has_binding = res.found
    mode = jnp.where(has_binding, res.vals[:, AB_MODE], default_mode)

    disabled = mode == MODE_DISABLED

    # --- IPv4 (antispoof.c:219-253) ---
    v4_valid = has_binding & ((res.vals[:, AB_VALIDS] & VALID_V4) != 0)
    strict_ok = (parsed.src_ip == res.vals[:, AB_IPV4])
    # loose: membership in any allowed range (dense prefix compare)
    plen = allowed_ranges[:, 0]
    net = allowed_ranges[:, 1]
    sh = jnp.clip(32 - plen.astype(jnp.int32), 0, 32)
    sh1 = jnp.minimum(sh, 16)
    sh2 = sh - sh1
    src_pfx = ((parsed.src_ip[:, None] >> sh1[None, :]) >> sh2[None, :])
    net_pfx = ((net >> sh1) >> sh2)[None, :]
    in_range = jnp.any((src_pfx == net_pfx) & (plen != 0)[None, :], axis=1)

    v4_allowed = jnp.where(
        v4_valid,
        ((mode == MODE_STRICT) | (mode == MODE_LOG_ONLY)) & strict_ok,
        (mode == MODE_LOOSE) & in_range,
    )
    v4_viol = parsed.is_ipv4 & ~disabled & ~v4_allowed
    v4_drop = v4_viol & (mode != MODE_LOG_ONLY)

    # --- IPv6 (antispoof.c:256-288) ---
    v6_valid = has_binding & ((res.vals[:, AB_VALIDS] & VALID_V6) != 0)
    src6 = B_.bytes_at(pkt, parsed.l3_off + 8, 16)  # IPv6 saddr
    w = src6.astype(jnp.uint32).reshape(Bsz, 4, 4)
    src6_words = (w[:, :, 0] << 24) | (w[:, :, 1] << 16) | (w[:, :, 2] << 8) | w[:, :, 3]
    bound6 = res.vals[:, AB_V6_0 : AB_V6_3 + 1]
    v6_match = jnp.all(src6_words == bound6, axis=1)
    # loose mode with no binding allows (antispoof.c:273-277)
    v6_allowed = jnp.where(v6_valid, v6_match, mode == MODE_LOOSE)
    v6_viol = parsed.is_ipv6 & ~disabled & ~v6_allowed
    v6_drop = v6_viol & (mode != MODE_LOG_ONLY)

    dropped = v4_drop | v6_drop
    violation = v4_viol | v6_viol
    log_on = config[1] != 0

    stats = jnp.zeros((ANTISPOOF_NSTATS,), dtype=jnp.uint32)
    stats = stats.at[AST_DROPPED].add(jnp.sum(dropped, dtype=jnp.uint32))
    stats = stats.at[AST_ALLOWED].add(jnp.sum(~dropped, dtype=jnp.uint32))
    stats = stats.at[AST_V4_VIOL].add(jnp.sum(v4_drop, dtype=jnp.uint32))
    stats = stats.at[AST_V6_VIOL].add(jnp.sum(v6_drop, dtype=jnp.uint32))
    stats = stats.at[AST_LOGGED].add(jnp.sum(violation & log_on, dtype=jnp.uint32))

    return AntispoofResult(dropped=dropped, violation=violation & log_on, stats=stats)
