"""DHCP fast-path kernel: batched in-device OFFER/ACK generation.

TPU re-expression of the XDP program dhcp_fastpath_prog
(bpf/dhcp_fastpath.c:619-813). One XDP invocation = one lane of a [B, L]
batch; `return XDP_PASS/XDP_TX` becomes per-lane verdict masks; the three
eBPF map lookups become cuckoo-table gathers; the in-place packet rewrite +
bpf_xdp_adjust_tail becomes a canonical-reply compose with per-lane VLAN
reinsertion (a single gather — TPUs shift bytes with index arithmetic, not
memmove).

Parity notes (cited against /root/reference):
- msg-type extraction at fixed offsets {0,1,3,4,5,6}: dhcp_fastpath.c:216-250
- circuit-ID extraction at fixed positions {3, 12..19}: dhcp_fastpath.c:267-323
- lookup cascade VLAN -> circuit-ID -> MAC: dhcp_fastpath.c:653-681
- lease expiry check: dhcp_fastpath.c:690-695
- relay (giaddr!=0) vs broadcast reply: dhcp_fastpath.c:721-756
- option build order 53,54,51,1,3,[6],58,59,255: dhcp_fastpath.c:519-602
- stats enum: dhcp_fastpath.c:117-128
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops import bytes as B_
from bng_tpu.ops.checksum import ipv4_header_checksum
from bng_tpu.ops.parse import Parsed
from bng_tpu.ops.table import TableGeom, TableState, lookup

# ---- DHCP constants ----
DHCP_SERVER_PORT = 67
DHCP_CLIENT_PORT = 68
DHCP_MAGIC = 0x63825363
BOOTREQUEST, BOOTREPLY = 1, 2
DISCOVER, OFFER, REQUEST, ACK = 1, 2, 3, 5
FLAG_BROADCAST = 0x8000

# pool_assignment value-word layout (parity: bpf/maps.h:89-97)
AV_POOL_ID, AV_IP, AV_VLAN, AV_CLASS, AV_LEASE_EXP, AV_FLAGS = range(6)
ASSIGN_WORDS = 8

# ip_pool row layout (parity: bpf/maps.h:135-144); dense array, pool_id index
PV_NETWORK, PV_PREFIX, PV_GATEWAY, PV_DNS1, PV_DNS2, PV_LEASE_T, PV_VALID = range(7)
POOL_WORDS = 8

# server_config layout (parity: bpf/maps.h:153-159)
SC_MAC_HI, SC_MAC_LO, SC_IP = range(3)
SERVER_WORDS = 4

# stats indices (parity: enum stat_counter, dhcp_fastpath.c:117-128)
(ST_TOTAL, ST_HIT, ST_MISS, ST_ERROR, ST_EXPIRED,
 ST_OPT82_PRESENT, ST_OPT82_ABSENT, ST_BCAST, ST_UCAST, ST_VLAN) = range(10)
NSTATS = 10

CID_KEY_LEN = 32  # bpf/maps.h:216
CID_WORDS = 8

# canonical (untagged) reply geometry
_ETH, _IP, _UDP, _BOOTP = 14, 20, 8, 240
_OPT_HEAD = 27  # 53(3) + 54(6) + 51(6) + 1(6) + 3(6)
_OPT_DNS_MAX = 10
_OPT_TAIL = 13  # 58(6) + 59(6) + 255(1)
_OPT_MAX = _OPT_HEAD + _OPT_DNS_MAX + _OPT_TAIL
CANON_LEN = _ETH + _IP + _UDP + _BOOTP + _OPT_MAX  # 332


class DHCPTables(NamedTuple):
    """Device-side state for the DHCP fast path (pytree)."""

    sub: TableState  # key [mac_hi, mac_lo] -> assignment (subscriber_pools)
    vlan: TableState  # key [s_tag<<16|c_tag] -> assignment (vlan_subscriber_pools)
    cid: TableState  # key 8 words (32B circuit-id) -> assignment (circuit_id_subscribers)
    pools: jax.Array  # [P, POOL_WORDS] dense (ip_pools; pool_id is a small int)
    server: jax.Array  # [SERVER_WORDS] (server_config)


class DHCPGeom(NamedTuple):
    """Static table geometry (part of the jit closure / static args)."""

    sub: TableGeom
    vlan: TableGeom
    cid: TableGeom


class DHCPResult(NamedTuple):
    is_reply: jax.Array  # [B] bool — lane answered on device (XDP_TX)
    is_dhcp: jax.Array  # [B] bool — lane is a DHCP request (reply or slow path)
    out_pkt: jax.Array  # [B, L] uint8 — reply bytes (valid where is_reply)
    out_len: jax.Array  # [B] uint32
    stats: jax.Array  # [NSTATS] uint32 batch deltas


def _extract_msg_type(pkt, opts_off, opts_in_bounds):
    """Fixed-offset option-53 scan. Parity: get_dhcp_msg_type."""
    found = jnp.zeros_like(opts_in_bounds)
    mtype = jnp.zeros(pkt.shape[0], dtype=jnp.uint32)
    for o in (0, 1, 3, 4, 5, 6):  # same offsets, same order as the reference
        ok = (B_.u8_at(pkt, opts_off + o) == 53) & (B_.u8_at(pkt, opts_off + o + 1) == 1)
        take = ok & ~found & opts_in_bounds
        mtype = jnp.where(take, B_.u8_at(pkt, opts_off + o + 2), mtype)
        found = found | take
    return jnp.where(opts_in_bounds, mtype, 0)


def _extract_circuit_id(pkt, opts_off, length):
    """Fixed-position Option-82 circuit-ID extraction.

    Parity: extract_circuit_id_fixed (dhcp_fastpath.c:267-323).
    Returns (found [B] bool, cid [B, 32] uint8 zero-padded).
    """
    Bsz = pkt.shape[0]
    scan_ok = (opts_off.astype(jnp.uint32) + 64) <= length

    found = jnp.zeros((Bsz,), dtype=bool)
    cid = jnp.zeros((Bsz, CID_KEY_LEN), dtype=jnp.uint8)

    def try_pos(found, cid, tag_off, len_off, sub_off, cidlen_off, cid_off, extra_ok):
        tag = B_.u8_at(pkt, opts_off + tag_off)
        o82len = B_.u8_at(pkt, opts_off + len_off)
        sub1 = B_.u8_at(pkt, opts_off + sub_off)
        cl = B_.u8_at(pkt, opts_off + cidlen_off)
        in_b = (opts_off.astype(jnp.uint32) + cid_off + cl) <= length
        ok = (
            scan_ok & extra_ok & (tag == 82) & (o82len >= 4) & (sub1 == 1)
            & (cl > 0) & (cl <= CID_KEY_LEN) & in_b & ~found
        )
        raw = B_.bytes_at(pkt, opts_off + cid_off, CID_KEY_LEN)  # [B, 32]
        mask = jnp.arange(CID_KEY_LEN)[None, :] < cl[:, None]
        cand = jnp.where(mask, raw, 0)
        cid = jnp.where(ok[:, None], cand, cid)
        return found | ok, cid

    # Position A: [53][1][x][82][len][sub=1][cl][cid...] (tag at opts+3)
    o82len_a = B_.u8_at(pkt, opts_off + 4)
    a_extra = (opts_off.astype(jnp.uint32) + 5 + o82len_a) <= length
    found, cid = try_pos(found, cid, 3, 4, 5, 6, 7, a_extra)
    # Positions 12..19
    for p in range(12, 20):
        p_extra = (opts_off.astype(jnp.uint32) + p + 8) <= length
        found, cid = try_pos(found, cid, p, p + 1, p + 2, p + 3, p + 4, p_extra)
    return found, cid


def pack_cid_words(cid_bytes):
    """[B, 32] uint8 -> [B, 8] uint32 big-endian words (table key form)."""
    b = cid_bytes.astype(jnp.uint32).reshape(cid_bytes.shape[0], CID_WORDS, 4)
    return (b[:, :, 0] << 24) | (b[:, :, 1] << 16) | (b[:, :, 2] << 8) | b[:, :, 3]


def _prefix_to_mask(plen):
    """CIDR prefix -> netmask. Parity: prefix_to_mask (dhcp_fastpath.c:510).

    Shift in two halves to dodge the undefined shift-by-32 (plen=0).
    """
    full = jnp.full_like(plen.astype(jnp.uint32), 0xFFFFFFFF)
    sh = jnp.clip(32 - plen.astype(jnp.int32), 0, 32)
    sh1 = jnp.minimum(sh, 16)
    sh2 = sh - sh1
    return (full << sh1) << sh2


def dhcp_fastpath(
    pkt: jax.Array,
    length: jax.Array,
    parsed: Parsed,
    tables: DHCPTables,
    geom: DHCPGeom,
    now_s: jax.Array,
) -> DHCPResult:
    Bsz, L = pkt.shape
    length = length.astype(jnp.uint32)
    stats = jnp.zeros((NSTATS,), dtype=jnp.uint32)

    def count(m):
        return jnp.sum(m, dtype=jnp.uint32)

    # --- eligibility (parity: parse + op + magic checks, :624-633) ---
    dhcp_off = parsed.l4_off + _UDP
    is_dhcp_port = parsed.is_udp & (parsed.dst_port == DHCP_SERVER_PORT)
    hdr_in_bounds = (dhcp_off.astype(jnp.uint32) + _BOOTP) <= length
    base = is_dhcp_port & hdr_in_bounds
    op = B_.u8_at(pkt, dhcp_off)
    magic = B_.be32_at(pkt, dhcp_off + 236)
    base = base & (op == BOOTREQUEST) & (magic == DHCP_MAGIC)

    # vlan_packets counts every tagged frame the hook sees, not just DHCP
    # (the reference increments it mid-parse, dhcp_fastpath.c:384, before
    # the IPv4/UDP/port-67 filters)
    stats = stats.at[ST_VLAN].add(count(parsed.is_vlan & (length > 0)))
    stats = stats.at[ST_TOTAL].add(count(base))

    # --- message type (parity :639-645) ---
    opts_off = dhcp_off + 240
    opts_in_bounds = (opts_off.astype(jnp.uint32) + 12) <= length
    mtype = _extract_msg_type(pkt, opts_off, opts_in_bounds & base)
    is_fast_type = (mtype == DISCOVER) | (mtype == REQUEST)
    wrong_type = base & ~is_fast_type
    stats = stats.at[ST_MISS].add(count(wrong_type))
    elig = base & is_fast_type

    # --- lookup cascade (parity :653-681) ---
    # 1) VLAN key
    vlan_key = ((parsed.s_tag << 16) | parsed.c_tag)[:, None].astype(jnp.uint32)
    vlan_res = lookup(tables.vlan, vlan_key, geom.vlan)
    vlan_hit = vlan_res.found & parsed.is_vlan & elig

    # 2) circuit-ID
    cid_found, cid_bytes = _extract_circuit_id(pkt, opts_off, length)
    cid_res = lookup(tables.cid, pack_cid_words(cid_bytes), geom.cid)
    cid_hit = cid_res.found & cid_found & elig & ~vlan_hit

    # 3) MAC (chaddr at dhcp_off+28)
    mac_hi = B_.be16_at(pkt, dhcp_off + 28)
    mac_lo = B_.be32_at(pkt, dhcp_off + 30)
    mac_key = jnp.stack([mac_hi, mac_lo], axis=1)
    mac_res = lookup(tables.sub, mac_key, geom.sub)
    mac_hit = mac_res.found & elig & ~vlan_hit & ~cid_hit

    stats = stats.at[ST_OPT82_PRESENT].add(count(cid_hit))

    hit = vlan_hit | cid_hit | mac_hit
    assign = jnp.where(
        vlan_hit[:, None], vlan_res.vals,
        jnp.where(cid_hit[:, None], cid_res.vals, mac_res.vals),
    )
    stats = stats.at[ST_MISS].add(count(elig & ~hit))

    # --- lease expiry (parity :690-695) ---
    lease_exp = assign[:, AV_LEASE_EXP]
    expired = hit & (now_s > lease_exp)
    stats = stats.at[ST_EXPIRED].add(count(expired))
    live = hit & ~expired

    # --- pool + server config (parity :698-713) ---
    P = tables.pools.shape[0]
    pool_id = assign[:, AV_POOL_ID]
    pool_ok_idx = pool_id < P
    pool_row = tables.pools[jnp.minimum(pool_id, P - 1).astype(jnp.int32)]  # [B, POOL_WORDS]
    pool_valid = pool_ok_idx & (pool_row[:, PV_VALID] != 0)
    pool_err = live & ~pool_valid
    stats = stats.at[ST_ERROR].add(count(pool_err))
    reply = live & pool_valid
    stats = stats.at[ST_HIT].add(count(reply))

    # --- reply field computation ---
    server_mac_hi = tables.server[SC_MAC_HI]
    server_mac_lo = tables.server[SC_MAC_LO]
    cfg_server_ip = tables.server[SC_IP]
    gateway = pool_row[:, PV_GATEWAY]
    server_ip = jnp.where(cfg_server_ip != 0, cfg_server_ip, gateway)  # :724

    reply_type = jnp.where(mtype == DISCOVER, OFFER, ACK)

    xid_b = B_.bytes_at(pkt, dhcp_off + 4, 4)
    secs_b = B_.bytes_at(pkt, dhcp_off + 8, 2)
    flags = B_.be16_at(pkt, dhcp_off + 10)
    ciaddr = B_.be32_at(pkt, dhcp_off + 12)
    giaddr = B_.be32_at(pkt, dhcp_off + 24)
    chaddr_b = B_.bytes_at(pkt, dhcp_off + 28, 16)
    giaddr_b = B_.bytes_at(pkt, dhcp_off + 24, 4)

    relayed = giaddr != 0
    # broadcast decision (parity: setup_reply_l2_headers :436-462 — every
    # non-relay case with ciaddr==0 broadcasts; ciaddr!=0 without the
    # broadcast flag unicasts to chaddr)
    use_bcast = (~relayed) & (((flags & FLAG_BROADCAST) != 0) | (ciaddr == 0))
    stats = stats.at[ST_BCAST].add(count(reply & use_bcast))
    stats = stats.at[ST_UCAST].add(count(reply & ~use_bcast))  # covers relay :743

    # L2 dest: relay -> requester's src MAC; bcast -> ff:..; else chaddr
    req_src = B_.bytes_at(pkt, jnp.zeros_like(dhcp_off) + 6, 6)
    bcast_mac = jnp.full((Bsz, 6), 0xFF, dtype=jnp.uint8)
    dst_mac = jnp.where(
        relayed[:, None], req_src, jnp.where(use_bcast[:, None], bcast_mac, chaddr_b[:, :6])
    )

    ip_dst = jnp.where(relayed, giaddr, jnp.uint32(0xFFFFFFFF))  # :734 / :749
    udp_dst = jnp.where(relayed, DHCP_SERVER_PORT, DHCP_CLIENT_PORT)  # :740 / :754

    # --- options geometry ---
    dns1 = pool_row[:, PV_DNS1]
    dns2 = pool_row[:, PV_DNS2]
    dns_sz = jnp.where(dns1 == 0, 0, jnp.where(dns2 == 0, 6, 10)).astype(jnp.int32)
    opt_len = _OPT_HEAD + dns_sz + _OPT_TAIL
    lease_t = pool_row[:, PV_LEASE_T]
    t1 = lease_t // 2  # :585
    t2 = (lease_t * 7) // 8  # :593
    mask32 = _prefix_to_mask(pool_row[:, PV_PREFIX])

    dhcp_len = (_BOOTP + opt_len).astype(jnp.uint32)
    udp_len = 8 + dhcp_len
    ip_len = 20 + udp_len
    canon_total = 14 + ip_len
    out_len = canon_total + parsed.vlan_offset.astype(jnp.uint32)

    # --- canonical reply compose ---
    # One concatenation of [B, n] segments instead of ~60 chained
    # .at[].set() updates: each set() is a dynamic-update-slice (a serial
    # read-modify-write of the whole buffer); concat is a single kernel.
    ip_csum = ipv4_header_checksum([
        jnp.full((Bsz,), 0x4500, dtype=jnp.uint32), ip_len,
        jnp.zeros((Bsz,), dtype=jnp.uint32), jnp.zeros((Bsz,), dtype=jnp.uint32),
        jnp.full((Bsz,), (64 << 8) | 17, dtype=jnp.uint32), jnp.zeros((Bsz,), dtype=jnp.uint32),
        server_ip >> 16, server_ip & 0xFFFF, ip_dst >> 16, ip_dst & 0xFFFF,
    ])
    ones = jnp.ones_like(flags)
    canon = jnp.concatenate([
        # Ethernet
        dst_mac,                                     # 0: dst MAC
        B_.be16_seg(server_mac_hi * ones),           # 6: src MAC (server)
        B_.be32_seg(server_mac_lo * ones),
        B_.const_seg(Bsz, 0x08, 0x00),               # 12: ethertype IPv4
        # IPv4 (TTL=64, proto=UDP; :735/:750)
        B_.const_seg(Bsz, 0x45, 0x00),               # 14: ver/ihl, tos
        B_.be16_seg(ip_len),                         # 16: total length
        B_.const_seg(Bsz, 0, 0, 0, 0, 64, 17),       # 18: id, frag, ttl, proto
        B_.be16_seg(ip_csum),                        # 24: header checksum
        B_.be32_seg(server_ip),                      # 26: src IP
        B_.be32_seg(ip_dst),                         # 30: dst IP
        # UDP (checksum 0: legal for IPv4, matches :741/:755)
        B_.const_seg(Bsz, 0, DHCP_SERVER_PORT),      # 34: src port 67
        B_.be16_seg(udp_dst),                        # 36: dst port
        B_.be16_seg(udp_len),                        # 38: length
        B_.const_seg(Bsz, 0, 0),                     # 40: checksum
        # BOOTP (:759-766)
        B_.const_seg(Bsz, BOOTREPLY, 1, 6, 0),       # 42: op, htype, hlen, hops
        xid_b,                                       # 46
        secs_b,                                      # 50
        B_.be16_seg(flags),                          # 52
        B_.be32_seg(ciaddr),                         # 54
        B_.be32_seg(assign[:, AV_IP]),               # 58: yiaddr :761
        B_.be32_seg(server_ip),                      # 62: siaddr :762
        giaddr_b,                                    # 66
        chaddr_b,                                    # 70: chaddr (16B)
        jnp.zeros((Bsz, 192), dtype=jnp.uint8),      # 86: sname/file
        B_.be32_seg(jnp.full((Bsz,), DHCP_MAGIC, dtype=jnp.uint32)),  # 278
    ], axis=1)

    # options: head segment [B, 27] (order 53,54,51,1,3 — :519-602)
    head = jnp.concatenate([
        B_.const_seg(Bsz, 53, 1), B_.u8_seg(reply_type),
        B_.const_seg(Bsz, 54, 4), B_.be32_seg(server_ip),
        B_.const_seg(Bsz, 51, 4), B_.be32_seg(lease_t),
        B_.const_seg(Bsz, 1, 4), B_.be32_seg(mask32),
        B_.const_seg(Bsz, 3, 4), B_.be32_seg(gateway),
    ], axis=1)
    # dns segment [B, 10]
    dns = jnp.concatenate([
        B_.const_seg(Bsz, 6), B_.u8_seg(jnp.where(dns2 == 0, 4, 8)),
        B_.be32_seg(dns1), B_.be32_seg(dns2),
    ], axis=1)
    # tail segment [B, 13]
    tail = jnp.concatenate([
        B_.const_seg(Bsz, 58, 4), B_.be32_seg(t1),
        B_.const_seg(Bsz, 59, 4), B_.be32_seg(t2),
        B_.const_seg(Bsz, 255),
    ], axis=1)

    # compose options area [B, _OPT_MAX]: head is fixed-offset; dns and tail
    # shift with dns_sz, handled by two index-arithmetic gathers
    oj = jnp.arange(_OPT_MAX, dtype=jnp.int32)[None, :]
    head_p = jnp.zeros((Bsz, _OPT_MAX), dtype=jnp.uint8).at[:, :_OPT_HEAD].set(head)
    dns_idx = jnp.broadcast_to(jnp.clip(oj - _OPT_HEAD, 0, _OPT_DNS_MAX - 1), (Bsz, _OPT_MAX))
    tail_idx = jnp.clip(oj - _OPT_HEAD - dns_sz[:, None], 0, _OPT_TAIL - 1)
    dns_g = jnp.take_along_axis(dns, dns_idx, axis=1)
    tail_g = jnp.take_along_axis(tail, tail_idx, axis=1)
    opt_area = jnp.where(
        oj < _OPT_HEAD,
        head_p,
        jnp.where(
            oj < (_OPT_HEAD + dns_sz[:, None]),
            dns_g,
            jnp.where(oj < opt_len[:, None], tail_g, 0),
        ),
    )
    canon = jnp.concatenate([canon, opt_area.astype(jnp.uint8)], axis=1)

    # --- final compose with VLAN reinsertion ---
    canon_L = jnp.zeros((Bsz, L), dtype=jnp.uint8).at[:, :CANON_LEN].set(canon)
    jj = jnp.arange(L, dtype=jnp.int32)[None, :]
    vo = parsed.vlan_offset[:, None]
    shift_idx = jnp.clip(jj - vo, 0, L - 1)
    canon_shift = jnp.take_along_axis(canon_L, shift_idx, axis=1)
    out = jnp.where(jj < 12, canon_L, jnp.where(jj < 14 + vo, pkt, canon_shift))
    out = jnp.where(jj < out_len[:, None].astype(jnp.int32), out, 0)

    return DHCPResult(
        is_reply=reply,
        is_dhcp=base,
        out_pkt=out,
        out_len=jnp.where(reply, out_len, 0),
        stats=stats,
    )
