"""Internet checksums, vectorized.

Parity targets: ip_checksum (bpf/dhcp_fastpath.c:488-503) and the
incremental update helpers update_csum/update_csum16/csum_fold
(bpf/nat44.c:378-398), as [B]-wide uint32 lane math.

Convention: 16-bit fields are held in uint32 lanes in *host order*; byte
composition happens in bytes.py. One's-complement sums are byte-order
agnostic as long as old/new values use consistent order, so host-order
arithmetic gives byte-identical packets after composition.
"""

from __future__ import annotations

import jax.numpy as jnp


def fold16(s):
    """Fold a uint32 one's-complement accumulator to 16 bits."""
    s = (s & 0xFFFF) + (s >> 16)
    s = (s & 0xFFFF) + (s >> 16)
    return s


def csum_finish(s):
    return (~fold16(s)) & 0xFFFF


def ipv4_header_checksum(words):
    """Checksum from a list of 16-bit field values ([B] uint32 each).

    The checksum field itself must be passed as 0.
    """
    s = jnp.zeros_like(words[0])
    for w in words:
        s = s + (w & 0xFFFF)
    return csum_finish(s)


def csum_update32(csum, old32, new32):
    """Incremental checksum update for a changed 32-bit value.

    Parity: update_csum (bpf/nat44.c:384-391). csum/old/new are [B] uint32
    (csum holds a 16-bit value).
    """
    s = (~csum) & 0xFFFF
    s = s + ((~old32) & 0xFFFF)
    s = s + ((~(old32 >> 16)) & 0xFFFF)
    s = s + (new32 & 0xFFFF)
    s = s + (new32 >> 16)
    return (~fold16(s)) & 0xFFFF


def csum_update16(csum, old16, new16):
    """Parity: update_csum16 (bpf/nat44.c:393-398)."""
    s = (~csum) & 0xFFFF
    s = s + ((~old16) & 0xFFFF)
    s = s + (new16 & 0xFFFF)
    return (~fold16(s)) & 0xFFFF
