"""Batched PPPoE session-stage encap/decap + QinQ push/pop on device.

The reference runs the whole PPPoE stack in userspace Go over AF_PACKET
(pkg/pppoe/server.go:263-301): discovery and LCP/IPCP negotiation are
control traffic, but every DATA packet of an established session also
crosses into userspace (server.go:854). On TPU the session-stage framing
is pure per-lane byte movement — exactly what the batch engine is for —
so established-session data rides the device fast path and only
discovery (0x8863) and LCP/auth/IPCP control frames (PPP proto !=
0x0021) punt to the host PPPoE server, the same cache/miss split as the
DHCP fast path (SURVEY.md §7, BASELINE config 4).

Frame layouts:
  decap: [eth][vlans 0/4/8][0x8864][PPPoE hdr 6B][PPP proto 2B][IPv4...]
     ->  [eth][vlans]][0x0800][IPv4...]            (8-byte contraction)
  encap: the reverse 8-byte expansion, session id from the subscriber
     session table (keyed by dst IP on the downstream direction).

Validation on decap mirrors pppoe_session dispatch (server.go:466-499):
ver/type 0x11, code 0, session id found in the session table and bound
to the same MAC. Byte movement is index arithmetic (one gather), not
per-lane scatters.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops import bytes as B_
from bng_tpu.ops.parse import ETH_P_8021AD, ETH_P_8021Q, ETH_P_IP, ETH_P_IPV6
from bng_tpu.ops.table import TableGeom, TableState, lookup

ETH_PPPOE_SESSION = 0x8864
ETH_PPPOE_DISC = 0x8863
PPP_IPV4 = 0x0021
PPP_IPV6 = 0x0057
PPPOE_HDR = 8  # 6B PPPoE header + 2B PPP protocol

# session table value words (device mirror of control.pppoe.PPPoESession);
# padded to the 8-word gather-fast row shape (BNG014 / PERF_NOTES §2)
(PS_SESSION_ID, PS_MAC_HI, PS_MAC_LO, PS_IP, PS_FLAGS) = range(5)
PPPOE_WORDS = 8

# stats
(PST_DECAP, PST_ENCAP, PST_CTRL_PUNT, PST_BAD, PST_MISS) = range(5)
PPPOE_NSTATS = 5


class PPPoEResult(NamedTuple):
    out_pkt: jax.Array  # [B, L] uint8
    out_len: jax.Array  # [B] uint32
    done: jax.Array  # [B] bool — lane rewritten by this op
    punt: jax.Array  # [B] bool — PPPoE control traffic for the host stack
    src_ip_hint: jax.Array  # [B] uint32 session IP (antispoof cross-check)
    stats: jax.Array  # [PPPOE_NSTATS] uint32


def _shift_bytes(pkt, shift, gate, start):
    """Shift packet bytes at/after per-lane `start` by per-lane +/-shift.

    Positive shift contracts (decap: byte j reads from j+shift), negative
    expands (encap). Bytes before `start` (L2 addresses and any VLAN
    tags) never move. One gather per call.
    """
    L = pkt.shape[1]
    jj = jnp.arange(L, dtype=jnp.int32)[None, :]
    src = jnp.clip(jj + shift[:, None], 0, L - 1)
    moved = jnp.take_along_axis(pkt, src, axis=1)
    keep_head = jj < jnp.asarray(start).reshape(-1, 1)
    return jnp.where(gate[:, None] & ~keep_head, moved, pkt)


def pppoe_decap(
    pkt: jax.Array,
    length: jax.Array,
    vlan_offset: jax.Array,  # [B] int32 from parse (0/4/8)
    ethertype: jax.Array,  # [B] inner ethertype after VLANs
    sessions: TableState,
    geom: TableGeom,
) -> PPPoEResult:
    """Strip PPPoE+PPP framing from established-session IPv4/IPv6 data."""
    Bsz, L = pkt.shape
    length = length.astype(jnp.uint32)
    et_off = 12 + vlan_offset  # offset of the ethertype field itself
    ph = et_off + 2  # PPPoE header start

    is_sess = ethertype == ETH_PPPOE_SESSION
    is_disc = ethertype == ETH_PPPOE_DISC
    hdr_ok = (ph.astype(jnp.uint32) + PPPOE_HDR) <= length

    ver_type = B_.u8_at(pkt, ph)
    code = B_.u8_at(pkt, ph + 1)
    session_id = B_.be16_at(pkt, ph + 2)
    plen = B_.be16_at(pkt, ph + 4)  # PPPoE payload length (PPP proto + data)
    ppp_proto = B_.be16_at(pkt, ph + 6)

    # length-field validation parity with codec.PPPoEPacket.decode: the
    # declared payload must fit the frame (frames may carry Ethernet
    # padding beyond it) and must at least hold the PPP protocol word
    plen_ok = (plen >= 2) & ((ph + 6).astype(jnp.uint32) + plen <= length)
    well_formed = is_sess & hdr_ok & (ver_type == 0x11) & (code == 0) & plen_ok
    # Only IPv4 data decaps on device for now: the encap direction is
    # IPv4-keyed (by_ip), so v6 PPP data punts to the host v6 stack to
    # keep the two directions symmetric (and src_ip_hint meaningful).
    is_data = well_formed & (ppp_proto == PPP_IPV4)
    is_malformed = is_sess & ~well_formed
    # control inside the session (LCP 0xC021, PAP/CHAP, IPCP 0x8021, v6...)
    is_ctrl = is_disc | (well_formed & ~is_data) | is_malformed

    # session validation: id+MAC must match the table (server.go:478-487)
    z = jnp.zeros((Bsz,), dtype=jnp.int32)
    src_mac_hi = B_.be16_at(pkt, z + 6)
    src_mac_lo = B_.be32_at(pkt, z + 8)
    res = lookup(sessions, session_id[:, None].astype(jnp.uint32), geom)
    bound = (
        res.found
        & (res.vals[:, PS_MAC_HI] == src_mac_hi)
        & (res.vals[:, PS_MAC_LO] == src_mac_lo)
    )
    ok = is_data & bound
    miss = is_data & ~bound  # unknown/foreign session -> punt (teardown path)

    # contract by 8: bytes after the ethertype slide left, ethertype
    # becomes the inner protocol
    out = _shift_bytes(pkt, jnp.where(ok, PPPOE_HDR, 0).astype(jnp.int32), ok, et_off)
    inner_et = jnp.where(ppp_proto == PPP_IPV4, ETH_P_IP, ETH_P_IPV6)
    out = B_.scatter_be16_at_masked(out, et_off, inner_et, ok)
    # inner frame = L2 up to ethertype (et_off+2) + IP bytes (plen-2);
    # trailing Ethernet padding past the declared payload is dropped
    out_len = jnp.where(ok, et_off.astype(jnp.uint32) + plen, length)

    stats = jnp.zeros((PPPOE_NSTATS,), dtype=jnp.uint32)
    stats = stats.at[PST_DECAP].add(jnp.sum(ok, dtype=jnp.uint32))
    # disjoint buckets: a malformed frame counts only as BAD, never CTRL
    stats = stats.at[PST_CTRL_PUNT].add(
        jnp.sum(is_disc | (well_formed & ~is_data), dtype=jnp.uint32))
    stats = stats.at[PST_MISS].add(jnp.sum(miss, dtype=jnp.uint32))
    stats = stats.at[PST_BAD].add(jnp.sum(is_malformed, dtype=jnp.uint32))

    return PPPoEResult(
        out_pkt=out,
        out_len=out_len,
        done=ok,
        punt=is_ctrl | miss,
        src_ip_hint=jnp.where(ok, res.vals[:, PS_IP], 0),
        stats=stats,
    )


def pppoe_encap(
    pkt: jax.Array,
    length: jax.Array,
    vlan_offset: jax.Array,
    ethertype: jax.Array,
    dst_ip: jax.Array,  # [B] from parse — downstream subscriber IP
    by_ip: TableState,  # session table keyed by subscriber IP
    geom: TableGeom,
    server_mac: jax.Array | None,  # [2] uint32 (hi16, lo32) AC MAC — REQUIRED
) -> PPPoEResult:
    """Add PPPoE+PPP framing to downstream IPv4 data for PPPoE subscribers.

    server_mac: the access concentrator's own MAC, written as the L2
    source of every encapsulated frame (the reference builds downstream
    frames with src=serverMAC, pkg/pppoe/server.go BuildEthernetFrame;
    without it the frame would carry the upstream router's source MAC —
    round-1 ADVICE finding). Deliberately has NO default: an integrator
    must either thread the AC MAC or pass None explicitly to declare the
    frames are pre-stamped upstream.
    """
    Bsz, L = pkt.shape
    length = length.astype(jnp.uint32)
    et_off = 12 + vlan_offset

    res = lookup(by_ip, dst_ip[:, None].astype(jnp.uint32), geom)
    is_v4 = ethertype == ETH_P_IP
    ok = is_v4 & res.found & ((length + PPPOE_HDR) <= L)

    # expand by 8 after the ethertype
    out = _shift_bytes(pkt, jnp.where(ok, -PPPOE_HDR, 0).astype(jnp.int32), ok, et_off)
    out = B_.scatter_be16_at_masked(out, et_off, jnp.full((Bsz,), ETH_PPPOE_SESSION, dtype=jnp.uint32), ok)
    ph = et_off + 2
    payload_len = length - et_off.astype(jnp.uint32)  # PPP proto (2B) + IP bytes
    out = B_.scatter_be16_at_masked(out, ph, jnp.full((Bsz,), 0x1100, dtype=jnp.uint32), ok)
    out = B_.scatter_be16_at_masked(out, ph + 2, res.vals[:, PS_SESSION_ID], ok)
    out = B_.scatter_be16_at_masked(out, ph + 4, payload_len, ok)
    out = B_.scatter_be16_at_masked(out, ph + 6, jnp.full((Bsz,), PPP_IPV4, dtype=jnp.uint32), ok)
    # rewrite L2 dest to the subscriber MAC from the session row
    out = B_.scatter_be16_at_masked(out, jnp.zeros_like(et_off), res.vals[:, PS_MAC_HI], ok)
    out = B_.scatter_be32_at_masked(out, jnp.zeros_like(et_off) + 2, res.vals[:, PS_MAC_LO], ok)
    if server_mac is not None:
        # ...and L2 source to the AC's MAC (src of all downstream frames)
        src_hi = jnp.broadcast_to(server_mac[0], (Bsz,)).astype(jnp.uint32)
        src_lo = jnp.broadcast_to(server_mac[1], (Bsz,)).astype(jnp.uint32)
        out = B_.scatter_be16_at_masked(out, jnp.zeros_like(et_off) + 6, src_hi, ok)
        out = B_.scatter_be32_at_masked(out, jnp.zeros_like(et_off) + 8, src_lo, ok)
    out_len = jnp.where(ok, length + PPPOE_HDR, length)

    stats = jnp.zeros((PPPOE_NSTATS,), dtype=jnp.uint32)
    stats = stats.at[PST_ENCAP].add(jnp.sum(ok, dtype=jnp.uint32))

    return PPPoEResult(
        out_pkt=out,
        out_len=out_len,
        done=ok,
        punt=jnp.zeros((Bsz,), dtype=bool),
        src_ip_hint=jnp.zeros((Bsz,), dtype=jnp.uint32),
        stats=stats,
    )


# ---- QinQ push/pop (pkg/qinq role, device side) ----


def qinq_push(pkt, length, s_tag, c_tag, gate):
    """Insert 802.1ad S-tag + 802.1Q C-tag after the MAC addresses.

    Parity: the QinQ framing dhcp_fastpath.c parses (:373-398), built
    host-side by pkg/qinq/VLANPair; here applied to a whole batch.
    """
    Bsz, L = pkt.shape
    length = length.astype(jnp.uint32)
    ok = gate & ((length + 8) <= L)
    z = jnp.zeros((Bsz,), dtype=jnp.int32)
    out = _shift_bytes(pkt, jnp.where(ok, -8, 0).astype(jnp.int32), ok, z + 12)
    out = B_.scatter_be16_at_masked(out, z + 12, jnp.full((Bsz,), ETH_P_8021AD, dtype=jnp.uint32), ok)
    out = B_.scatter_be16_at_masked(out, z + 14, s_tag & 0x0FFF, ok)
    out = B_.scatter_be16_at_masked(out, z + 16, jnp.full((Bsz,), ETH_P_8021Q, dtype=jnp.uint32), ok)
    out = B_.scatter_be16_at_masked(out, z + 18, c_tag & 0x0FFF, ok)
    return out, jnp.where(ok, length + 8, length), ok


def qinq_pop(pkt, length, vlan_offset, gate):
    """Strip all VLAN tags (0/4/8 bytes) from gated lanes."""
    length = length.astype(jnp.uint32)
    vo = vlan_offset.astype(jnp.int32)
    ok = gate & (vo > 0)
    out = _shift_bytes(pkt, jnp.where(ok, vo, 0), ok, jnp.full_like(vo, 12))
    return out, jnp.where(ok, length - vo.astype(jnp.uint32), length), ok
