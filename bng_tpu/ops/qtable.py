"""Bucket-packed QoS policy table — one wide gather per hash probe.

Why this exists (measured on a real v5e through the round-3 profiling
sessions): the generic cuckoo table (ops/table.py) stores keys as [S, K]
and occupancy as [S]. For the QoS table K=1, so a probe compiles to many
*narrow* gathers (1 uint32 per index). On TPU those lower at ~7ns/element
(58µs per 8192-lane gather, 16 gathers per lookup ≈ 1ms/batch) while
*row* gathers of >=8-word rows run at full speed (~13µs for [8192, 8]).
That one layout artifact made the QoS kernel the bottleneck of the whole
dataplane (VERDICT r2: 0.114 Mpps standalone, 65ms fixed cost).

So the QoS table is **way-granular**: every 4-way bucket is four
consecutive 8-word rows, and ALL of a subscriber's state — policy AND
mutable token state — lives in its one row:

    rows[nbuckets*4, 8] u32:
        +0 key (subscriber ip)   +1 flags (bit0 = used)
        +2 rate_lo  +3 rate_hi   +4 burst  +5 priority
        +6 tokens (f32 bitcast)  +7 last_us

A lookup is exactly two [B, 32] row gathers (rows viewed [nbuckets, 32]:
bucket 1, bucket 2) plus branch-free lane compares — tokens included, no
separate narrow token gather. The QoS kernel's token writeback is ONE
wide [B, 8] row scatter (the head lane of each bucket rewrites its whole
way row: policy words unchanged, +6/+7 updated). Host policy sync is a
wide [U, 8] row scatter at changed slots only, so sibling ways' device-
authoritative tokens are never touched by an update.

Parity: the row carries the same fields as the reference's
``struct token_bucket`` (bpf/qos_ratelimit.c:24-31); the host mirror
plays pkg/qos/manager.go's role (install/remove policies, single writer).
Cuckoo relocation happens host-side exactly like ops/table.py; a
relocated entry's bucket refills to full burst (documented divergence —
the host cannot read device tokens mid-flight, and a one-off burst grant
on policy churn is bounded and harmless).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from bng_tpu.ops.hashing import SEED1, SEED2, hash_words

WAYS = 4
SLOT_W = 8  # words per way row
ROW_W = WAYS * SLOT_W  # 32 — the probe gather width
MAX_KICKS = 128

# word offsets within a way row
(QW_KEY, QW_FLAGS, QW_RATE_LO, QW_RATE_HI, QW_BURST, QW_PRIORITY,
 QW_TOKENS, QW_LAST_US) = range(8)
FLAG_USED = np.uint32(1)


def _f2u(v: float) -> int:
    return int(np.array(v, dtype=np.float32).view(np.uint32))


def _u2f(u: int) -> float:
    return float(np.array(u, dtype=np.uint32).view(np.float32))


class QTableState(NamedTuple):
    """Device array (a pytree of one leaf; host writes policy rows, the
    QoS kernel writes token state — both as wide row scatters)."""

    rows: jax.Array  # [NB*4, 8] uint32 packed way rows


class QTableUpdate(NamedTuple):
    """Bounded dirty-slot scatter (host -> device policy sync).

    slot >= NB*4 rows are dropped padding. Only changed slots are written,
    so sibling ways keep their device-side token state untouched."""

    slot: jax.Array  # [U] int32 global slot indices
    rows: jax.Array  # [U, 8] uint32 full replacement way rows


class QTableGeom(NamedTuple):
    """Static geometry. axis/n_shards mirror TableGeom so the pipeline's
    chip-local guard logic reads the same fields (QoS tables are placed by
    subscriber affinity, never hash-sharded — see ops/qos.py)."""

    nbuckets: int
    axis: str | None = None
    n_shards: int = 1


class QLookup(NamedTuple):
    found: jax.Array  # [B] bool
    slot: jax.Array  # [B] int32 global slot (valid where found)
    row: jax.Array  # [B, 8] uint32 the selected way row (stale where not found)
    rate_lo: jax.Array  # [B] uint32
    rate_hi: jax.Array  # [B] uint32
    burst: jax.Array  # [B] uint32
    priority: jax.Array  # [B] uint32
    tokens: jax.Array  # [B] float32 (stale where not found)
    last_us: jax.Array  # [B] uint32


def apply_qupdate(state: QTableState, upd: QTableUpdate) -> QTableState:
    """Scatter dirty way rows (inside jit) — one wide row scatter."""
    return QTableState(rows=state.rows.at[upd.slot].set(upd.rows, mode="drop"))


def qlookup(state: QTableState, ip: jax.Array, g: QTableGeom) -> QLookup:
    """Branch-free probe: 2 wide row gathers + lane compares.

    ip: [B] uint32 keys.
    """
    Bsz = ip.shape[0]
    mask = np.uint32(g.nbuckets - 1)
    b1 = (hash_words([ip], SEED1) & mask).astype(jnp.int32)
    b2 = (hash_words([ip], SEED2) & mask).astype(jnp.int32)

    wide = state.rows.reshape(g.nbuckets, ROW_W)
    r1 = wide[b1]  # [B, 32] — the fast gather shape
    r2 = wide[b2]
    cand = jnp.concatenate(
        [r1.reshape(Bsz, WAYS, SLOT_W), r2.reshape(Bsz, WAYS, SLOT_W)], axis=1
    )  # [B, 2W, 8]

    match = (cand[:, :, QW_KEY] == ip[:, None]) & (
        (cand[:, :, QW_FLAGS] & FLAG_USED) != 0
    )  # [B, 2W]
    found = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1)  # [B] in [0, 2W)
    # way select as a one-hot masked sum (pure VPU) — the take_along_axis
    # form lowered to a 65µs in-context gather on v5e (PERF_NOTES §2)
    onehot = jnp.arange(2 * WAYS, dtype=jnp.int32)[None, :] == first[:, None]
    sel = jnp.sum(jnp.where(onehot[:, :, None], cand, 0), axis=1,
                  dtype=jnp.uint32)  # [B, 8]

    bucket = jnp.where(first < WAYS, b1, b2)
    slot = bucket * WAYS + (first % WAYS)

    return QLookup(
        found=found,
        slot=slot,
        row=sel,
        rate_lo=sel[:, QW_RATE_LO],
        rate_hi=sel[:, QW_RATE_HI],
        burst=sel[:, QW_BURST],
        priority=sel[:, QW_PRIORITY],
        tokens=jax.lax.bitcast_convert_type(sel[:, QW_TOKENS], jnp.float32),
        last_us=sel[:, QW_LAST_US],
    )


def write_token_rows(state: QTableState, wslot: jax.Array, row: jax.Array,
                     tokens: jax.Array, now_us: jax.Array) -> QTableState:
    """Device-side token writeback: head lanes rewrite their way row with
    updated +6/+7 — one wide [B, 8] row scatter, no scalar scatters.

    wslot: [B] int32, >= NB*4 where the lane must not write (dropped).
    row: [B, 8] the looked-up way rows (policy words are rewritten with
    the values read this same step — the host applies updates between
    steps, so the sequencing is linear and nothing can be clobbered).
    """
    Bsz = wslot.shape[0]
    tok_u = jax.lax.bitcast_convert_type(tokens.astype(jnp.float32), jnp.uint32)
    now_b = jnp.broadcast_to(now_us, (Bsz,)).astype(jnp.uint32)
    new_row = jnp.concatenate(
        [row[:, :QW_TOKENS], tok_u[:, None], now_b[:, None]], axis=1)
    return QTableState(rows=state.rows.at[wslot].set(new_row, mode="drop"))


class HostQTable:
    """Host-authoritative mirror (numpy, single writer) of one QoS table.

    Same role as ops/table.py:HostTable (pkg/ebpf loader map-CRUD), with
    slot-granular dirty tracking: a policy change marks its way row dirty
    and the whole 8-word row (config + re-seeded tokens) is rescattered.
    """

    def __init__(self, nbuckets: int, name: str = ""):
        if nbuckets & (nbuckets - 1):
            raise ValueError("nbuckets must be a power of two")
        self.nbuckets = nbuckets
        self.S = nbuckets * WAYS
        self.name = name
        self.rows = np.zeros((self.S, SLOT_W), dtype=np.uint32)
        self.count = 0
        self._dirty: set[int] = set()
        self._dirty_all = False
        self._rng = np.random.default_rng(0xB46)

    # -- hashing (must match qlookup bit-for-bit) --
    def _buckets(self, ip: int) -> tuple[int, int]:
        k = np.asarray([ip], dtype=np.uint32)
        m = np.uint32(self.nbuckets - 1)
        return int((hash_words([k], SEED1) & m)[0]), int((hash_words([k], SEED2) & m)[0])

    def _find(self, ip: int) -> int | None:
        b1, b2 = self._buckets(ip)
        for b in (b1, b2):
            for w in range(WAYS):
                s = self.rows[b * WAYS + w]
                if (s[QW_FLAGS] & 1) and int(s[QW_KEY]) == (ip & 0xFFFFFFFF):
                    return b * WAYS + w
        return None

    def _place(self, slot: int, ip: int, rate_bps: int, burst: int,
               priority: int, start_full: bool) -> int:
        s = self.rows[slot]
        s[QW_KEY] = ip & 0xFFFFFFFF
        s[QW_FLAGS] = 1
        s[QW_RATE_LO] = rate_bps & 0xFFFFFFFF
        s[QW_RATE_HI] = (rate_bps >> 32) & 0xFFFFFFFF
        s[QW_BURST] = burst
        s[QW_PRIORITY] = priority
        s[QW_TOKENS] = _f2u(float(burst if start_full else 0))
        s[QW_LAST_US] = 0
        self._dirty.add(slot)
        return slot

    def insert(self, ip: int, rate_bps: int, burst: int, priority: int = 0,
               start_full: bool = True) -> int:
        """Install or update a policy. Returns the global slot index."""
        hit = self._find(ip)
        if hit is not None:  # update config in place; re-seed tokens
            return self._place(hit, ip, rate_bps, burst, priority, start_full)

        cur = (ip, rate_bps, burst, priority, start_full)
        moves: list[tuple[int, np.ndarray]] = []
        for _ in range(MAX_KICKS):
            b1, b2 = self._buckets(cur[0])
            for b in (b1, b2):
                for w in range(WAYS):
                    if not (self.rows[b * WAYS + w][QW_FLAGS] & 1):
                        self._place(b * WAYS + w, *cur)
                        self.count += 1
                        hit = self._find(ip)
                        assert hit is not None
                        return hit
            # both buckets full -> evict a random way; relocated entries
            # refill to full burst (host can't read device tokens)
            b = b1 if self._rng.integers(2) == 0 else b2
            w = int(self._rng.integers(WAYS))
            slot = b * WAYS + w
            s = self.rows[slot].copy()
            moves.append((slot, s))
            ev_rate = int(s[QW_RATE_LO]) | (int(s[QW_RATE_HI]) << 32)
            self._place(slot, *cur)
            cur = (int(s[QW_KEY]), ev_rate, int(s[QW_BURST]), int(s[QW_PRIORITY]), True)

        for slot, s in reversed(moves):  # roll back, keep old entries
            self.rows[slot] = s
            self._dirty.add(slot)
        raise RuntimeError(
            f"qos table {self.name!r} full (count={self.count}, "
            f"nbuckets={self.nbuckets}); size buckets >= subscribers/2")

    def delete(self, ip: int) -> bool:
        slot = self._find(ip)
        if slot is None:
            return False
        self.rows[slot] = 0
        self.count -= 1
        self._dirty.add(slot)
        return True

    def lookup(self, ip: int) -> dict | None:
        slot = self._find(ip)
        if slot is None:
            return None
        s = self.rows[slot]
        return {
            "slot": slot,
            "rate_bps": int(s[QW_RATE_LO]) | (int(s[QW_RATE_HI]) << 32),
            "burst": int(s[QW_BURST]),
            "priority": int(s[QW_PRIORITY]),
            "tokens": _u2f(int(s[QW_TOKENS])),
        }

    def bulk_insert(self, ips: np.ndarray, rates_bps: np.ndarray,
                    bursts: np.ndarray, priorities: np.ndarray | None = None,
                    start_full: bool = True) -> None:
        """Vectorized initial build (1M-subscriber scale; see
        HostTable.bulk_insert for the pass structure). Keys must be new."""
        ips = np.asarray(ips, dtype=np.uint32).reshape(-1)
        rates = np.asarray(rates_bps, dtype=np.uint64).reshape(-1)
        bursts = np.asarray(bursts, dtype=np.uint32).reshape(-1)
        prios = (np.zeros_like(ips) if priorities is None
                 else np.asarray(priorities, dtype=np.uint32).reshape(-1))
        n = len(ips)
        if n == 0:
            return
        m = np.uint32(self.nbuckets - 1)
        b1 = (hash_words([ips], SEED1) & m).astype(np.int64)
        b2 = (hash_words([ips], SEED2) & m).astype(np.int64)

        flags = self.rows[:, QW_FLAGS].reshape(self.nbuckets, WAYS)
        unplaced = np.ones((n,), dtype=bool)
        for side in (b1, b2):
            for w in range(WAYS):
                idxs = np.nonzero(unplaced)[0]
                if len(idxs) == 0:
                    break
                bb = side[idxs]
                free = flags[bb, w] == 0
                idxs, bb = idxs[free], bb[free]
                if len(idxs) == 0:
                    continue
                uq_b, firsti = np.unique(bb, return_index=True)
                take = idxs[firsti]
                slots = uq_b * WAYS + w
                self.rows[slots, QW_KEY] = ips[take]
                self.rows[slots, QW_FLAGS] = 1
                self.rows[slots, QW_RATE_LO] = (rates[take] & 0xFFFFFFFF).astype(np.uint32)
                self.rows[slots, QW_RATE_HI] = (rates[take] >> 32).astype(np.uint32)
                self.rows[slots, QW_BURST] = bursts[take]
                self.rows[slots, QW_PRIORITY] = prios[take]
                self.rows[slots, QW_TOKENS] = (
                    bursts[take].astype(np.float32).view(np.uint32)
                    if start_full else _f2u(0.0))
                self.rows[slots, QW_LAST_US] = 0
                unplaced[take] = False
                self.count += len(take)
                if n <= 256:  # small batches stay on the bounded-delta path
                    self._dirty.update(int(s) for s in slots)

        for i in np.nonzero(unplaced)[0]:  # cuckoo-kick residue
            self.insert(int(ips[i]), int(rates[i]), int(bursts[i]), int(prios[i]),
                        start_full)

        if n > 256:
            self._dirty.clear()
            self._dirty_all = True

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    def checkpoint_geom(self) -> dict:
        return {"nbuckets": self.nbuckets}

    def checkpoint_arrays(self) -> dict[str, np.ndarray]:
        """The packed way rows carry policy AND token state — one array
        is the whole mirror."""
        return {"rows": self.rows}

    def restore_arrays(self, arrays: dict[str, np.ndarray],
                       geom: dict) -> int:
        """Overwrite the mirror from a checkpoint (reject-on-mismatch;
        abandons delta tracking like bulk_insert — caller must follow
        with a full device upload). Returns the restored policy count."""
        if geom != self.checkpoint_geom():
            raise ValueError(
                f"qos table {self.name!r}: checkpoint geometry {geom} != "
                f"live geometry {self.checkpoint_geom()}")
        src = arrays["rows"]
        if src.shape != self.rows.shape or src.dtype != self.rows.dtype:
            raise ValueError(
                f"qos table {self.name!r}: checkpoint rows are "
                f"{src.dtype}{src.shape}, expected "
                f"{self.rows.dtype}{self.rows.shape}")
        self.rows[:] = src
        self.count = int(np.count_nonzero(self.rows[:, QW_FLAGS] & 1))
        self._dirty.clear()
        self._dirty_all = True
        return self.count

    # -- device synchronization --
    def device_state(self) -> QTableState:
        self._dirty.clear()
        self._dirty_all = False
        return QTableState(rows=jnp.asarray(self.rows))

    def dirty_count(self) -> int:
        return self.S if self._dirty_all else len(self._dirty)

    def mark_dirty(self, slots) -> int:
        """Queue way rows for the next bounded drain without touching the
        host rows — the delta-replay primitive (see HostTable.mark_dirty).
        Returns the number of NEWLY queued slots (already-dirty ones add
        no drain traffic)."""
        before = len(self._dirty)
        self._dirty.update(int(s) for s in slots)
        return len(self._dirty) - before

    def make_update(self, max_slots: int) -> QTableUpdate:
        """Drain up to max_slots dirty way rows (bounded host->HBM traffic)."""
        if self._dirty_all:
            raise RuntimeError(
                f"qos table {self.name!r}: bulk_insert invalidated delta sync; "
                "call device_state() for a full upload first")
        take = sorted(self._dirty)[:max_slots]
        self._dirty.difference_update(take)
        n = len(take)
        slot = np.full((max_slots,), self.S, dtype=np.int32)
        rows = np.zeros((max_slots, SLOT_W), dtype=np.uint32)
        if n:
            ss = np.asarray(take, dtype=np.int32)
            slot[:n] = ss
            rows[:n] = self.rows[ss]
        return QTableUpdate(slot=jnp.asarray(slot), rows=jnp.asarray(rows))

    def empty_update(self, max_slots: int) -> QTableUpdate:
        """All-padding QTableUpdate (no-op scatter), built without touching
        dirty tracking and cached per size — see HostTable.empty_update
        for the scheduler no-drain-step rationale."""
        cache = getattr(self, "_empty_upd_cache", None)
        if cache is None:
            cache = self._empty_upd_cache = {}
        upd = cache.get(max_slots)
        if upd is None:
            upd = cache[max_slots] = QTableUpdate(
                slot=jnp.full((max_slots,), self.S, dtype=jnp.int32),
                rows=jnp.zeros((max_slots, SLOT_W), dtype=jnp.uint32))
        return upd
