"""Bucket-packed QoS policy table — one wide gather per hash probe.

Why this exists (measured on a real v5e through the round-3 profiling
sessions): the generic cuckoo table (ops/table.py) stores keys as [S, K]
and occupancy as [S]. For the QoS table K=1, so a probe compiles to many
*narrow* gathers (1 uint32 per index). On TPU those lower at ~7ns/element
(58µs per 8192-lane gather, 16 gathers per lookup ≈ 1ms/batch) while
*row* gathers of >=8-word rows run at full speed (~13µs for [8192, 8]).
That one layout artifact made the QoS kernel the bottleneck of the whole
dataplane (VERDICT r2: 0.114 Mpps standalone, 65ms fixed cost).

So the QoS table packs each 4-way bucket into ONE 32-word row:

    rows[nbuckets, 32] u32 —  way-major, 8 words per way:
        +0 key (subscriber ip)   +1 flags (bit0 = used)
        +2 rate_lo  +3 rate_hi   +4 burst  +5 priority  +6/+7 pad

A lookup is exactly two [B, 32] row gathers (bucket 1, bucket 2) plus
branch-free lane compares — the narrow-gather shape never appears.
Mutable token state lives beside it in flat arrays (device-authoritative,
written by the QoS kernel's scatter):

    tokens[nbuckets*4] f32, last_us[nbuckets*4] u32

Parity: the packed row carries the same fields as the reference's
``struct token_bucket`` (bpf/qos_ratelimit.c:24-31); the host mirror
plays pkg/qos/manager.go's role (install/remove policies, single writer).
Cuckoo relocation happens host-side exactly like ops/table.py; a
relocated entry's bucket refills to full burst (documented divergence —
the host cannot read device tokens mid-flight, and a one-off burst grant
on policy churn is bounded and harmless).
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from bng_tpu.ops.hashing import SEED1, SEED2, hash_words

WAYS = 4
SLOT_W = 8  # words per way in the packed row
ROW_W = WAYS * SLOT_W  # 32
MAX_KICKS = 128

# word offsets within a way's 8-word slice
(QW_KEY, QW_FLAGS, QW_RATE_LO, QW_RATE_HI, QW_BURST, QW_PRIORITY) = range(6)
FLAG_USED = np.uint32(1)


class QTableState(NamedTuple):
    """Device arrays (a pytree; rows are host-written, tokens device-written)."""

    rows: jax.Array  # [NB, 32] uint32 packed policy rows
    tokens: jax.Array  # [NB*4] float32 current tokens
    last_us: jax.Array  # [NB*4] uint32 last refill timestamp


class QTableUpdate(NamedTuple):
    """Bounded dirty-bucket scatter (host -> device policy sync).

    bidx >= NB rows are dropped padding. Token/timestamp writes apply only
    to `slot` (the slot whose policy changed); sibling ways keep their
    device-side token state.
    """

    bidx: jax.Array  # [U] int32 bucket index
    rows: jax.Array  # [U, 32] uint32 full replacement rows
    slot: jax.Array  # [U, WAYS] int32 global slots to re-seed, or >=NB*4 (skip)
    tokens: jax.Array  # [U, WAYS] float32
    last_us: jax.Array  # [U, WAYS] uint32


class QTableGeom(NamedTuple):
    """Static geometry. axis/n_shards mirror TableGeom so the pipeline's
    chip-local guard logic reads the same fields (QoS tables are placed by
    subscriber affinity, never hash-sharded — see ops/qos.py)."""

    nbuckets: int
    axis: str | None = None
    n_shards: int = 1


class QLookup(NamedTuple):
    found: jax.Array  # [B] bool
    slot: jax.Array  # [B] int32 global slot (valid where found)
    rate_lo: jax.Array  # [B] uint32
    rate_hi: jax.Array  # [B] uint32
    burst: jax.Array  # [B] uint32
    priority: jax.Array  # [B] uint32
    tokens: jax.Array  # [B] float32 (stale where not found)
    last_us: jax.Array  # [B] uint32


def apply_qupdate(state: QTableState, upd: QTableUpdate) -> QTableState:
    """Scatter dirty buckets + changed-slot token resets (inside jit)."""
    return QTableState(
        rows=state.rows.at[upd.bidx].set(upd.rows, mode="drop"),
        tokens=state.tokens.at[upd.slot].set(upd.tokens, mode="drop"),
        last_us=state.last_us.at[upd.slot].set(upd.last_us, mode="drop"),
    )


def qlookup(state: QTableState, ip: jax.Array, g: QTableGeom) -> QLookup:
    """Branch-free probe: 2 wide row gathers + lane compares.

    ip: [B] uint32 keys.
    """
    Bsz = ip.shape[0]
    mask = np.uint32(g.nbuckets - 1)
    b1 = (hash_words([ip], SEED1) & mask).astype(jnp.int32)
    b2 = (hash_words([ip], SEED2) & mask).astype(jnp.int32)

    r1 = state.rows[b1]  # [B, 32] — the fast gather shape
    r2 = state.rows[b2]
    cand = jnp.concatenate(
        [r1.reshape(Bsz, WAYS, SLOT_W), r2.reshape(Bsz, WAYS, SLOT_W)], axis=1
    )  # [B, 2W, 8]

    match = (cand[:, :, QW_KEY] == ip[:, None]) & (
        (cand[:, :, QW_FLAGS] & FLAG_USED) != 0
    )  # [B, 2W]
    found = jnp.any(match, axis=1)
    first = jnp.argmax(match, axis=1)  # [B] in [0, 2W)
    sel = jnp.take_along_axis(cand, first[:, None, None], axis=1)[:, 0]  # [B, 8]

    bucket = jnp.where(first < WAYS, b1, b2)
    slot = bucket * WAYS + (first % WAYS)

    return QLookup(
        found=found,
        slot=slot,
        rate_lo=sel[:, QW_RATE_LO],
        rate_hi=sel[:, QW_RATE_HI],
        burst=sel[:, QW_BURST],
        priority=sel[:, QW_PRIORITY],
        tokens=state.tokens[slot],
        last_us=state.last_us[slot],
    )


class HostQTable:
    """Host-authoritative mirror (numpy, single writer) of one QoS table.

    Same role as ops/table.py:HostTable (pkg/ebpf loader map-CRUD), with
    bucket-granular dirty tracking: a policy change marks its bucket dirty
    and the whole 32-word row is rescattered (policy data is tiny and
    host-owned); token state is re-seeded only for the changed slot.
    """

    def __init__(self, nbuckets: int, name: str = ""):
        if nbuckets & (nbuckets - 1):
            raise ValueError("nbuckets must be a power of two")
        self.nbuckets = nbuckets
        self.S = nbuckets * WAYS
        self.name = name
        self.rows = np.zeros((nbuckets, ROW_W), dtype=np.uint32)
        self.tokens = np.zeros((self.S,), dtype=np.float32)
        self.last_us = np.zeros((self.S,), dtype=np.uint32)
        self.count = 0
        # dirty buckets; value = set of slots whose tokens must be re-seeded
        self._dirty: dict[int, set[int]] = {}
        self._dirty_all = False
        self._rng = np.random.default_rng(0xB46)

    # -- hashing (must match qlookup bit-for-bit) --
    def _buckets(self, ip: int) -> tuple[int, int]:
        k = np.asarray([ip], dtype=np.uint32)
        m = np.uint32(self.nbuckets - 1)
        return int((hash_words([k], SEED1) & m)[0]), int((hash_words([k], SEED2) & m)[0])

    def _way(self, b: int, w: int) -> np.ndarray:
        return self.rows[b, w * SLOT_W : (w + 1) * SLOT_W]

    def _find(self, ip: int) -> tuple[int, int] | None:
        b1, b2 = self._buckets(ip)
        for b in (b1, b2):
            for w in range(WAYS):
                s = self._way(b, w)
                if (s[QW_FLAGS] & 1) and int(s[QW_KEY]) == (ip & 0xFFFFFFFF):
                    return b, w
        return None

    def _place(self, b: int, w: int, ip: int, rate_bps: int, burst: int,
               priority: int, start_full: bool) -> int:
        s = self._way(b, w)
        s[QW_KEY] = ip & 0xFFFFFFFF
        s[QW_FLAGS] = 1
        s[QW_RATE_LO] = rate_bps & 0xFFFFFFFF
        s[QW_RATE_HI] = (rate_bps >> 32) & 0xFFFFFFFF
        s[QW_BURST] = burst
        s[QW_PRIORITY] = priority
        slot = b * WAYS + w
        self.tokens[slot] = float(burst if start_full else 0)
        self.last_us[slot] = 0
        self._dirty.setdefault(b, set()).add(slot)
        return slot

    def insert(self, ip: int, rate_bps: int, burst: int, priority: int = 0,
               start_full: bool = True) -> int:
        """Install or update a policy. Returns the global slot index."""
        hit = self._find(ip)
        if hit is not None:  # update config in place; re-seed tokens
            b, w = hit
            return self._place(b, w, ip, rate_bps, burst, priority, start_full)

        cur = (ip, rate_bps, burst, priority, start_full)
        moves: list[tuple[int, int, np.ndarray, float, int]] = []
        for _ in range(MAX_KICKS):
            b1, b2 = self._buckets(cur[0])
            for b in (b1, b2):
                for w in range(WAYS):
                    if not (self._way(b, w)[QW_FLAGS] & 1):
                        self._place(b, w, *cur)
                        self.count += 1
                        hit = self._find(ip)
                        assert hit is not None
                        return hit[0] * WAYS + hit[1]
            # both buckets full -> evict a random way; relocated entries
            # refill to full burst (host can't read device tokens)
            b = b1 if self._rng.integers(2) == 0 else b2
            w = int(self._rng.integers(WAYS))
            s = self._way(b, w).copy()
            slot = b * WAYS + w
            moves.append((b, w, s, float(self.tokens[slot]), int(self.last_us[slot])))
            ev_rate = int(s[QW_RATE_LO]) | (int(s[QW_RATE_HI]) << 32)
            self._place(b, w, *cur)
            cur = (int(s[QW_KEY]), ev_rate, int(s[QW_BURST]), int(s[QW_PRIORITY]), True)

        for b, w, s, tok, last in reversed(moves):  # roll back, keep old entries
            self.rows[b, w * SLOT_W : (w + 1) * SLOT_W] = s
            self.tokens[b * WAYS + w] = tok
            self.last_us[b * WAYS + w] = last
            self._dirty.setdefault(b, set()).add(b * WAYS + w)
        raise RuntimeError(
            f"qos table {self.name!r} full (count={self.count}, "
            f"nbuckets={self.nbuckets}); size buckets >= subscribers/2")

    def delete(self, ip: int) -> bool:
        hit = self._find(ip)
        if hit is None:
            return False
        b, w = hit
        self._way(b, w)[:] = 0
        self.tokens[b * WAYS + w] = 0.0
        self.last_us[b * WAYS + w] = 0
        self.count -= 1
        self._dirty.setdefault(b, set()).add(b * WAYS + w)
        return True

    def lookup(self, ip: int) -> dict | None:
        hit = self._find(ip)
        if hit is None:
            return None
        b, w = hit
        s = self._way(b, w)
        return {
            "slot": b * WAYS + w,
            "rate_bps": int(s[QW_RATE_LO]) | (int(s[QW_RATE_HI]) << 32),
            "burst": int(s[QW_BURST]),
            "priority": int(s[QW_PRIORITY]),
            "tokens": float(self.tokens[b * WAYS + w]),
        }

    def bulk_insert(self, ips: np.ndarray, rates_bps: np.ndarray,
                    bursts: np.ndarray, priorities: np.ndarray | None = None,
                    start_full: bool = True) -> None:
        """Vectorized initial build (1M-subscriber scale; see
        HostTable.bulk_insert for the pass structure). Keys must be new."""
        ips = np.asarray(ips, dtype=np.uint32).reshape(-1)
        rates = np.asarray(rates_bps, dtype=np.uint64).reshape(-1)
        bursts = np.asarray(bursts, dtype=np.uint32).reshape(-1)
        prios = (np.zeros_like(ips) if priorities is None
                 else np.asarray(priorities, dtype=np.uint32).reshape(-1))
        n = len(ips)
        if n == 0:
            return
        m = np.uint32(self.nbuckets - 1)
        b1 = (hash_words([ips], SEED1) & m).astype(np.int64)
        b2 = (hash_words([ips], SEED2) & m).astype(np.int64)

        flags = self.rows[:, QW_FLAGS::SLOT_W]  # [NB, WAYS] view
        unplaced = np.ones((n,), dtype=bool)
        for side in (b1, b2):
            for w in range(WAYS):
                idxs = np.nonzero(unplaced)[0]
                if len(idxs) == 0:
                    break
                bb = side[idxs]
                free = flags[bb, w] == 0
                idxs, bb = idxs[free], bb[free]
                if len(idxs) == 0:
                    continue
                uq_b, firsti = np.unique(bb, return_index=True)
                take = idxs[firsti]
                base = w * SLOT_W
                self.rows[uq_b, base + QW_KEY] = ips[take]
                self.rows[uq_b, base + QW_FLAGS] = 1
                self.rows[uq_b, base + QW_RATE_LO] = (rates[take] & 0xFFFFFFFF).astype(np.uint32)
                self.rows[uq_b, base + QW_RATE_HI] = (rates[take] >> 32).astype(np.uint32)
                self.rows[uq_b, base + QW_BURST] = bursts[take]
                self.rows[uq_b, base + QW_PRIORITY] = prios[take]
                slots = uq_b * WAYS + w
                self.tokens[slots] = bursts[take].astype(np.float32) if start_full else 0.0
                self.last_us[slots] = 0
                unplaced[take] = False
                self.count += len(take)
                if n <= 256:  # small batches stay on the bounded-delta path
                    for bkt, s in zip(uq_b, slots):
                        self._dirty.setdefault(int(bkt), set()).add(int(s))

        for i in np.nonzero(unplaced)[0]:  # cuckoo-kick residue
            self.insert(int(ips[i]), int(rates[i]), int(bursts[i]), int(prios[i]),
                        start_full)

        if n > 256:
            self._dirty.clear()
            self._dirty_all = True

    # -- device synchronization --
    def device_state(self) -> QTableState:
        self._dirty.clear()
        self._dirty_all = False
        return QTableState(
            rows=jnp.asarray(self.rows),
            tokens=jnp.asarray(self.tokens),
            last_us=jnp.asarray(self.last_us),
        )

    def dirty_count(self) -> int:
        return self.nbuckets if self._dirty_all else len(self._dirty)

    def make_update(self, max_buckets: int) -> QTableUpdate:
        """Drain up to max_buckets dirty buckets (bounded host->HBM traffic)."""
        if self._dirty_all:
            raise RuntimeError(
                f"qos table {self.name!r}: bulk_insert invalidated delta sync; "
                "call device_state() for a full upload first")
        take = sorted(self._dirty)[:max_buckets]
        slot_sets = [self._dirty.pop(b) for b in take]
        n = len(take)
        bidx = np.full((max_buckets,), self.nbuckets, dtype=np.int32)
        rows = np.zeros((max_buckets, ROW_W), dtype=np.uint32)
        slot = np.full((max_buckets, WAYS), self.S, dtype=np.int32)
        tok = np.zeros((max_buckets, WAYS), dtype=np.float32)
        last = np.zeros((max_buckets, WAYS), dtype=np.uint32)
        if n:
            bs = np.asarray(take, dtype=np.int32)
            bidx[:n] = bs
            rows[:n] = self.rows[bs]
            for i, ss in enumerate(slot_sets):
                for j, s in enumerate(sorted(ss)[:WAYS]):
                    slot[i, j] = s
                    tok[i, j] = self.tokens[s]
                    last[i, j] = self.last_us[s]
        return QTableUpdate(
            bidx=jnp.asarray(bidx), rows=jnp.asarray(rows),
            slot=jnp.asarray(slot), tokens=jnp.asarray(tok),
            last_us=jnp.asarray(last),
        )
