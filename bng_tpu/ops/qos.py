"""Per-subscriber token-bucket rate limiting, batched.

TPU re-expression of bpf/qos_ratelimit.c. The eBPF program does a
read-modify-write of one token bucket per packet (qos_ratelimit.c:70-104);
on TPU a batch may contain many packets for the same subscriber, so the
sequential "consume if tokens suffice" semantics are recovered with a
**stable-sort segment prefix sum**: lanes sorted by bucket slot (stable,
preserving arrival order), per-segment cumulative byte counts via cumsum +
cummax head-carry, admission decided against the bucket's available
tokens, then results unsorted. O(B log B) time, O(B) memory — scales to
the 8k+ lane batches the throughput target needs.

Admission rule: lane i passes iff (sum of lengths of same-bucket lanes
j<=i) <= available tokens at batch start. This is the reference's TBF with
one conservative difference: a dropped packet's bytes still occupy the
in-batch prefix (batch windows are ~µs, so the divergence is bounded by
one batch of one subscriber's traffic).

Token state is device-authoritative (tokens, last_update); the host only
writes rows when installing/changing a policy (pkg/qos/manager.go:167-246
role). Timestamps are µs with wrap-safe uint32 arithmetic.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops.qtable import QTableGeom, QTableState, qlookup, write_token_rows

# token_bucket fields (parity: qos_ratelimit.c:24-31) live in the packed
# 8-word way rows of ops/qtable.py (policy + token state in one row)
QOS_WORDS = 8

# stats (parity: struct qos_stats, qos_ratelimit.c:53-58)
(QST_PKTS_PASSED, QST_PKTS_DROPPED, QST_BYTES_PASSED, QST_BYTES_DROPPED) = range(4)
QOS_NSTATS = 4


# QoS table geometry is the packed-bucket table's
QoSGeom = QTableGeom

# Same-bucket aggregation strategy:
#   "sort"   — stable argsort + segment cumsum (works on every backend)
#   "pallas" — MXU tiled equality-matmul kernel (ops.pallas_qos); on CPU
#              it runs in interpret mode (tests), on TPU compiled
#   "auto"   — pallas on TPU, sort elsewhere
# Default from BNG_QOS_PREFIX; "sort" until the pallas path has been
# timed on hardware (flip to "auto" once it wins).
PREFIX_IMPL = os.environ.get("BNG_QOS_PREFIX", "sort")


def _prefix_consumed(limited, slot, lens_u, avail):
    """Returns (allowed, consumed_f32, is_head) using the configured impl.

    allowed: sequential-TBF admission per lane (arrival = lane order);
    consumed: admitted bytes of the lane's bucket (valid on limited lanes);
    is_head: first limited lane of each bucket in the batch.
    """
    Bsz = slot.shape[0]
    impl = PREFIX_IMPL
    if impl == "auto":
        # Mosaic lowering is TPU-only; every other backend gets the sort
        impl = "pallas" if jax.default_backend() == "tpu" else "sort"

    # lanes without a limit get unique negative ids -> group with nobody
    slot_eff = jnp.where(limited, slot, -1 - jnp.arange(Bsz, dtype=jnp.int32))

    if impl == "pallas":
        from bng_tpu.ops.pallas_qos import seg_prefix_total

        # NOTE: f32 matmul accumulation is exact only below 2^24 bytes
        # per bucket per batch (the sort path's u32 cumsum is exact to
        # 2^32); a single bucket attempting >16.7MB in one batch can
        # flip a boundary admission vs the sort/eBPF reference.
        # Mosaic lowering is TPU-only: every other backend (cpu, gpu, ...)
        # runs interpret mode (ADVICE r1: a GPU backend must not try to
        # compile the Mosaic kernel).
        interp = jax.default_backend() != "tpu"
        lens_f = lens_u.astype(jnp.float32)
        cum_incl, _ = seg_prefix_total(slot_eff, lens_f, interpret=interp,
                                       compute="prefix")
        allowed = ~limited | (cum_incl <= avail)
        admitted = jnp.where(allowed & limited, lens_f, 0.0)
        _, consumed = seg_prefix_total(slot_eff, admitted, interpret=interp,
                                       compute="total")
        is_head = limited & (cum_incl <= lens_f)  # no earlier same-bucket lane
        return allowed, consumed, is_head

    # ---- sort path ----
    # Narrow (1-word-per-index) gathers are the measured TPU pathology
    # (PERF_NOTES.md §2; >=8-word rows gather at full speed), so the
    # permutation moves ONE packed [B,8] row per lane instead of four
    # scalar gathers, and the unsort is ONE packed row scatter instead of
    # an inverse-permutation + three gathers. tests/test_hlo_structure.py
    # pins these counts.
    order = jnp.argsort(slot_eff, stable=True)
    avail_int = jnp.clip(avail, 0.0, 4.0e9).astype(jnp.uint32)
    zero = jnp.zeros_like(lens_u)
    packed = jnp.stack(
        [slot_eff.astype(jnp.uint32), lens_u, avail_int,
         limited.astype(jnp.uint32), zero, zero, zero, zero], axis=1)  # [B, 8]
    ps = packed[order]
    s_sorted = ps[:, 0].astype(jnp.int32)
    lens_sorted = ps[:, 1]
    avail_sorted = ps[:, 2]
    limited_sorted = ps[:, 3] != 0

    csum = jnp.cumsum(lens_sorted)
    is_head_sorted = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), s_sorted[1:] != s_sorted[:-1]])
    is_last_sorted = jnp.concatenate(
        [s_sorted[1:] != s_sorted[:-1], jnp.ones((1,), dtype=bool)])
    seg_base = jax.lax.cummax(jnp.where(is_head_sorted, csum - lens_sorted, 0))
    cum_incl_sorted = csum - seg_base
    allowed_sorted = ~limited_sorted | (cum_incl_sorted <= avail_sorted)

    # consumed = admitted bytes of the lane's whole segment, computed
    # without segment_sum's scatter/gather pair: admitted cumsum is
    # non-decreasing, so a reverse cummin over (segment-last -> its
    # cumsum, else +inf) fills every lane with ITS segment end's value
    admitted_sorted = jnp.where(allowed_sorted & limited_sorted, lens_sorted, 0)
    adm_csum = jnp.cumsum(admitted_sorted)
    seg_end = jax.lax.cummin(
        jnp.where(is_last_sorted, adm_csum, jnp.uint32(0xFFFFFFFF)),
        reverse=True)
    adm_base = jax.lax.cummax(
        jnp.where(is_head_sorted, adm_csum - admitted_sorted, 0))
    consumed_sorted = seg_end - adm_base

    zs = jnp.zeros_like(consumed_sorted)
    res_sorted = jnp.stack(
        [allowed_sorted.astype(jnp.uint32), consumed_sorted,
         (is_head_sorted & limited_sorted).astype(jnp.uint32),
         zs, zs, zs, zs, zs], axis=1)  # [B, 8] — wide unsort scatter
    res = jnp.zeros((Bsz, 8), dtype=jnp.uint32).at[order].set(res_sorted)
    return (res[:, 0] != 0,
            res[:, 1].astype(jnp.float32),
            (res[:, 2] != 0) & limited)


class QoSResult(NamedTuple):
    allowed: jax.Array  # [B] bool (True also for no-policy lanes)
    dropped: jax.Array  # [B] bool (policy present and bucket empty)
    priority: jax.Array  # [B] uint32 (skb->priority parity, :166)
    table: QTableState  # updated token state
    stats: jax.Array  # [QOS_NSTATS] uint32


def qos_kernel(
    ip_key: jax.Array,  # [B] uint32 — dst_ip for download, src_ip for upload
    pkt_len: jax.Array,  # [B] uint32
    active: jax.Array,  # [B] bool — lanes subject to this QoS direction
    table: QTableState,
    geom: QTableGeom,
    now_us: jax.Array,  # uint32 scalar, wraps
) -> QoSResult:
    # qos is the only device-side *writer* of its table: the token/timestamp
    # writeback below scatters into the LOCAL arrays at res.slot, which under
    # a sharded geometry would be an owner-local slot — silent corruption.
    # QoS tables are chip-local by design (subscriber traffic affinity).
    if geom.axis is not None and geom.n_shards > 1:
        raise ValueError("qos_kernel requires a chip-local table (geom.axis=None); "
                         "QoS state is placed by subscriber affinity, not hash-sharding")
    Bsz = ip_key.shape[0]
    res = qlookup(table, ip_key, geom)
    has_policy = res.found & active
    # rate==0 means unlimited (qos_ratelimit.c:79-80)
    limited = has_policy & ((res.rate_lo | res.rate_hi) != 0)

    burst_f = res.burst.astype(jnp.float32)

    # refill (f32 math: |err| ~1e-7 relative, fine for shaping):
    # bytes/sec = rate_bps / 8; refill = elapsed_us * Bps / 1e6
    elapsed_us = (now_us - res.last_us).astype(jnp.float32)  # uint32 wrap-safe diff
    rate_bps = res.rate_lo.astype(jnp.float32) + res.rate_hi.astype(jnp.float32) * jnp.float32(2.0**32)
    refill = elapsed_us * (rate_bps / 8.0) * jnp.float32(1e-6)
    avail = jnp.minimum(res.tokens + refill, burst_f)

    # --- same-bucket aggregation (sequential TBF admission per lane) ---
    # impl-pluggable: stable-sort segment cumsum (u32-exact to 4GB per
    # batch), or the Pallas MXU equality-matmul kernel (ops.pallas_qos,
    # f32-exact to 2^24 bytes per bucket per batch) — see PREFIX_IMPL.
    lens_u = pkt_len.astype(jnp.uint32)
    allowed, consumed, first = _prefix_consumed(limited, res.slot, lens_u, avail)
    dropped = limited & ~allowed
    new_tokens = jnp.clip(avail - consumed, 0.0, burst_f)
    S = table.rows.shape[0]
    wslot = jnp.where(first, res.slot, S).astype(jnp.int32)
    # head lanes rewrite their whole way row (one wide [B,8] scatter —
    # no scalar token/timestamp scatters; see qtable.write_token_rows)
    new_table = write_token_rows(table, wslot, res.row, new_tokens, now_us)

    priority = jnp.where(has_policy, res.priority, 0)

    stats = jnp.zeros((QOS_NSTATS,), dtype=jnp.uint32)
    counted = has_policy  # stats only update when a policy exists (:149-162)
    stats = stats.at[QST_PKTS_PASSED].add(jnp.sum(counted & allowed, dtype=jnp.uint32))
    stats = stats.at[QST_PKTS_DROPPED].add(jnp.sum(dropped, dtype=jnp.uint32))
    stats = stats.at[QST_BYTES_PASSED].add(jnp.sum(jnp.where(counted & allowed, pkt_len, 0), dtype=jnp.uint32))
    stats = stats.at[QST_BYTES_DROPPED].add(jnp.sum(jnp.where(dropped, pkt_len, 0), dtype=jnp.uint32))

    return QoSResult(
        allowed=allowed,
        dropped=dropped,
        priority=priority,
        table=new_table,
        stats=stats,
    )
