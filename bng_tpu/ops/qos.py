"""Per-subscriber token-bucket rate limiting, batched.

TPU re-expression of bpf/qos_ratelimit.c. The eBPF program does a
read-modify-write of one token bucket per packet (qos_ratelimit.c:70-104);
on TPU a batch may contain many packets for the same subscriber, so the
sequential "consume if tokens suffice" semantics are recovered with a
**stable-sort segment prefix sum**: lanes sorted by bucket slot (stable,
preserving arrival order), per-segment cumulative byte counts via cumsum +
cummax head-carry, admission decided against the bucket's available
tokens, then results unsorted. O(B log B) time, O(B) memory — scales to
the 8k+ lane batches the throughput target needs.

Admission rule: lane i passes iff (sum of lengths of same-bucket lanes
j<=i) <= available tokens at batch start. This is the reference's TBF with
one conservative difference: a dropped packet's bytes still occupy the
in-batch prefix (batch windows are ~µs, so the divergence is bounded by
one batch of one subscriber's traffic).

Token state is device-authoritative (tokens, last_update); the host only
writes rows when installing/changing a policy (pkg/qos/manager.go:167-246
role). Timestamps are µs with wrap-safe uint32 arithmetic.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops.parse import Parsed
from bng_tpu.ops.table import TableGeom, TableState, lookup

# token_bucket value words (parity: qos_ratelimit.c:24-31)
(QV_RATE_BPS_LO, QV_RATE_BPS_HI, QV_BURST, QV_TOKENS, QV_LAST_US, QV_PRIORITY) = range(6)
QOS_WORDS = 8

# stats (parity: struct qos_stats, qos_ratelimit.c:53-58)
(QST_PKTS_PASSED, QST_PKTS_DROPPED, QST_BYTES_PASSED, QST_BYTES_DROPPED) = range(4)
QOS_NSTATS = 4


# QoS has a single table per direction; its geometry IS a TableGeom
QoSGeom = TableGeom


class QoSResult(NamedTuple):
    allowed: jax.Array  # [B] bool (True also for no-policy lanes)
    dropped: jax.Array  # [B] bool (policy present and bucket empty)
    priority: jax.Array  # [B] uint32 (skb->priority parity, :166)
    table: TableState  # updated token state
    stats: jax.Array  # [QOS_NSTATS] uint32


def qos_kernel(
    ip_key: jax.Array,  # [B] uint32 — dst_ip for download, src_ip for upload
    pkt_len: jax.Array,  # [B] uint32
    active: jax.Array,  # [B] bool — lanes subject to this QoS direction
    table: TableState,
    geom: TableGeom,
    now_us: jax.Array,  # uint32 scalar, wraps
) -> QoSResult:
    # qos is the only device-side *writer* of its table: the token/timestamp
    # writeback below scatters into the LOCAL table at res.slot, which under
    # a sharded geometry would be an owner-local slot — silent corruption.
    # QoS tables are chip-local by design (subscriber traffic affinity).
    if geom.axis is not None and geom.n_shards > 1:
        raise ValueError("qos_kernel requires a chip-local table (geom.axis=None); "
                         "QoS state is placed by subscriber affinity, not hash-sharding")
    Bsz = ip_key.shape[0]
    res = lookup(table, ip_key[:, None], geom)
    has_policy = res.found & active
    rate_lo = res.vals[:, QV_RATE_BPS_LO]
    rate_hi = res.vals[:, QV_RATE_BPS_HI]
    # rate==0 means unlimited (qos_ratelimit.c:79-80)
    limited = has_policy & ((rate_lo | rate_hi) != 0)

    burst = res.vals[:, QV_BURST]
    tokens = res.vals[:, QV_TOKENS]
    last_us = res.vals[:, QV_LAST_US]

    # refill (f32 math: |err| ~1e-7 relative, fine for shaping):
    # bytes/sec = rate_bps / 8; refill = elapsed_us * Bps / 1e6
    elapsed_us = (now_us - last_us).astype(jnp.float32)  # uint32 wrap-safe diff
    rate_bps = rate_lo.astype(jnp.float32) + rate_hi.astype(jnp.float32) * jnp.float32(2.0**32)
    refill = elapsed_us * (rate_bps / 8.0) * jnp.float32(1e-6)
    avail = jnp.minimum(tokens.astype(jnp.float32) + refill, burst.astype(jnp.float32))

    # --- sort-based segment prefix sum over same-slot lanes ---
    # O(B log B) and O(B) memory (an equality-matrix/MXU variant is O(B^2)
    # bytes — 268MB at B=8192 — which swamps HBM bandwidth). A stable sort
    # groups same-bucket lanes while preserving lane order, so the
    # sequential TBF admission order survives.
    # integer byte accounting: an f32 cumsum loses integer exactness past
    # 2^24 total batch bytes (8k jumbo-frame lanes), flipping boundary
    # admissions — uint32 is exact to 4GB per batch
    lens_u = pkt_len.astype(jnp.uint32)
    slot_eff = jnp.where(limited, res.slot, -1 - jnp.arange(Bsz, dtype=jnp.int32))
    order = jnp.argsort(slot_eff, stable=True)
    s_sorted = slot_eff[order]
    lens_sorted = lens_u[order]
    avail_sorted = avail[order]
    limited_sorted = limited[order]

    csum = jnp.cumsum(lens_sorted)
    is_head = jnp.concatenate([jnp.ones((1,), dtype=bool), s_sorted[1:] != s_sorted[:-1]])
    seg_id = jnp.cumsum(is_head.astype(jnp.int32)) - 1  # dense segment rank
    # bytes consumed before each segment starts: carry the head's base forward
    seg_base = jax.lax.cummax(jnp.where(is_head, csum - lens_sorted, 0))
    cum_incl_sorted = csum - seg_base  # attempted bytes up to & incl me, in my bucket
    # floor(avail) in uint32 keeps the admission compare fully integral
    avail_int = jnp.clip(avail_sorted, 0.0, 4.0e9).astype(jnp.uint32)
    allowed_sorted = ~limited_sorted | (cum_incl_sorted <= avail_int)

    # per-bucket admitted-byte totals -> token writeback
    admitted_sorted = jnp.where(allowed_sorted & limited_sorted, lens_sorted, 0)
    seg_totals = jax.ops.segment_sum(admitted_sorted, seg_id, num_segments=Bsz)
    consumed_sorted = seg_totals[seg_id]
    new_tokens_sorted = jnp.clip(avail_sorted - consumed_sorted.astype(jnp.float32), 0.0,
                                 burst[order].astype(jnp.float32))

    # unsort lane-wise results
    inv = jnp.zeros((Bsz,), dtype=jnp.int32).at[order].set(jnp.arange(Bsz, dtype=jnp.int32))
    allowed = allowed_sorted[inv]
    dropped = limited & ~allowed
    new_tokens = new_tokens_sorted[inv]

    # the head lane of each bucket writes the new state (no conflicts)
    first = (is_head & limited_sorted)[inv] & limited
    S = table.vals.shape[0]
    wslot = jnp.where(first, res.slot, S).astype(jnp.int32)
    vals = table.vals.at[wslot, QV_TOKENS].set(new_tokens.astype(jnp.uint32), mode="drop")
    vals = vals.at[wslot, QV_LAST_US].set(jnp.broadcast_to(now_us, (Bsz,)).astype(jnp.uint32), mode="drop")

    priority = jnp.where(has_policy, res.vals[:, QV_PRIORITY], 0)

    stats = jnp.zeros((QOS_NSTATS,), dtype=jnp.uint32)
    counted = has_policy  # stats only update when a policy exists (:149-162)
    stats = stats.at[QST_PKTS_PASSED].add(jnp.sum(counted & allowed, dtype=jnp.uint32))
    stats = stats.at[QST_PKTS_DROPPED].add(jnp.sum(dropped, dtype=jnp.uint32))
    stats = stats.at[QST_BYTES_PASSED].add(jnp.sum(jnp.where(counted & allowed, pkt_len, 0), dtype=jnp.uint32))
    stats = stats.at[QST_BYTES_DROPPED].add(jnp.sum(jnp.where(dropped, pkt_len, 0), dtype=jnp.uint32))

    return QoSResult(
        allowed=allowed,
        dropped=dropped,
        priority=priority,
        table=table._replace(vals=vals),
        stats=stats,
    )


def make_bucket_row(rate_bps: int, burst_bytes: int, priority: int, start_full: bool = True):
    """Host-side helper: token_bucket row for table insert."""
    import numpy as np

    v = np.zeros((QOS_WORDS,), dtype=np.uint32)
    v[QV_RATE_BPS_LO] = rate_bps & 0xFFFFFFFF
    v[QV_RATE_BPS_HI] = (rate_bps >> 32) & 0xFFFFFFFF
    v[QV_BURST] = burst_bytes
    v[QV_TOKENS] = burst_bytes if start_full else 0
    v[QV_LAST_US] = 0
    v[QV_PRIORITY] = priority
    return v
