"""Per-subscriber token-bucket rate limiting, batched.

TPU re-expression of bpf/qos_ratelimit.c. The eBPF program does a
read-modify-write of one token bucket per packet (qos_ratelimit.c:70-104);
on TPU a batch may contain many packets for the same subscriber, so the
sequential "consume if tokens suffice" semantics are recovered with a
**segment prefix sum computed on the MXU**: an equality matrix
(same-bucket lanes) masked lower-triangular, matmul'd against packet
lengths. B=2048 lanes -> a [B,B]@[B] f32 matmul — exactly what the
systolic array is for; no sorting, no scatter conflicts.

Admission rule: lane i passes iff (sum of lengths of same-bucket lanes
j<=i) <= available tokens at batch start. This is the reference's TBF with
one conservative difference: a dropped packet's bytes still occupy the
in-batch prefix (batch windows are ~µs, so the divergence is bounded by
one batch of one subscriber's traffic).

Token state is device-authoritative (tokens, last_update); the host only
writes rows when installing/changing a policy (pkg/qos/manager.go:167-246
role). Timestamps are µs with wrap-safe uint32 arithmetic.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops.parse import Parsed
from bng_tpu.ops.table import TableState, device_lookup

# token_bucket value words (parity: qos_ratelimit.c:24-31)
(QV_RATE_BPS_LO, QV_RATE_BPS_HI, QV_BURST, QV_TOKENS, QV_LAST_US, QV_PRIORITY) = range(6)
QOS_WORDS = 8

# stats (parity: struct qos_stats, qos_ratelimit.c:53-58)
(QST_PKTS_PASSED, QST_PKTS_DROPPED, QST_BYTES_PASSED, QST_BYTES_DROPPED) = range(4)
QOS_NSTATS = 4


class QoSGeom(NamedTuple):
    nbuckets: int
    stash: int


class QoSResult(NamedTuple):
    allowed: jax.Array  # [B] bool (True also for no-policy lanes)
    dropped: jax.Array  # [B] bool (policy present and bucket empty)
    priority: jax.Array  # [B] uint32 (skb->priority parity, :166)
    table: TableState  # updated token state
    stats: jax.Array  # [QOS_NSTATS] uint32


def qos_kernel(
    ip_key: jax.Array,  # [B] uint32 — dst_ip for download, src_ip for upload
    pkt_len: jax.Array,  # [B] uint32
    active: jax.Array,  # [B] bool — lanes subject to this QoS direction
    table: TableState,
    geom: QoSGeom,
    now_us: jax.Array,  # uint32 scalar, wraps
) -> QoSResult:
    Bsz = ip_key.shape[0]
    res = device_lookup(table, ip_key[:, None], geom.nbuckets, geom.stash)
    has_policy = res.found & active
    rate_lo = res.vals[:, QV_RATE_BPS_LO]
    rate_hi = res.vals[:, QV_RATE_BPS_HI]
    # rate==0 means unlimited (qos_ratelimit.c:79-80)
    limited = has_policy & ((rate_lo | rate_hi) != 0)

    burst = res.vals[:, QV_BURST]
    tokens = res.vals[:, QV_TOKENS]
    last_us = res.vals[:, QV_LAST_US]

    # refill (f32 math: |err| ~1e-7 relative, fine for shaping):
    # bytes/sec = rate_bps / 8; refill = elapsed_us * Bps / 1e6
    elapsed_us = (now_us - last_us).astype(jnp.float32)  # uint32 wrap-safe diff
    rate_bps = rate_lo.astype(jnp.float32) + rate_hi.astype(jnp.float32) * jnp.float32(2.0**32)
    refill = elapsed_us * (rate_bps / 8.0) * jnp.float32(1e-6)
    avail = jnp.minimum(tokens.astype(jnp.float32) + refill, burst.astype(jnp.float32))

    # --- MXU segment prefix sum over same-slot lanes ---
    slot = jnp.where(limited, res.slot, -1 - jnp.arange(Bsz, dtype=jnp.int32))  # unique per inactive lane
    same = (slot[:, None] == slot[None, :]).astype(jnp.float32)  # [B, B]
    tri_incl = jnp.tril(jnp.ones((Bsz, Bsz), dtype=jnp.float32))  # j <= i
    lens = pkt_len.astype(jnp.float32)
    cum_incl = (same * tri_incl) @ lens  # [B] bytes attempted up to & incl me
    allowed = ~limited | (cum_incl <= avail)
    dropped = limited & ~allowed

    # consumed per bucket = sum of admitted lanes' bytes (full row sum)
    admitted_lens = jnp.where(allowed & limited, lens, 0.0)
    consumed = same @ admitted_lens  # same total for every lane of the bucket
    new_tokens = jnp.clip(avail - consumed, 0.0, burst.astype(jnp.float32))

    # first lane of each bucket writes the new state (no scatter conflicts)
    tri_strict = jnp.tril(jnp.ones((Bsz, Bsz), dtype=jnp.float32), k=-1)
    prior_same = (same * tri_strict) @ jnp.ones((Bsz,), dtype=jnp.float32)
    first = limited & (prior_same == 0)
    S = table.vals.shape[0]
    wslot = jnp.where(first, res.slot, S).astype(jnp.int32)
    vals = table.vals.at[wslot, QV_TOKENS].set(new_tokens.astype(jnp.uint32), mode="drop")
    vals = vals.at[wslot, QV_LAST_US].set(jnp.broadcast_to(now_us, (Bsz,)).astype(jnp.uint32), mode="drop")

    priority = jnp.where(has_policy, res.vals[:, QV_PRIORITY], 0)

    stats = jnp.zeros((QOS_NSTATS,), dtype=jnp.uint32)
    counted = has_policy  # stats only update when a policy exists (:149-162)
    stats = stats.at[QST_PKTS_PASSED].add(jnp.sum(counted & allowed, dtype=jnp.uint32))
    stats = stats.at[QST_PKTS_DROPPED].add(jnp.sum(dropped, dtype=jnp.uint32))
    stats = stats.at[QST_BYTES_PASSED].add(jnp.sum(jnp.where(counted & allowed, pkt_len, 0), dtype=jnp.uint32))
    stats = stats.at[QST_BYTES_DROPPED].add(jnp.sum(jnp.where(dropped, pkt_len, 0), dtype=jnp.uint32))

    return QoSResult(
        allowed=allowed,
        dropped=dropped,
        priority=priority,
        table=table._replace(vals=vals),
        stats=stats,
    )


def make_bucket_row(rate_bps: int, burst_bytes: int, priority: int, start_full: bool = True):
    """Host-side helper: token_bucket row for table insert."""
    import numpy as np

    v = np.zeros((QOS_WORDS,), dtype=np.uint32)
    v[QV_RATE_BPS_LO] = rate_bps & 0xFFFFFFFF
    v[QV_RATE_BPS_HI] = (rate_bps >> 32) & 0xFFFFFFFF
    v[QV_BURST] = burst_bytes
    v[QV_TOKENS] = burst_bytes if start_full else 0
    v[QV_LAST_US] = 0
    v[QV_PRIORITY] = priority
    return v
