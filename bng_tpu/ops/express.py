"""Express OFFER fast path: the minimal device program the 50us budget
permits (ISSUE 13).

The full DHCP-only program (`ops/dhcp.py dhcp_fastpath`) parses the raw
[B, L] frame batch on device and composes the complete reply bytes —
~60 gather/concat kernels over 512-byte lanes, almost all of it spent
re-deriving facts the host admission path already touched (VLAN tags,
chaddr, xid) and assembling bytes the host could patch into a
preassembled template. This module splits the work at the only boundary
the 50us `device` budget cares about:

- **Admission (host, once per frame):** `parse_express` extracts the
  express descriptor — the lane columns the probe cascade needs (MAC
  key words, VLAN key, circuit-ID key words, eligibility flags) plus
  the host-only patch-in fields (xid, msg type, offsets). Its parse
  semantics mirror `ops/parse.py parse_batch` + the fixed-offset
  option scans of `dhcp_fastpath` bit-for-bit: a frame this parser
  deems ineligible is exactly a frame the device program would have
  PASSed.
- **Device (`express_verdicts`):** the three-tier cuckoo probe
  (VLAN -> circuit-ID -> MAC, `BNG_TABLE_IMPL`-selectable via
  ops/table.device_lookup), lease-expiry and pool-validity checks, and
  a [B, XD_WORDS] verdict block: verdict + yiaddr + pool/lease words.
  No packet bytes enter or leave the program.
- **Retire (host):** the verdict block selects a preassembled
  `ExpressWireTemplate` (control/dhcp_codec.py, built on the same
  ReplyTemplate machinery the slow-path server renders through) and
  patches the per-client words — byte-identical to the dhcp_fastpath
  compose, pinned by tests/test_express.py.

The descriptor is donated to the program and the verdict block is
written over its first columns (`desc.at[...].set`), so XLA aliases the
output onto the input buffer — no per-dispatch allocation on the fast
lane. Stats use the `ops/dhcp.py` counter indices; divergences from the
full program's counting (wrong-type frames are rejected at admission
and never reach the device, so they are absent from ST_MISS here) are
confined to frames the express lane never answers.
"""

from __future__ import annotations

import struct
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from bng_tpu.ops.dhcp import (
    AV_IP,
    AV_LEASE_EXP,
    AV_POOL_ID,
    DHCP_MAGIC,
    DHCPGeom,
    DHCPTables,
    DISCOVER,
    NSTATS,
    PV_LEASE_T,
    PV_VALID,
    REQUEST,
    ST_BCAST,
    ST_ERROR,
    ST_EXPIRED,
    ST_HIT,
    ST_MISS,
    ST_OPT82_PRESENT,
    ST_TOTAL,
    ST_UCAST,
    ST_VLAN,
    CID_KEY_LEN,
)
from bng_tpu.ops.table import lookup

# ---- descriptor layout: one [XD_WORDS] uint32 row per express frame ----
# Columns 0..3 double as the verdict block on the way back (the program
# donates the descriptor and writes the verdict over these columns, so
# the output aliases the input staging buffer).
XD_FLAGS = 0  # XF_* eligibility bits
XD_MAC_HI = 1  # chaddr hi16 (table key word 0)
XD_MAC_LO = 2  # chaddr lo32 (table key word 1)
XD_VLAN = 3  # s_tag<<16 | c_tag (vlan table key)
XD_XID = 4  # host-only: request xid (identity/debug)
XD_MSG = 5  # host-only: DHCP message type (reply-type selection)
XD_CID0 = 8  # 8 big-endian uint32 words of the 32-byte circuit-id key
XD_WORDS = 16

# verdict block columns (overlaid on XD_FLAGS..XD_VLAN)
VB_VERDICT = 0  # 1 = answered on device (host patches a template reply)
VB_YIADDR = 1
VB_POOL = 2  # pool id (template selection)
VB_LEASE_T = 3  # pool lease seconds (the device-serving lease words)

XF_VALID = 1  # eligible DISCOVER/REQUEST (probe it)
XF_VLAN = 2  # frame was VLAN-tagged (vlan-key tier eligible)
XF_CID = 4  # option-82 circuit-id extracted (cid tier eligible)
XF_BCAST = 8  # reply will broadcast (stats parity: ST_BCAST/ST_UCAST)
XF_RELAYED = 16  # giaddr != 0 (host-side reply addressing)

# traces of express_verdicts since process start — incremented at TRACE
# time only, so tests can assert an AOT geometry hit serves without
# retracing (tests/test_express.py::TestAotCache)
TRACE_COUNT = 0


class ExpressDesc(NamedTuple):
    """One admitted express frame: device columns + host patch-in meta."""

    words: np.ndarray  # [XD_WORDS] uint32 (the device descriptor row)
    vlan_off: int  # 0 / 4 / 8 — reply copies frame[12:14+vlan_off]
    dhcp_off: int  # BOOTP payload offset in the frame
    msg_type: int  # DISCOVER or REQUEST
    relayed: bool  # giaddr != 0 -> unicast to giaddr, udp dst 67
    use_bcast: bool  # L2/L3 broadcast reply (dhcp_fastpath.c:436-462)


class ExpressResult(NamedTuple):
    """Device outputs of one express dispatch (futures until retire)."""

    block: jax.Array  # [B, XD_WORDS] uint32; cols VB_* are the verdict
    stats: jax.Array  # [NSTATS] uint32 batch deltas (ops/dhcp indices)


def _u16(frame: bytes, off: int) -> int:
    return (frame[off] << 8) | frame[off + 1]


def parse_express(frame: bytes) -> ExpressDesc | None:
    """Host-side express admission parse: frame -> descriptor, or None
    when the device program would not have answered it anyway (the
    frame takes the slow path / fused pipeline unchanged).

    Semantics mirror the device parse exactly — parse_batch's VLAN peel
    (outer 0x8100/0x88A8, inner 0x8100 only), dhcp_fastpath's bounds
    checks, its fixed-offset option-53 scan ({0,1,3,4,5,6}, first
    match) and its fixed-position option-82 circuit-id scan (position A
    then 12..19). A drift here would mis-steer a frame the device
    cascade resolves differently, so tests pin byte-identity of the
    whole express path against the full program across geometries.
    """
    L = len(frame)
    if L < 34:
        return None
    # VLAN peel (parse_batch semantics)
    et = _u16(frame, 12)
    vlan_off, s_tag, c_tag = 0, 0, 0
    tagged = et in (0x8100, 0x88A8)
    if tagged:
        if L < 18:
            return None
        s_tag = _u16(frame, 14) & 0x0FFF
        et1 = _u16(frame, 16)
        if et1 == 0x8100:  # QinQ: inner must be 802.1Q
            if L < 22:
                return None
            c_tag = _u16(frame, 18) & 0x0FFF
            vlan_off, et = 8, _u16(frame, 20)
        else:
            vlan_off, et = 4, et1
    l3 = 14 + vlan_off
    if et != 0x0800 or L < l3 + 20 or (frame[l3] >> 4) != 4:
        return None
    ihl = (frame[l3] & 0x0F) * 4
    if ihl < 20 or frame[l3 + 9] != 17:
        return None
    l4 = l3 + ihl
    if L < l4 + 8 or _u16(frame, l4 + 2) != 67:
        return None
    dhcp_off = l4 + 8
    if (L < dhcp_off + 240 or frame[dhcp_off] != 1
            or int.from_bytes(frame[dhcp_off + 236: dhcp_off + 240],
                              "big") != DHCP_MAGIC):
        return None

    # fixed-offset option-53 scan (dhcp_fastpath.c:216-250 order)
    opts = dhcp_off + 240
    mtype = 0
    if opts + 12 <= L:
        for o in (0, 1, 3, 4, 5, 6):
            if frame[opts + o] == 53 and frame[opts + o + 1] == 1:
                mtype = frame[opts + o + 2]
                break
    if mtype not in (DISCOVER, REQUEST):
        return None

    # fixed-position option-82 circuit-id (dhcp_fastpath.c:267-323)
    cid = b""
    if opts + 64 <= L:
        o82len_a = frame[opts + 4]
        positions = [(3, 4, 5, 6, 7, opts + 5 + o82len_a <= L)]
        positions += [(p, p + 1, p + 2, p + 3, p + 4, opts + p + 8 <= L)
                      for p in range(12, 20)]
        for tag_o, len_o, sub_o, cl_o, cid_o, extra_ok in positions:
            cl = frame[opts + cl_o]
            if (extra_ok and frame[opts + tag_o] == 82
                    and frame[opts + len_o] >= 4
                    and frame[opts + sub_o] == 1
                    and 0 < cl <= CID_KEY_LEN
                    and opts + cid_o + cl <= L):
                cid = frame[opts + cid_o: opts + cid_o + cl]
                break

    xid, secs, flags16 = struct.unpack_from("!IHH", frame, dhcp_off + 4)
    del secs  # patched into the reply straight from the frame at retire
    ciaddr, = struct.unpack_from("!I", frame, dhcp_off + 12)
    giaddr, = struct.unpack_from("!I", frame, dhcp_off + 24)
    relayed = giaddr != 0
    use_bcast = (not relayed) and ((flags16 & 0x8000) != 0 or ciaddr == 0)

    w = np.zeros((XD_WORDS,), dtype=np.uint32)
    fl = XF_VALID
    if tagged:
        fl |= XF_VLAN
    if cid:
        fl |= XF_CID
    if use_bcast:
        fl |= XF_BCAST
    if relayed:
        fl |= XF_RELAYED
    w[XD_FLAGS] = fl
    w[XD_MAC_HI] = _u16(frame, dhcp_off + 28)
    w[XD_MAC_LO] = int.from_bytes(frame[dhcp_off + 30: dhcp_off + 34], "big")
    w[XD_VLAN] = (s_tag << 16) | c_tag
    w[XD_XID] = xid
    w[XD_MSG] = mtype
    if cid:
        buf = (cid + b"\x00" * CID_KEY_LEN)[:CID_KEY_LEN]
        w[XD_CID0: XD_CID0 + 8] = np.frombuffer(buf, dtype=">u4")
    return ExpressDesc(words=w, vlan_off=vlan_off, dhcp_off=dhcp_off,
                       msg_type=mtype, relayed=relayed, use_bcast=use_bcast)


def express_verdicts(
    tables: DHCPTables,
    desc: jax.Array,
    geom: DHCPGeom,
    now_s: jax.Array,
) -> ExpressResult:
    """The minimal express device program: probe cascade + verdict block.

    Identical resolution semantics to `dhcp_fastpath` (VLAN ->
    circuit-ID -> MAC, lease expiry against now_s, pool validity) over
    pre-extracted descriptor columns instead of raw frames. The reply
    bytes never touch the device: the host patches verdict/yiaddr into
    a preassembled wire template at retire.
    """
    global TRACE_COUNT
    TRACE_COUNT += 1  # trace-time only: AOT geometry hits never re-enter

    flags = desc[:, XD_FLAGS]
    valid = (flags & XF_VALID) != 0

    def count(m):
        return jnp.sum(m, dtype=jnp.uint32)

    # --- lookup cascade (dhcp_fastpath.c:653-681 order) ---
    vlan_res = lookup(tables.vlan, desc[:, XD_VLAN: XD_VLAN + 1], geom.vlan)
    vlan_hit = vlan_res.found & ((flags & XF_VLAN) != 0) & valid
    cid_res = lookup(tables.cid, desc[:, XD_CID0: XD_CID0 + 8], geom.cid)
    cid_hit = cid_res.found & ((flags & XF_CID) != 0) & valid & ~vlan_hit
    mac_res = lookup(tables.sub, desc[:, XD_MAC_HI: XD_MAC_HI + 2], geom.sub)
    mac_hit = mac_res.found & valid & ~vlan_hit & ~cid_hit
    hit = vlan_hit | cid_hit | mac_hit
    assign = jnp.where(
        vlan_hit[:, None], vlan_res.vals,
        jnp.where(cid_hit[:, None], cid_res.vals, mac_res.vals))

    # --- lease expiry + pool validity (dhcp_fastpath.c:690-713) ---
    expired = hit & (now_s > assign[:, AV_LEASE_EXP])
    live = hit & ~expired
    P = tables.pools.shape[0]
    pool_id = assign[:, AV_POOL_ID]
    pool_row = tables.pools[jnp.minimum(pool_id, P - 1).astype(jnp.int32)]
    pool_valid = (pool_id < P) & (pool_row[:, PV_VALID] != 0)
    reply = live & pool_valid

    stats = jnp.zeros((NSTATS,), dtype=jnp.uint32)
    stats = stats.at[ST_TOTAL].add(count(valid))
    stats = stats.at[ST_VLAN].add(count(valid & ((flags & XF_VLAN) != 0)))
    stats = stats.at[ST_OPT82_PRESENT].add(count(cid_hit))
    stats = stats.at[ST_MISS].add(count(valid & ~hit))
    stats = stats.at[ST_EXPIRED].add(count(expired))
    stats = stats.at[ST_ERROR].add(count(live & ~pool_valid))
    stats = stats.at[ST_HIT].add(count(reply))
    bcast = (flags & XF_BCAST) != 0
    stats = stats.at[ST_BCAST].add(count(reply & bcast))
    stats = stats.at[ST_UCAST].add(count(reply & ~bcast))

    # verdict block written over the donated descriptor's lead columns:
    # XLA aliases the output onto the input staging buffer
    block = (desc
             .at[:, VB_VERDICT].set(reply.astype(jnp.uint32))
             .at[:, VB_YIADDR].set(jnp.where(reply, assign[:, AV_IP], 0))
             .at[:, VB_POOL].set(jnp.where(reply, pool_id, 0))
             .at[:, VB_LEASE_T].set(
                 jnp.where(reply, pool_row[:, PV_LEASE_T], 0)))
    return ExpressResult(block=block, stats=stats)
