"""Hash mixing shared bit-for-bit between host (numpy) and device (jax.numpy).

The reference hashes keys with FNV-1a (pkg/ebpf/loader.go:546-553,
pkg/nexus/client.go:694) and relies on the kernel's htab hashing for eBPF
maps. Here the host is the single writer of device tables, so the host-side
(numpy) and device-side (jnp) hash of a key MUST agree exactly; both call
these functions, which only use uint32 ops with identical wrapping semantics
under numpy>=2 weak promotion and jax.

The mixer is the public-domain "lowbias32" integer finalizer; two different
seeds give the two independent hash functions cuckoo hashing needs.
"""

from __future__ import annotations

import numpy as np

# Two independent seeds for the cuckoo table's two hash functions.
# np.uint32-wrapped: jax refuses python ints above int32 max next to uint32
# arrays, and numpy scalars would raise on overflow; uint32 scalars wrap
# identically on both sides.
SEED1 = np.uint32(0x9E3779B9)
SEED2 = np.uint32(0x85EBCA6B)

_M1 = np.uint32(0x7FEB352D)
_M2 = np.uint32(0x846CA68B)


def mix32(h):
    """lowbias32 avalanche mixer. Works on numpy or jnp uint32 arrays."""
    h = h ^ (h >> 16)
    h = h * _M1
    h = h ^ (h >> 15)
    h = h * _M2
    h = h ^ (h >> 16)
    return h


def hash_words(words, seed):
    """Hash a sequence of uint32 word arrays into one uint32 array.

    `words` is a list of arrays (all the same shape); the hash is order
    dependent. Equivalent role to FNV-1a over the key bytes in the
    reference, but word-wide for TPU vector units.
    """
    h = words[0] ^ seed
    h = mix32(h)
    for w in words[1:]:
        h = mix32(h ^ w)
    return h
