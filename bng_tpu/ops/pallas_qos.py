"""Pallas TPU kernel: segmented running sums via tiled equality matmuls.

The QoS token bucket needs, per lane i, the bytes attempted by earlier
same-bucket lanes (sequential TBF admission semantics, qos_ratelimit.c:
70-104 applied per packet). ops.qos recovers this with a stable
argsort + segment cumsum — O(B log B) with two sorts per batch, and XLA
sorts are the most serial op in the pipeline.

This kernel computes the same quantity on the MXU instead:

    prefix_incl[i] = sum_j [slot_j == slot_i][j <= i] * vec[j]
    total[i]       = sum_j [slot_j == slot_i]         * vec[j]

tiled as [T, T] equality blocks contracted against vec tiles — one
(T x T) @ (T, 1) matmul per grid cell. The full [B, B] equality matrix
is never materialized in HBM (at B=8192 it would be 256MB f32): each
tile lives in VMEM only. O(B^2/T) MXU work replaces the sort's serial
latency, and lane order IS arrival order — no sort, no unsort.

Grid iteration order is (i outer, j inner); the output tile for row
block i accumulates across the j sweep (revisited-output pattern),
initialized at j == 0.

f32 accumulation is exact for per-bucket byte sums < 2^24 — same
integer-exactness envelope ops.qos documents for its u32 path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU memory spaces; unavailable on CPU-only jaxlib (interpret mode)
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except (ImportError, NotImplementedError):  # pragma: no cover - env specific
    pltpu = None
    _VMEM = None

LANE_TILE = 256  # rows per grid cell; [256, 256] eq tiles feed the MXU
SUBLANES = 8  # Mosaic tiling: rank>=2 blocks need (8k, 128m) trailing dims


def _block(shape, index_map):
    if _VMEM is not None:
        return pl.BlockSpec(shape, index_map, memory_space=_VMEM)
    return pl.BlockSpec(shape, index_map)


def _seg_kernel(slot_i_ref, slot_j_ref, vec_ref, pref_ref, tot_ref,
                *, want_prefix: bool, want_total: bool):
    # refs are (1, SUBLANES, T): each tile's lane vector replicated across
    # 8 sublanes so the block's trailing dims are Mosaic-legal (8, 256) —
    # a (1, T) block is rejected ("block shape ... divisible by 8 and 128",
    # the round-2 lowering failure). Row 0 carries the data.
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        pref_ref[:] = jnp.zeros_like(pref_ref)
        tot_ref[:] = jnp.zeros_like(tot_ref)

    T = pref_ref.shape[2]
    slots_i = slot_i_ref[0, 0, :]
    slots_j = slot_j_ref[0, 0, :]
    vec_j = vec_ref[0, 0, :]
    eq = (slots_i[:, None] == slots_j[None, :]).astype(jnp.float32)
    contrib = jnp.dot(eq, vec_j[:, None],
                      preferred_element_type=jnp.float32)[:, 0]
    if want_total:
        tot_ref[0, 0, :] = tot_ref[0, 0, :] + contrib

    if want_prefix:
        # prefix: blocks left of the diagonal contribute fully; the
        # diagonal block takes its lower triangle (arrival order within
        # the block)
        @pl.when(j < i)
        def _():
            pref_ref[0, 0, :] = pref_ref[0, 0, :] + contrib

        @pl.when(j == i)
        def _():
            row = jax.lax.broadcasted_iota(jnp.int32, (T, T), 0)
            col = jax.lax.broadcasted_iota(jnp.int32, (T, T), 1)
            tri = jnp.where(col <= row, eq, 0.0)
            pref = jnp.dot(tri, vec_j[:, None],
                           preferred_element_type=jnp.float32)[:, 0]
            pref_ref[0, 0, :] = pref_ref[0, 0, :] + pref


@functools.partial(jax.jit, static_argnames=("interpret", "compute"))
def seg_prefix_total(slot: jax.Array, vec: jax.Array, interpret: bool = False,
                     compute: str = "both"):
    """Per-lane same-slot inclusive prefix sum and full segment total.

    slot: [B] int32 segment ids (make them unique-negative for lanes that
    must not group). vec: [B] values (cast to f32; per-bucket sums are
    exact below 2^24). compute: "prefix" | "total" | "both" — skip the
    unneeded half of the tile work.
    Returns (prefix_incl [B] f32, total [B] f32); the uncomputed output
    is zeros.
    """
    B = slot.shape[0]
    T = LANE_TILE
    nt = -(-B // T)
    Bp = nt * T
    slot = slot.astype(jnp.int32)
    vec = vec.astype(jnp.float32)
    if Bp != B:
        # pad lanes get unique negative ids that match nothing real
        pad_ids = -(jnp.arange(Bp - B, dtype=jnp.int32) + (1 << 30))
        slot = jnp.concatenate([slot, pad_ids])
        vec = jnp.concatenate([vec, jnp.zeros((Bp - B,), dtype=jnp.float32)])

    # lane vectors replicated across 8 sublanes for Mosaic-legal blocks
    slot3d = jnp.broadcast_to(slot.reshape(nt, 1, T), (nt, SUBLANES, T))
    vec3d = jnp.broadcast_to(vec.reshape(nt, 1, T), (nt, SUBLANES, T))

    kernel = functools.partial(_seg_kernel,
                               want_prefix=compute in ("prefix", "both"),
                               want_total=compute in ("total", "both"))
    pref, tot = pl.pallas_call(
        kernel,
        grid=(nt, nt),
        in_specs=[
            _block((1, SUBLANES, T), lambda i, j: (i, 0, 0)),
            _block((1, SUBLANES, T), lambda i, j: (j, 0, 0)),
            _block((1, SUBLANES, T), lambda i, j: (j, 0, 0)),
        ],
        out_specs=[
            _block((1, SUBLANES, T), lambda i, j: (i, 0, 0)),
            _block((1, SUBLANES, T), lambda i, j: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nt, SUBLANES, T), jnp.float32),
            jax.ShapeDtypeStruct((nt, SUBLANES, T), jnp.float32),
        ],
        interpret=interpret,
    )(slot3d, slot3d, vec3d)
    return pref[:, 0, :].reshape(Bp)[:B], tot[:, 0, :].reshape(Bp)[:B]
