"""HBM-resident cuckoo hash tables — the TPU replacement for eBPF maps.

Design rationale (vs. the reference's kernel hash maps, bpf/maps.h:99-234):

- eBPF maps are pointer-chasing hash tables updated from both kernel and
  userspace. TPUs have no pointers and no atomics visible to XLA, but they
  have enormous gather bandwidth. So tables are structure-of-arrays uint32
  buffers in HBM, and lookup is **bucketized cuckoo hashing**: exactly two
  vectorized gathers of 4-way buckets per probe batch — branch-free, fixed
  cost, ideal for the VPU. (The reference already bounds probe loops to 64
  for the BPF verifier, bpf/nat44.c:423 — we go further: bound of 2.)
- The **host is the single writer** (insert/delete/relocate run on a numpy
  mirror; the device only gathers). This mirrors the reference's design
  where the Go slow path populates the fast-path cache
  (pkg/dhcp/server.go:1057-1097) and means no device-side synchronization
  is ever needed. Dirty slots are applied to the device copy as a bounded
  scatter inside the jitted step (see `TableUpdate` / `apply_update`).
- Cuckoo relocations on insert happen host-side; an insert that fails after
  MAX_KICKS goes to a small linear **stash** which the device compares
  against with one broadcast — the overflow path the reference gets from
  htab chaining.

Capacity sizing: ways=4 buckets sustain >90% load factor, so a 1M-entry
subscriber table (bpf/maps.h:10 MAX_SUBSCRIBERS) fits in 2^18 buckets x 4.
"""

from __future__ import annotations

import contextlib
import os
from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from bng_tpu.ops.hashing import SEED1, SEED2, hash_words, mix32

WAYS = 4  # slots per bucket; one bucket = one contiguous gather
MAX_KICKS = 128  # bounded cuckoo eviction walk (host side)

# Probe implementation (the qos_kernel[sort|pallas] mold):
#   "xla"    — the composed wide-gather cascade below (every backend)
#   "pallas" — the fused probe kernel (ops.pallas_table); on CPU it runs
#              in interpret mode (tests), on TPU compiled via Mosaic
#   "auto"   — self-timed: bench.py races both post-compile and pins the
#              winner (set_auto_choice); until resolved, pallas on TPU
#              and xla elsewhere
# Default from BNG_TABLE_IMPL; "xla" until the pallas path has been
# timed on hardware (flip to "auto" once it wins — PERF_NOTES §13).
TABLE_IMPL = os.environ.get("BNG_TABLE_IMPL", "xla")

TABLE_IMPLS = ("xla", "pallas")

# resolved "auto" winner (bench.py --autotune / _pick_table_impl)
_AUTO_CHOICE: str | None = None

# trace-time override stack: jitted-program factories (engine, sharded)
# pin the impl PER COMPILED PROGRAM so one process can hold programs
# traced under different impls (the A/B race) without global races
_FORCED: list[str] = []


def set_auto_choice(impl: str | None) -> None:
    """Pin the winner of an auto self-timing race (None clears)."""
    global _AUTO_CHOICE
    if impl is not None and impl not in TABLE_IMPLS:
        raise ValueError(f"unknown table impl {impl!r}")
    _AUTO_CHOICE = impl


@contextlib.contextmanager
def forced_impl(impl: str):
    """Trace-time impl pin — wrap the traced body, not the jit call."""
    if impl not in TABLE_IMPLS:
        raise ValueError(f"unknown table impl {impl!r}")
    _FORCED.append(impl)
    try:
        yield
    finally:
        _FORCED.pop()


def resolved_table_impl() -> str:
    """The impl device_lookup dispatches to at trace time."""
    if _FORCED:
        return _FORCED[-1]
    impl = TABLE_IMPL
    if impl == "auto":
        if _AUTO_CHOICE is not None:
            return _AUTO_CHOICE
        # Mosaic lowering is TPU-only; un-raced auto favors the kernel
        # there and the known-good cascade everywhere else
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in TABLE_IMPLS:
        raise ValueError(
            f"BNG_TABLE_IMPL={impl!r}: expected one of "
            f"{TABLE_IMPLS + ('auto',)}")
    return impl


def current_impl_label() -> str:
    """Best-effort impl label for fingerprints/bench lines — never
    raises and never triggers backend init beyond what is already up
    (ledger.environment_fingerprint calls this via sys.modules)."""
    try:
        return resolved_table_impl()
    except Exception:  # noqa: BLE001 — a bad env var must not sink a line
        return TABLE_IMPL


def way_stride(key_words: int) -> int:
    """Words per way in the packed probe rows: key words + used flag,
    rounded up to a multiple of 8 — narrow (<8-word) gathers serialize at
    ~7ns/element on v5e while >=8-word row gathers run at full speed
    (PERF_NOTES §2; same finding drove ops/qtable.py)."""
    return ((key_words + 1 + 7) // 8) * 8


class TableState(NamedTuple):
    """Device-side table arrays (a pytree; all uint32).

    The probe data (keys + used) is bucket-packed: one [WAYS*KW]-word row
    per bucket, KW = way_stride(K), each way carrying its K key words then
    the used flag at word K. A probe is two wide row gathers — the
    narrow per-way key/used gathers of rounds 1-2 never appear. The host
    is the single writer of krows/stash_rows, so updates scatter whole
    bucket rows with no clobber hazard; vals keeps per-slot granularity
    because device kernels write it (NAT session accounting).

    krows:      [NB, WAYS*KW]  packed bucket probe rows
    stash_rows: [stash, KW]    packed stash probe rows
    vals:       [S, V]         value words; S = NB*WAYS + stash
    """

    krows: jax.Array
    stash_rows: jax.Array
    vals: jax.Array


class TableUpdate(NamedTuple):
    """A bounded batch of dirty rows/slots to scatter into a TableState.

    Index rows >= the target's length are dropped by the scatter (padding).
    A dirty slot's whole bucket row rides along (the host mirror knows all
    four ways), value updates stay slot-granular.
    """

    bidx: jax.Array  # [U] int32 bucket indices
    brows: jax.Array  # [U, WAYS*KW] uint32 replacement bucket rows
    sidx: jax.Array  # [U] int32 stash-local indices
    srows: jax.Array  # [U, KW] uint32 replacement stash rows
    idx: jax.Array  # [U] int32 global slots (val updates)
    vals: jax.Array  # [U, V] uint32


class LookupResult(NamedTuple):
    found: jax.Array  # [B] bool
    slot: jax.Array  # [B] int32 (valid only where found; owner-local if sharded)
    vals: jax.Array  # [B, V] uint32 (zeros where not found)
    # sharded lookups only: lane exceeded the per-destination exchange
    # capacity and was NOT probed (found=False there too) — the caller's
    # slow path must treat it as a miss-with-retry, not a definitive miss
    punted: jax.Array | None = None


class TableGeom(NamedTuple):
    """Static geometry of one table, plus optional ICI sharding.

    axis=None: the table is chip-local (or replicated) — plain 2-gather
    lookup. axis="x": the table is hash-sharded across `n_shards` devices
    on mesh axis "x"; lookups ride an all-to-all key/result exchange
    (see sharded_lookup). This is the TPU re-expression of the reference's
    hash-partitioned tables across nodes (SURVEY.md §2.3: Nexus hashring /
    rendezvous placement).
    """

    nbuckets: int
    stash: int
    axis: str | None = None
    n_shards: int = 1
    # sharded exchange sizing: per-destination capacity = ceil(b/N) *
    # capacity_factor (rounded up to 8 lanes). At factor f the exchange
    # moves f/N of the worst-case traffic; lanes beyond a destination's
    # capacity punt to the slow path (see sharded_lookup). factor >= N
    # reproduces the exact worst-case (never-punt) exchange.
    capacity_factor: float = 2.0


# shard-owner hash seed — distinct from the cuckoo bucket seeds so shard
# routing and in-table placement are independent
SEED_SHARD = np.uint32(0xC2B2AE35)


def shard_owner(query_words, n_shards: int):
    """Owner shard of each key: mix(key) % n_shards. Host (numpy) and
    device (jnp) both call this — routing must agree bit-for-bit."""
    h = hash_words(query_words, SEED_SHARD)
    return h % np.uint32(n_shards)


def apply_update(state: TableState, upd: TableUpdate) -> TableState:
    """Scatter dirty rows into the device table (inside jit, donated) —
    three wide row scatters (bucket rows, stash rows, value rows)."""
    return TableState(
        krows=state.krows.at[upd.bidx].set(upd.brows, mode="drop"),
        stash_rows=state.stash_rows.at[upd.sidx].set(upd.srows, mode="drop"),
        vals=state.vals.at[upd.idx].set(upd.vals, mode="drop"),
    )


def exchange_capacity(b: int, g: TableGeom) -> int:
    """Per-destination lane capacity of the sharded exchange for a local
    batch of b lanes: factor x the balanced share, 8-aligned, capped at b.
    Single source of truth — tests assert punt boundaries against this."""
    return min(b, max(8, int(-(-b // g.n_shards) * g.capacity_factor + 7) & ~7))


def lookup(state: TableState, query: jax.Array, g: TableGeom) -> LookupResult:
    """Geometry-dispatched lookup: local 2-gather probe, or sharded
    all-to-all exchange when g.axis names a mesh axis."""
    if g.axis is None or g.n_shards == 1:
        return device_lookup(state, query, g.nbuckets, g.stash)
    return sharded_lookup(state, query, g)


def sharded_lookup(state: TableState, query: jax.Array, g: TableGeom) -> LookupResult:
    """Cross-chip lookup via MoE-style dispatch over ICI.

    Must run inside shard_map over mesh axis g.axis. Each chip holds one
    hash-shard of the table (an independent cuckoo table) and a local
    [b, K] query batch. Only keys and result rows ride the interconnect —
    packets never move:

      1. owner = shard_owner(key) for each lane
      2. keys are packed into a [N, C, K] per-destination buffer with
         C = ceil(b/N) * capacity_factor (round-1 ask #7: the worst-case
         C = b exchange moved N*b rows per collective, N x the useful
         traffic on an N-chip mesh). Lanes past a destination's capacity
         PUNT: returned found=False + punted=True so the slow path
         retries them (a bounded-skew batch never punts; a pathological
         all-one-shard batch degrades to slow path instead of reserving
         worst-case ICI bandwidth on every batch)
      3. lax.all_to_all exchanges request buffers (one ICI shuffle)
      4. each chip probes its local shard for all received keys
      5. a second all_to_all returns results; lane i reads its
         (owner, position) cell

    The reference does this routing with HTTP forwards to the hashring
    owner (pkg/nexus/client.go:487-577, pkg/pool/peer.go:230-368); here
    it is two ICI collectives per batch.
    """
    b, K = query.shape
    N = g.n_shards
    C = exchange_capacity(b, g)
    words = [query[:, k] for k in range(K)]
    owner = shard_owner(words, N).astype(jnp.int32)  # [b]

    onehot = (owner[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]).astype(jnp.int32)
    pos = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - 1, owner[:, None], axis=1)[:, 0]
    fits = pos < C
    flat = jnp.where(fits, owner * C + pos, N * C)  # overflow -> dropped

    req = jnp.zeros((N * C, K), dtype=jnp.uint32).at[flat].set(query, mode="drop")
    req = req.reshape(N, C, K)
    req_recv = jax.lax.all_to_all(req, g.axis, split_axis=0, concat_axis=0, tiled=True)

    local = device_lookup(state, req_recv.reshape(N * C, K), g.nbuckets, g.stash)
    # pack found/slot/vals into ONE response buffer -> one return collective
    # (three separate all_to_alls would triple the response latency)
    V = local.vals.shape[1]
    packed = jnp.concatenate(
        [local.vals,
         local.found.astype(jnp.uint32)[:, None],
         local.slot.astype(jnp.uint32)[:, None]],
        axis=1,
    ).reshape(N, C, V + 2)
    resp = jax.lax.all_to_all(packed, g.axis, split_axis=0, concat_axis=0, tiled=True)

    cell = resp[owner, jnp.minimum(pos, C - 1)]  # [b, V+2]
    return LookupResult(
        found=(cell[:, V] != 0) & fits,
        slot=cell[:, V + 1].astype(jnp.int32),
        vals=jnp.where(fits[:, None], cell[:, :V], 0),
        punted=~fits,
    )


def device_lookup(state: TableState, query: jax.Array, nbuckets: int, stash: int) -> LookupResult:
    """Impl-dispatched batched probe (every hot-path kernel funnels
    here: DHCP 3-tier chain, NAT44 forward/reverse, antispoof, garden,
    PPPoE, and the sharded step's local probe).

    Resolution happens at TRACE time (resolved_table_impl): the fused
    Pallas kernel when selected, else the XLA wide-gather cascade.
    Both are bit-identical (tests/test_pallas_table.py pins it).

    query: [B, K] uint32 key words.
    """
    if resolved_table_impl() == "pallas":
        from bng_tpu.ops.pallas_table import pallas_lookup

        return pallas_lookup(state, query, nbuckets, stash)
    return xla_lookup(state, query, nbuckets, stash)


def xla_lookup(state: TableState, query: jax.Array, nbuckets: int, stash: int) -> LookupResult:
    """Branch-free batched lookup: 2 wide bucket-row gathers + stash
    broadcast + 1 value-row gather — no narrow gathers anywhere.

    query: [B, K] uint32 key words.
    """
    B, K = query.shape
    KW = state.stash_rows.shape[1]
    words = [query[:, k] for k in range(K)]
    mask = np.uint32(nbuckets - 1)
    b1 = (hash_words(words, SEED1) & mask).astype(jnp.int32)
    b2 = (hash_words(words, SEED2) & mask).astype(jnp.int32)

    r1 = state.krows[b1]  # [B, WAYS*KW] — the fast gather shape
    r2 = state.krows[b2]
    cand = jnp.concatenate(
        [r1.reshape(B, WAYS, KW), r2.reshape(B, WAYS, KW)], axis=1
    )  # [B, 2W, KW]
    cand_match = jnp.all(cand[:, :, :K] == query[:, None, :], axis=-1) & (
        cand[:, :, K] != 0
    )  # [B, 2W]
    ways = jnp.arange(WAYS, dtype=jnp.int32)[None, :]
    cand_slots = jnp.concatenate(
        [b1[:, None] * WAYS + ways, b2[:, None] * WAYS + ways], axis=1
    )  # [B, 2W]

    if stash > 0:
        base = nbuckets * WAYS
        sm = jnp.all(state.stash_rows[None, :, :K] == query[:, None, :], axis=-1) & (
            state.stash_rows[None, :, K] != 0
        )  # [B, stash]
        s_slots = jnp.broadcast_to(
            base + jnp.arange(stash, dtype=jnp.int32)[None, :], sm.shape
        )
        cand_slots = jnp.concatenate([cand_slots, s_slots], axis=1)
        cand_match = jnp.concatenate([cand_match, sm], axis=1)

    found = jnp.any(cand_match, axis=1)
    first = jnp.argmax(cand_match, axis=1)
    # slot select as a one-hot masked sum (VPU) — take_along_axis lowers
    # to an in-context gather (65µs at B=8192, PERF_NOTES §2)
    onehot = jnp.arange(cand_slots.shape[1], dtype=jnp.int32)[None, :] == first[:, None]
    slot = jnp.sum(jnp.where(onehot, cand_slots, 0), axis=1)
    vals = jnp.where(found[:, None], state.vals[slot], 0)
    return LookupResult(found=found, slot=slot, vals=vals)


class HostTable:
    """Host-authoritative mirror of one device table (numpy, single writer).

    insert/delete mutate the numpy arrays and record dirty slots; drain the
    dirty set with `make_update()` to get a fixed-size TableUpdate for the
    jitted step. This is the pkg/ebpf/loader.go map-CRUD role
    (loader.go:352-442) re-hosted: map writes become HBM scatters.
    """

    def __init__(self, nbuckets: int, key_words: int, val_words: int,
                 stash: int = 64, name: str = "",
                 compat_val_pad_from: tuple[int, ...] = ()):
        if nbuckets & (nbuckets - 1):
            raise ValueError("nbuckets must be a power of two")
        self.nbuckets = nbuckets
        self.K = key_words
        self.KW = way_stride(key_words)
        self.V = val_words
        self.stash = stash
        self.name = name
        # historical val_words this table's live layout is a PURE
        # zero-pad of (checkpoint restore migration — see restore_arrays)
        self.compat_val_pad_from = tuple(compat_val_pad_from)
        S = nbuckets * WAYS + stash
        self.S = S
        self.keys = np.zeros((S, key_words), dtype=np.uint32)
        self.vals = np.zeros((S, val_words), dtype=np.uint32)
        self.used = np.zeros((S,), dtype=np.uint32)
        self.count = 0
        self._dirty: set[int] = set()
        self._dirty_all = False  # set by large bulk_insert: full resync needed
        self._rng = np.random.default_rng(0xB46)

    # -- hashing (must match device_lookup exactly) --
    def _buckets(self, key: np.ndarray) -> tuple[int, int]:
        # 1-element arrays, not scalars: numpy scalar uint32 ops raise on
        # overflow while array ops wrap (and must match device semantics).
        words = [key[k : k + 1] for k in range(self.K)]
        m = np.uint32(self.nbuckets - 1)
        return int((hash_words(words, SEED1) & m)[0]), int((hash_words(words, SEED2) & m)[0])

    def _find_slot(self, key: np.ndarray) -> int | None:
        b1, b2 = self._buckets(key)
        for b in (b1, b2):
            for w in range(WAYS):
                s = b * WAYS + w
                if self.used[s] and np.array_equal(self.keys[s], key):
                    return s
        base = self.nbuckets * WAYS
        for s in range(base, base + self.stash):
            if self.used[s] and np.array_equal(self.keys[s], key):
                return s
        return None

    def _place(self, s: int, key: np.ndarray, val: np.ndarray) -> None:
        self.keys[s] = key
        self.vals[s] = val
        self.used[s] = 1
        self._dirty.add(s)

    def insert(self, key, val) -> int:
        """Insert or update. Returns the slot index."""
        key = np.asarray(key, dtype=np.uint32).reshape(self.K)
        val = np.asarray(val, dtype=np.uint32).reshape(self.V)
        s = self._find_slot(key)
        if s is not None:  # update in place
            self.vals[s] = val
            self._dirty.add(s)
            return s

        cur_key, cur_val = key, val
        moves: list[tuple[int, np.ndarray, np.ndarray]] = []  # for rollback
        for _kick in range(MAX_KICKS):
            b1, b2 = self._buckets(cur_key)
            for b in (b1, b2):
                for w in range(WAYS):
                    slot = b * WAYS + w
                    if not self.used[slot]:
                        self._place(slot, cur_key, cur_val)
                        self.count += 1
                        return self._find_slot(key)  # original key's slot
                # both buckets full -> evict a random way from a random bucket
            b = b1 if self._rng.integers(2) == 0 else b2
            w = int(self._rng.integers(WAYS))
            slot = b * WAYS + w
            evict_key = self.keys[slot].copy()
            evict_val = self.vals[slot].copy()
            self._place(slot, cur_key, cur_val)
            moves.append((slot, evict_key, evict_val))
            cur_key, cur_val = evict_key, evict_val

        # eviction walk exhausted -> stash the displaced key
        base = self.nbuckets * WAYS
        for s in range(base, base + self.stash):
            if not self.used[s]:
                self._place(s, cur_key, cur_val)
                self.count += 1
                return self._find_slot(key)

        # Table genuinely full: roll the eviction walk back (otherwise the
        # last displaced key — possibly a long-standing entry — is lost).
        for slot, old_key, old_val in reversed(moves):
            self._place(slot, old_key, old_val)
        raise RuntimeError(f"table {self.name!r} full (count={self.count})")

    def bulk_insert(self, keys: np.ndarray, vals: np.ndarray) -> None:
        """Vectorized batch insert for initial table builds (1M-entry scale).

        The per-key `insert` path is a Python loop — fine for slow-path
        churn (hundreds/sec), infeasible for building the reference-scale
        1M-subscriber table (bpf/maps.h:10). This places a whole batch with
        8 vectorized passes (2 buckets x 4 ways, first-wins conflict
        resolution via np.unique) and falls back to the cuckoo-kick path
        only for the residue whose candidate slots were all taken (<1% at
        the sizing rule of ~50% load).

        Keys must be unique within the batch and not already present
        (bulk = initial build / bulk restore, not upsert). After a large
        bulk insert the dirty set is abandoned: call device_state() for a
        full upload, as startup does anyway.
        """
        keys = np.ascontiguousarray(np.asarray(keys, dtype=np.uint32).reshape(-1, self.K))
        vals = np.ascontiguousarray(np.asarray(vals, dtype=np.uint32).reshape(-1, self.V))
        n = len(keys)
        if n == 0:
            return
        words = [keys[:, k] for k in range(self.K)]
        m = np.uint32(self.nbuckets - 1)
        b1 = (hash_words(words, SEED1) & m).astype(np.int64)
        b2 = (hash_words(words, SEED2) & m).astype(np.int64)

        unplaced = np.ones((n,), dtype=bool)
        placed_slots: list[np.ndarray] = []
        for side in (b1, b2):
            for w in range(WAYS):
                idxs = np.nonzero(unplaced)[0]
                if len(idxs) == 0:
                    break
                slot = side[idxs] * WAYS + w
                free = self.used[slot] == 0
                idxs, slot = idxs[free], slot[free]
                if len(idxs) == 0:
                    continue
                # first-wins per slot within this pass
                uq_slot, first = np.unique(slot, return_index=True)
                take = idxs[first]
                self.keys[uq_slot] = keys[take]
                self.vals[uq_slot] = vals[take]
                self.used[uq_slot] = 1
                unplaced[take] = False
                placed_slots.append(uq_slot)
        self.count += sum(len(s) for s in placed_slots)

        residue = np.nonzero(unplaced)[0]
        for i in residue:  # cuckoo-kick / stash path for the stragglers
            self.insert(keys[i], vals[i])

        # dirty tracking: a large bulk build invalidates bounded-delta sync
        if n > self.stash:
            self._dirty.clear()
            self._dirty_all = True
        else:
            for s in placed_slots:
                self._dirty.update(int(x) for x in s)

    def delete(self, key) -> bool:
        key = np.asarray(key, dtype=np.uint32).reshape(self.K)
        s = self._find_slot(key)
        if s is None:
            return False
        self.used[s] = 0
        self.keys[s] = 0
        self.vals[s] = 0
        self.count -= 1
        self._dirty.add(s)
        return True

    def lookup(self, key) -> np.ndarray | None:
        key = np.asarray(key, dtype=np.uint32).reshape(self.K)
        s = self._find_slot(key)
        return self.vals[s].copy() if s is not None else None

    def update_val_words(self, key, word_idx: int, words) -> bool:
        """Patch specific value words of an existing entry (e.g. lease expiry)."""
        key = np.asarray(key, dtype=np.uint32).reshape(self.K)
        s = self._find_slot(key)
        if s is None:
            return False
        words = np.atleast_1d(np.asarray(words, dtype=np.uint32))
        self.vals[s, word_idx : word_idx + len(words)] = words
        self._dirty.add(s)
        return True

    # -- device synchronization --
    def _pack_bucket_rows(self, buckets: np.ndarray,
                          mask_dirty: bool = False) -> np.ndarray:
        """Packed [len(buckets), WAYS*KW] probe rows from the host mirror.

        mask_dirty=True (partial drains): ways whose slot is STILL dirty
        get used=0 in the row — a half-drained bucket must not expose a
        sibling whose value row has not shipped yet (it would read as a
        hit with stale/zero vals; a temporary miss just takes the slow
        path, which is the correct conservative behavior)."""
        nb = len(buckets)
        rows = np.zeros((nb, WAYS * self.KW), dtype=np.uint32)
        r3 = rows.reshape(nb, WAYS, self.KW)
        slots = buckets[:, None] * WAYS + np.arange(WAYS)[None, :]  # [nb, WAYS]
        r3[:, :, : self.K] = self.keys[slots]
        used = self.used[slots]
        if mask_dirty and self._dirty:
            still_dirty = np.isin(slots, np.fromiter(self._dirty, dtype=np.int64,
                                                     count=len(self._dirty)))
            used = np.where(still_dirty, 0, used)
        r3[:, :, self.K] = used
        return rows

    def _pack_stash_rows(self, sidx: np.ndarray) -> np.ndarray:
        """Packed [len(sidx), KW] stash probe rows (sidx is stash-local)."""
        rows = np.zeros((len(sidx), self.KW), dtype=np.uint32)
        g = self.nbuckets * WAYS + sidx
        rows[:, : self.K] = self.keys[g]
        rows[:, self.K] = self.used[g]
        return rows

    def device_state(self) -> TableState:
        """Full upload (startup / resync)."""
        self._dirty.clear()
        self._dirty_all = False
        return TableState(
            krows=jnp.asarray(self._pack_bucket_rows(np.arange(self.nbuckets))),
            stash_rows=jnp.asarray(self._pack_stash_rows(np.arange(self.stash))),
            vals=jnp.asarray(self.vals),
        )

    def dirty_count(self) -> int:
        return self.S if self._dirty_all else len(self._dirty)

    def mark_dirty(self, slots) -> int:
        """Queue slots for the next bounded update drain without touching
        their host rows — the delta-replay primitive (blue/green standby
        hydration diffs host arrays against a snapshot and re-ships only
        the changed slots). Returns the number of NEWLY queued slots
        (already-dirty slots don't add drain traffic and must not inflate
        the delta_rows report)."""
        before = len(self._dirty)
        self._dirty.update(int(s) for s in slots)
        return len(self._dirty) - before

    def make_update(self, max_slots: int) -> TableUpdate:
        """Drain up to max_slots dirty slots into a fixed-size TableUpdate.

        Remaining dirty slots stay queued for the next batch (bounded
        host->HBM traffic per step, like bounded map-update syscalls).
        A drained bucket slot carries its whole (current) bucket row with
        still-dirty siblings masked used=0 (their vals have not shipped —
        see _pack_bucket_rows); each sibling rewrites the row on its own
        drain."""
        if self._dirty_all:
            raise RuntimeError(
                f"table {self.name!r}: bulk_insert invalidated delta sync; "
                "call device_state() for a full upload first")
        take = sorted(self._dirty)[:max_slots]
        self._dirty.difference_update(take)
        base = self.nbuckets * WAYS
        b_take = sorted({s // WAYS for s in take if s < base})
        s_take = [s - base for s in take if s >= base]

        U = max_slots
        bidx = np.full((U,), self.nbuckets, dtype=np.int32)  # NB = dropped
        brows = np.zeros((U, WAYS * self.KW), dtype=np.uint32)
        sidx = np.full((U,), self.stash, dtype=np.int32)
        srows = np.zeros((U, self.KW), dtype=np.uint32)
        idx = np.full((U,), self.S, dtype=np.int32)
        vv = np.zeros((U, self.V), dtype=np.uint32)
        if b_take:
            bs = np.asarray(b_take, dtype=np.int32)
            bidx[: len(bs)] = bs
            brows[: len(bs)] = self._pack_bucket_rows(bs, mask_dirty=True)
        if s_take:
            ss = np.asarray(s_take, dtype=np.int32)
            sidx[: len(ss)] = ss
            srows[: len(ss)] = self._pack_stash_rows(ss)
        n = len(take)
        if n:
            ts = np.asarray(take, dtype=np.int32)
            idx[:n] = ts
            vv[:n] = self.vals[ts]
        return TableUpdate(
            bidx=jnp.asarray(bidx), brows=jnp.asarray(brows),
            sidx=jnp.asarray(sidx), srows=jnp.asarray(srows),
            idx=jnp.asarray(idx), vals=jnp.asarray(vv),
        )

    def empty_update(self, max_slots: int) -> TableUpdate:
        """An all-padding TableUpdate (applying it is a no-op scatter).

        Built WITHOUT touching dirty tracking — the latency scheduler's
        no-drain bulk steps pass this instead of make_update() so pending
        host deltas stay queued for the next drain-cadence step rather
        than being consumed by a step that won't ship them. The result is
        cached per size: update buffers are not donated by the jitted
        step, so one device-resident copy serves every no-drain step
        (zero host->HBM traffic, the entire point of the cadence)."""
        cache = getattr(self, "_empty_upd_cache", None)
        if cache is None:
            cache = self._empty_upd_cache = {}
        upd = cache.get(max_slots)
        if upd is None:
            U = max_slots
            upd = cache[max_slots] = TableUpdate(
                bidx=jnp.full((U,), self.nbuckets, dtype=jnp.int32),
                brows=jnp.zeros((U, WAYS * self.KW), dtype=jnp.uint32),
                sidx=jnp.full((U,), self.stash, dtype=jnp.int32),
                srows=jnp.zeros((U, self.KW), dtype=jnp.uint32),
                idx=jnp.full((U,), self.S, dtype=jnp.int32),
                vals=jnp.zeros((U, self.V), dtype=jnp.uint32),
            )
        return upd

    # -- checkpoint/warm-restart (runtime/checkpoint.py) ----------------
    def checkpoint_geom(self) -> dict:
        """Geometry signature a checkpoint must match to be restorable:
        slot indices/hashes are only meaningful at identical shape."""
        return {"nbuckets": self.nbuckets, "key_words": self.K,
                "val_words": self.V, "stash": self.stash}

    def checkpoint_arrays(self) -> dict[str, np.ndarray]:
        """The complete host-authoritative mirror state (slot-exact, so a
        restore needs no rehash and preserves cuckoo/stash placement)."""
        return {"keys": self.keys, "vals": self.vals, "used": self.used}

    def restore_arrays(self, arrays: dict[str, np.ndarray],
                       geom: dict) -> int:
        """Overwrite the mirror from checkpointed arrays. Raises
        ValueError on any geometry/shape mismatch (reject-on-mismatch —
        a silently reshaped table would corrupt every later probe).
        Abandons delta tracking like bulk_insert: the caller must follow
        with a full device upload (device_state / resync_tables).
        Returns the restored row count.

        One sanctioned mismatch: a checkpoint whose val_words appears in
        `compat_val_pad_from` (a construction-time declaration that the
        live width is a PURE zero-pad of that historical layout — the
        ISSUE 11 row widenings) restores with the value rows
        zero-padded, so warm restarts and HA failover survive the
        upgrade instead of cold-starting away all session state. Any
        other difference still rejects: only the declaring table knows
        its old words kept their meaning."""
        live = self.checkpoint_geom()
        pad_vals_from = None
        if geom != live:
            narrow = dict(geom)
            vw = narrow.pop("val_words", None)
            wide = dict(live)
            wide.pop("val_words")
            if (narrow == wide and vw in self.compat_val_pad_from):
                pad_vals_from = int(vw)
            else:
                raise ValueError(
                    f"table {self.name!r}: checkpoint geometry {geom} != "
                    f"live geometry {live}")
        for name, target in (("keys", self.keys), ("vals", self.vals),
                             ("used", self.used)):
            src = arrays[name]
            expect = target.shape
            if name == "vals" and pad_vals_from is not None:
                expect = (target.shape[0], pad_vals_from)
            if src.shape != expect or src.dtype != target.dtype:
                raise ValueError(
                    f"table {self.name!r}: checkpoint array {name!r} is "
                    f"{src.dtype}{src.shape}, expected "
                    f"{target.dtype}{expect}")
            if name == "vals" and pad_vals_from is not None:
                target[:] = 0
                target[:, :pad_vals_from] = src
            else:
                target[:] = src
        self.count = int(np.count_nonzero(self.used))
        self._dirty.clear()
        self._dirty_all = True
        return self.count

    def lookup_batch_host(self, queries: np.ndarray) -> np.ndarray:
        """Reference host-side batched lookup (for tests)."""
        out = np.zeros((len(queries), self.V), dtype=np.uint32)
        for i, q in enumerate(queries):
            v = self.lookup(q)
            if v is not None:
                out[i] = v
        return out
