"""Batched packet header parsing: Eth -> [802.1ad/802.1Q] -> IPv4 -> L4.

Behavioral parity with parse_packet_headers (bpf/dhcp_fastpath.c:352-428)
and the L2/L3 parses in nat44.c/qos_ratelimit.c/antispoof.c, vectorized over
a [B, L] uint8 batch. Instead of the reference's early-return control flow,
every lane is parsed unconditionally and validity is tracked in boolean
flags — the XDP verdict "return XDP_PASS" becomes a lane mask.

All IPs/ports are returned as host-order uint32 values (10.0.0.1 ->
0x0A000001) for arithmetic; byte order only matters at the
compose/rewrite boundary in bytes.py.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops.bytes import be16_at, be32_at, u8_at

ETH_P_IP = 0x0800
ETH_P_IPV6 = 0x86DD
ETH_P_8021Q = 0x8100
ETH_P_8021AD = 0x88A8

PROTO_ICMP = 1
PROTO_TCP = 6
PROTO_UDP = 17


class Parsed(NamedTuple):
    """Structure-of-arrays parse result; all fields [B]."""

    # L2
    dst_mac_hi: jax.Array  # uint32, bytes 0-1
    dst_mac_lo: jax.Array  # uint32, bytes 2-5
    src_mac_hi: jax.Array
    src_mac_lo: jax.Array
    ethertype: jax.Array  # inner ethertype after VLAN tags
    is_vlan: jax.Array  # bool: at least one tag
    is_qinq: jax.Array  # bool: two tags
    s_tag: jax.Array  # outer VID (0 if untagged)
    c_tag: jax.Array  # inner VID (0 unless QinQ)
    vlan_offset: jax.Array  # int32: 0 / 4 / 8
    # L3 (IPv4)
    is_ipv4: jax.Array  # bool: ethertype==0x0800 and header in bounds
    is_ipv6: jax.Array  # bool (antispoof needs the flag; no v6 L4 parse)
    l3_off: jax.Array  # int32: 14 + vlan_offset
    ihl_bytes: jax.Array  # int32
    total_len: jax.Array  # uint32 (IP total length field)
    ttl: jax.Array
    proto: jax.Array
    src_ip: jax.Array  # uint32 host order
    dst_ip: jax.Array
    # L4
    l4_off: jax.Array  # int32
    is_udp: jax.Array
    is_tcp: jax.Array
    is_icmp: jax.Array
    src_port: jax.Array  # uint32 (ICMP: echo id for egress tracking)
    dst_port: jax.Array
    tcp_flags: jax.Array  # uint32 (byte 13 of TCP header; 0 otherwise)


def mac_words_at(pkt, off):
    """6 bytes at per-lane offset -> (hi16, lo32) uint32 words.

    Matches utils.net.mac_to_u64's split: u64 key = hi<<32 | lo.
    """
    hi = be16_at(pkt, off)
    lo = be32_at(pkt, off + 2)
    return hi, lo


def eth_vlan(pkt: jax.Array) -> tuple[jax.Array, jax.Array]:
    """VLAN peel only: per-lane (vlan_offset, inner ethertype).

    The PPPoE decap pre-stage needs just these two fields BEFORE the full
    parse (which must see the decapped bytes) — 4 halfword reads instead
    of the whole Parsed gather set."""
    B = pkt.shape[0]
    zero32 = jnp.zeros((B,), dtype=jnp.int32)
    et0 = be16_at(pkt, zero32 + 12)
    outer_tagged = (et0 == ETH_P_8021Q) | (et0 == ETH_P_8021AD)
    et1 = be16_at(pkt, zero32 + 16)
    inner_tagged = outer_tagged & (et1 == ETH_P_8021Q)
    et2 = be16_at(pkt, zero32 + 20)
    vlan_offset = jnp.where(inner_tagged, 8,
                            jnp.where(outer_tagged, 4, 0)).astype(jnp.int32)
    ethertype = jnp.where(inner_tagged, et2, jnp.where(outer_tagged, et1, et0))
    return vlan_offset, ethertype


def parse_batch(pkt: jax.Array, length: jax.Array) -> Parsed:
    """Parse [B, L] uint8 packets with [B] uint32 actual lengths."""
    B = pkt.shape[0]
    zero32 = jnp.zeros((B,), dtype=jnp.int32)
    length = length.astype(jnp.uint32)

    dst_mac_hi, dst_mac_lo = mac_words_at(pkt, zero32)
    src_mac_hi, src_mac_lo = mac_words_at(pkt, zero32 + 6)

    # --- VLAN peeling (parity: dhcp_fastpath.c:373-398) ---
    et0 = be16_at(pkt, zero32 + 12)
    outer_tagged = (et0 == ETH_P_8021Q) | (et0 == ETH_P_8021AD)
    outer_vid = be16_at(pkt, zero32 + 14) & 0x0FFF
    et1 = be16_at(pkt, zero32 + 16)  # ethertype after one tag
    # QinQ: inner tag is 802.1Q only (reference checks ETH_P_8021Q)
    inner_tagged = outer_tagged & (et1 == ETH_P_8021Q)
    inner_vid = be16_at(pkt, zero32 + 18) & 0x0FFF
    et2 = be16_at(pkt, zero32 + 20)

    is_qinq = inner_tagged
    is_vlan = outer_tagged
    vlan_offset = jnp.where(is_qinq, 8, jnp.where(is_vlan, 4, 0)).astype(jnp.int32)
    ethertype = jnp.where(is_qinq, et2, jnp.where(is_vlan, et1, et0))
    s_tag = jnp.where(is_vlan, outer_vid, 0)
    c_tag = jnp.where(is_qinq, inner_vid, 0)

    l3_off = 14 + vlan_offset

    # --- IPv4 ---
    ver_ihl = u8_at(pkt, l3_off)
    ihl = (ver_ihl & 0x0F).astype(jnp.int32) * 4
    version = ver_ihl >> 4
    total_len = be16_at(pkt, l3_off + 2)
    ttl = u8_at(pkt, l3_off + 8)
    proto = u8_at(pkt, l3_off + 9)
    src_ip = be32_at(pkt, l3_off + 12)
    dst_ip = be32_at(pkt, l3_off + 16)

    ip_in_bounds = (l3_off.astype(jnp.uint32) + 20) <= length
    is_ipv4 = (ethertype == ETH_P_IP) & (version == 4) & (ihl >= 20) & ip_in_bounds
    is_ipv6 = (ethertype == ETH_P_IPV6) & ((l3_off.astype(jnp.uint32) + 40) <= length)

    # --- L4 ---
    l4_off = l3_off + ihl
    l4_in_bounds = (l4_off.astype(jnp.uint32) + 8) <= length
    is_udp = is_ipv4 & (proto == PROTO_UDP) & l4_in_bounds
    is_tcp = is_ipv4 & (proto == PROTO_TCP) & ((l4_off.astype(jnp.uint32) + 20) <= length)
    is_icmp = is_ipv4 & (proto == PROTO_ICMP) & l4_in_bounds

    sp = be16_at(pkt, l4_off)
    dp = be16_at(pkt, l4_off + 2)
    icmp_id = be16_at(pkt, l4_off + 4)  # echo id
    # ICMP "ports" for session tracking (parity: nat44.c:643-649,846-851):
    # egress uses echo id as src_port; ingress matches echo id as dst_port.
    src_port = jnp.where(is_icmp, icmp_id, jnp.where(is_udp | is_tcp, sp, 0))
    dst_port = jnp.where(is_icmp, icmp_id, jnp.where(is_udp | is_tcp, dp, 0))
    tcp_flags = jnp.where(is_tcp, u8_at(pkt, l4_off + 13), 0)

    return Parsed(
        dst_mac_hi=dst_mac_hi,
        dst_mac_lo=dst_mac_lo,
        src_mac_hi=src_mac_hi,
        src_mac_lo=src_mac_lo,
        ethertype=ethertype,
        is_vlan=is_vlan,
        is_qinq=is_qinq,
        s_tag=s_tag,
        c_tag=c_tag,
        vlan_offset=vlan_offset,
        is_ipv4=is_ipv4,
        is_ipv6=is_ipv6,
        l3_off=l3_off,
        ihl_bytes=ihl,
        total_len=total_len,
        ttl=ttl,
        proto=proto,
        src_ip=src_ip,
        dst_ip=dst_ip,
        l4_off=l4_off,
        is_udp=is_udp,
        is_tcp=is_tcp,
        is_icmp=is_icmp,
        src_port=src_port,
        dst_port=dst_port,
        tcp_flags=tcp_flags,
    )
