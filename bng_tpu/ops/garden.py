"""Device-side walled-garden gate — beyond the reference.

The reference's walled garden is slow-path only: its `SetEBPFMaps` hooks
have no consuming bpf program (/root/reference/pkg/walledgarden/
manager.go:172-178), so an unauthenticated subscriber's data traffic
simply PASSes to the host. The fused TPU pipeline already sees every
packet, so enforcement moves on-device: a gardened subscriber's upstream
traffic to a non-allowed destination DROPs at batch rate, and only
portal/DNS flows (the manager's allowed destinations,
manager.go:95-103) reach anything at all.

Design (TPU-first):
- gardened-subscriber membership is a bucket-packed cuckoo table keyed by
  the subscriber's private IPv4 (the identity the data path actually
  has; the host control plane maps MAC->lease IP at each garden/lease
  transition). Values are 8-word rows (word 0 = gardened flag) — the
  wide-row shape the HLO budget pins (PERF_NOTES §2: narrow gathers
  serialize).
- allowed destinations are a dense [D, 3] uint32 array (ip, port, proto;
  port/proto 0 = wildcard, ip 0 = empty row) compared [B, D] broadcast —
  the same dense-beats-trie call as the antispoof ranges
  (ops/antispoof.py): D <= 64 destinations is a handful of VPU compares.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from bng_tpu.ops.parse import Parsed
from bng_tpu.ops.table import TableGeom, TableState, lookup

GARDEN_WORDS = 8  # value row: [flag, 7 spare] — wide-row gather shape
GV_FLAG = 0

# stats
(GST_GATED_DROPS, GST_ALLOWED_HITS) = range(2)
GARDEN_NSTATS = 2

GardenGeom = TableGeom


class GardenResult(NamedTuple):
    gate_drop: jax.Array  # [B] bool — gardened lane to a non-allowed dest
    gardened: jax.Array  # [B] bool — lane belongs to a gardened subscriber
    stats: jax.Array  # [GARDEN_NSTATS] uint32


def garden_kernel(
    parsed: Parsed,
    eligible: jax.Array,  # [B] bool — upstream IPv4 data lanes (not DHCP)
    subscribers: TableState,
    geom: GardenGeom,
    allowed: jax.Array,  # [D, 3] uint32: (ip, port, proto); ip 0 = empty
) -> GardenResult:
    res = lookup(subscribers, parsed.src_ip[:, None].astype(jnp.uint32), geom)
    gardened = res.found & (res.vals[:, GV_FLAG] != 0) & eligible

    ip = allowed[:, 0]
    port = allowed[:, 1]
    proto = allowed[:, 2]
    dst_ok = parsed.dst_ip[:, None] == ip[None, :]
    port_ok = (port[None, :] == 0) | (parsed.dst_port.astype(jnp.uint32)[:, None]
                                      == port[None, :])
    proto_ok = (proto[None, :] == 0) | (parsed.proto.astype(jnp.uint32)[:, None]
                                        == proto[None, :])
    valid_row = (ip != 0)[None, :]
    allowed_lane = (dst_ok & port_ok & proto_ok & valid_row).any(axis=1)

    gate_drop = gardened & ~allowed_lane
    stats = jnp.stack([
        gate_drop.sum().astype(jnp.uint32),
        (gardened & allowed_lane).sum().astype(jnp.uint32),
    ])
    return GardenResult(gate_drop=gate_drop, gardened=gardened, stats=stats)
