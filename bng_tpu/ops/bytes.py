"""Vectorized byte extraction/composition over packet batches.

Packets live as `[B, L]` uint8 arrays (L static, default 512 — covers DHCP's
~350 bytes worst case, bpf/maps.h:22 caps option scans at 312). All helpers
are branch-free gathers/selects so the whole parse lowers to a handful of
fused XLA ops — the TPU equivalent of the reference's verifier-safe
fixed-offset parsing style (bpf/dhcp_fastpath.c:216-250).

Offsets may be per-lane (`[B]` int32) because VLAN tagging shifts L3 by
0/4/8 bytes per packet (bpf/dhcp_fastpath.c:352-428).
"""

from __future__ import annotations

import jax.numpy as jnp

PKT_LEN = 512  # static packet slot size


def _off(offs):
    return jnp.asarray(offs).astype(jnp.int32)


def u8_at(pkt, offs):
    """Gather one byte per lane at per-lane offsets -> [B] uint32."""
    idx = jnp.clip(_off(offs), 0, pkt.shape[1] - 1)
    return jnp.take_along_axis(pkt, idx[:, None], axis=1)[:, 0].astype(jnp.uint32)


def be16_at(pkt, offs):
    return (u8_at(pkt, offs) << 8) | u8_at(pkt, offs + 1)


def be32_at(pkt, offs):
    return (be16_at(pkt, offs) << 16) | be16_at(pkt, offs + 2)


def bytes_at(pkt, offs, n: int):
    """Gather n consecutive bytes per lane -> [B, n] uint8 (n static)."""
    idx = _off(offs)[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, pkt.shape[1] - 1)
    return jnp.take_along_axis(pkt, idx, axis=1)


# Per-lane writes are SELECTS, not scatters: a scatter with per-lane
# column indices serializes on TPU (row-at-a-time dynamic-update-slice),
# while a broadcast compare + where is one fused VPU pass over [B, L].
# An n-byte field costs one pass; consecutive field writes fuse.


def _select_write(pkt, offs, val, nbytes: int, mask=None):
    """Write an nbytes big-endian field at per-lane offsets via select."""
    col = jnp.arange(pkt.shape[1], dtype=jnp.int32)[None, :]
    rel = col - _off(offs)[:, None]  # [B, L] position within the field
    inb = (rel >= 0) & (rel < nbytes)
    if mask is not None:
        inb = inb & mask[:, None]
    sh = jnp.clip((nbytes - 1 - rel) * 8, 0, 31).astype(jnp.uint32)
    byte = (val.astype(jnp.uint32)[:, None] >> sh) & 0xFF
    return jnp.where(inb, byte.astype(pkt.dtype), pkt)


def scatter_u8_at(pkt, offs, val):
    """Write one byte per lane at per-lane offsets (in-place rewrite path).

    Used by NAT44 where a few fields are rewritten at VLAN/IHL-dependent
    offsets (bpf/nat44.c:752-801).
    """
    return _select_write(pkt, offs, val, 1)


def scatter_be16_at(pkt, offs, val):
    return _select_write(pkt, offs, val, 2)


def scatter_be32_at(pkt, offs, val):
    return _select_write(pkt, offs, val, 4)


def scatter_u8_at_masked(pkt, offs, val, mask):
    """Masked per-lane byte write: lanes with mask=False keep old bytes."""
    return _select_write(pkt, offs, val, 1, mask)


def scatter_be16_at_masked(pkt, offs, val, mask):
    return _select_write(pkt, offs, val, 2, mask)


def scatter_be32_at_masked(pkt, offs, val, mask):
    return _select_write(pkt, offs, val, 4, mask)


# ---- segment builders (compose-by-concatenation path) ----
# Building a reply by chaining .at[:, col].set(...) creates one
# dynamic-update-slice per field — dozens of serial buffer copies. Instead
# build [B, n] byte segments and concatenate once.


def const_seg(Bsz: int, *vals: int):
    """[B, len(vals)] uint8 segment of per-batch constants."""
    row = jnp.asarray(vals, dtype=jnp.uint8)
    return jnp.broadcast_to(row[None, :], (Bsz, len(vals)))


def be16_seg(val):
    """[B] value -> [B, 2] big-endian bytes."""
    v = val.astype(jnp.uint32)
    return jnp.stack([(v >> 8) & 0xFF, v & 0xFF], axis=1).astype(jnp.uint8)


def be32_seg(val):
    v = val.astype(jnp.uint32)
    return jnp.stack(
        [(v >> 24) & 0xFF, (v >> 16) & 0xFF, (v >> 8) & 0xFF, v & 0xFF], axis=1
    ).astype(jnp.uint8)


def u8_seg(val):
    return val.astype(jnp.uint8)[:, None]
