"""Vectorized byte extraction/composition over packet batches.

Packets live as `[B, L]` uint8 arrays (L static, default 512 — covers DHCP's
~350 bytes worst case, bpf/maps.h:22 caps option scans at 312). All helpers
are branch-free gathers/selects so the whole parse lowers to a handful of
fused XLA ops — the TPU equivalent of the reference's verifier-safe
fixed-offset parsing style (bpf/dhcp_fastpath.c:216-250).

Offsets may be per-lane (`[B]` int32) because VLAN tagging shifts L3 by
0/4/8 bytes per packet (bpf/dhcp_fastpath.c:352-428).
"""

from __future__ import annotations

import jax.numpy as jnp

PKT_LEN = 512  # static packet slot size


def _off(offs):
    return offs.astype(jnp.int32)


def u8_at(pkt, offs):
    """Gather one byte per lane at per-lane offsets -> [B] uint32."""
    idx = jnp.clip(_off(offs), 0, pkt.shape[1] - 1)
    return jnp.take_along_axis(pkt, idx[:, None], axis=1)[:, 0].astype(jnp.uint32)


def be16_at(pkt, offs):
    return (u8_at(pkt, offs) << 8) | u8_at(pkt, offs + 1)


def be32_at(pkt, offs):
    return (be16_at(pkt, offs) << 16) | be16_at(pkt, offs + 2)


def bytes_at(pkt, offs, n: int):
    """Gather n consecutive bytes per lane -> [B, n] uint8 (n static)."""
    idx = _off(offs)[:, None] + jnp.arange(n, dtype=jnp.int32)[None, :]
    idx = jnp.clip(idx, 0, pkt.shape[1] - 1)
    return jnp.take_along_axis(pkt, idx, axis=1)


def set_u8(buf, col: int, val):
    """Set a static column to per-lane byte values."""
    return buf.at[:, col].set(val.astype(jnp.uint8))


def set_const(buf, col: int, val: int):
    return buf.at[:, col].set(jnp.uint8(val))


def set_be16(buf, col: int, val):
    buf = set_u8(buf, col, (val >> 8) & 0xFF)
    return set_u8(buf, col + 1, val & 0xFF)


def set_be32(buf, col: int, val):
    buf = set_be16(buf, col, (val >> 16) & 0xFFFF)
    return set_be16(buf, col + 2, val & 0xFFFF)


def set_bytes(buf, col: int, vals):
    """Set a static range of columns to [B, n] uint8 values."""
    return buf.at[:, col : col + vals.shape[1]].set(vals.astype(jnp.uint8))


def scatter_u8_at(pkt, offs, val):
    """Write one byte per lane at per-lane offsets (in-place rewrite path).

    Used by NAT44 where a few fields are rewritten at VLAN/IHL-dependent
    offsets (bpf/nat44.c:752-801).
    """
    idx = jnp.clip(_off(offs), 0, pkt.shape[1] - 1)
    rows = jnp.arange(pkt.shape[0], dtype=jnp.int32)
    return pkt.at[rows, idx].set(val.astype(jnp.uint8))


def scatter_be16_at(pkt, offs, val):
    pkt = scatter_u8_at(pkt, offs, (val >> 8) & 0xFF)
    return scatter_u8_at(pkt, offs + 1, val & 0xFF)


def scatter_be32_at(pkt, offs, val):
    pkt = scatter_be16_at(pkt, offs, (val >> 16) & 0xFFFF)
    return scatter_be16_at(pkt, offs + 2, val & 0xFFFF)


def scatter_u8_at_masked(pkt, offs, val, mask):
    """Masked per-lane byte write: lanes with mask=False keep old bytes."""
    old = u8_at(pkt, offs)
    new = jnp.where(mask, val, old)
    return scatter_u8_at(pkt, offs, new)


def scatter_be16_at_masked(pkt, offs, val, mask):
    pkt = scatter_u8_at_masked(pkt, offs, (val >> 8) & 0xFF, mask)
    return scatter_u8_at_masked(pkt, offs + 1, val & 0xFF, mask)


def scatter_be32_at_masked(pkt, offs, val, mask):
    pkt = scatter_be16_at_masked(pkt, offs, (val >> 16) & 0xFFFF, mask)
    return scatter_be16_at_masked(pkt, offs + 2, val & 0xFFFF, mask)
